"""Command-line interface (ref: cake-cli/src/main.rs:23-93 — subcommands
run | serve | pull | list | chat | rm | split | worker).

    cake-tpu run Qwen/Qwen3-0.6B "hello"          one-shot generation
    cake-tpu run MODEL --cluster-key K            distributed master
    cake-tpu worker --name w0 --cluster-key K     worker node
    cake-tpu serve MODEL [--port 8000]            OpenAI-compatible API + UI
    cake-tpu chat MODEL | --api URL               terminal chat
    cake-tpu top [--api URL]                      live fleet dashboard
    cake-tpu pull/list/rm                          model cache management
    cake-tpu split MODEL TOPOLOGY OUT             per-worker weight bundles
"""
from __future__ import annotations

import argparse
import json
import logging
import os
import sys
import time

from . import knobs


def _add_common_model_args(p: argparse.ArgumentParser):
    p.add_argument("model", help="model dir or HF repo id")
    p.add_argument("--dtype", default="bf16", help="bf16|f16|f32")
    p.add_argument("--arch", default=None,
                   help="force architecture (e.g. qwen3, llama3)")
    p.add_argument("--max-cache-len", type=int, default=2048)
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--cluster-key", default=knobs.get("CAKE_CLUSTER_KEY"),
                   help="enable distributed mode (env: CAKE_CLUSTER_KEY)")
    p.add_argument("--topology", default=None, help="topology YAML path")
    p.add_argument("--no-download", action="store_true")
    p.add_argument("--fp8-native", action="store_true",
                   help="keep FP8 weights 1 byte/param in HBM, dequant "
                        "per layer (FP8 checkpoints only)")
    p.add_argument("--tp", default=None,
                   help="in-host tensor parallelism: 'auto' shards over all "
                        "local devices, N over the first N (default: 1 chip)")
    p.add_argument("--sp", type=int, default=None,
                   help="in-host sequence parallelism: shard long-prompt "
                        "prefill over N devices via ring attention "
                        "(composes with --tp; tp*sp devices are used)")
    p.add_argument("--expert-offload", action="store_true",
                   help="MoE: stream experts from disk instead of holding "
                        "them in HBM (capacity over throughput; serves "
                        "models whose expert banks exceed device memory)")
    p.add_argument("--discovery-timeout", type=float, default=3.0,
                   help="seconds to wait for UDP worker discovery")
    p.add_argument("--min-workers", type=int, default=0,
                   help="stop discovery as soon as this many workers "
                        "replied (0 = wait the full timeout)")


def _add_sampling_args(p: argparse.ArgumentParser):
    p.add_argument("--max-tokens", type=int, default=256)
    p.add_argument("--temperature", type=float, default=0.7)
    p.add_argument("--top-k", type=int, default=None)
    p.add_argument("--top-p", type=float, default=None)
    p.add_argument("--repeat-penalty", type=float, default=1.0)
    p.add_argument("--repeat-last-n", type=int, default=64,
                   help="window the repeat penalty looks back over")
    p.add_argument("--system-prompt", default=None,
                   help="system message for the chat template (the "
                        "reference defaults to 'You are a helpful AI "
                        "assistant.'; here omitted unless given)")


def _sampling(args):
    from .ops.sampling import SamplingConfig
    return SamplingConfig(temperature=args.temperature, top_k=args.top_k,
                          top_p=args.top_p,
                          repeat_penalty=args.repeat_penalty,
                          repeat_last_n=args.repeat_last_n)


def _messages(args, prompt: str) -> list[dict]:
    msgs = []
    if getattr(args, "system_prompt", None):
        msgs.append({"role": "system", "content": args.system_prompt})
    msgs.append({"role": "user", "content": prompt})
    return msgs


def _build(args):
    from .runtime import build_text_model
    return build_text_model(
        args.model, dtype=args.dtype, arch=args.arch,
        max_cache_len=args.max_cache_len, seed=args.seed,
        cluster_key=args.cluster_key, topology_path=args.topology,
        download=not args.no_download,
        fp8_native=getattr(args, "fp8_native", False),
        tp=getattr(args, "tp", None), sp=getattr(args, "sp", None),
        discovery_timeout=getattr(args, "discovery_timeout", 3.0),
        min_workers=getattr(args, "min_workers", 0),
        expert_offload=getattr(args, "expert_offload", False))


def cmd_run(args) -> int:
    gen, tokenizer, model_id, _ = _build(args)
    prompt = args.prompt or "Hello"
    if args.raw:
        ids = tokenizer.encode(prompt)
        _, stats = gen.generate(ids, max_new_tokens=args.max_tokens,
                                sampling=_sampling(args),
                                on_token=_print_token)
    else:
        _, stats = gen.chat_generate(
            _messages(args, prompt),
            max_new_tokens=args.max_tokens, sampling=_sampling(args),
            on_token=_print_token)
    print()
    print(f"[{stats['decode_tokens']} tokens, {stats['tok_per_s']:.1f} tok/s, "
          f"ttft {stats['ttft_s'] * 1000:.0f} ms]", file=sys.stderr)
    return 0


def _print_token(tok):
    if tok.text and not tok.is_end_of_stream:
        print(tok.text, end="", flush=True)


def cmd_image(args) -> int:
    """One-shot image generation to a PNG (ref: `cake run --model-type
    image-model --image-output out.png`; here a dedicated subcommand)."""
    from .runtime import build_image_model
    model = build_image_model(args.model, dtype=args.dtype,
                              fp8_native=getattr(args, "fp8_native", False))
    kwargs = dict(width=args.width, height=args.height, seed=args.seed)
    if args.steps is not None:
        kwargs["steps"] = args.steps
    if args.guidance is not None:
        kwargs["guidance"] = args.guidance
    if args.negative_prompt is not None:
        kwargs["negative_prompt"] = args.negative_prompt
    if args.init_image:
        # img2img (ref: --sd-img2img FILE + --sd-img2img-strength)
        if not hasattr(model, "init_latent_from"):
            raise SystemExit("--init-image needs an SD model (FLUX is "
                             "guidance-distilled text-to-image only)")
        from PIL import Image
        try:
            kwargs["init_image"] = model.init_latent_from(
                Image.open(args.init_image), args.width, args.height)
        except ValueError as e:
            raise SystemExit(str(e))
        kwargs["strength"] = args.strength
    t0 = time.monotonic()
    image = model.generate_image(args.prompt, **kwargs)
    image.save(args.out, format="PNG")
    print(f"[{args.out}: {args.width}x{args.height} in "
          f"{time.monotonic() - t0:.1f}s]", file=sys.stderr)
    return 0


def cmd_tts(args) -> int:
    """One-shot TTS to a WAV (ref: `cake run --model-type audio-model
    --audio-output output.wav`; here a dedicated subcommand)."""
    from .runtime import build_audio_model
    model = build_audio_model(args.model, dtype=args.dtype)
    voice_wav = None
    if args.voice_wav:
        with open(args.voice_wav, "rb") as f:
            voice_wav = f.read()
    kwargs = dict(voice=args.voice, voice_wav=voice_wav, seed=args.seed)
    if args.frames is not None:
        kwargs["max_frames"] = args.frames
    if args.steps is not None:
        kwargs["steps"] = args.steps
    if args.cfg_scale is not None:
        kwargs["cfg_scale"] = args.cfg_scale
    t0 = time.monotonic()
    audio = model.generate_speech(args.text, **kwargs)
    with open(args.out, "wb") as f:
        f.write(audio.wav_bytes())
    print(f"[{args.out}: {time.monotonic() - t0:.1f}s]", file=sys.stderr)
    return 0


def cmd_serve(args) -> int:
    from .api import ApiState, serve
    gen, tokenizer, model_id, topo = _build(args)
    image_model = audio_model = None
    if args.image_model:
        from .runtime import build_image_model
        image_model = build_image_model(
            args.image_model, dtype=args.dtype,
            fp8_native=getattr(args, "fp8_native", False))
    if args.audio_model:
        from .runtime import build_audio_model
        audio_model = build_audio_model(args.audio_model, dtype=args.dtype)
    layer_tensors = None
    try:
        # resolve the same way _build did (repo id -> cached snapshot dir)
        from .api.ui import layer_tensor_details
        from .utils.hub import resolve_model
        layer_tensors = layer_tensor_details(
            resolve_model(os.path.expanduser(args.model), download=False))
    except Exception:
        pass        # GGUF-only dirs / unresolved ids: UI shows no detail
    state = ApiState(model=gen, tokenizer=tokenizer, model_id=model_id,
                     topology=topo, image_model=image_model,
                     audio_model=audio_model, voices_dir=args.voices_dir,
                     layer_tensors=layer_tensors,
                     sd_intermediate_every=args.sd_intermediate_every,
                     sd_trace_dir=args.sd_trace_dir)
    # continuous batching for plain local TextModels (CAKE_SERVE_SLOTS
    # slots, CAKE_MAX_QUEUE admission bound, CAKE_SERVE_CTX per-slot
    # context; CAKE_SERVE_SLOTS=0 disables). Distributed/offload models
    # return None here and keep the locked one-at-a-time path.
    from .serve import maybe_engine
    state.engine = maybe_engine(gen)
    if state.engine is not None:
        print(f"[serve engine: {state.engine.slots} slots x "
              f"{state.engine.ctx} ctx, queue {state.engine.queue.maxsize}]",
              file=sys.stderr)
    # unified admission plane: QoS classes + tenant quotas for every
    # endpoint, heavy-job executor for images/audio (worker threads
    # start on the first job). Created eagerly so /health carries the
    # admission block from boot and SIGTERM drain covers job lanes.
    from .serve.admission import get_plane
    plane = get_plane(state)
    print(f"[admission plane: {plane.jobs.workers} job worker(s), "
          f"tenants={'on' if plane.tenants.policies else 'open'}]",
          file=sys.stderr)
    advertiser = None
    if args.announce:
        # announce this replica over the cluster discovery/PSK plumbing
        # so a fleet router (`cake route --cluster-key K`) finds it: same
        # UDP protocol as workers, caps tagged role=serve so routers and
        # masters never confuse the two populations
        key = args.cluster_key or knobs.get("CAKE_CLUSTER_KEY")
        if not key:
            print("error: --announce needs --cluster-key "
                  "(or CAKE_CLUSTER_KEY)", file=sys.stderr)
            return 2
        from .cluster.discovery import WorkerAdvertiser, detect_capabilities
        caps = {**detect_capabilities(), "role": "serve"}
        advertiser = WorkerAdvertiser(args.announce_name or os.uname().nodename,
                                      key, args.port, caps=caps).start()
        print(f"[announcing replica {advertiser.name} on UDP discovery]",
              file=sys.stderr)
    try:
        serve(state, host=args.host, port=args.port,
              basic_auth=args.basic_auth)
    finally:
        if advertiser is not None:
            advertiser.stop()
    return 0


def cmd_route(args) -> int:
    """Fleet router: front N `cake serve` replicas with health-driven
    membership, prefix-affinity failover and router-level 429s."""
    replicas = []
    for spec in args.replica or []:
        name, sep, url = spec.partition("=")
        if not sep:
            url = spec
            name = spec.split("//")[-1].replace(":", "-").replace("/", "")
        if "://" not in url:
            url = "http://" + url
        replicas.append((name, url))
    key = args.cluster_key or knobs.get("CAKE_CLUSTER_KEY")
    scaling = bool(args.autoscale or knobs.get("CAKE_SCALE"))
    if not replicas and not key and not (scaling and
                                         knobs.get_str("CAKE_SCALE_SPAWN_CMD")):
        print("error: need --replica host:port entries, --cluster-key "
              "for UDP discovery, or --autoscale with CAKE_SCALE_SPAWN_CMD "
              "to bootstrap an empty fleet", file=sys.stderr)
        return 2
    from .fleet import serve_router
    serve_router(replicas, host=args.host, port=args.port, cluster_key=key,
                 autoscale=True if args.autoscale else None)
    return 0


def cmd_top(args) -> int:
    """Live fleet dashboard: render the router's telemetry rollup
    (burn rates, headroom, per-replica SLO rows) in the terminal."""
    from .fleet.top import run_top
    url = args.api
    if "://" not in url:
        url = "http://" + url
    return run_top(url, interval_s=args.interval, once=args.once,
                   plain=args.plain, timeout_s=args.timeout)


def cmd_worker(args) -> int:
    from .cluster import run_worker
    if not args.cluster_key:
        print("error: --cluster-key (or CAKE_CLUSTER_KEY) required",
              file=sys.stderr)
        return 2
    run_worker(args.name, args.cluster_key, port=args.port,
               model_dir=args.model_dir, tp=args.tp)
    return 0


def cmd_pull(args) -> int:
    from .utils.hub import pull
    path = pull(args.repo)
    print(path)
    return 0


def cmd_list(args) -> int:
    from .utils.models import list_models
    rows = list_models()
    if not rows:
        print("no cached models")
        return 0
    w = max(len(m.repo_id) for m in rows) + 2
    for m in rows:
        status = "complete" if m.complete else "PARTIAL"
        print(f"{m.repo_id:<{w}} {m.source:<5} {m.size_bytes / 1e9:7.2f} GB  "
              f"{status}")
    return 0


def cmd_rm(args) -> int:
    from .utils.models import delete_model
    if delete_model(args.repo):
        print(f"removed {args.repo}")
        return 0
    print(f"{args.repo} not found", file=sys.stderr)
    return 1


def cmd_split(args) -> int:
    from .cluster.topology import Topology
    from .runtime import load_config_and_quant
    from .utils.hub import resolve_model
    from .utils.split import split_model
    model_dir = resolve_model(args.model, download=not args.no_download)
    cfg, _, _ = load_config_and_quant(model_dir)
    topo = Topology.from_path(args.topology)
    assignments = {name: n.layer_range for name, n in topo.nodes.items()
                   if n.layer_range}
    out = split_model(model_dir, assignments, args.out,
                      cfg.num_hidden_layers,
                      tie_word_embeddings=cfg.tie_word_embeddings)
    for worker, path in out.items():
        print(f"{worker}: {path}")
    return 0


def cmd_chat(args) -> int:
    sys_p = getattr(args, "system_prompt", None)
    if args.tui:
        from .tui import ChatSession, run_tui
        if args.api:
            session = ChatSession(api_url=args.api, api_key=args.api_key,
                                  system_prompt=sys_p)
        else:
            gen, tokenizer, model_id, _ = _build(args)
            session = ChatSession(gen=gen, sampling=_sampling(args),
                                  max_tokens=args.max_tokens,
                                  model_id=model_id, system_prompt=sys_p)
        return run_tui(session)
    from .chat import chat_local, chat_remote
    if args.api:
        return chat_remote(args.api, args.api_key, system_prompt=sys_p)
    gen, tokenizer, model_id, _ = _build(args)
    return chat_local(gen, model_id, _sampling(args), args.max_tokens,
                      system_prompt=sys_p)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="cake-tpu",
                                 description="TPU-native distributed "
                                             "multimodal inference")
    ap.add_argument("-v", "--verbose", action="count", default=0)
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU platform (the JAX_PLATFORMS env "
                         "var is ignored when a sitecustomize pre-imports "
                         "jax)")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("run", help="generate text for a prompt")
    _add_common_model_args(p)
    _add_sampling_args(p)
    p.add_argument("prompt", nargs="?", default=None)
    p.add_argument("--raw", action="store_true",
                   help="no chat template, raw completion")
    p.set_defaults(fn=cmd_run)

    p = sub.add_parser("image", help="generate an image to a PNG file")
    p.add_argument("model", help="image model dir ('demo:flux'/'demo:sd' "
                                 "for random weights)")
    p.add_argument("prompt")
    p.add_argument("--out", default="output.png")
    p.add_argument("--width", type=int, default=1024)
    p.add_argument("--height", type=int, default=1024)
    p.add_argument("--steps", type=int, default=None)
    p.add_argument("--guidance", type=float, default=None)
    p.add_argument("--seed", type=int, default=None)
    p.add_argument("--negative-prompt", default=None)
    p.add_argument("--init-image", default=None,
                   help="img2img: start from this image (SD; ref "
                        "--sd-img2img)")
    p.add_argument("--strength", type=float, default=0.8,
                   help="img2img denoise depth (ref --sd-img2img-strength)")
    p.add_argument("--dtype", default="bf16")
    p.add_argument("--fp8-native", action="store_true",
                   help="FLUX.1 fp8 checkpoints stay 1 byte/param in HBM")
    p.set_defaults(fn=cmd_image)

    p = sub.add_parser("tts", help="synthesize speech to a WAV file")
    p.add_argument("model", help="TTS model dir ('demo:vibevoice' | "
                                 "'demo:luxtts')")
    p.add_argument("text")
    p.add_argument("--out", default="output.wav")
    p.add_argument("--frames", type=int, default=None,
                   help="max speech frames (~133ms each for VibeVoice)")
    p.add_argument("--steps", type=int, default=None,
                   help="diffusion steps per frame")
    p.add_argument("--cfg-scale", type=float, default=None)
    p.add_argument("--voice", default=None,
                   help="voice-prompt .safetensors path (VibeVoice)")
    p.add_argument("--voice-wav", default=None,
                   help="clone the voice from this reference WAV")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--dtype", default="bf16")
    p.set_defaults(fn=cmd_tts)

    p = sub.add_parser("serve", help="OpenAI-compatible API server")
    _add_common_model_args(p)
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--basic-auth", default=None, help="user:pass")
    p.add_argument("--image-model", default=None,
                   help="image model dir ('demo:flux' for random weights)")
    p.add_argument("--voices-dir", default=None,
                   help="directory of voice-prompt .safetensors files "
                        "served by name via the API")
    p.add_argument("--audio-model", default=None,
                   help="TTS model dir ('demo:vibevoice' | 'demo:luxtts')")
    p.add_argument("--sd-intermediate-every", type=int, default=0,
                   help="save the in-progress SD image every N denoise "
                        "steps (ref: intermediary_images)")
    p.add_argument("--sd-trace-dir", default=None,
                   help="write a JAX profiler trace of SD generation here "
                        "(ref: --sd-tracing)")
    p.add_argument("--announce", action="store_true",
                   help="advertise this replica on UDP discovery so a "
                        "fleet router (`cake-tpu route`) can find it "
                        "(needs --cluster-key / CAKE_CLUSTER_KEY)")
    p.add_argument("--announce-name", default=None,
                   help="replica name for discovery (default: hostname)")
    p.set_defaults(fn=cmd_serve)

    p = sub.add_parser("route", help="fleet router over N serve replicas")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8100)
    p.add_argument("--replica", action="append", default=[],
                   help="replica as NAME=URL or host:port "
                        "(repeatable; e.g. r0=http://10.0.0.5:8000)")
    p.add_argument("--cluster-key", default=None,
                   help="PSK for UDP discovery of `cake serve --announce` "
                        "replicas (CAKE_CLUSTER_KEY also works)")
    p.add_argument("--autoscale", action="store_true",
                   help="run the closed-loop autoscaler (scale replicas "
                        "out/in from telemetry; needs "
                        "CAKE_SCALE_SPAWN_CMD to scale out — same as "
                        "CAKE_SCALE=1, see docs/autoscaling.md)")
    p.set_defaults(fn=cmd_route)

    p = sub.add_parser("top", help="live fleet dashboard (telemetry "
                                   "rollup from a `route` process)")
    p.add_argument("--api", default="127.0.0.1:8100",
                   help="fleet router base URL (default 127.0.0.1:8100)")
    p.add_argument("--interval", type=float, default=2.0,
                   help="refresh period in seconds")
    p.add_argument("--once", action="store_true",
                   help="print one plain-text snapshot and exit")
    p.add_argument("--plain", action="store_true",
                   help="plain text instead of curses (implied when "
                        "stdout is not a tty)")
    p.add_argument("--timeout", type=float, default=3.0,
                   help="per-fetch HTTP timeout in seconds")
    p.set_defaults(fn=cmd_top)

    p = sub.add_parser("worker", help="run as a cluster worker")
    p.add_argument("--name", default=os.uname().nodename)
    p.add_argument("--cluster-key", default=knobs.get("CAKE_CLUSTER_KEY"))
    p.add_argument("--port", type=int, default=10128)
    p.add_argument("--model-dir", default=None,
                   help="pre-provisioned weights (from `cake-tpu split`)")
    p.add_argument("--tp", default=None,
                   help="in-host tensor parallelism over this worker's "
                        "local devices ('auto' = all)")
    p.set_defaults(fn=cmd_worker)

    p = sub.add_parser("pull", help="download a model")
    p.add_argument("repo")
    p.set_defaults(fn=cmd_pull)

    p = sub.add_parser("list", help="list cached models")
    p.set_defaults(fn=cmd_list)

    p = sub.add_parser("rm", help="delete a cached model")
    p.add_argument("repo")
    p.set_defaults(fn=cmd_rm)

    p = sub.add_parser("split", help="write per-worker weight bundles")
    p.add_argument("model")
    p.add_argument("topology")
    p.add_argument("out")
    p.add_argument("--no-download", action="store_true")
    p.set_defaults(fn=cmd_split)

    p = sub.add_parser("chat", help="interactive terminal chat")
    _add_common_model_args(p)
    _add_sampling_args(p)
    p.add_argument("--api", default=None,
                   help="chat against a remote cake-tpu API URL instead")
    p.add_argument("--api-key", default=None)
    p.add_argument("--tui", action="store_true",
                   help="full-screen 2-tab interface (Chat + Cluster)")
    p.set_defaults(fn=cmd_chat)

    args = ap.parse_args(argv)
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
    logging.basicConfig(
        level=[logging.WARNING, logging.INFO, logging.DEBUG][min(args.verbose, 2)],
        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
