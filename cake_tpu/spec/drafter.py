"""Drafters: proposal sources for speculative decoding.

A Drafter looks at the committed sequence (prompt + generated ids, host
side) and proposes up to k continuation tokens for ONE verify step to
check. Proposals are free to be wrong — the traced accept/reject rule
(ops/sampling.spec_accept) guarantees the emitted sequence keeps the
target model's semantics regardless — so a drafter's only job is to be
cheap and right often enough that accepted-tokens-per-step beats 1.0.

Two built-ins:

  * NGramDrafter — zero-weight prompt-lookup (Saxena 2023 "prompt lookup
    decoding"; the APD idea in Leviathan et al.'s framing with a
    copy-from-context q): match the last few tokens against the earlier
    sequence and propose whatever followed last time. Free, and strong
    exactly where decode is most wasteful — summarization, code editing,
    RAG, anything that restates its input.
  * DraftModelDrafter — classic two-model speculation: a smaller model
    with the SAME tokenizer greedily rolls out k tokens against its own
    small KV cache, rolling its speculative suffix back between calls
    with cache.truncate_cache.

Both are deterministic (point-mass q), which is what the acceptance rule
in ops/sampling.spec_accept assumes.
"""
from __future__ import annotations

from typing import Protocol, Sequence, runtime_checkable

import numpy as np

from .. import knobs

DEFAULT_SPEC_K = 6
MAX_SPEC_K = 32


@runtime_checkable
class Drafter(Protocol):
    """Proposal source for speculative decoding.

    `shareable` marks a drafter safe to share across concurrent sequences
    (stateless propose) — required by the serve engine, which calls one
    instance from every speculating slot.
    """

    name: str
    shareable: bool

    def propose(self, ids: Sequence[int], k: int) -> list[int]:
        """Up to k proposed continuation tokens for the sequence `ids`
        (prompt + generated so far). Return [] to abstain — the verify
        step then degenerates to a plain (distribution-preserving)
        decode step."""
        ...

    def reset(self) -> None:
        """Drop any per-sequence state before a new generation."""
        ...


class NGramDrafter:
    """Prompt-lookup drafter: no weights, no cache, no device work.

    Matches the last m tokens (m from max_ngram down to min_ngram)
    against the earlier sequence; on a hit, proposes the k tokens that
    followed the most recent earlier occurrence WITH A FULL k-token
    continuation (matches near the sequence end can only offer a stub —
    a 1-token proposal wastes the verify's amortized weight read, so a
    slightly older occurrence that fills the whole draft window beats a
    fresher one that cannot; when no occurrence fills it, the longest
    available continuation wins). Abstains when nothing repeats — a
    random prompt costs speculation nothing, a repetitive one (quote
    the context, fix this code, summarize) gets multi-token accepts for
    free. min_ngram >= 2 by default so single-token coincidences don't
    spray junk proposals.
    """

    name = "ngram"
    shareable = True

    def __init__(self, max_ngram: int = 3, min_ngram: int = 2):
        if not 1 <= min_ngram <= max_ngram:
            raise ValueError(f"need 1 <= min_ngram <= max_ngram, got "
                             f"{min_ngram}..{max_ngram}")
        self.max_ngram = max_ngram
        self.min_ngram = min_ngram

    def propose(self, ids: Sequence[int], k: int) -> list[int]:
        arr = np.asarray(list(ids), dtype=np.int64)
        n = int(arr.shape[0])
        if k <= 0 or n < self.min_ngram + 1:
            return []
        for m in range(min(self.max_ngram, n - 1), self.min_ngram - 1, -1):
            suffix = arr[n - m:]
            # candidate starts 0..n-m-1: the last window (the suffix
            # itself) is excluded, and every candidate has >= 1
            # continuation token
            windows = np.lib.stride_tricks.sliding_window_view(
                arr, m)[:n - m]
            hits = np.nonzero((windows == suffix).all(axis=1))[0]
            if hits.size == 0:
                continue
            # most recent occurrence whose continuation fills the whole
            # draft window; else the longest continuation on offer
            full = hits[hits + m + k <= n]
            j = int(full[-1]) if full.size else int(hits[0])
            cont = arr[j + m:j + m + k]
            if cont.size:
                return [int(t) for t in cont]
        return []

    def reset(self) -> None:
        pass


class DraftModelDrafter:
    """Greedy rollout from a smaller TextModel sharing the target's
    tokenizer (classic speculative sampling, Leviathan/Chen 2023).

    The drafter owns a small KV cache that always holds exactly the
    CONFIRMED prefix between calls: propose() forwards the unseen suffix
    (one bucketed prefill), greedily decodes k tokens, then rolls its own
    speculative suffix back out with cache.truncate_cache — the caller's
    sequence is append-only, so the prefix stays valid even when the
    target rejects every proposal. Attention-only draft models required:
    a linear-attention state cannot roll back (truncate_cache raises).

    Per-sequence state => NOT shareable across serve-engine slots; use it
    on the generate() path (or one engine slot pool per drafter).
    """

    name = "draft_model"
    shareable = False

    def __init__(self, model):
        specs = model.cfg.layer_specs()
        if any(s.kind == "linear" for s in specs):
            raise ValueError(
                "draft model has linear-attention layers; their recurrent "
                "state cannot roll back between proposals — use an "
                "attention-only draft model or the n-gram drafter")
        self.model = model
        self.reset()

    def reset(self) -> None:
        self.cache = None
        self.kv_len = 0
        self.n_valid = 0        # cache holds exactly positions [0, n_valid)

    def propose(self, ids: Sequence[int], k: int) -> list[int]:
        from ..models.common.cache import truncate_cache
        from ..models.common.text_model import bucket_for
        m = self.model
        n = len(ids)
        if n == 0 or n >= m.max_cache_len:
            return []
        # greedy decode writes positions n .. n+k-2; stay inside the cache
        k = min(k, m.max_cache_len - n)
        if k <= 0:
            return []
        need = n + k
        if self.cache is None:
            self.kv_len = bucket_for(need, m.max_cache_len)
            self.cache = m.new_cache(1, kv_len=self.kv_len)
            self.n_valid = 0
        elif need > self.kv_len:
            self.kv_len = bucket_for(need, m.max_cache_len)
            self.cache = m._grow_to(self.cache, new_len=self.kv_len)
        # forward the unseen suffix (>= 1 token: re-forwarding the last
        # position on a no-delta call just rewrites identical KV)
        start = min(self.n_valid, n - 1)
        logits, self.cache = m.prefill(self.cache, list(ids[start:n]),
                                       pos0=start)
        self.n_valid = n
        # lint: disable=host-sync — draft proposals are host ints by contract
        # (the drafter feeds the verify program's host-built token block)
        props = [int(np.argmax(np.asarray(logits[0])))]
        for _ in range(k - 1):
            logits, self.cache = m.decode_logits(self.cache, props[-1])
            # lint: disable=host-sync — same: each draft id seeds the next draft
            # decode step on the host path
            props.append(int(np.argmax(np.asarray(logits[0]))))
        if len(props) > 1:
            # decode committed positions n .. n+k-2 — our own speculation;
            # drop it so the cache again holds exactly the confirmed prefix
            self.cache = truncate_cache(m.cfg, self.cache, n)
        return props


def resolve_drafter(spec, k: int | None = None):
    """(drafter | None, k) from a generate()/engine `spec` argument.

    spec: None reads env CAKE_SPEC ("" / unset = off, "ngram" = prompt
    lookup); False forces off; "ngram" / a Drafter instance / a draft
    TextModel are taken as-is. k defaults from CAKE_SPEC_K, clamped to
    [1, 32]; the n-gram drafter's match window comes from
    CAKE_SPEC_NGRAM (max match length, min stays 2).
    """
    if k is None:
        k = knobs.get("CAKE_SPEC_K")
    k = max(1, min(int(k), MAX_SPEC_K))
    if spec is None:
        spec = knobs.get("CAKE_SPEC")
    if spec is None or spec is False:
        return None, k
    if isinstance(spec, str):
        s = spec.strip().lower()
        if s in ("", "0", "off", "none", "false"):
            return None, k
        if s in ("ngram", "prompt", "prompt_lookup", "lookup"):
            # clamp to >= 2: min_ngram stays at the documented
            # junk-proposal guard (single-token coincidences must never
            # spray k-token drafts through the wider verify forward)
            mg = max(2, int(knobs.get("CAKE_SPEC_NGRAM")))
            return NGramDrafter(max_ngram=mg), k
        raise ValueError(
            f"unknown drafter {spec!r}: pass 'ngram', a Drafter instance, "
            "or a draft TextModel")
    if isinstance(spec, Drafter):
        return spec, k
    if hasattr(spec, "prefill") and hasattr(spec, "decode_logits"):
        return DraftModelDrafter(spec), k
    raise TypeError(f"cannot build a drafter from {type(spec).__name__}")
