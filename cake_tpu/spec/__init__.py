"""Speculative decoding subsystem: draft, verify, accept — without
leaving the device.

Decode is memory-bound: every step reads all the weights to emit ONE
token, leaving the MXUs idle. Speculation converts that idle compute
into extra tokens: a cheap DRAFTER proposes up to CAKE_SPEC_K
continuation tokens, one bucketed VERIFY step forwards them all (the
weight read is amortized over k+1 positions), and a traced
accept/reject rule keeps exactly the prefix the target model agrees
with — greedy output is bit-identical to plain decoding, sampled output
keeps the target distribution (Leviathan et al. 2023; Chen et al. 2023).

Layout:
  drafter.py — Drafter protocol, NGramDrafter (zero-weight prompt
               lookup), DraftModelDrafter (two-model speculation)
  verify.py  — the generate()-path host loop + shared spec metrics;
               the traced pieces are ops/sampling.spec_accept (batched
               accept/resample) and TextModel's verify programs
               (verify_tokens batch-1; spec_slots/spec_slots_paged —
               the serve engine's batched multi-token verify with
               ragged per-slot acceptance), with the rejected-suffix
               rollback in cache.truncate_layers (contiguous) and the
               paged write-back's commit mask

Entry points: TextModel.generate(spec=..., spec_k=...) and the serve
engine's batched accept-aware iteration (serve/engine.py); env knobs
CAKE_SPEC / CAKE_SPEC_K / CAKE_SPEC_NGRAM / CAKE_SPEC_RESERVE. See
docs/speculative.md.
"""
from .drafter import (DEFAULT_SPEC_K, Drafter, DraftModelDrafter,
                      MAX_SPEC_K, NGramDrafter, resolve_drafter)
from .verify import record_step, spec_decode_loop, spec_stats_dict

__all__ = [
    "Drafter", "DraftModelDrafter", "NGramDrafter", "resolve_drafter",
    "spec_decode_loop", "record_step", "spec_stats_dict",
    "DEFAULT_SPEC_K", "MAX_SPEC_K",
]
