"""Host-side speculative decode loop: draft -> verify -> emit.

The traced pieces live elsewhere — ops/sampling.spec_accept (the
Leviathan/Chen accept/reject rule, batched), TextModel._spec_verify /
._spec_slots / ._spec_slots_paged (one bucketed forward + acceptance +
rejected-suffix rollback per device call; the _slots variants serve the
engine's batched ragged-acceptance iteration) — this module owns what
must stay on the host: asking the drafter,
growing the KV bucket, truncating emission at EOS / budget, and the spec
metrics every path shares (cake_serve_spec_{proposed,accepted}_total +
the accepted-length histogram).
"""
from __future__ import annotations

import jax
import numpy as np

from ..obs import (RECORDER, SPEC_ACCEPTED, SPEC_ACCEPTED_LEN,
                   SPEC_BUCKET_ACCEPTED, SPEC_PROPOSED)


def record_step(n_proposed: int, n_acc: int, bucket: int | None = None) -> None:
    """Feed the shared spec instruments from one completed verify step
    (generate loop and serve engine both call this — one call-site shape,
    both paths). `bucket` is the batched dispatch's slot-count bucket
    (engine path only): it labels the acceptance-x-occupancy histogram
    the serve bench reads."""
    SPEC_PROPOSED.inc(n_proposed)
    SPEC_ACCEPTED.inc(n_acc)
    SPEC_ACCEPTED_LEN.observe(n_acc)
    if bucket is not None:
        SPEC_BUCKET_ACCEPTED.observe(n_acc, bucket=str(bucket))


def spec_stats_dict(steps: int, proposed: int, accepted: int) -> dict:
    """Per-generation speculative stats block (stats dict / bench JSON)."""
    return {
        "spec_steps": steps,
        "spec_proposed": proposed,
        "spec_accepted": accepted,
        "spec_accept_rate": round(accepted / proposed, 4) if proposed else 0.0,
        # tokens emitted per device step (the speedup proxy: 1.0 == plain
        # decode; every verify emits its correction/bonus token + accepts)
        "spec_tokens_per_step": round((accepted + steps) / steps, 4)
        if steps else 0.0,
    }


def spec_decode_loop(model, drafter, k: int, prompt_ids: list[int],
                     out: list[int], cache, kv_len: int, rng, recent,
                     scfg, max_new_tokens: int, on_token, done: bool):
    """Speculative replacement for TextModel.generate's decode loop.

    `out` already holds the first sampled token (emitted by generate's
    shared prefill preamble); `done` is True when it was EOS. Each
    iteration: the drafter proposes up to k tokens from the host-side
    sequence, ONE verify call checks them all (and commits exactly the
    accepted prefix), and the host fans out n_acc + 1 tokens. Greedy
    output is bit-identical to the non-speculative path; EOS inside the
    accepted prefix truncates emission exactly where one-token-at-a-time
    decoding would have stopped.

    Returns (out, spec_stats).
    """
    cfg = model.cfg
    drafter.reset()
    pos = len(prompt_ids)               # next KV write position
    n_total = min(max_new_tokens - 1, model.max_cache_len - pos - 1)
    emitted = 0
    steps = proposed = accepted = 0
    while not done and emitted < n_total:
        # room for the widest verify (k drafts + the input token)
        if pos + k + 1 > kv_len and kv_len < model.max_cache_len:
            from ..models.common.text_model import bucket_for
            kv_len = bucket_for(pos + k + 1, model.max_cache_len)
            cache = model._grow_to(cache, new_len=kv_len)
        # never draft past the cache or the budget (a step emits at most
        # n_draft + 1 tokens; the +1 correction token always fits)
        n_draft = min(k, kv_len - pos - 1, max(n_total - emitted - 1, 0))
        draft = list(drafter.propose(prompt_ids + out, n_draft))[:n_draft] \
            if n_draft > 0 else []
        rng, sub = jax.random.split(rng)
        with RECORDER.span("spec.verify", cat="gen", drafts=len(draft),
                           pos=pos):
            packed, cache, recent = model.verify_tokens(
                cache, out[-1], draft, k, pos, sub, recent, scfg)
            # lint: disable=host-sync — the verify loop's one planned fetch per
            # step: [n_acc, next] in a single small transfer
            arr = np.asarray(packed)
        n_acc, nxt = int(arr[0]), int(arr[1])
        steps += 1
        proposed += len(draft)
        accepted += n_acc
        record_step(len(draft), n_acc)
        for t in draft[:n_acc] + [nxt]:
            out.append(t)
            emitted += 1
            if on_token is not None:
                on_token(model._mk_token(t))
            if cfg.is_eos(t) or emitted >= n_total:
                done = True
                break
        pos += n_acc + 1
    return out, spec_stats_dict(steps, proposed, accepted)
