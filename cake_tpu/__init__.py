"""cake-tpu: a TPU-native distributed multimodal AI inference framework.

A ground-up re-design of the capabilities of evilsocket/cake (a Rust/candle
LAN-cluster inference server) for TPU hardware: the compute path is JAX/XLA
(jit-compiled contiguous decoder-layer ranges, static shapes, Pallas kernels
for the hot fused ops), the cluster plane is the same host-side architecture
(UDP discovery, PSK auth, framed TCP activation shipping) re-implemented in
asyncio + a C++ framing/IO core.

Layer map (mirrors reference SURVEY §1):
  ops/       - op/kernel library        (ref: cake-core/src/backends/)
  utils/     - weights, quant, hub      (ref: cake-core/src/utils/)
  models/    - model zoo                (ref: cake-core/src/models/)
  cluster/   - distributed runtime      (ref: cake-core/src/cake/sharding/)
  api/       - OpenAI-compatible server (ref: cake-core/src/cake/sharding/api/)
  parallel/  - TPU-native mesh/sharding (beyond reference: TP/DP/SP over ICI)
  cli.py     - command line             (ref: cake-cli/)
"""

__version__ = "0.1.0"
