"""FP8 (E4M3) block-wise dequantization.

The reference stores FP8 weights with a per-128x128-block scale tensor
`weight_scale_inv` and dequantizes either at load (utils/fp8.rs) or
per-layer at forward for memory parity (utils/native_dtype_backend.rs,
backends/mod.rs f8e4m3_to_{f32,f16,bf16}). On TPU, float8_e4m3fn is a
native dtype: dequant is a cast + broadcast-multiply that XLA fuses into
the consuming matmul.
"""
from __future__ import annotations

import jax.numpy as jnp

FP8_BLOCK = 128  # ref: utils/fp8.rs block-wise (128x128) scales


def dequant_fp8_blockwise(weight_fp8, scale_inv, out_dtype=jnp.bfloat16,
                          block: int = FP8_BLOCK):
    """weight_fp8: [O, I] float8_e4m3fn; scale_inv: [ceil(O/b), ceil(I/b)] f32.

    Returns weight in out_dtype. Handles edge blocks when O/I are not
    multiples of the block size.
    """
    o, i = weight_fp8.shape
    w = weight_fp8.astype(jnp.float32)
    # Expand each block scale across its 128x128 tile, then crop.
    s = jnp.repeat(jnp.repeat(scale_inv, block, axis=0), block, axis=1)[:o, :i]
    return (w * s).astype(out_dtype)


def quant_fp8_blockwise(weight, block: int = FP8_BLOCK):
    """Inverse helper (tests + splitter): returns (fp8 weight, scale_inv)."""
    import numpy as np
    o, i = weight.shape
    po = (-o) % block
    pi = (-i) % block
    wp = jnp.pad(weight.astype(jnp.float32), ((0, po), (0, pi)))
    blocks = wp.reshape(
        (o + po) // block, block, (i + pi) // block, block).transpose(0, 2, 1, 3)
    amax = jnp.max(jnp.abs(blocks), axis=(2, 3))
    amax = jnp.maximum(amax, 1e-12)
    scale = 448.0 / amax                       # E4M3 max normal = 448
    scale_inv = 1.0 / scale
    wq = blocks * scale[:, :, None, None]
    wq = wq.transpose(0, 2, 1, 3).reshape(o + po, i + pi)[:o, :i]
    return wq.astype(jnp.float8_e4m3fn), scale_inv.astype(jnp.float32)
