"""Linear / embedding primitives.

Weights follow the HF/safetensors convention [out_features, in_features]
(ref: backends/mod.rs matmul / linear_forward / preprocess_linear_weight —
on TPU no weight preprocessing is needed: XLA lays out operands for the MXU).
"""
from __future__ import annotations

import jax.numpy as jnp


def resolve_weight(weight, dtype=None):
    """Materialize an fp8-native weight dict ({"fp8", "scale_inv"}) to the
    compute dtype inside the jitted forward; plain arrays pass through.
    XLA fuses the dequant into the consuming matmul, so HBM holds 1
    byte/param (ref: native_dtype_backend.rs)."""
    if isinstance(weight, dict):
        f8 = weight.get("fp8", weight.get("__fp8__"))
        if f8 is not None:
            from .fp8 import dequant_fp8_blockwise
            return dequant_fp8_blockwise(f8, weight["scale_inv"],
                                         out_dtype=dtype or jnp.bfloat16)
    return weight


def linear(x, weight, bias=None):
    """y = x @ W^T (+ b). x: [..., in], weight: [out, in] (or an fp8-native
    dict, dequantized on the fly)."""
    weight = resolve_weight(weight, x.dtype)
    y = jnp.einsum("...i,oi->...o", x, weight)
    if bias is not None:
        y = y + bias
    return y


def embedding(token_ids, table):
    """table: [vocab, hidden]; token_ids: int32 [...]."""
    return jnp.take(table, token_ids, axis=0)
