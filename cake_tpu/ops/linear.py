"""Linear / embedding primitives.

Weights follow the HF/safetensors convention [out_features, in_features]
(ref: backends/mod.rs matmul / linear_forward / preprocess_linear_weight —
on TPU no weight preprocessing is needed: XLA lays out operands for the MXU).
"""
from __future__ import annotations

import jax.numpy as jnp


def linear(x, weight, bias=None):
    """y = x @ W^T (+ b). x: [..., in], weight: [out, in]."""
    y = jnp.einsum("...i,oi->...o", x, weight)
    if bias is not None:
        y = y + bias
    return y


def embedding(token_ids, table):
    """table: [vocab, hidden]; token_ids: int32 [...]."""
    return jnp.take(table, token_ids, axis=0)
