"""Mixture-of-Experts routing and dispatch.

Reference semantics (ref: models/qwen3_moe/moe.rs, qwen3_5_moe/moe.rs):
softmax (or sigmoid) router -> top-k experts -> optional weight
renormalization -> weighted sum of expert FFNs (+ always-active shared
expert gated by sigmoid for Qwen3.5 MoE).

TPU formulation: experts are stacked [E, ...] tensors and dispatch is a
dense combine-weights einsum — every expert runs on every token and the
[T, E] combine matrix (zero outside top-k) selects. For decode (T is 1-8)
this is a batched matvec that keeps the MXU busy with zero gather/scatter
overhead. A sort-based ragged dispatch for long prefill is a planned
optimization; correctness and decode perf come first.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def router_topk(logits, k: int, norm_topk_prob: bool, gate_act: str = "softmax"):
    """logits: [T, E] -> (weights [T, k] f32, idx [T, k] int32).

    softmax gate: probabilities over experts then top-k (Qwen3 MoE).
    sigmoid gate: per-expert sigmoid scores then top-k (Qwen3.5 MoE).
    """
    lf = logits.astype(jnp.float32)
    if gate_act == "softmax":
        probs = jax.nn.softmax(lf, axis=-1)
    elif gate_act == "sigmoid":
        probs = jax.nn.sigmoid(lf)
    else:
        raise ValueError(f"unknown gate activation {gate_act}")
    weights, idx = jax.lax.top_k(probs, k)
    if norm_topk_prob:
        weights = weights / jnp.sum(weights, axis=-1, keepdims=True)
    return weights, idx.astype(jnp.int32)


def combine_weights(weights, idx, num_experts: int):
    """Scatter top-k (weight, index) into a dense [T, E] combine matrix."""
    t, k = weights.shape
    w_te = jnp.zeros((t, num_experts), weights.dtype)
    rows = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32)[:, None], (t, k))
    return w_te.at[rows, idx].add(weights)


def moe_ffn(x, router_weight, gate_proj, up_proj, down_proj, k: int,
            norm_topk_prob: bool, gate_act: str = "softmax", act: str = "silu"):
    """x: [T, H]; router_weight: [E, H]; gate/up_proj: [E, I, H];
    down_proj: [E, H, I]. Returns [T, H] in x.dtype.
    """
    e = gate_proj.shape[0]
    logits = jnp.einsum("th,eh->te", x, router_weight,
                        preferred_element_type=jnp.float32)
    weights, idx = router_topk(logits, k, norm_topk_prob, gate_act)
    w_te = combine_weights(weights, idx, e).astype(x.dtype)

    g = jnp.einsum("th,eih->tei", x, gate_proj)         # [T, E, I]
    u = jnp.einsum("th,eih->tei", x, up_proj)
    if act == "silu":
        a = jax.nn.silu(g) * u
    else:
        a = jax.nn.gelu(g, approximate=True) * u
    y_e = jnp.einsum("tei,ehi->teh", a, down_proj)      # [T, E, H]
    return jnp.einsum("te,teh->th", w_te, y_e).astype(x.dtype)
