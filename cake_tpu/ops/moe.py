"""Mixture-of-Experts routing and dispatch.

Reference semantics (ref: models/qwen3_moe/moe.rs, qwen3_5_moe/moe.rs):
softmax (or sigmoid) router -> top-k experts -> optional weight
renormalization -> weighted sum of expert FFNs (+ always-active shared
expert gated by sigmoid for Qwen3.5 MoE).

TPU formulation: experts are stacked [E, ...] tensors with two dispatch
strategies sharing one router:

  * dense combine (decode, T < RAGGED_MIN_TOKENS): every expert runs on
    every token and a [T, E] combine matrix (zero outside top-k) selects —
    for T of 1-8 this is a batched matvec with zero gather/scatter
    overhead, cheaper than any routing machinery.
  * sort-based ragged dispatch (prefill): the T*k (token, expert)
    assignments are sorted by expert and each expert multiplies only its
    contiguous slice via `lax.ragged_dot_general` (TPU ragged segment-GEMM
    over the stored [E, I, H] banks, no transpose/relayout) — FLOPs scale
    with k/E instead of E/E (ref: qwen3_moe/moe.rs top-8 over 128 experts
    = 16x prefill FLOP reduction; SURVEY hard-part #4).

Both paths compute identical expert math; tests/test_moe_ragged.py pins
them against each other and tests/test_hf_parity.py pins the
router+combine semantics to transformers.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

# below this many tokens the dense combine wins (decode / tiny chunks):
# the ragged path's sort/gather/scatter overhead only pays off once the
# per-expert GEMMs are big enough to tile the MXU
RAGGED_MIN_TOKENS = 32


def _ragged_available() -> bool:
    """lax.ragged_dot_general landed in newer jax releases; on older ones
    the dense combine serves every shape (same math, more FLOPs)."""
    import jax.lax
    return hasattr(jax.lax, "ragged_dot_general")


def _ragged_enabled() -> bool:
    """CAKE_MOE_RAGGED=0 pins every shape to the dense combine (escape
    hatch if a backend mishandles ragged_dot_general); also gated on the
    installed jax actually providing ragged_dot_general."""
    from .. import knobs
    return knobs.get("CAKE_MOE_RAGGED") and _ragged_available()


def router_topk(logits, k: int, norm_topk_prob: bool, gate_act: str = "softmax"):
    """logits: [T, E] -> (weights [T, k] f32, idx [T, k] int32).

    softmax gate: probabilities over experts then top-k (Qwen3 MoE).
    sigmoid gate: per-expert sigmoid scores then top-k (Qwen3.5 MoE).
    """
    lf = logits.astype(jnp.float32)
    if gate_act == "softmax":
        probs = jax.nn.softmax(lf, axis=-1)
    elif gate_act == "sigmoid":
        probs = jax.nn.sigmoid(lf)
    else:
        raise ValueError(f"unknown gate activation {gate_act}")
    weights, idx = jax.lax.top_k(probs, k)
    if norm_topk_prob:
        weights = weights / jnp.sum(weights, axis=-1, keepdims=True)
    return weights, idx.astype(jnp.int32)


def combine_weights(weights, idx, num_experts: int):
    """Scatter top-k (weight, index) into a dense [T, E] combine matrix."""
    t, k = weights.shape
    w_te = jnp.zeros((t, num_experts), weights.dtype)
    rows = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32)[:, None], (t, k))
    return w_te.at[rows, idx].add(weights)


def _expert_act(g, u, act: str):
    if act == "silu":
        return jax.nn.silu(g) * u
    return jax.nn.gelu(g, approximate=True) * u


def moe_ffn(x, router_weight, gate_proj, up_proj, down_proj, k: int,
            norm_topk_prob: bool, gate_act: str = "softmax", act: str = "silu"):
    """x: [T, H]; router_weight: [E, H]; gate/up_proj: [E, I, H];
    down_proj: [E, H, I]. Returns [T, H] in x.dtype.

    Static dispatch on T (a compile-time shape): ragged segment-GEMM for
    prefill-sized batches, dense combine for decode.
    """
    e = gate_proj.shape[0]
    logits = jnp.einsum("th,eh->te", x, router_weight,
                        preferred_element_type=jnp.float32)
    weights, idx = router_topk(logits, k, norm_topk_prob, gate_act)

    if x.shape[0] >= RAGGED_MIN_TOKENS and _ragged_enabled():
        return _moe_ragged(x, weights, idx, gate_proj, up_proj, down_proj,
                           act)
    w_te = combine_weights(weights, idx, e).astype(x.dtype)
    g = jnp.einsum("th,eih->tei", x, gate_proj)         # [T, E, I]
    u = jnp.einsum("th,eih->tei", x, up_proj)
    a = _expert_act(g, u, act)
    y_e = jnp.einsum("tei,ehi->teh", a, down_proj)      # [T, E, H]
    return jnp.einsum("te,teh->th", w_te, y_e).astype(x.dtype)


def _ragged_dn(lhs_contract: int, rhs_contract: int):
    from jax.lax import RaggedDotDimensionNumbers
    return RaggedDotDimensionNumbers(
        dot_dimension_numbers=(((lhs_contract,), (rhs_contract,)), ((), ())),
        lhs_ragged_dimensions=[0], rhs_group_dimensions=[0])


def _moe_ragged(x, weights, idx, gate_proj, up_proj, down_proj, act: str):
    """Sort the T*k assignments by expert; each expert GEMMs only its own
    contiguous token slice. Exact — group sizes come from the real
    assignment counts, so nothing is dropped or padded (no capacity
    factor), and the FLOPs are (k/E) * dense."""
    from jax.lax import ragged_dot_general
    t, h = x.shape
    k = idx.shape[1]
    e = gate_proj.shape[0]

    flat_expert = idx.reshape(t * k)
    order = jnp.argsort(flat_expert)                    # stable
    tok_of = order // k                                 # [T*k]
    xs = x[tok_of]                                      # [T*k, H]
    group_sizes = jnp.bincount(flat_expert, length=e).astype(jnp.int32)

    g = ragged_dot_general(xs, gate_proj, group_sizes, _ragged_dn(1, 2))
    u = ragged_dot_general(xs, up_proj, group_sizes, _ragged_dn(1, 2))
    a = _expert_act(g, u, act).astype(x.dtype)          # [T*k, I]
    y = ragged_dot_general(a, down_proj, group_sizes, _ragged_dn(1, 2))
    # combine in f32: the dense path's einsum accumulates on the MXU in
    # f32, so the bf16 scatter-add here must not be the lower-precision one
    w_flat = weights.reshape(t * k)[order]                 # f32 from router
    out = jnp.zeros((t, h), jnp.float32)
    out = out.at[tok_of].add(y.astype(jnp.float32) * w_flat[:, None])
    return out.astype(x.dtype)
