"""Normalization ops.

TPU-native equivalents of the reference ComputeBackend norm methods
(ref: cake-core/src/backends/mod.rs rms_norm / layer_norm / group_norm /
rms_norm_gated / add_rms_norm / rms_norm_channel). On TPU these are plain
jnp expressions: XLA fuses them into the surrounding jitted layer, which
replaces the reference's hand-written CUDA/MSL/WGSL kernels.

All norms accumulate in float32 and cast back to the input dtype, matching
the reference's F32-internal kernel semantics.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(x, weight, eps: float = 1e-6):
    """weight * x / rms(x). Weight may already include the (1+w) residual
    offset (applied at load time, ref: config.rs load_rms_norm_weight)."""
    dt = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    y = xf * jax.lax.rsqrt(var + eps)
    return (y * weight.astype(jnp.float32)).astype(dt)


def add_rms_norm(x, residual, weight, eps: float = 1e-6):
    """Fused residual-add + RMS norm: returns (normed(x+residual), x+residual).
    (ref: backends/mod.rs add_rms_norm)"""
    s = x + residual
    return rms_norm(s, weight, eps), s


def rms_norm_gated(x, gate, weight, eps: float = 1e-6, activation: str = "silu"):
    """Gated RMS norm used by GatedDeltaNet: rms_norm(x) * act(gate).
    (ref: backends/mod.rs rms_norm_gated; qwen3_5/linear_attention.rs)"""
    y = rms_norm(x, weight, eps)
    gf = gate.astype(jnp.float32)
    if activation == "silu":
        g = gf * jax.nn.sigmoid(gf)
    elif activation == "sigmoid":
        g = jax.nn.sigmoid(gf)
    else:
        raise ValueError(f"unknown gate activation {activation}")
    return (y.astype(jnp.float32) * g).astype(x.dtype)


def rms_norm_channel(x, weight, eps: float = 1e-6, axis: int = 1):
    """RMS norm over a channel axis that is not the last one (streaming VAE
    conv stacks normalize over channels of [B, C, T] tensors).
    (ref: backends/mod.rs rms_norm_channel)"""
    dt = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=axis, keepdims=True)
    y = xf * jax.lax.rsqrt(var + eps)
    wshape = [1] * x.ndim
    wshape[axis] = x.shape[axis]
    return (y * weight.astype(jnp.float32).reshape(wshape)).astype(dt)


def layer_norm(x, weight, bias=None, eps: float = 1e-5):
    """Standard layer norm over the last axis (ref: backends/mod.rs layer_norm)."""
    dt = x.dtype
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=-1, keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    y = y * weight.astype(jnp.float32)
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    return y.astype(dt)


def group_norm(x, weight, bias, num_groups: int, eps: float = 1e-5):
    """GroupNorm over [B, C, *spatial] (ref: backends/mod.rs group_norm)."""
    dt = x.dtype
    b, c = x.shape[0], x.shape[1]
    spatial = x.shape[2:]
    xf = x.astype(jnp.float32).reshape(b, num_groups, c // num_groups, -1)
    mean = jnp.mean(xf, axis=(2, 3), keepdims=True)
    var = jnp.var(xf, axis=(2, 3), keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    y = y.reshape(b, c, *spatial)
    wshape = [1, c] + [1] * len(spatial)
    y = y * weight.astype(jnp.float32).reshape(wshape)
    if bias is not None:
        y = y + bias.astype(jnp.float32).reshape(wshape)
    return y.astype(dt)


def load_rms_norm_weight(weight, residual: bool):
    """Apply the residual (1+w) pattern at load time in f32
    (ref: models/common/config.rs load_rms_norm_weight)."""
    if not residual:
        return weight
    return (weight.astype(jnp.float32) + 1.0).astype(weight.dtype)
