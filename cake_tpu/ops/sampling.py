"""On-device token sampling.

The reference keeps sampling on-GPU so only 4 bytes/token cross the bus:
Gumbel-softmax sampling (ref: text_model.rs create_logits_processor) and a
scatter-based sign-aware repeat penalty (ref: text_model.rs
apply_repeat_penalty_gpu). Here everything — penalty, temperature, top-k,
top-p, gumbel argmax — runs inside the jitted decode step, and only the
sampled token id leaves the TPU.
"""
from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class SamplingConfig:
    """Static sampling parameters — one compiled decode step per config
    (matches ref Sampling enum: ArgMax / GumbelSoftmax / TopK / TopP /
    TopKThenTopP)."""
    temperature: float = 0.0
    top_k: int | None = None
    top_p: float | None = None
    repeat_penalty: float = 1.0
    repeat_last_n: int = 64


def apply_repeat_penalty(logits, recent_tokens, penalty: float):
    """Sign-aware repeat penalty on device.

    logits: [V] (unbatched — the scatter is along the vocab axis);
    recent_tokens: [N] int32 with -1 padding (dropped by the scatter).
    logit >= 0 -> logit/penalty, logit < 0 -> logit*penalty
    (ref: text_model.rs apply_repeat_penalty_gpu).
    """
    if logits.ndim != 1:
        raise ValueError("apply_repeat_penalty expects unbatched [V] logits")
    # -1 padding would wrap to the last vocab entry; remap to an out-of-bounds
    # positive index so mode="drop" discards it.
    idx = jnp.where(recent_tokens < 0, logits.shape[-1], recent_tokens)
    flagged = jnp.zeros(logits.shape, jnp.bool_).at[idx].set(True, mode="drop")
    penalized = jnp.where(logits >= 0, logits / penalty, logits * penalty)
    return jnp.where(flagged, penalized, logits)


def _gumbel(rng, shape):
    return jax.random.gumbel(rng, shape, dtype=jnp.float32)


def sample_argmax(logits):
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


def sample_gumbel(logits, rng, temperature: float):
    """Gumbel-max sampling == categorical sampling, fully on device."""
    z = logits.astype(jnp.float32) / temperature + _gumbel(rng, logits.shape)
    return jnp.argmax(z, axis=-1).astype(jnp.int32)


def sample_top_k(logits, rng, k: int, temperature: float):
    vals, idx = jax.lax.top_k(logits.astype(jnp.float32), k)
    z = vals / temperature + _gumbel(rng, vals.shape)
    choice = jnp.argmax(z, axis=-1)
    return jnp.take_along_axis(idx, choice[..., None], axis=-1)[..., 0].astype(jnp.int32)


def _top_p_mask(sorted_probs, p: float):
    """Keep the smallest prefix of (descending) sorted probs whose mass >= p.
    A token is kept if the cumulative mass *before* it is < p."""
    cum = jnp.cumsum(sorted_probs, axis=-1)
    prev = cum - sorted_probs
    return prev < p


def sample_top_p(logits, rng, p: float, temperature: float):
    lf = logits.astype(jnp.float32) / temperature
    order = jnp.argsort(lf, axis=-1)[..., ::-1]          # one O(V log V) sort
    sorted_logits = jnp.take_along_axis(lf, order, axis=-1)
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    keep = _top_p_mask(probs, p)
    masked = jnp.where(keep, sorted_logits, -jnp.inf)
    z = masked + _gumbel(rng, masked.shape)
    choice = jnp.argmax(z, axis=-1)
    return jnp.take_along_axis(order, choice[..., None], axis=-1)[..., 0].astype(jnp.int32)


def sample_top_k_top_p(logits, rng, k: int, p: float, temperature: float):
    vals, idx = jax.lax.top_k(logits.astype(jnp.float32), k)
    vals = vals / temperature
    probs = jax.nn.softmax(vals, axis=-1)
    keep = _top_p_mask(probs, p)
    masked = jnp.where(keep, vals, -jnp.inf)
    z = masked + _gumbel(rng, masked.shape)
    choice = jnp.argmax(z, axis=-1)
    return jnp.take_along_axis(idx, choice[..., None], axis=-1)[..., 0].astype(jnp.int32)


def sample(logits, rng, cfg: SamplingConfig, recent_tokens=None):
    """Dispatch on the static SamplingConfig (ref: create_logits_processor).

    logits: [V] ([B, V] allowed only when repeat_penalty is off — the
    penalty scatter is vocab-axis only). recent_tokens: [N] int32 (-1 padded).
    """
    if cfg.repeat_penalty != 1.0 and recent_tokens is not None:
        logits = apply_repeat_penalty(logits, recent_tokens, cfg.repeat_penalty)
    if cfg.temperature <= 0.0:
        return sample_argmax(logits)
    if cfg.top_k is None and cfg.top_p is None:
        return sample_gumbel(logits, rng, cfg.temperature)
    if cfg.top_k is not None and cfg.top_p is None:
        return sample_top_k(logits, rng, cfg.top_k, cfg.temperature)
    if cfg.top_k is None and cfg.top_p is not None:
        return sample_top_p(logits, rng, cfg.top_p, cfg.temperature)
    return sample_top_k_top_p(logits, rng, cfg.top_k, cfg.top_p, cfg.temperature)


def push_recent_token(recent_tokens, token):
    """Shift a new token into the device-resident recent-token ring
    (drives the repeat penalty without host round-trips)."""
    return jnp.concatenate([recent_tokens[1:], token.reshape(1)])
