"""On-device token sampling.

The reference keeps sampling on-GPU so only 4 bytes/token cross the bus:
Gumbel-softmax sampling (ref: text_model.rs create_logits_processor) and a
scatter-based sign-aware repeat penalty (ref: text_model.rs
apply_repeat_penalty_gpu). Here everything — penalty, temperature, top-k,
top-p, gumbel argmax — runs inside the jitted decode step, and only the
sampled token id leaves the TPU.
"""
from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class SamplingConfig:
    """Static sampling parameters — one compiled decode step per config
    (matches ref Sampling enum: ArgMax / GumbelSoftmax / TopK / TopP /
    TopKThenTopP)."""
    temperature: float = 0.0
    top_k: int | None = None
    top_p: float | None = None
    repeat_penalty: float = 1.0
    repeat_last_n: int = 64


def apply_repeat_penalty(logits, recent_tokens, penalty: float):
    """Sign-aware repeat penalty on device.

    logits: [V] (unbatched — the scatter is along the vocab axis);
    recent_tokens: [N] int32 with -1 padding (dropped by the scatter).
    logit >= 0 -> logit/penalty, logit < 0 -> logit*penalty
    (ref: text_model.rs apply_repeat_penalty_gpu).
    """
    if logits.ndim != 1:
        raise ValueError("apply_repeat_penalty expects unbatched [V] logits")
    # -1 padding would wrap to the last vocab entry; remap to an out-of-bounds
    # positive index so mode="drop" discards it.
    idx = jnp.where(recent_tokens < 0, logits.shape[-1], recent_tokens)
    flagged = jnp.zeros(logits.shape, jnp.bool_).at[idx].set(True, mode="drop")
    penalized = jnp.where(logits >= 0, logits / penalty, logits * penalty)
    return jnp.where(flagged, penalized, logits)


def _gumbel(rng, shape):
    return jax.random.gumbel(rng, shape, dtype=jnp.float32)


def sample_argmax(logits):
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


def sample_gumbel(logits, rng, temperature: float):
    """Gumbel-max sampling == categorical sampling, fully on device."""
    z = logits.astype(jnp.float32) / temperature + _gumbel(rng, logits.shape)
    return jnp.argmax(z, axis=-1).astype(jnp.int32)


def sample_top_k(logits, rng, k: int, temperature: float):
    vals, idx = jax.lax.top_k(logits.astype(jnp.float32), k)
    z = vals / temperature + _gumbel(rng, vals.shape)
    choice = jnp.argmax(z, axis=-1)
    return jnp.take_along_axis(idx, choice[..., None], axis=-1)[..., 0].astype(jnp.int32)


def _top_p_mask(sorted_probs, p: float):
    """Keep the smallest prefix of (descending) sorted probs whose mass >= p.
    A token is kept if the cumulative mass *before* it is < p."""
    cum = jnp.cumsum(sorted_probs, axis=-1)
    prev = cum - sorted_probs
    return prev < p


def sample_top_p(logits, rng, p: float, temperature: float):
    lf = logits.astype(jnp.float32) / temperature
    order = jnp.argsort(lf, axis=-1)[..., ::-1]          # one O(V log V) sort
    sorted_logits = jnp.take_along_axis(lf, order, axis=-1)
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    keep = _top_p_mask(probs, p)
    masked = jnp.where(keep, sorted_logits, -jnp.inf)
    z = masked + _gumbel(rng, masked.shape)
    choice = jnp.argmax(z, axis=-1)
    return jnp.take_along_axis(order, choice[..., None], axis=-1)[..., 0].astype(jnp.int32)


def sample_top_k_top_p(logits, rng, k: int, p: float, temperature: float):
    vals, idx = jax.lax.top_k(logits.astype(jnp.float32), k)
    vals = vals / temperature
    probs = jax.nn.softmax(vals, axis=-1)
    keep = _top_p_mask(probs, p)
    masked = jnp.where(keep, vals, -jnp.inf)
    z = masked + _gumbel(rng, masked.shape)
    choice = jnp.argmax(z, axis=-1)
    return jnp.take_along_axis(idx, choice[..., None], axis=-1)[..., 0].astype(jnp.int32)


def sample(logits, rng, cfg: SamplingConfig, recent_tokens=None):
    """Dispatch on the static SamplingConfig (ref: create_logits_processor).

    logits: [V] ([B, V] allowed only when repeat_penalty is off — the
    penalty scatter is vocab-axis only). recent_tokens: [N] int32 (-1 padded).
    """
    if cfg.repeat_penalty != 1.0 and recent_tokens is not None:
        logits = apply_repeat_penalty(logits, recent_tokens, cfg.repeat_penalty)
    if cfg.temperature <= 0.0:
        return sample_argmax(logits)
    if cfg.top_k is None and cfg.top_p is None:
        return sample_gumbel(logits, rng, cfg.temperature)
    if cfg.top_k is not None and cfg.top_p is None:
        return sample_top_k(logits, rng, cfg.top_k, cfg.temperature)
    if cfg.top_k is None and cfg.top_p is not None:
        return sample_top_p(logits, rng, cfg.top_p, cfg.temperature)
    return sample_top_k_top_p(logits, rng, cfg.top_k, cfg.top_p, cfg.temperature)


def sample_traced(logits, rng, temperature, top_k, top_p, repeat_penalty,
                  recent_tokens):
    """Fully-traced sampling: every parameter is a runtime value, so ONE
    compiled program serves any mix of per-request configs — the batched
    continuous-batching decode step cannot afford a static SamplingConfig
    (each slot would multiply the executable count by the whole grid).

    logits: [V]; temperature/top_p/repeat_penalty: traced f32 scalars;
    top_k: traced int32 (>= V disables); recent_tokens: [N] int32, -1 padded.
    Disabled values: temperature <= 0 -> argmax, top_p >= 1.0 -> off,
    repeat_penalty == 1.0 -> identity (naturally, via the arithmetic).

    Equivalence to the static `sample` dispatch: temperature <= 0 matches
    sample_argmax after the same penalty (argsort of the negated logits is
    stable, so ties break to the lowest id exactly like jnp.argmax); the
    stochastic paths draw gumbel noise over the full sorted vocab instead
    of the top-k prefix, so they match in distribution, not per-key.
    """
    v = logits.shape[-1]
    lf = logits.astype(jnp.float32)
    # sign-aware repeat penalty with a traced strength (identity at 1.0)
    idx = jnp.where(recent_tokens < 0, v, recent_tokens)
    flagged = jnp.zeros((v,), jnp.bool_).at[idx].set(True, mode="drop")
    penalized = jnp.where(lf >= 0, lf / repeat_penalty, lf * repeat_penalty)
    lf = jnp.where(flagged, penalized, lf)
    # one descending sort serves argmax (rank 0), top-k (rank mask) and
    # top-p (cumulative-mass mask) — same O(V log V) the static top-p pays
    scaled = lf / jnp.maximum(temperature, 1e-6)
    order = jnp.argsort(-scaled)                       # stable: ties -> low id
    sorted_logits = scaled[order]
    rank = jnp.arange(v, dtype=jnp.int32)
    # top-p mass is measured on the top-k-truncated RENORMALIZED
    # distribution, matching sample_top_k_top_p's softmax-within-top-k
    # (with top_k >= V the where is identity, so pure top-p matches too)
    probs = jax.nn.softmax(jnp.where(rank < top_k, sorted_logits, -jnp.inf))
    prev_mass = jnp.cumsum(probs) - probs
    keep = (rank < top_k) & (prev_mass < top_p)
    keep = keep.at[0].set(True)                        # never mask every token
    z = jnp.where(keep, sorted_logits, -jnp.inf) + _gumbel(rng, (v,))
    choice = order[jnp.argmax(z)]
    return jnp.where(temperature > 0.0, choice, order[0]).astype(jnp.int32)


def config_has_filters(scfg: "SamplingConfig") -> bool:
    """True when `scfg` actually filters the vocabulary (top-k or
    top-p enabled) — the host-side gate for the verify programs' static
    `use_filters` escape hatch. Greedy and pure-temperature configs
    return False: their target distribution needs no sort."""
    return scfg.top_k is not None or (
        scfg.top_p is not None and scfg.top_p < 1.0)


def push_recent_token(recent_tokens, token):
    """Shift a new token into the device-resident recent-token ring
    (drives the repeat penalty without host round-trips)."""
    return jnp.concatenate([recent_tokens[1:], token.reshape(1)])


# -- speculative decoding: traced target distribution + acceptance rule ------


def filtered_probs(logits, temperature, top_k, top_p, repeat_penalty,
                   recent_tokens, use_filters: bool = True):
    """The target distribution p the sampled decode path draws from, as an
    explicit [V] probability vector in VOCAB order — the quantity the
    speculative accept/reject rule needs (sample_traced only ever needs the
    argmax of the gumbel-perturbed logits, so it never materializes p).

    Same traced pipeline as sample_traced: sign-aware repeat penalty,
    temperature, one descending sort serving the top-k rank mask and the
    top-p cumulative-mass mask measured on the top-k-renormalized
    distribution. temperature <= 0 degenerates to (almost) a point mass at
    the penalized argmax — ties split evenly, and downstream greedy
    consumers take jnp.argmax(p), which breaks ties to the lowest id
    exactly like sample_argmax.

    `use_filters` is a STATIC escape hatch for callers that know top_k
    and top_p are disabled for the whole dispatch (greedy and pure-
    temperature traffic — the serve engine's common case): the sort that
    serves the rank and cumulative-mass masks is skipped entirely and p
    is the plain penalized/tempered softmax. XLA's CPU sort is slow
    enough that it dominated the batched verify's accept rule; with
    filters disabled the masks are identity, so skipping the sort is
    exact (argmax and softmax are permutation-free)."""
    v = logits.shape[-1]
    lf = logits.astype(jnp.float32)
    idx = jnp.where(recent_tokens < 0, v, recent_tokens)
    flagged = jnp.zeros((v,), jnp.bool_).at[idx].set(True, mode="drop")
    penalized = jnp.where(lf >= 0, lf / repeat_penalty, lf * repeat_penalty)
    lf = jnp.where(flagged, penalized, lf)
    scaled = lf / jnp.maximum(temperature, 1e-6)
    if not use_filters:
        return jax.nn.softmax(scaled)
    order = jnp.argsort(-scaled)                       # stable: ties -> low id
    sorted_logits = scaled[order]
    rank = jnp.arange(v, dtype=jnp.int32)
    probs = jax.nn.softmax(jnp.where(rank < top_k, sorted_logits, -jnp.inf))
    prev_mass = jnp.cumsum(probs) - probs
    keep = (rank < top_k) & (prev_mass < top_p)
    keep = keep.at[0].set(True)                        # never mask every token
    kept = jnp.where(keep, probs, 0.0)
    kept = kept / jnp.maximum(jnp.sum(kept), 1e-30)
    return jnp.zeros((v,), jnp.float32).at[order].set(kept)


def spec_accept(logits, draft, n_draft, rng, temperature, top_k, top_p,
                repeat_penalty, recent_tokens, use_filters: bool = True):
    """Traced speculative accept/reject loop (Leviathan et al. 2023; Chen
    et al. 2023) for a DETERMINISTIC drafter (point-mass q — the n-gram
    drafter and the greedy draft-model drafter both are).

    logits: [S, V] verify-forward logits, row i = target distribution for
    the token following input i (S >= n_draft + 1); draft: [K] int32
    proposals, entries >= n_draft are padding; rng: consumed key.

    Greedy target (temperature <= 0): accept draft[i] iff it equals the
    penalized argmax — exact prefix match, so the emitted sequence is
    BIT-IDENTICAL to non-speculative greedy decoding. Sampled target: with
    q = delta at draft[i], the rejection rule accepts with probability
    min(1, p(x)/q(x)) = p(x) and on rejection resamples from the residual
    norm(max(0, p - q)) = p with x's mass removed — the marginal
    distribution of each emitted token is exactly p (p(x)*1 +
    (1-p(x)) * p(t)/(1-p(x)) = p(t)), so speculation never changes the
    output distribution, only the number of device steps.

    Returns (n_acc in [0, n_draft], next_token, recent') where next_token
    is the correction (rejection at position n_acc) or the bonus token
    (all n_draft accepted), and recent' has the accepted tokens AND
    next_token pushed — positions later in the same verify step see
    earlier accepted tokens in their repeat-penalty window, matching the
    one-token-at-a-time path.

    The rule is evaluated BATCHED, not as a sequential scan: row i's
    outcome only matters when every earlier draft accepted (acceptance
    is a prefix), so row i's target distribution may be computed under
    the assumption that drafts 0..i-1 were pushed into the penalty
    window — every row's filtered_probs runs in one vmap, the accepted
    prefix length falls out of a cumulative product, and the per-row
    penalty windows are a sliding gather over [recent ; draft]. A
    sequential fori_loop here cost ~1 ms/step on CPU (it serialized k
    sorts and k threefry folds) and dominated the whole batched-verify
    dispatch; the vectorized rule is shape-identical and draws the SAME
    per-row uniforms (fold_in(rng, i)), so outcomes are unchanged.

    `use_filters` (STATIC) mirrors filtered_probs': pass False when the
    caller knows every slot in the dispatch has top-k/top-p disabled and
    the per-row sorts vanish.
    """
    k = draft.shape[0]
    n = recent_tokens.shape[0]
    greedy = temperature <= 0.0
    # per-row penalty windows under the accepted-prefix assumption:
    # win[i] = [recent ; draft][i : i+n] (row i sees drafts 0..i-1)
    big = jnp.concatenate([recent_tokens, draft])
    win = big[jnp.arange(k + 1)[:, None] + jnp.arange(n)[None, :]]
    # S may be as small as n_draft + 1: clamp row gathers like the old
    # traced logits[i] indexing did (rows past S are never accepted)
    row = jnp.minimum(jnp.arange(k + 1), logits.shape[0] - 1)
    probs = jax.vmap(
        lambda lg, w: filtered_probs(lg, temperature, top_k, top_p,
                                     repeat_penalty, w,
                                     use_filters))(logits[row], win)
    idx = jnp.arange(k, dtype=jnp.int32)
    u = jax.vmap(lambda i: jax.random.uniform(jax.random.fold_in(rng, i)))(
        idx)
    p_draft = jnp.take_along_axis(probs[:k], draft[:, None], axis=1)[:, 0]
    ok = jnp.where(greedy, draft == jnp.argmax(probs[:k], axis=1),
                   u < p_draft)
    ok = ok & (idx < n_draft)
    # accepted prefix length: leading run of accepts
    n_acc = jnp.sum(jnp.cumprod(ok.astype(jnp.int32)))
    p = probs[n_acc]
    recent = win[n_acc]
    # rejected at n_acc: resample from the residual (p minus the rejected
    # point mass, renormalized); all accepted: plain sample from p
    rejected = n_acc < n_draft
    d_rej = draft[jnp.clip(n_acc, 0, k - 1)]
    resid = p.at[d_rej].set(jnp.where(rejected, 0.0, p[d_rej]))
    resid = resid / jnp.maximum(jnp.sum(resid), 1e-30)
    nxt = jnp.where(
        greedy, jnp.argmax(p),
        jax.random.categorical(jax.random.fold_in(rng, k),
                               jnp.log(jnp.maximum(resid, 1e-38)))
    ).astype(jnp.int32)
    return n_acc, nxt, push_recent_token(recent, nxt)
