"""Diffusion samplers/schedulers.

  * Flow-matching Euler sampler — FLUX denoise loop and LuxTTS decoder
    (ref: models/flux/flux1_model.rs denoise; luxtts flow-matching Euler)
  * DPM-Solver++(2M) — VibeVoice's 10-step diffusion head
    (ref: models/vibevoice/ddpm.rs DPM-Solver++)
  * Classifier-free guidance combine

All loops are host-side over a jitted model call: step counts are small
(10-50) and static, the model call dominates.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def flux_time_shift(t: np.ndarray, mu: float = 1.15, sigma: float = 1.0):
    """FLUX resolution-dependent timestep shift: exp(mu)/(exp(mu)+(1/t-1)^sigma)."""
    return np.exp(mu) / (np.exp(mu) + (1.0 / t - 1.0) ** sigma)


def flow_matching_schedule(steps: int, shift_mu: float | None = None):
    """Linear t: 1 -> 0 timesteps (steps+1 points), optionally FLUX-shifted."""
    t = np.linspace(1.0, 0.0, steps + 1)
    if shift_mu is not None:
        valid = t > 0
        t = np.where(valid, flux_time_shift(np.clip(t, 1e-5, 1.0), shift_mu), 0.0)
    return t.astype(np.float32)


def flow_matching_euler_step(x, velocity, t_cur: float, t_next: float):
    """x_{t_next} = x + (t_next - t_cur) * v  (velocity parameterization)."""
    return x + (t_next - t_cur) * velocity


def cfg_combine(uncond, cond, scale: float):
    """Classifier-free guidance (ref: vibevoice CFG pos+neg streams)."""
    return uncond + scale * (cond - uncond)


class DpmSolverPP:
    """DPM-Solver++(2M) for epsilon-prediction models over a trained
    discrete schedule (ref: models/vibevoice/ddpm.rs — 10 steps, CFG 1.3).

    alphas_cumprod: full training schedule (e.g. 1000 steps); `timesteps(n)`
    picks n inference steps; `step` consumes model outputs sequentially.
    """

    def __init__(self, alphas_cumprod: np.ndarray,
                 prediction_type: str = "v_prediction"):
        self.alphas_cumprod = np.asarray(alphas_cumprod, np.float64)
        self.T = len(self.alphas_cumprod)
        self.prediction_type = prediction_type
        self.reset()

    @classmethod
    def from_betas(cls, beta_start=0.00085, beta_end=0.012, n=1000,
                   schedule="scaled_linear", **kw):
        if schedule == "scaled_linear":
            betas = np.linspace(beta_start ** 0.5, beta_end ** 0.5, n) ** 2
        elif schedule == "linear":
            betas = np.linspace(beta_start, beta_end, n)
        elif schedule == "squaredcos_cap_v2":
            return cls.from_cosine(n=n, **kw)
        else:
            # a silently-wrong noise schedule produces garbage images with
            # no diagnostic — reject instead
            raise NotImplementedError(f"beta schedule {schedule!r}")
        return cls(np.cumprod(1.0 - betas), **kw)

    @classmethod
    def from_cosine(cls, n=1000, s=0.008, max_beta=0.999, **kw):
        """squaredcos_cap_v2 schedule (VibeVoice's ddpm_beta_schedule
        default 'cosine' — ref: vibevoice/config.rs)."""
        def f(t):
            return np.cos((t / n + s) / (1 + s) * np.pi / 2) ** 2
        t = np.arange(n)
        betas = np.clip(1.0 - f(t + 1) / f(t), 0.0, max_beta)
        return cls(np.cumprod(1.0 - betas), **kw)

    def reset(self):
        self._last_x0 = None
        self._last_lambda = None

    def timesteps(self, steps: int) -> np.ndarray:
        return np.linspace(self.T - 1, 0, steps).round().astype(np.int64)

    def _coeffs(self, t: int):
        # python floats throughout: np.float64 scalars would promote bf16
        # latents to f32 mid-loop (same hazard as the flux euler step)
        a = float(self.alphas_cumprod[t])
        alpha_t = float(a ** 0.5)
        sigma_t = float((1.0 - a) ** 0.5)
        lam = float(np.log(alpha_t) - np.log(sigma_t))
        return alpha_t, sigma_t, lam

    def _to_x0(self, model_out, x, t: int):
        alpha_t, sigma_t, _ = self._coeffs(t)
        if self.prediction_type == "epsilon":
            return (x - sigma_t * model_out) / alpha_t
        if self.prediction_type == "v_prediction":
            return alpha_t * x - sigma_t * model_out
        return model_out  # "sample"

    def step(self, model_out, t: int, t_next: int, x):
        """One DPM-Solver++(2M) update: multistep with the previous x0."""
        x0 = self._to_x0(model_out, x, t)
        alpha_s, sigma_s, lam_s = self._coeffs(t)
        if t_next <= 0:
            out = x0
        else:
            alpha_t, sigma_t, lam_t = self._coeffs(t_next)
            h = lam_t - lam_s
            r = float(np.exp(-h))
            if self._last_x0 is None:
                d = x0
            else:
                h_last = lam_s - self._last_lambda
                r0 = h_last / h if h != 0 else 1.0
                d = (1 + 1 / (2 * r0)) * x0 - (1 / (2 * r0)) * self._last_x0
            out = (sigma_t / sigma_s) * r * x + alpha_t * (1 - r) * d
        self._last_x0 = x0
        self._last_lambda = lam_s
        return out
