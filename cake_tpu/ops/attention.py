"""Scaled-dot-product attention with GQA, causal masking and sliding windows.

TPU-first design notes (vs ref: cake-core/src/models/common/attention.rs):
  * Activations stay in [B, S, H, D] layout end-to-end; GQA is expressed as a
    grouped einsum so no repeat_kv materialization and no transposes — the
    reference's seq_len==1 transpose-avoidance hack is unnecessary under XLA.
  * Masking is position-based: the KV cache carries an absolute-position array
    (-1 = empty slot), so one code path serves prefill, chunked prefill into an
    existing cache, decode, and sliding-window ring buffers. The reference
    instead trims/concats the KV tensors dynamically (cache.rs:163-210), which
    would recompile under XLA's static shapes.
  * Softmax/accumulation in f32 (matches the reference's F32 attention path).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30  # large-negative instead of -inf: keeps softmax NaN-free for all-masked rows


def make_attention_mask(q_positions, kv_positions, window: int | None = None,
                        causal: bool = True):
    """Boolean attend-mask [B, Sq, Skv].

    q_positions:  [B, Sq] absolute positions of the queries.
    kv_positions: [B, Skv] absolute positions in the KV cache, -1 for empty.
    window: sliding-window size W — key visible iff q_pos - W < k_pos.
    """
    q = q_positions[:, :, None]
    k = kv_positions[:, None, :]
    mask = k >= 0
    if causal:
        mask &= k <= q
    if window is not None:
        mask &= k > q - window
    return mask


def multi_head_attention(q, k, v, mask=None, scale: float | None = None):
    """Grouped-query attention.

    q: [B, Sq, Hq, D], k/v: [B, Skv, Hkv, D] with Hq a multiple of Hkv.
    mask: bool [B, Sq, Skv] (True = attend) or None for full attention.
    Returns [B, Sq, Hq, D] in q.dtype.
    """
    b, sq, hq, d = q.shape
    hkv = k.shape[2]
    g = hq // hkv
    if scale is None:
        scale = 1.0 / (d ** 0.5)

    qf = q.reshape(b, sq, hkv, g, d)
    # scores: [B, Hkv, G, Sq, Skv]
    scores = jnp.einsum("bskgd,btkd->bkgst", qf, k,
                        preferred_element_type=jnp.float32) * scale
    if mask is not None:
        scores = jnp.where(mask[:, None, None, :, :], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bkgst,btkd->bskgd", probs.astype(v.dtype), v)
    return out.reshape(b, sq, hq, d).astype(q.dtype)


def causal_sdpa(q, k, v, scale: float | None = None):
    """Plain causal attention for prefill without a cache (B,S,H,D)."""
    b, s = q.shape[0], q.shape[1]
    pos = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32)[None, :], (b, s))
    mask = make_attention_mask(pos, pos)
    return multi_head_attention(q, k, v, mask, scale)
