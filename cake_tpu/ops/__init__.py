"""TPU op/kernel library.

The reference's ComputeBackend trait (~35 methods over CUDA/Metal/Vulkan/
ROCm/CPU — ref: cake-core/src/backends/mod.rs) collapses on TPU into this
flat module of jit-fusable functions plus Pallas kernels for the few ops
where hand-scheduling beats XLA (flash attention for long prefill).
"""
from .activations import (add3, add_scaled, adaln_modulate, exp_mul, gelu,
                          gelu_mul, gelu_tanh, sigmoid, silu, silu_mul,
                          softmax, stable_softplus, sub_mul)
from .attention import (causal_sdpa, make_attention_mask,
                        multi_head_attention)
from .conv import (causal_depthwise_conv1d_update, conv1d, conv2d,
                   conv_transpose1d, depthwise_conv1d, depthwise_conv1d_silu)
from .fp8 import dequant_fp8_blockwise, quant_fp8_blockwise
from .linear import embedding, linear
from .norms import (add_rms_norm, group_norm, layer_norm,
                    load_rms_norm_weight, rms_norm, rms_norm_channel,
                    rms_norm_gated)
from .rope import RopeScaling, apply_rope, inv_frequencies, rope_tables
from .sampling import (SamplingConfig, apply_repeat_penalty,
                       push_recent_token, sample)

__all__ = [n for n in dir() if not n.startswith("_")]
