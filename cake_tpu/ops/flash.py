"""Pallas flash attention for TPU (prefill path).

The reference reaches flash-attention through candle-flash-attn on CUDA
(ref: utils/flash_attn.rs, attention.rs:270-277). On TPU the equivalent is
a Pallas kernel: blockwise Q x K^T with the online-softmax accumulator so
the [S, S] score matrix never leaves VMEM tiles (same algebra as
parallel/ring_attention.py, scheduled on one chip).

Layout: q/k/v in [B, S, H, D] (the framework-wide activation layout); the
kernel grid is (batch*q_heads, q_blocks) with the K loop inside, GQA via
q_head -> kv_head integer division. Causal masking by absolute block
bounds; optional valid_len clamps padded prefill tails.

Dispatched from the serving prefill when the cache is FRESH (pos0 == 0 —
a host-static property, threaded as the `fresh` flag through
forward_layers) and seq_len >= FLASH_MIN_SEQ on TPU. The XLA einsum path
remains the fallback (and the CPU/test path — interpret mode validates the
kernel without hardware). Inference-only: no custom VJP is defined, so the
differentiable training path never dispatches here.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
FLASH_MIN_SEQ = 256
NEG_INF = -1e30


def _flash_kernel(vl_ref, q_ref, k_ref, v_ref, o_ref, *, scale, block_k,
                  kv_len, causal):
    """One (batch*head, q_block) program: loop K blocks with online softmax.

    vl_ref: (1, 1) SMEM valid-length scalar (dynamic — padded prefill);
    q_ref: [block_q, D]; k_ref/v_ref: [kv_len, D]; o_ref: [block_q, D].
    """
    block_q, d = q_ref.shape
    qi = pl.program_id(1)
    q_start = qi * block_q

    q = q_ref[:].astype(jnp.float32) * scale
    acc = jnp.zeros((block_q, d), jnp.float32)
    m = jnp.full((block_q,), -jnp.inf, jnp.float32)
    l = jnp.zeros((block_q,), jnp.float32)

    q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    limit = vl_ref[0, 0]

    def body(ki, carry):
        acc, m, l = carry
        k_start = ki * block_k
        k_blk = k_ref[pl.ds(k_start, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(k_start, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)
        k_pos = k_start + jax.lax.broadcasted_iota(jnp.int32,
                                                   (block_q, block_k), 1)
        mask = k_pos < limit
        if causal:
            mask &= k_pos <= q_pos
        s = jnp.where(mask, s, NEG_INF)

        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        acc = acc * alpha[:, None] + jnp.dot(p, v_blk,
                                             preferred_element_type=jnp.float32)
        l = l * alpha + jnp.sum(p, axis=-1)
        return acc, m_new, l

    if causal:
        # skip K blocks entirely above the causal diagonal
        n_k = (q_start + block_q + block_k - 1) // block_k
    else:
        n_k = kv_len // block_k
    acc, m, l = jax.lax.fori_loop(0, n_k, body, (acc, m, l))
    o_ref[:] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


def flash_attention(q, k, v, scale: float | None = None, causal: bool = True,
                    valid_len=None, block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K, interpret: bool = False):
    """q: [B, S, Hq, D]; k/v: [B, S, Hkv, D] (Hq multiple of Hkv).

    Returns [B, S, Hq, D]. S must be a multiple of block sizes (the caller
    pads — bucketed prefill already guarantees power-of-two lengths).
    valid_len: int or traced scalar bounding valid keys (padded prefill
    tails); None means all S keys are valid.
    """
    b, s, hq, d = q.shape
    hkv = k.shape[2]
    g = hq // hkv
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    assert s % block_q == 0 and s % block_k == 0, (s, block_q, block_k)

    # [B, S, H, D] -> [B*H, S, D] with GQA expansion folded into indexing
    qt = q.transpose(0, 2, 1, 3).reshape(b * hq, s, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * hkv, s, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * hkv, s, d)

    vl = jnp.asarray(s if valid_len is None else valid_len,
                     jnp.int32).reshape(1, 1)
    kernel = functools.partial(_flash_kernel, scale=scale, block_k=block_k,
                               kv_len=s, causal=causal)
    out = pl.pallas_call(
        kernel,
        grid=(b * hq, s // block_q),
        in_specs=[
            pl.BlockSpec((1, 1), lambda h, i: (0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((None, block_q, d), lambda h, i: (h, i, 0)),
            pl.BlockSpec((None, s, d), lambda h, i: (h // g, 0, 0)),
            pl.BlockSpec((None, s, d), lambda h, i: (h // g, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, d), lambda h, i: (h, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * hq, s, d), q.dtype),
        interpret=interpret,
    )(vl, qt, kt, vt)
    return out.reshape(b, hq, s, d).transpose(0, 2, 1, 3)


def flash_enabled() -> bool:
    """Flash prefill opt-in: on for TPU backends unless CAKE_TPU_FLASH=0."""
    if os.environ.get("CAKE_TPU_FLASH") == "0":
        return False
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False
