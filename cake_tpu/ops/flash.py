"""Pallas flash attention for TPU (prefill path).

The reference reaches flash-attention through candle-flash-attn on CUDA
(ref: utils/flash_attn.rs, attention.rs:270-277). On TPU the equivalent is
a Pallas kernel: blockwise Q x K^T with the online-softmax accumulator so
the [S, S] score matrix never leaves VMEM tiles (same algebra as
parallel/ring_attention.py, scheduled on one chip).

Layout: q/k/v in [B, S, H, D] (the framework-wide activation layout); the
kernel grid is (batch*q_heads, q_blocks) with the K loop inside, GQA via
q_head -> kv_head integer division. Causal masking by absolute block
bounds; optional valid_len clamps padded prefill tails.

Dispatched from the serving prefill via the host-static `flash_mode`
threaded through forward_layers: "fresh" (pos0 == 0; SWA layers included
via the kernel's window mask) and "append" (continued prefill — the chunk
is scattered into the cache first, then the kernel runs over the unwrapped
buffer with a q_offset scalar), for seq_len >= FLASH_MIN_SEQ on TPU. The
XLA einsum path remains the fallback (and the CPU/test path — interpret
mode validates the kernel without hardware). Inference-only: no custom VJP
is defined, so the differentiable training path never dispatches here.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
FLASH_MIN_SEQ = 256
NEG_INF = -1e30


def _flash_kernel(vl_ref, off_ref, q_ref, k_ref, v_ref, o_ref, *, scale,
                  block_k, kv_len, causal, window):
    """One (batch*head, q_block) program: loop K blocks with online softmax.

    vl_ref:  (1, 1) SMEM scalar — absolute key-position limit (valid keys
             occupy positions [0, limit); padded prefill tails excluded).
    off_ref: (1, 1) SMEM scalar — absolute position of query row 0
             (continued prefill appends at pos0 > 0; keys' positions are
             their buffer indices, valid because append mode requires an
             unwrapped cache).
    q_ref: [block_q, D]; k_ref/v_ref: [kv_len, D]; o_ref: [block_q, D].
    window: sliding-window size (None = full attention) — key visible iff
             q_pos - window < k_pos.
    """
    block_q, d = q_ref.shape
    qi = pl.program_id(1)
    q_start = qi * block_q
    off = off_ref[0, 0]

    q = q_ref[:].astype(jnp.float32) * scale
    acc = jnp.zeros((block_q, d), jnp.float32)
    m = jnp.full((block_q,), -jnp.inf, jnp.float32)
    l = jnp.zeros((block_q,), jnp.float32)

    q_pos = off + q_start + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    limit = vl_ref[0, 0]

    def body(ki, carry):
        acc, m, l = carry
        k_start = ki * block_k
        k_blk = k_ref[pl.ds(k_start, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(k_start, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)
        k_pos = k_start + jax.lax.broadcasted_iota(jnp.int32,
                                                   (block_q, block_k), 1)
        mask = k_pos < limit
        if causal:
            mask &= k_pos <= q_pos
        if window is not None:
            mask &= k_pos > q_pos - window
        s = jnp.where(mask, s, NEG_INF)

        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        acc = acc * alpha[:, None] + jnp.dot(p, v_blk,
                                             preferred_element_type=jnp.float32)
        l = l * alpha + jnp.sum(p, axis=-1)
        return acc, m_new, l

    n_k_full = kv_len // block_k
    if causal:
        # skip K blocks entirely above the causal diagonal (traced bound:
        # off is dynamic in append mode)
        n_k = jnp.minimum(
            (off + q_start + block_q + block_k - 1) // block_k, n_k_full)
    else:
        n_k = n_k_full
    if window is not None:
        # skip K blocks entirely below the window
        lo = jnp.maximum((off + q_start - window + 1) // block_k, 0)
    else:
        lo = 0
    acc, m, l = jax.lax.fori_loop(lo, n_k, body, (acc, m, l))
    o_ref[:] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


def _pad_seq(x, mult: int):
    s = x.shape[1]
    pad = (-s) % mult
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0), (0, 0)))
    return x


def flash_attention(q, k, v, scale: float | None = None, causal: bool = True,
                    valid_len=None, q_offset=None, window: int | None = None,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K, interpret: bool = False):
    """q: [B, Sq, Hq, D]; k/v: [B, Skv, Hkv, D] (Hq multiple of Hkv).

    Returns [B, Sq, Hq, D]. Non-multiple-of-block lengths are padded here
    (pad keys are masked via the limit, pad query rows sliced off).
    valid_len: int or traced scalar — number of valid NEW keys; the
       absolute limit becomes q_offset + valid_len.
    q_offset: absolute position of query row 0 (continued prefill over an
       unwrapped cache buffer whose index == position); None/0 = fresh.
    window: sliding-window size for SWA layers.
    """
    b, s, hq, d = q.shape
    skv = k.shape[1]
    hkv = k.shape[2]
    g = hq // hkv
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    # blocks stay multiples of 16 (bf16 TPU tile); _pad_seq covers the rest
    block_q = min(block_q, max(-(-s // 16) * 16, 16))
    block_k = min(block_k, max(-(-skv // 16) * 16, 16))

    off = jnp.asarray(0 if q_offset is None else q_offset, jnp.int32)
    vl = off + jnp.asarray(s if valid_len is None else valid_len, jnp.int32)

    q = _pad_seq(q, block_q)
    k = _pad_seq(k, block_k)
    v = _pad_seq(v, block_k)
    s_p, skv_p = q.shape[1], k.shape[1]

    # [B, S, H, D] -> [B*H, S, D] with GQA expansion folded into indexing
    qt = q.transpose(0, 2, 1, 3).reshape(b * hq, s_p, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * hkv, skv_p, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * hkv, skv_p, d)

    kernel = functools.partial(_flash_kernel, scale=scale, block_k=block_k,
                               kv_len=skv_p, causal=causal, window=window)
    out = pl.pallas_call(
        kernel,
        grid=(b * hq, s_p // block_q),
        in_specs=[
            pl.BlockSpec((1, 1), lambda h, i: (0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1), lambda h, i: (0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((None, block_q, d), lambda h, i: (h, i, 0)),
            pl.BlockSpec((None, skv_p, d), lambda h, i: (h // g, 0, 0)),
            pl.BlockSpec((None, skv_p, d), lambda h, i: (h // g, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, d), lambda h, i: (h, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * hq, s_p, d), q.dtype),
        interpret=interpret,
    )(vl.reshape(1, 1), off.reshape(1, 1), qt, kt, vt)
    out = out.reshape(b, hq, s_p, d).transpose(0, 2, 1, 3)
    return out[:, :s]


def flash_enabled() -> bool:
    """Flash prefill opt-in: on for TPU backends unless CAKE_TPU_FLASH=0."""
    from .. import knobs
    if not knobs.get("CAKE_TPU_FLASH"):
        return False
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False
