"""Fused elementwise ops.

The reference backs these with hand-written CUDA/MSL/WGSL kernels
(ref: cake-core/src/backends/mod.rs silu_mul / stable_softplus / add3 /
exp_mul / sub_mul / add_scaled / adaln_modulate; backends/cuda/ops.cu).
On TPU they are jnp expressions fused by XLA into the surrounding jit —
keeping them as named functions preserves the reference's op inventory
and gives Pallas a single place to swap in custom kernels if profiling
ever shows XLA fusion is insufficient.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def silu(x):
    return jax.nn.silu(x)


def gelu(x):
    """Exact GELU (erf form)."""
    return jax.nn.gelu(x, approximate=False)


def gelu_tanh(x):
    """Approximate (tanh) GELU — Gemma3 MLP (ref: config.rs use_gelu_mlp)."""
    return jax.nn.gelu(x, approximate=True)


def sigmoid(x):
    return jax.nn.sigmoid(x)


def silu_mul(gate, up):
    """silu(gate) * up — the fused SwiGLU elementwise
    (ref: backends/mod.rs silu_mul, models/common/mlp.rs)."""
    return jax.nn.silu(gate) * up


def gelu_mul(gate, up, approximate: bool = True):
    """gelu(gate) * up — Gemma3-style GEGLU."""
    return jax.nn.gelu(gate, approximate=approximate) * up


def stable_softplus(x):
    """log(1+exp(x)) without overflow (ref: backends/mod.rs stable_softplus)."""
    return jax.nn.softplus(x)


def add3(a, b, c):
    """(ref: backends/mod.rs add3)"""
    return a + b + c


def exp_mul(x, y):
    """exp(x) * y (ref: backends/mod.rs exp_mul)"""
    return jnp.exp(x) * y


def sub_mul(a, b, c):
    """(a - b) * c (ref: backends/mod.rs sub_mul)"""
    return (a - b) * c


def add_scaled(a, b, scale):
    """a + b * scale (ref: backends/mod.rs add_scaled)"""
    return a + b * scale


def adaln_modulate(x, shift, scale):
    """Adaptive layer-norm modulation used by DiT diffusion heads:
    x * (1 + scale) + shift (ref: backends/mod.rs adaln_modulate,
    models/vibevoice/ddpm.rs)."""
    return x * (1.0 + scale) + shift


def softmax(x, axis: int = -1):
    """Softmax with f32 accumulation (ref: backends/mod.rs softmax)."""
    dt = x.dtype
    return jax.nn.softmax(x.astype(jnp.float32), axis=axis).astype(dt)
