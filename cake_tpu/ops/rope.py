"""Rotary position embeddings.

TPU-native RoPE: cos/sin tables are precomputed once per model in f32
(ref: models/common/cache.rs:49-99 — incl. llama3 frequency scaling) and
gathered by position index inside the jitted step, so decode (pos is a
traced scalar) and bucketed prefill reuse the same compiled code.

Layout note: the reference applies RoPE on [B, H, S, D] after transpose
(ref: attention.rs apply_rotary_emb). We keep activations in [B, S, H, D]
throughout — on TPU the einsum-based attention never needs the transpose.
"""
from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class RopeScaling:
    """llama3-style frequency scaling (ref: config.rs RopeScaling)."""
    factor: float = 8.0
    high_freq_factor: float = 4.0
    low_freq_factor: float = 1.0
    original_max_position_embeddings: int = 8192
    rope_type: str | None = None


def inv_frequencies(rotary_dim: int, theta: float,
                    scaling: RopeScaling | None = None) -> np.ndarray:
    """Per-pair inverse frequencies, with optional llama3 smoothing
    (ref: cache.rs:49-80)."""
    inv = 1.0 / (theta ** (np.arange(0, rotary_dim, 2, dtype=np.float64) / rotary_dim))
    if scaling is None or not scaling.factor or scaling.factor == 1.0:
        return inv.astype(np.float64)
    if scaling.rope_type == "linear":
        # uniform position interpolation (HF "linear"; Gemma3 global layers)
        inv = inv / scaling.factor
    elif scaling.rope_type == "default":
        pass                        # HF "default" ignores the factor
    elif scaling.rope_type in (None, "llama3"):
        low_wavelen = scaling.original_max_position_embeddings / scaling.low_freq_factor
        high_wavelen = scaling.original_max_position_embeddings / scaling.high_freq_factor
        wavelen = 2.0 * np.pi / inv
        scaled = np.where(wavelen > low_wavelen, inv / scaling.factor, inv)
        smooth = (scaling.original_max_position_embeddings / wavelen
                  - scaling.low_freq_factor) / (scaling.high_freq_factor
                                                - scaling.low_freq_factor)
        mid = (1.0 - smooth) * inv / scaling.factor + smooth * inv
        is_mid = (wavelen <= low_wavelen) & (wavelen >= high_wavelen)
        inv = np.where(is_mid, mid, scaled)
    else:
        # unimplemented scaling flavors (yarn, dynamic, ...) degrade to
        # unscaled RoPE with a warning — same tolerance posture as the
        # unknown-architecture fallback (config.py ARCH_ADAPTERS)
        import logging
        logging.getLogger(__name__).warning(
            "rope_type %r not implemented; using unscaled RoPE",
            scaling.rope_type)
    return inv.astype(np.float64)


def rope_tables(max_seq_len: int, rotary_dim: int, theta: float,
                scaling: RopeScaling | None = None,
                dtype=jnp.float32):
    """Precompute (cos, sin) of shape [max_seq_len, rotary_dim // 2]."""
    inv = inv_frequencies(rotary_dim, theta, scaling)
    t = np.arange(max_seq_len, dtype=np.float64)
    freqs = np.outer(t, inv)
    return jnp.asarray(np.cos(freqs), dtype=dtype), jnp.asarray(np.sin(freqs), dtype=dtype)


def apply_rope(x, cos, sin, positions, rotary_dim: int | None = None,
               interleaved: bool = False):
    """Apply RoPE to x: [B, S, H, D] with positions: [B, S] or [S] (int32).

    rotary_dim < D applies partial RoPE to the first rotary_dim channels and
    passes the rest through (ref: attention.rs apply_rotary_emb; Phi-4
    partial_rotary_factor 0.25).
    """
    d = x.shape[-1]
    rd = d if rotary_dim is None else rotary_dim
    if positions.ndim == 1:
        positions = positions[None, :]
    c = cos[positions][:, :, None, :].astype(jnp.float32)   # [B, S, 1, rd/2]
    s = sin[positions][:, :, None, :].astype(jnp.float32)

    x_rot, x_pass = x[..., :rd], x[..., rd:]
    xf = x_rot.astype(jnp.float32)
    if interleaved:
        x1 = xf[..., 0::2]
        x2 = xf[..., 1::2]
        o1 = x1 * c - x2 * s
        o2 = x1 * s + x2 * c
        out = jnp.stack([o1, o2], axis=-1).reshape(xf.shape)
    else:
        half = rd // 2
        x1 = xf[..., :half]
        x2 = xf[..., half:]
        o1 = x1 * c - x2 * s
        o2 = x1 * s + x2 * c
        out = jnp.concatenate([o1, o2], axis=-1)
    out = out.astype(x.dtype)
    if rd == d:
        return out
    return jnp.concatenate([out, x_pass], axis=-1)
