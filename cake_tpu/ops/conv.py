"""1D/2D convolution ops for the audio (VibeVoice/LuxTTS) and image (VAE)
stacks, plus the fused depthwise-conv variants used by streaming decoders
and GatedDeltaNet (ref: backends/mod.rs conv1d / conv_transpose1d / conv2d /
depthwise_conv1d_{silu,bias,bias_ctx}).

Layout: channels-first [B, C, T] / [B, C, H, W], matching the reference.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def conv1d(x, weight, bias=None, stride: int = 1, padding: int = 0,
           dilation: int = 1, groups: int = 1):
    """x: [B, Cin, T], weight: [Cout, Cin/groups, K]."""
    y = jax.lax.conv_general_dilated(
        x, weight,
        window_strides=(stride,),
        padding=[(padding, padding)],
        rhs_dilation=(dilation,),
        feature_group_count=groups,
        dimension_numbers=("NCH", "OIH", "NCH"),
    )
    if bias is not None:
        y = y + bias[None, :, None]
    return y


def conv_transpose1d(x, weight, bias=None, stride: int = 1, padding: int = 0):
    """x: [B, Cin, T], weight: [Cin, Cout, K] (torch convention).

    Torch semantics: out_len = (T-1)*stride + K - 2*padding, and torch
    applies the kernel flipped relative to jax.lax.conv_transpose — so flip
    the spatial axis, compute VALID, and crop `padding` from both ends.
    """
    y = jax.lax.conv_transpose(
        x, weight[:, :, ::-1],
        strides=(stride,),
        padding="VALID",
        dimension_numbers=("NCH", "IOH", "NCH"),
    )
    if padding:
        y = y[:, :, padding:y.shape[2] - padding]
    if bias is not None:
        y = y + bias[None, :, None]
    return y


def conv2d(x, weight, bias=None, stride: int = 1, padding: int = 0,
           dilation: int = 1, groups: int = 1):
    """x: [B, Cin, H, W], weight: [Cout, Cin/groups, Kh, Kw]."""
    if isinstance(stride, int):
        stride = (stride, stride)
    if isinstance(padding, int):
        padding = [(padding, padding), (padding, padding)]
    if isinstance(dilation, int):
        dilation = (dilation, dilation)
    y = jax.lax.conv_general_dilated(
        x, weight,
        window_strides=stride,
        padding=padding,
        rhs_dilation=dilation,
        feature_group_count=groups,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )
    if bias is not None:
        y = y + bias[None, :, None, None]
    return y


def depthwise_conv1d(x, weight, bias=None, padding: int = 0):
    """Depthwise conv: x [B, C, T], weight [C, 1, K]."""
    return conv1d(x, weight, bias, padding=padding, groups=x.shape[1])


def depthwise_conv1d_silu(x, weight, bias=None, padding: int = 0):
    """Fused depthwise conv + SiLU (ref: backends/mod.rs depthwise_conv1d_silu;
    used by GDN's short causal conv)."""
    return jax.nn.silu(depthwise_conv1d(x, weight, bias, padding))


def causal_depthwise_conv1d_update(x_t, conv_state, weight, bias=None,
                                   activation: str | None = "silu"):
    """Single-step causal depthwise conv for decode.

    x_t: [B, C] new frame; conv_state: [B, C, K-1] previous frames.
    Returns (y_t [B, C], new_conv_state). This is the streaming form of the
    reference's depthwise_conv1d_bias_ctx (VibeVoice VAE) and the GDN conv
    state update (ref: cache.rs conv states :221-238).
    """
    k = weight.shape[-1]
    window = jnp.concatenate([conv_state, x_t[:, :, None]], axis=-1)  # [B,C,K]
    y = jnp.einsum("bck,ck->bc", window, weight[:, 0, :])
    if bias is not None:
        y = y + bias[None, :]
    if activation == "silu":
        y = jax.nn.silu(y)
    new_state = window[:, :, 1:] if k > 1 else conv_state
    return y, new_state
