"""Central registry of every `CAKE_*` environment knob.

Before this module existed, 27 raw `os.environ` reads in 18 files each
carried their own default and their own parsing quirks, and the knob
tables in docs/ drifted from the code (the serving docs said one default,
the engine shipped another). Now:

  * every knob is declared ONCE here with a type, a default and a
    one-line doc;
  * call sites read through :func:`get` (env is still consulted on every
    call, so tests that monkeypatch `os.environ` keep working — nothing
    is snapshotted at import);
  * `docs/knobs.md` is GENERATED from this registry (`make knobs-doc`,
    `python -m cake_tpu.knobs`), and tests/test_analysis.py pins the file
    to the registry so it cannot drift again;
  * the `knob-registry` lint rule (cake_tpu/analysis) fails the build on
    any raw `os.environ`/`os.getenv` read of a `CAKE_*` name outside this
    module.

Empty-string env values fall back to the default everywhere (the historic
call sites were split between `get(k, d)` and `get(k, d) or d`; the `or`
form is the one that survives `CAKE_X=` in a wrapper script).
"""
from __future__ import annotations

import os
from dataclasses import dataclass

__all__ = ["Knob", "REGISTRY", "get", "get_str", "generate_doc"]


@dataclass(frozen=True)
class Knob:
    name: str
    cast: type              # int | float | str | bool
    default: object
    area: str               # docs/knobs.md section
    doc: str                # one line, imperative — what turning it does


REGISTRY: dict[str, Knob] = {}


def _knob(name: str, cast: type, default, area: str, doc: str) -> None:
    if name in REGISTRY:
        raise ValueError(f"duplicate knob {name}")
    REGISTRY[name] = Knob(name, cast, default, area, doc)


def _parse_bool(raw: str) -> bool:
    return raw.strip().lower() not in ("0", "false", "off", "no")


def get(name: str):
    """Typed value of knob `name`: the parsed env var when set and
    non-empty, else the registered default. Unregistered names are a
    programming error (KeyError), not a silent empty read."""
    kb = REGISTRY[name]
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return kb.default
    if kb.cast is bool:
        return _parse_bool(raw)
    return kb.cast(raw)


def get_str(name: str) -> str:
    """`get` for str knobs where callers want "" (not None) when unset."""
    v = get(name)
    return "" if v is None else str(v)


# -- serve ----------------------------------------------------------------
_knob("CAKE_SERVE_SLOTS", int, 4, "serve",
      "KV slots = max concurrent batched decodes; 0 disables the engine "
      "(API falls back to the locked sequential path)")
_knob("CAKE_MAX_QUEUE", int, 64, "serve",
      "bounded admission queue: requests waiting beyond free slots; "
      "overflow answers HTTP 429 + Retry-After")
_knob("CAKE_SERVE_CTX", int, 4096, "serve",
      "per-slot context (prompt + generation), capped by the model's "
      "max_cache_len; pool HBM scales with slots x ctx")
_knob("CAKE_PREFILL_CHUNK", int, 256, "serve",
      "per-iteration chunked-admission token budget (clamped to a power "
      "of two in [16, ctx]); also the prefix-cache block size")
_knob("CAKE_PREFIX_CACHE_MB", float, 256.0, "serve",
      "device bytes for shared-prefix KV blocks (LRU); 0 disables "
      "prefix reuse")
_knob("CAKE_QUEUE_DEADLINE_S", float, 0.0, "serve",
      "max admission-queue wait before a request is 503ed instead of "
      "admitted for a client that gave up; 0 disables")
_knob("CAKE_DRAIN_TIMEOUT_S", float, 30.0, "serve",
      "graceful-shutdown budget: admission stops (503 + Retry-After) and "
      "active slots get this long to finish before close()")
_knob("CAKE_REQUEST_DEADLINE_S", float, 0.0, "serve",
      "max TOTAL request age (queue + prefill + decode) before an "
      "admitted slot is cancelled with a typed 504; 0 disables")
_knob("CAKE_STEP_WATCHDOG_S", float, 0.0, "serve",
      "wedge watchdog: a device dispatch in flight longer than this "
      "flags the engine wedged in /health (503) without killing it; "
      "0 disables — set it above your worst in-iteration XLA compile")
_knob("CAKE_ENGINE_REBUILDS", int, 3, "serve",
      "slot-pool rebuild-by-replay budget per rolling "
      "CAKE_ENGINE_REBUILD_WINDOW_S; exhausting it puts the engine in "
      "the honest DOWN state (503 + Retry-After, restore loop probing)")
_knob("CAKE_ENGINE_REBUILD_WINDOW_S", float, 300.0, "serve",
      "rolling window over which CAKE_ENGINE_REBUILDS is counted — a "
      "crash storm is a dying device, sparse blips are not")
_knob("CAKE_ENGINE_RESTORE_S", float, 5.0, "serve",
      "DOWN-state probe interval: a trial prefill runs this often until "
      "one succeeds, then the pool is rebuilt and admission reopens")
_knob("CAKE_KV_BLOCKS", int, 0, "serve",
      "paged-KV pool size in physical blocks; > 0 replaces the "
      "contiguous slots x ctx rows with a shared block pool behind "
      "per-slot block tables (refcounted prefix sharing + preemption); "
      "0 keeps the contiguous pool")
_knob("CAKE_KV_BLOCK_TOKENS", int, 16, "serve",
      "tokens per paged-KV block (clamped to a power of two in "
      "[8, CAKE_PREFILL_CHUNK] so chunk boundaries stay block-aligned); "
      "pool HBM = blocks x block-tokens of KV")
_knob("CAKE_PREEMPT_MODE", str, "swap", "serve",
      'paged-pool exhaustion policy: "swap" parks the victim\'s blocks '
      'in host RAM (bit-identical resume, even sampled); "recompute" '
      "drops them and replays prompt+generated at resume (greedy "
      "bit-identical)")
_knob("CAKE_SERVE_FAULT_PLAN", str, None, "serve",
      'deterministic serve-engine fault injection (tests/drills only), '
      'e.g. "raise_on_step=6;kind=device" — see serve/faults.py')

# -- qos (unified admission plane) ----------------------------------------
_knob("CAKE_QOS_WEIGHTS", str, None, "qos",
      'weighted-fair dequeue weights per QoS class, e.g. '
      '"interactive=8,standard=4,batch=1" (the default); weights must '
      "be > 0 — under saturation service converges to the weight ratio "
      "and every class still progresses")
_knob("CAKE_QOS_BOUNDS", str, None, "qos",
      'per-class admission-queue bounds overriding the engine default, '
      'e.g. "batch=128,interactive=32"; overflow answers a class-aware '
      "429 whose Retry-After reflects that class's backlog")
_knob("CAKE_QOS_TENANTS", str, None, "qos",
      'per-tenant quota policies, e.g. "acme:rps=5,burst=10,inflight=4,'
      'max_class=standard;*:rps=20" — token-bucket rate + concurrent '
      "inflight + QoS ceiling, keyed by X-Cake-Tenant or the bearer "
      "key; unconfigured tenants are default-open (typed 429 "
      "tenant_quota when over)")
_knob("CAKE_JOB_WORKERS", int, 1, "qos",
      "max concurrently RUNNING heavy generation jobs (image "
      "diffusion / TTS) under the admission plane; queued jobs drain "
      "weighted-fair behind interactive traffic")
_knob("CAKE_IMAGE_MAX_SIZE", int, 2048, "qos",
      "max image width/height the /v1/images endpoints accept; "
      "out-of-range sizes answer 400 instead of letting one request "
      "OOM the device")
_knob("CAKE_QOS_BATCH_SHED_FRAC", float, 0.8, "qos",
      "router-tier batch shedding threshold as a fraction of the "
      "global in-flight cap: batch-class requests shed 429 at this "
      "fill level so the remaining headroom stays reserved for "
      "interactive traffic; >= 1 disables the early shed")

# -- speculative decoding -------------------------------------------------
_knob("CAKE_SPEC", str, None, "spec",
      'drafter for spec=None paths: "ngram" enables prompt-lookup '
      'speculation; unset/empty/"off" disables')
_knob("CAKE_SPEC_K", int, 6, "spec",
      "per-slot draft window: tokens proposed per verify step, clamped "
      "to [1, 32]; in the serve engine every occupied slot carries its "
      "own window through ONE batched verify dispatch (one executable "
      "per slot-bucket, k static via the draft shape)")
_knob("CAKE_SPEC_NGRAM", int, 3, "spec",
      "n-gram drafter max match window: the prompt-lookup drafter "
      "matches the last [2, this] tokens against the slot's own history "
      "(bigger = more specific matches tried first)")
_knob("CAKE_SPEC_RESERVE", int, 0, "spec",
      "paged-mode speculative frontier-reservation cap, tokens per slot "
      "per verify: draft windows are clamped so at most this much "
      "unwritten frontier is backed by blocks ahead of the dispatch "
      "(rolled back on rejection/preemption); 0 = the full draft window")

# -- fleet (router tier over N serve replicas) ----------------------------
_knob("CAKE_FLEET_PROBE_S", float, 2.0, "fleet",
      "router health-probe interval per replica: each tick GETs /health "
      "and consumes the engine block (down/wedged/draining, queue depth, "
      "kv_pool occupancy) into the membership state machine")
_knob("CAKE_FLEET_EJECT_FAILS", int, 3, "fleet",
      "consecutive transport failures (connect refused/reset/timeout) "
      "that eject a replica from routing")
_knob("CAKE_FLEET_ERR_WINDOW", int, 32, "fleet",
      "rolling per-replica result window the gray-failure detector "
      "computes its error rate and TTFT p95 over")
_knob("CAKE_FLEET_ERR_RATE", float, 0.5, "fleet",
      "gray-failure eject threshold: error rate over the rolling window "
      "(needs >= 8 samples) at or above this ejects the replica")
_knob("CAKE_FLEET_DEGRADED_TTFT_MS", float, 0.0, "fleet",
      "gray-failure eject threshold on rolling TTFT p95 — a slow-but-"
      "alive replica is ejected before clients notice; 0 disables "
      "(same shape as the cluster hop detector's CAKE_HOP_DEGRADED_MS)")
_knob("CAKE_FLEET_EJECT_S", float, 5.0, "fleet",
      "ejection hold before the half-open probe (doubles per consecutive "
      "re-eject, capped at 8x); a half-open replica readmits on one "
      "successful trial request or two consecutive healthy probes")
_knob("CAKE_FLEET_RETRIES", int, 2, "fleet",
      "per-request failover budget: how many ADDITIONAL replicas a "
      "non-streamed (or pre-first-token streamed) request may retry on "
      "after its first attempt fails; exhaustion answers a typed 503")
_knob("CAKE_FLEET_BACKOFF_S", float, 0.05, "fleet",
      "retry backoff base between failover attempts (capped exponential "
      "+/-25% jitter, same scheme as cluster recovery)")
_knob("CAKE_FLEET_HEDGE_MS", float, 0.0, "fleet",
      "tail-hedging threshold for non-streamed requests: no reply after "
      "this long fires a duplicate at the next-best replica and the "
      "first response wins (Dean & Barroso hedged requests); 0 disables")
_knob("CAKE_FLEET_MAX_INFLIGHT", int, 0, "fleet",
      "global router admission bound: in-flight proxied requests at or "
      "past this shed typed 429s AT THE ROUTER before any replica "
      "admits; 0 = auto (sum of per-replica caps)")
_knob("CAKE_FLEET_REPLICA_INFLIGHT", int, 0, "fleet",
      "per-replica in-flight cap; 0 = auto (2x the replica's slot count "
      "from its last health probe, 8 before the first probe lands)")
_knob("CAKE_FLEET_AFFINITY", bool, True, "fleet",
      "prefix-affinity routing (blake2b chain over the rendered prompt, "
      "rendezvous-hashed onto replicas so conversational follow-ups land "
      "on the replica holding their KV blocks); off = round-robin")
_knob("CAKE_FLEET_AFFINITY_BLOCKS", int, 64, "fleet",
      "affinity chain depth cap in 256-byte blocks over the conversation "
      "head (leading system message + first user message) — a cost "
      "backstop against pathological first messages, NOT a spreading "
      "window: it must comfortably cover the system prompt, or every "
      "conversation hashes to the same key and one replica goes hot")
_knob("CAKE_FLEET_ATTEMPT_TIMEOUT_S", float, 0.0, "fleet",
      "DEPRECATED single per-attempt deadline on one replica try "
      "(connect + full response); still honored when set > 0, but the "
      "0.0=forever default is superseded by the split "
      "CAKE_FLEET_CONNECT_TIMEOUT_S / CAKE_FLEET_FIRST_BYTE_TIMEOUT_S "
      "deadlines, which bound the partition-shaped hangs this knob left "
      "unbounded by default")
_knob("CAKE_FLEET_CONNECT_TIMEOUT_S", float, 5.0, "fleet",
      "per-attempt TCP connect deadline on one replica try; an overrun "
      "counts as a transport failure and the request fails over — "
      "bounds the refused/black-holed-SYN partition shapes; 0 disables "
      "(not recommended: that re-opens the unbounded hang)")
_knob("CAKE_FLEET_FIRST_BYTE_TIMEOUT_S", float, 120.0, "fleet",
      "per-attempt first-byte deadline: time from request sent to the "
      "first response byte (headers) on one replica try, covering the "
      "accept-then-never-respond black hole; streamed bodies stay "
      "unbounded after the first byte (the stream-resume plane handles "
      "mid-body breaks); an overrun is a retryable transport failure; "
      "0 disables")
_knob("CAKE_FLEET_DISCOVER_S", float, 0.0, "fleet",
      "periodic UDP re-discovery interval: newly announced `cake serve "
      "--announce` replicas join the registry without a router restart; "
      "0 = discover once at startup only")
_knob("CAKE_FLEET_STREAM_RESUMES", int, 1, "fleet",
      "per-stream self-healing budget: how many times the router may "
      "transparently splice-resume a stream broken AFTER its commit "
      "point (first relayed byte) by re-issuing the buffered partial "
      "content in continuation mode on the affinity next-best replica; "
      "0 restores the client-visible typed error event on every break")
_knob("CAKE_FLEET_RESUME_BUFFER_KB", int, 256, "fleet",
      "per-stream replay-buffer bound (KB of relayed assistant text) "
      "the resume splice is built from; a stream whose content outgrows "
      "the buffer falls back to the typed error event (the resume_token "
      "still lets the client finish via continuation mode)")
_knob("CAKE_FLEET_FAULT_PLAN", str, None, "fleet",
      'deterministic router fault injection (tests/drills only), e.g. '
      '"replica=r1;refuse_after_ops=3" — see fleet/faults.py')
_knob("CAKE_KVSHARE", bool, False, "fleet",
      "fleet-shared KV tier (fleet/kvshare/): replicas export/import "
      "prefix-cache chains as checksummed blobs, the router injects a "
      "peer directory so cache-cold replicas fetch a warm peer's prefix "
      "instead of re-prefilling, and broken/drained streams migrate "
      "their live swap blob to the new owner (bit-exact resume, rng "
      "included); off keeps all KV strictly replica-local")
_knob("CAKE_KVSHARE_FETCH_TIMEOUT_S", float, 2.0, "fleet",
      "deadline on ONE cross-replica KV blob fetch (prefix fetch-"
      "before-recompute, and the router's stream-blob GET/POST legs); "
      "an overrun falls back to honest recompute / continuation-mode "
      "re-prefill — never a client-visible error")
_knob("CAKE_KVSHARE_INVENTORY", int, 32, "fleet",
      "hot chain keys each replica advertises through /health into the "
      "router's peer directory (most-recently-used first); bounds the "
      "directory header the router injects per request, so it must stay "
      "well under the ~8 KB header limit")

# -- telemetry (fleet rollups, SLO objectives) ----------------------------
_knob("CAKE_SLO_TTFT_MS", float, 2000.0, "telemetry",
      "fleet TTFT objective in milliseconds: a request whose serve-side "
      "TTFT lands in a histogram bucket above this counts as BAD in the "
      "burn-rate computation (alongside errored requests)")
_knob("CAKE_SLO_ERR_RATE", float, 0.01, "telemetry",
      "fleet error budget as a bad-request fraction: burn rate = "
      "windowed bad fraction / this, so burn > 1 means the budget is "
      "burning faster than it accrues and burn = 1 exactly spends it")
_knob("CAKE_TELEM_FAST_WINDOW_S", float, 300.0, "telemetry",
      "fast burn-rate window (page-worthy: a high burn here means the "
      "budget dies in hours) — also the window for headroom token rates")
_knob("CAKE_TELEM_SLOW_WINDOW_S", float, 3600.0, "telemetry",
      "slow burn-rate window (ticket-worthy sustained burn); also the "
      "retention window of every telemetry ring, so it bounds how much "
      "history /api/v1/fleet/telemetry can return")
_knob("CAKE_TELEM_RING", int, 4096, "telemetry",
      "hard per-series sample cap backing the fixed-window rings — a "
      "memory bound independent of probe rate x window length")
_knob("CAKE_TELEM_OUTLIER_K", float, 3.0, "telemetry",
      "anomaly threshold: a replica whose TTFT p95 or error rate sits "
      "more than k robust standard deviations (MAD-scaled) from the "
      "fleet median is flagged `outlier` in /fleet — never auto-ejected")
_knob("CAKE_TELEM_OUTLIER_MIN_N", int, 3, "telemetry",
      "minimum live replicas before outlier detection runs (a median "
      "over 2 replicas cannot say which one is wrong)")

# -- autoscale (closed-loop elastic fleet) --------------------------------
_knob("CAKE_SCALE", bool, False, "autoscale",
      "closed-loop autoscaling in the router: each probe/telemetry "
      "cycle the controller (fleet/autoscale.py) decides scale-out / "
      "scale-in / hold and the lifecycle manager executes it; off = "
      "the telemetry plane stays advisory")
_knob("CAKE_SCALE_SPAWN_CMD", str, None, "autoscale",
      'scale-out spawn template, e.g. "cake serve model.safetensors '
      '--announce --port {port}" — {port} and {name} are filled per '
      "spawn; the replica is admitted to routing only after its "
      "/health answers 200 (UDP discovery admits announced replicas "
      "too); unset disables scale-out execution (decisions still log)")
_knob("CAKE_SCALE_BURN_FAST", float, 2.0, "autoscale",
      "scale-out trigger on the FAST-window SLO burn rate: burn above "
      "this means interactive TTFT/error budget is burning page-fast, "
      "so capacity is added even while batch backlog absorbs")
_knob("CAKE_SCALE_HEADROOM_MIN", float, 0.0, "autoscale",
      "scale-out trigger on fleet capacity headroom (tokens/s): "
      "headroom below this floor adds a replica before saturation "
      "turns into burn; 0 disables the headroom trigger")
_knob("CAKE_SCALE_HEADROOM_HIGH", float, 0.0, "autoscale",
      "scale-in high-water mark (tokens/s): only when headroom sits "
      "ABOVE this continuously for a full CAKE_SCALE_COOLDOWN_S with "
      "clean fast+slow burn does the controller retire a replica; "
      "0 disables scale-in entirely (scale-out-only autoscaling)")
_knob("CAKE_SCALE_COOLDOWN_S", float, 60.0, "autoscale",
      "hysteresis clock: minimum spacing between scale actions, AND "
      "how long the scale-in conditions must hold continuously before "
      "one fires (restoring the CAKE_SCALE_MIN floor is exempt)")
_knob("CAKE_SCALE_MIN", int, 1, "autoscale",
      "replica floor: scale-in never drops below it, and a fleet found "
      "under it (replica died, kill -9) is topped back up immediately, "
      "cooldown or not")
_knob("CAKE_SCALE_MAX", int, 8, "autoscale",
      "replica ceiling: scale-out (pending spawns included) never "
      "exceeds it no matter how hard the burn/headroom triggers pull")
_knob("CAKE_SCALE_WARMUP_S", float, 30.0, "autoscale",
      "warm-up grace after a replica is first seen (or restarts): "
      "while any replica is this young the controller holds — a cold "
      "replica's empty histograms would misread as zero headroom and "
      "re-trigger the very scale-out that just ran")
_knob("CAKE_SCALE_SPAWN_TIMEOUT_S", float, 180.0, "autoscale",
      "spawn-to-healthy admission deadline: a spawned replica whose "
      "/health never answers 200 within this is killed and the spawn "
      "recorded spawn_failed (model load + XLA compile budget)")
_knob("CAKE_SCALE_DECISIONS", int, 256, "autoscale",
      "decisions-ring capacity: typed controller/lifecycle events kept "
      "for GET /api/v1/fleet/autoscale (oldest dropped first)")

# -- cluster --------------------------------------------------------------
_knob("CAKE_CLUSTER_KEY", str, None, "cluster",
      "pre-shared key enabling distributed mode (mutual auth between "
      "master and workers); unset = single-host")
_knob("CAKE_HOP_TIMEOUT_S", float, 120.0, "cluster",
      "per-op deadline on every remote stage forward; an overrun is a "
      "typed `timeout` StageFailure and recovery takes over")
_knob("CAKE_HOP_DEGRADED_MS", float, 0.0, "cluster",
      "gray-failure threshold: rolling RTT p95 above this flags the hop "
      "degraded in /health without failing anything; 0 disables")
_knob("CAKE_REVIVE_GRACE_S", float, 60.0, "cluster",
      "deadline for the FIRST forward after a recovery reconnect (it may "
      "carry an in-band XLA compile on the re-assigned worker)")
_knob("CAKE_RECOVERY_RETRIES", int, 3, "cluster",
      "quarantine -> reconnect -> replay cycles one generation may spend "
      "before failing fast with ClusterDegradedError")
_knob("CAKE_RECOVERY_BACKOFF_S", float, 0.5, "cluster",
      "reconnect backoff base (exponential, capped, +/-25% jitter)")
_knob("CAKE_RESTORE_INTERVAL_S", float, 5.0, "cluster",
      "degraded-mode background probe interval until the lost worker "
      "comes back")
_knob("CAKE_FAULT_PLAN", str, None, "cluster",
      'deterministic fault injection plan (tests/drills only), e.g. '
      '"w0:drop_after_ops=5"')

# -- observability --------------------------------------------------------
_knob("CAKE_TRACE_DIR", str, None, "obs",
      "directory for Chrome-trace span exports; setting it also enables "
      "the span recorder at startup")
_knob("CAKE_TRACE_EVENTS", int, 16384, "obs",
      "span recorder ring-buffer capacity (oldest events drop first)")
_knob("CAKE_TRACE_REQUESTS", int, 256, "obs",
      "per-request timeline ring: how many recent requests keep their "
      "typed lifecycle timeline retrievable via /api/v1/requests/<id> "
      "(oldest evicted first; recording is always on)")
_knob("CAKE_FLIGHT_RECORDER", int, 256, "obs",
      "serve-engine flight recorder: scheduler iterations kept in the "
      "in-memory ring the supervisor dumps to CAKE_TRACE_DIR on a "
      "wedge flag or DOWN classification")

# -- ops / kernels --------------------------------------------------------
_knob("CAKE_MOE_RAGGED", bool, True, "ops",
      "ragged-dot MoE expert combine (falls back to the dense combine "
      "when off or when the installed jax lacks ragged_dot_general)")
_knob("CAKE_TPU_FLASH", bool, True, "ops",
      "flash prefill attention on TPU backends (CPU always uses the "
      "reference path)")

# -- paths ----------------------------------------------------------------
_knob("CAKE_TPU_CACHE", str, "~/.cache/cake-tpu", "paths",
      "worker model-data cache root (split weights, downloaded shards)")


_AREA_TITLES = (
    ("serve", "Serving (continuous-batching engine)"),
    ("qos", "QoS (unified admission plane)"),
    ("spec", "Speculative decoding"),
    ("fleet", "Fleet (router tier over N serve replicas)"),
    ("telemetry", "Telemetry (fleet rollups, SLO objectives)"),
    ("autoscale", "Autoscale (closed-loop elastic fleet)"),
    ("cluster", "Cluster (distributed pipeline + fault tolerance)"),
    ("obs", "Observability"),
    ("ops", "Ops / kernels"),
    ("paths", "Paths"),
)


def generate_doc() -> str:
    """docs/knobs.md body — one table per area, straight from REGISTRY."""
    out = [
        "# Environment knobs",
        "",
        "<!-- GENERATED FILE — do not edit. Source of truth is",
        "     cake_tpu/knobs.py; regenerate with `make knobs-doc`",
        "     (tests/test_analysis.py pins this file to the registry). -->",
        "",
        "Every `CAKE_*` environment variable, generated from the central",
        "registry in `cake_tpu/knobs.py`. All knobs are read at use time",
        "(not import time), and an empty value behaves like unset. The",
        "`knob-registry` lint rule (see [static_analysis.md]"
        "(static_analysis.md)) keeps raw `os.environ` reads of these",
        "names out of the tree.",
        "",
    ]
    for area, title in _AREA_TITLES:
        knobs = [k for k in REGISTRY.values() if k.area == area]
        if not knobs:
            continue
        out += [f"## {title}", "",
                "| knob | type | default | meaning |",
                "|---|---|---|---|"]
        for kb in knobs:
            default = "unset" if kb.default is None else str(kb.default)
            out.append(f"| `{kb.name}` | {kb.cast.__name__} | {default} "
                       f"| {kb.doc} |")
        out.append("")
    return "\n".join(out)


if __name__ == "__main__":
    print(generate_doc())
