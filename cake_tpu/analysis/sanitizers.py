"""Runtime sanitizers: the dynamic half of the static pass.

The AST rules prove the CODE cannot recompile or sync by accident; these
context managers prove the RUNTIME didn't. Wired into tests (and usable
around any suspect region in a bench or smoke script):

  * :func:`assert_no_recompiles` — snapshots the executable-cache size of
    every given jitted callable on entry and asserts nothing new was
    compiled on exit. Steady-state `decode_slots` must pass N iterations
    under it: one new executable means an unstable cache key slipped past
    the `recompile-hazard` rule.

  * :func:`no_implicit_transfers` — `jax.transfer_guard`-based: any
    implicit device<->host transfer inside the region raises. The batched
    decode step runs under it in tests: its contract is that ALL per-slot
    carries stay device-resident and an iteration ships nothing — the one
    planned fetch (`np.asarray(packed)`) happens OUTSIDE the guarded
    region, which is exactly the discipline the guard verifies.

Both are no-overhead outside tests: nothing here is installed globally.
"""
from __future__ import annotations

from contextlib import contextmanager

__all__ = ["cache_size", "assert_no_recompiles", "no_implicit_transfers",
           "decode_fns", "RecompileError"]


class RecompileError(AssertionError):
    """A guarded region compiled a new executable."""


def cache_size(fn) -> int:
    """Number of compiled executables a jitted callable holds (jax 0.4.x
    PjitFunction._cache_size; a jax upgrade that drops it should fail
    HERE, loudly, not silently stop guarding)."""
    if hasattr(fn, "_cache_size"):
        return fn._cache_size()
    raise RuntimeError(
        f"{fn!r} exposes no executable-cache size — wrap the jitted "
        "callable itself, or teach sanitizers.cache_size the new jax API")


def decode_fns(model) -> dict[str, object]:
    """The jitted callables that must stay compile-stable across
    steady-state serve iterations for `model` (a TextModel or anything
    publishing the same _build() attributes)."""
    out = {}
    for name in ("_decode_slots", "_decode_slots_paged", "_decode_step",
                 "_decode_chunk", "_decode_until", "_prefill_slot",
                 "_prefill_slot_paged", "_spec_slots", "_spec_slots_paged",
                 "_sample_traced"):
        fn = getattr(model, name, None)
        if fn is not None and hasattr(fn, "_cache_size"):
            out[name] = fn
    return out


@contextmanager
def assert_no_recompiles(*fns, label: str = ""):
    """Assert that none of the given jitted callables compile a new
    executable inside the with-block.

    Accepts jitted callables and/or model objects (expanded through
    :func:`decode_fns`). Raises :class:`RecompileError` naming the
    callable(s) that grew their cache and by how much.
    """
    tracked: dict[str, object] = {}
    for fn in fns:
        if hasattr(fn, "_cache_size"):
            tracked[getattr(fn, "__name__", repr(fn))] = fn
        else:
            sub = decode_fns(fn)
            if not sub:
                raise RuntimeError(
                    f"{fn!r} is neither a jitted callable nor a model "
                    "with jitted decode programs")
            tracked.update(sub)
    before = {name: cache_size(fn) for name, fn in tracked.items()}
    yield
    grew = {name: cache_size(tracked[name]) - n0
            for name, n0 in before.items()
            if cache_size(tracked[name]) != n0}
    if grew:
        what = ", ".join(f"{k} (+{v})" for k, v in sorted(grew.items()))
        raise RecompileError(
            f"steady-state region{f' {label!r}' if label else ''} "
            f"compiled new executables: {what} — an unstable jit cache "
            "key (see docs/static_analysis.md, rule recompile-hazard)")


@contextmanager
def no_implicit_transfers(level: str = "disallow"):
    """Fail on implicit device<->host transfers inside the region.

    `level` is any jax.transfer_guard level; "disallow" (the default)
    permits explicit transfers (jax.device_put / jax.device_get) but
    raises on implicit ones — a numpy array silently shipped
    host->device per call, or a device array concretized host-side.
    The planned fetch of a decode iteration belongs OUTSIDE the region.
    """
    import jax
    with jax.transfer_guard(level):
        yield
