"""Rule `metric-registry`: every Counter/Gauge/Histogram name constructed
under cake_tpu/ must appear in the generated metric catalog
(docs/observability.md).

The knob-registry rule's shape, pointed at instruments: the catalog is
generated from the canonical declarations in cake_tpu/obs/__init__.py
(`make metrics-doc`) and pinned to them by test, so a metric registered
anywhere else — or added to obs/__init__.py without regenerating the doc
— is a silently-undocumented instrument, exactly the drift that left the
hand-written observability page three subsystems stale. Registration is
idempotent by design, so nothing STOPS a module minting its own series;
this rule is what makes that visible.

Only literal `cake_*` first arguments to `.counter(` / `.gauge(` /
`.histogram(` calls are checked: dynamic names cannot be verified
statically and nothing in the tree builds one (keeping it that way is
the point).
"""
from __future__ import annotations

import ast
import os
import re

from .core import Checker, SourceFile, Violation, register, repo_root

_CATALOG_REL = os.path.join("docs", "observability.md")
_NAME_RE = re.compile(r"`(cake_[a-z0-9_]+)`")
_REGISTRY_METHODS = ("counter", "gauge", "histogram")


def catalog_names() -> frozenset:
    """Metric names the generated catalog documents (backticked
    `cake_*` tokens in docs/observability.md); empty when the catalog
    is missing — every instrument then fires, which is the right
    failure for a deleted catalog."""
    path = os.path.join(repo_root(), _CATALOG_REL)
    try:
        with open(path, encoding="utf-8") as f:
            return frozenset(_NAME_RE.findall(f.read()))
    except OSError:
        return frozenset()


class MetricRegistryChecker(Checker):
    name = "metric-registry"
    doc = ("Counter/Gauge/Histogram names constructed under cake_tpu/ "
           "must appear in the generated metric catalog "
           "(docs/observability.md; regenerate with `make metrics-doc`)")

    def __init__(self):
        self._catalog: frozenset | None = None

    def applies(self, sf: SourceFile) -> bool:
        return sf.rel.startswith("cake_tpu/")

    def check(self, sf: SourceFile):
        if self._catalog is None:
            self._catalog = catalog_names()
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            fn = node.func
            if not (isinstance(fn, ast.Attribute)
                    and fn.attr in _REGISTRY_METHODS):
                continue
            arg = node.args[0]
            if not (isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)
                    and arg.value.startswith("cake_")):
                continue
            if arg.value not in self._catalog:
                yield Violation(
                    self.name, sf.rel, node.lineno,
                    f"metric {arg.value!r} is not in the generated "
                    "catalog — declare it in cake_tpu/obs/__init__.py "
                    "and run `make metrics-doc`")


register(MetricRegistryChecker)
