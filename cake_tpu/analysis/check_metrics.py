"""Rule `metric-registry`: every Counter/Gauge/Histogram name constructed
under cake_tpu/ must appear in the generated metric catalog
(docs/observability.md) — and SLO-semantic histograms must share bucket
boundaries.

The knob-registry rule's shape, pointed at instruments: the catalog is
generated from the canonical declarations in cake_tpu/obs/__init__.py
(`make metrics-doc`) and pinned to them by test, so a metric registered
anywhere else — or added to obs/__init__.py without regenerating the doc
— is a silently-undocumented instrument, exactly the drift that left the
hand-written observability page three subsystems stale. Registration is
idempotent by design, so nothing STOPS a module minting its own series;
this rule is what makes that visible.

The bucket-consistency half exists for the fleet telemetry plane: it
merges per-replica SLO histograms BUCKET-WISE (fleet/telemetry.py), and
summing misaligned buckets silently produces garbage percentiles. So
every `cake_*_seconds` histogram carrying an SLO semantic (ttft / itl /
e2e in its name) must use the shared LATENCY_BUCKETS boundaries —
either by omitting `buckets` (the default), naming LATENCY_BUCKETS, or
spelling out a literal equal to it; and two same-semantic histograms in
one file must agree with each other.

Only literal `cake_*` first arguments to `.counter(` / `.gauge(` /
`.histogram(` calls are checked: dynamic names cannot be verified
statically and nothing in the tree builds one (keeping it that way is
the point).
"""
from __future__ import annotations

import ast
import os
import re

from .core import Checker, SourceFile, Violation, register, repo_root

_CATALOG_REL = os.path.join("docs", "observability.md")
_NAME_RE = re.compile(r"`(cake_[a-z0-9_]+)`")
_REGISTRY_METHODS = ("counter", "gauge", "histogram")

# SLO semantics whose histograms the fleet tier merges bucket-wise
_SLO_SEM_RE = re.compile(r"(?:^|_)(ttft|itl|e2e)_seconds$")

# the one sanctioned boundary set for SLO-semantic histograms
_CANONICAL_SIG = "default"


def catalog_names() -> frozenset:
    """Metric names the generated catalog documents (backticked
    `cake_*` tokens in docs/observability.md); empty when the catalog
    is missing — every instrument then fires, which is the right
    failure for a deleted catalog."""
    path = os.path.join(repo_root(), _CATALOG_REL)
    try:
        with open(path, encoding="utf-8") as f:
            return frozenset(_NAME_RE.findall(f.read()))
    except OSError:
        return frozenset()


def _bucket_signature(call: ast.Call) -> str | None:
    """Stable string signature of a histogram call's bucket boundaries:
    "default" for an omitted kwarg or the LATENCY_BUCKETS name (possibly
    attribute-qualified), the literal values for a constant tuple/list,
    None when unverifiable (a computed expression)."""
    buckets = None
    for kw in call.keywords:
        if kw.arg == "buckets":
            buckets = kw.value
            break
    if buckets is None and len(call.args) >= 4:
        buckets = call.args[3]
    if buckets is None:
        return "default"
    if isinstance(buckets, ast.Name) and buckets.id == "LATENCY_BUCKETS":
        return "default"
    if isinstance(buckets, ast.Attribute) \
            and buckets.attr == "LATENCY_BUCKETS":
        return "default"
    if isinstance(buckets, (ast.Tuple, ast.List)):
        vals = []
        for el in buckets.elts:
            if not (isinstance(el, ast.Constant)
                    and isinstance(el.value, (int, float))):
                return None
            vals.append(float(el.value))
        from ..obs.metrics import LATENCY_BUCKETS
        if tuple(sorted(vals)) == tuple(float(b) for b in LATENCY_BUCKETS):
            return "default"
        return "(" + ",".join(repr(v) for v in vals) + ")"
    return None


class MetricRegistryChecker(Checker):
    name = "metric-registry"
    doc = ("Counter/Gauge/Histogram names constructed under cake_tpu/ "
           "must appear in the generated metric catalog "
           "(docs/observability.md; regenerate with `make metrics-doc`), "
           "and SLO-semantic (ttft/itl/e2e) *_seconds histograms must "
           "share the LATENCY_BUCKETS boundaries so fleet-level "
           "bucket-wise merges stay sound")

    def __init__(self):
        self._catalog: frozenset | None = None

    def applies(self, sf: SourceFile) -> bool:
        return sf.rel.startswith("cake_tpu/")

    def check(self, sf: SourceFile):
        if self._catalog is None:
            self._catalog = catalog_names()
        # per-semantic bucket signatures seen in THIS file (same-file
        # drift is the realistic failure: the canonical declarations all
        # live in obs/__init__.py)
        seen_sigs: dict[str, tuple[str, int]] = {}
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            fn = node.func
            if not (isinstance(fn, ast.Attribute)
                    and fn.attr in _REGISTRY_METHODS):
                continue
            arg = node.args[0]
            if not (isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)
                    and arg.value.startswith("cake_")):
                continue
            if arg.value not in self._catalog:
                yield Violation(
                    self.name, sf.rel, node.lineno,
                    f"metric {arg.value!r} is not in the generated "
                    "catalog — declare it in cake_tpu/obs/__init__.py "
                    "and run `make metrics-doc`")
            if fn.attr == "histogram":
                yield from self._check_buckets(sf, node, arg.value,
                                               seen_sigs)

    def _check_buckets(self, sf: SourceFile, node: ast.Call, name: str,
                       seen_sigs: dict):
        """SLO-semantic histograms (ttft/itl/e2e *_seconds) must share
        boundaries: the fleet telemetry plane sums their buckets across
        replicas, and a mismatched declaration makes those percentiles
        silently wrong."""
        m = _SLO_SEM_RE.search(name)
        if not m:
            return
        sem = m.group(1)
        sig = _bucket_signature(node)
        if sig is None:
            yield Violation(
                self.name, sf.rel, node.lineno,
                f"SLO histogram {name!r} ({sem}) passes buckets this "
                "rule cannot verify statically — use the shared "
                "LATENCY_BUCKETS (fleet rollups merge these bucket-wise)")
            return
        if sig != _CANONICAL_SIG:
            yield Violation(
                self.name, sf.rel, node.lineno,
                f"SLO histogram {name!r} ({sem}) declares buckets "
                f"{sig} != the shared LATENCY_BUCKETS — fleet-level "
                "bucket-wise merging of same-semantic histograms "
                "produces garbage percentiles on mismatched boundaries")
        prior = seen_sigs.get(sem)
        if prior is not None and prior[0] != sig:
            yield Violation(
                self.name, sf.rel, node.lineno,
                f"SLO histogram {name!r} ({sem}) buckets differ from "
                f"the same-semantic declaration at line {prior[1]} — "
                "same-semantic histograms must be bucket-identical")
        else:
            seen_sigs.setdefault(sem, (sig, node.lineno))


register(MetricRegistryChecker)
