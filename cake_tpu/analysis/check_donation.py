"""Rule `use-after-donate`: reads of a buffer after jit donated it.

`donate_argnums` lets XLA alias an input buffer as an output — the KV
pool, the per-slot carries and every decode cache ride on it. But the
Python name still points at the now-invalid buffer: reading it after the
call returns garbage (TPU) or a RuntimeError (CPU, sometimes), and the
failure is timing-dependent — exactly the bug class static analysis
beats testing at.

Per function, statements are scanned in evaluation order. A call that
resolves to a donating target — a local jitted def (donate_argnums mapped
through its signature), a `self.X` binding to one, or a known donating
TextModel method (jitinfo.KNOWN_DONATING_METHODS: decode_slots,
prefill_chunk, ...) — marks the argument names at donated positions dead.
A later Load of a dead name fires; a Store (typically the same statement
unpacking the call's results back into the name) revives it. Tracked
names are bare locals and `self.*` attribute chains; anything fancier is
out of scope for a lint.

Limitations (by design, keep the rule quiet): no cross-iteration loop
analysis, no aliasing (`y = x` then donate x, read y), no cross-function
attribute tracking.
"""
from __future__ import annotations

import ast

from .core import Checker, SourceFile, Violation, register
from .jitinfo import (KNOWN_DONATING_METHODS, collect_attr_bindings,
                      collect_jit_fns, dotted_name, resolve_jit_callee)


def _trackable(node) -> str | None:
    """A donated-arg expression we can follow: bare name or self.* chain."""
    name = dotted_name(node)
    if name is None:
        return None
    if "." in name and not name.startswith("self."):
        return None
    return name


class _FnAnalysis:
    def __init__(self, sf, jits, bindings, rule):
        self.sf = sf
        self.jits = jits
        self.bindings = bindings
        self.rule = rule
        self.dead: dict[str, int] = {}      # name -> donation line
        self.out: list[Violation] = []

    # -- evaluation-order walk --------------------------------------------

    def run(self, fn: ast.FunctionDef):
        self.stmts(fn.body)
        return self.out

    def stmts(self, body):
        for stmt in body:
            self.stmt(stmt)

    def stmt(self, node):
        if isinstance(node, ast.Assign):
            self.expr(node.value)
            for tgt in node.targets:
                self.store(tgt)
        elif isinstance(node, ast.AugAssign):
            self.expr(node.value)
            self.expr(node.target, loading=True)
            self.store(node.target)
        elif isinstance(node, ast.AnnAssign):
            if node.value is not None:
                self.expr(node.value)
            self.store(node.target)
        elif isinstance(node, (ast.Expr, ast.Return)):
            if getattr(node, "value", None) is not None:
                self.expr(node.value)
        elif isinstance(node, (ast.If, ast.While)):
            self.expr(node.test)
            self.stmts(node.body)
            self.stmts(node.orelse)
        elif isinstance(node, ast.For):
            self.expr(node.iter)
            self.store(node.target)
            self.stmts(node.body)
            self.stmts(node.orelse)
        elif isinstance(node, ast.With):
            for item in node.items:
                self.expr(item.context_expr)
                if item.optional_vars is not None:
                    self.store(item.optional_vars)
            self.stmts(node.body)
        elif isinstance(node, ast.Try):
            self.stmts(node.body)
            for h in node.handlers:
                self.stmts(h.body)
            self.stmts(node.orelse)
            self.stmts(node.finalbody)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
            pass                        # nested scope: analyzed separately
        else:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.expr):
                    self.expr(child)

    # -- expressions -------------------------------------------------------

    def expr(self, node, loading=False):
        """Flag loads of dead names, then apply any donation this
        expression performs (sub-calls first — args evaluate before the
        call donates)."""
        for sub in ast.walk(node):
            name = None
            if isinstance(sub, (ast.Name, ast.Attribute)) and isinstance(
                    getattr(sub, "ctx", None), ast.Load):
                name = dotted_name(sub)
            if name and name in self.dead:
                # report the OUTERMOST chain only once per site
                self.out.append(Violation(
                    self.rule, self.sf.rel, sub.lineno,
                    f"{name!r} read after being donated at line "
                    f"{self.dead[name]} — donated buffers are dead; "
                    "rebind the name from the call's results first"))
                del self.dead[name]     # one report per donation
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                self.donate(sub)

    def donate(self, call: ast.Call):
        idx = None
        jf = resolve_jit_callee(call, self.jits, self.bindings)
        if jf is not None:
            idx = jf.donate_idx
        else:
            fname = dotted_name(call.func)
            if fname is not None and "." in fname:
                attr = fname.rsplit(".", 1)[1]
                if attr in KNOWN_DONATING_METHODS:
                    idx = set(KNOWN_DONATING_METHODS[attr])
        if not idx:
            return
        if any(isinstance(a, ast.Starred) for a in call.args):
            return                      # can't map positions
        for i in idx:
            if i < len(call.args):
                name = _trackable(call.args[i])
                if name is not None:
                    self.dead[name] = call.lineno

    def store(self, target):
        for sub in ast.walk(target):
            if isinstance(sub, (ast.Name, ast.Attribute)):
                name = dotted_name(sub)
                if name:
                    self.dead.pop(name, None)


class DonationChecker(Checker):
    name = "use-after-donate"
    doc = ("reads of a variable after it was passed at a donate_argnums "
           "position (donated buffers are dead after dispatch)")

    def check(self, sf: SourceFile):
        jits = collect_jit_fns(sf.tree)
        bindings = collect_attr_bindings(sf.tree)
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from _FnAnalysis(sf, jits, bindings,
                                       self.name).run(node)


register(DonationChecker)
