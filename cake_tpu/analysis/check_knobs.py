"""Rule `knob-registry`: every `CAKE_*` env var is read through
cake_tpu.knobs, never raw `os.environ`.

Before the registry, 27 scattered reads in 18 files each carried their
own default and parsing quirks, and the doc tables drifted from the code.
A raw read bypasses the typed default, the generated docs/knobs.md AND
the empty-string fallback — so it fires here. Writes (monkeypatching in
tests, `setdefault` in launch scripts) are fine: the registry governs how
knobs are READ, not how environments are built.
"""
from __future__ import annotations

import ast

from .core import Checker, SourceFile, Violation, register

_EXEMPT = ("cake_tpu/knobs.py",)


def _is_environ(node) -> bool:
    """`os.environ` / bare `environ`."""
    if isinstance(node, ast.Attribute) and node.attr == "environ":
        return isinstance(node.value, ast.Name) and node.value.id == "os"
    return isinstance(node, ast.Name) and node.id == "environ"


def _cake_const(node) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str) \
            and node.value.startswith("CAKE_"):
        return node.value
    return None


class KnobRegistryChecker(Checker):
    name = "knob-registry"
    doc = ("raw os.environ/os.getenv reads of CAKE_* names — go through "
           "cake_tpu.knobs.get (typed default + generated docs)")

    def applies(self, sf: SourceFile) -> bool:
        return sf.rel not in _EXEMPT

    def check(self, sf: SourceFile):
        for node in ast.walk(sf.tree):
            knob = None
            if isinstance(node, ast.Call):
                fn = node.func
                # os.environ.get("CAKE_X") / environ.get
                if isinstance(fn, ast.Attribute) and fn.attr == "get" \
                        and _is_environ(fn.value) and node.args:
                    knob = _cake_const(node.args[0])
                # os.getenv("CAKE_X") / getenv
                elif ((isinstance(fn, ast.Attribute)
                       and fn.attr == "getenv")
                      or (isinstance(fn, ast.Name)
                          and fn.id == "getenv")) and node.args:
                    knob = _cake_const(node.args[0])
            elif isinstance(node, ast.Subscript) \
                    and isinstance(node.ctx, ast.Load) \
                    and _is_environ(node.value):
                knob = _cake_const(node.slice)
            if knob:
                yield Violation(
                    self.name, sf.rel, node.lineno,
                    f"raw env read of {knob} — use "
                    f'cake_tpu.knobs.get("{knob}") (and register the knob '
                    "if it is new)")


register(KnobRegistryChecker)
