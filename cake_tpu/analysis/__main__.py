"""`python -m cake_tpu.analysis` — run the checkers, exit non-zero on any
unsuppressed violation. `make lint` is this.
"""
from __future__ import annotations

import argparse
import sys

from . import RULES, run_paths


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m cake_tpu.analysis",
        description="AST lint for the serving hot path (see "
                    "docs/static_analysis.md)")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to check (default: cake_tpu/ and "
                         "scripts/)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule subset (default: all)")
    ap.add_argument("--list", action="store_true", dest="list_rules",
                    help="list registered rules and exit")
    ap.add_argument("-v", "--verbose", action="store_true",
                    help="also print suppressed violations with their "
                         "reasons")
    args = ap.parse_args(argv)

    if args.list_rules:
        width = max(len(r) for r in RULES)
        for name, checker in sorted(RULES.items()):
            print(f"{name:<{width}}  {checker.doc}")
        return 0

    rules = [r.strip() for r in args.rules.split(",")] if args.rules \
        else None
    try:
        violations = run_paths(args.paths or None, rules)
    except KeyError as e:
        print(f"unknown rule {e.args[0]!r} (see --list)", file=sys.stderr)
        return 2

    fatal = [v for v in violations if not v.suppressed]
    suppressed = [v for v in violations if v.suppressed]
    if args.verbose:
        for v in suppressed:
            print(v.render())
    for v in fatal:
        print(v.render(), file=sys.stderr)
    n_rules = len(rules) if rules else len(RULES)
    print(f"[cake_tpu.analysis] {n_rules} rules, "
          f"{len(fatal)} violations, {len(suppressed)} suppressed")
    return 1 if fatal else 0


if __name__ == "__main__":
    sys.exit(main())
