"""The serving hot-path module set: every file whose code runs per token,
per scheduler iteration, or per wire frame. The host-sync and hot-timing
rules scope themselves to this set — cli/tui/image pipelines and
discovery are allowed plain host syncs and wall clocks (they are not hot).

Grown from PR 1's check_hot_timing list by the serve/spec subsystems that
landed since.
"""
from __future__ import annotations

HOT_PATHS = frozenset({
    # per-token model programs + their wrappers
    "cake_tpu/models/common/text_model.py",
    "cake_tpu/models/common/offload_model.py",
    # continuous-batching scheduler: one iteration per pool-wide token
    "cake_tpu/serve/engine.py",
    # unified admission plane: class-aware dequeue + tenant buckets run
    # per submitted request, job checkpoints per diffusion step
    "cake_tpu/serve/admission/__init__.py",
    "cake_tpu/serve/admission/classes.py",
    "cake_tpu/serve/admission/queue.py",
    "cake_tpu/serve/admission/tenants.py",
    "cake_tpu/serve/admission/jobs.py",
    "cake_tpu/serve/admission/plane.py",
    "cake_tpu/serve/slots.py",
    "cake_tpu/serve/prefix_cache.py",
    # paged KV: the allocator + table remaps run per scheduler iteration,
    # swap/preempt sit on the exhaustion path of the same loop
    "cake_tpu/serve/paged/__init__.py",
    "cake_tpu/serve/paged/allocator.py",
    "cake_tpu/serve/paged/pool.py",
    "cake_tpu/serve/paged/preempt.py",
    # crash-only supervision: arm/disarm + failure handling run per
    # dispatch / per recovery, and the fault hook sits on the dispatch
    # path itself
    "cake_tpu/serve/supervisor.py",
    "cake_tpu/serve/faults.py",
    # speculative decode: per verify step (drafting + accept/resample
    # ride every batched spec iteration)
    "cake_tpu/spec/drafter.py",
    "cake_tpu/spec/verify.py",
    "cake_tpu/ops/sampling.py",
    # cluster data plane: per hop
    "cake_tpu/cluster/master.py",
    "cake_tpu/cluster/worker.py",
    "cake_tpu/cluster/client.py",
    "cake_tpu/cluster/proto.py",
    # request routing
    "cake_tpu/api/state.py",
    # fleet router: membership recording + candidate ordering + the
    # outbound attempt seam all run per proxied request (and the probe
    # loop shares the same guarded state) — timings go through obs.now,
    # registry fields carry guarded-by annotations
    "cake_tpu/fleet/registry.py",
    "cake_tpu/fleet/routing.py",
    "cake_tpu/fleet/router.py",
    "cake_tpu/fleet/faults.py",
    # fleet-shared KV tier: run_pending drains the blob mailbox inside
    # every scheduler iteration, and export/import touch pool arrays
    # directly (each deliberate device->host pull carries a host-sync
    # disable comment)
    "cake_tpu/fleet/kvshare/replica.py",
})


def is_hot(rel: str) -> bool:
    return rel in HOT_PATHS
