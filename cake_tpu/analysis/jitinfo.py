"""Shared AST plumbing: find jit-compiled functions, their static and
donated arguments, and the `self.<attr> = <local jit fn>` bindings that
route method calls to them (the `_build()` idiom every model uses).

Used by the host-sync, recompile-hazard and use-after-donate rules — one
resolver so the three rules can never disagree about what is traced.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field

__all__ = ["JitFn", "collect_jit_fns", "collect_attr_bindings",
           "dotted_name", "KNOWN_DONATING_METHODS"]

# Cross-module donation knowledge: public TextModel wrappers whose jitted
# bodies donate buffers at these CALL-SITE positional indices (self
# already bound). The serve engine and the spec loop call these on a
# `model` object the per-module AST cannot see into.
KNOWN_DONATING_METHODS: dict[str, tuple[int, ...]] = {
    "decode_slots": (0, 1, 2, 3, 4),    # layers, toks, pos, rngs, recents
    "spec_slots": (0, 1, 2, 3, 4),
    "prefill_chunk": (0,),              # layers
    # paged variants: pool + rows donated, the block TABLE is not (the
    # engine remaps entries between iterations and keeps its handle)
    "decode_slots_paged": (0, 1, 3, 4, 5, 6),
    "spec_slots_paged": (0, 1, 3, 4, 5, 6),
    "prefill_chunk_paged": (0, 1),
    "row_install": (0,),                # rows
    "row_reset": (0,),
    "slot_assign": (0,),
    "slot_release": (0,),
    "slot_splice": (0,),
    "verify_tokens": (0,),              # cache
    "prefill": (0,),
    "decode_logits": (0,),
    "forward_hidden": (1,),             # x, CACHE, pos0, ...
}


@dataclass
class JitFn:
    name: str
    node: ast.FunctionDef
    params: list[str]
    static_names: set[str] = field(default_factory=set)
    donate_idx: set[int] = field(default_factory=set)


def _const_strs(node) -> list[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)]
    return []


def _const_ints(node) -> list[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, int)]
    return []


def _is_jax_jit(node) -> bool:
    """`jax.jit` / `jit` as an expression."""
    if isinstance(node, ast.Attribute) and node.attr == "jit":
        return True
    return isinstance(node, ast.Name) and node.id == "jit"


def jit_call_info(call: ast.Call):
    """(static_names, static_nums, donate_nums) from a
    `functools.partial(jax.jit, ...)` or `jax.jit(...)` call; None when
    the call is not a jit wrapper."""
    fn = call.func
    is_partial = (isinstance(fn, ast.Attribute) and fn.attr == "partial") \
        or (isinstance(fn, ast.Name) and fn.id == "partial")
    if is_partial:
        if not (call.args and _is_jax_jit(call.args[0])):
            return None
    elif not _is_jax_jit(fn):
        return None
    statics, snums, dnums = set(), [], []
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            statics.update(_const_strs(kw.value))
        elif kw.arg == "static_argnums":
            snums.extend(_const_ints(kw.value))
        elif kw.arg == "donate_argnums":
            dnums.extend(_const_ints(kw.value))
    return statics, snums, dnums


def _params_of(fn: ast.FunctionDef) -> list[str]:
    a = fn.args
    return [p.arg for p in (a.posonlyargs + a.args)]


def collect_jit_fns(tree: ast.Module) -> dict[str, JitFn]:
    """Every function in the module (at any nesting) compiled by jax.jit:
    decorated defs, plus `name = jax.jit(fn, ...)` assignments where `fn`
    is a local def or lambda."""
    defs: dict[str, ast.FunctionDef] = {}
    out: dict[str, JitFn] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            defs[node.name] = node
            for dec in node.decorator_list:
                info = None
                if isinstance(dec, ast.Call):
                    info = jit_call_info(dec)
                elif _is_jax_jit(dec):
                    info = (set(), [], [])
                if info is None:
                    continue
                params = _params_of(node)
                statics, snums, dnums = info
                statics |= {params[i] for i in snums if i < len(params)}
                out[node.name] = JitFn(node.name, node, params, statics,
                                       {i for i in dnums if i < len(params)})
                break
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) or not isinstance(node.value,
                                                              ast.Call):
            continue
        info = jit_call_info(node.value)
        if info is None or not node.value.args:
            continue
        wrapped = node.value.args[0]
        fnode = params = None
        if isinstance(wrapped, ast.Name) and wrapped.id in defs:
            fnode = defs[wrapped.id]
            params = _params_of(fnode)
        elif isinstance(wrapped, ast.Lambda):
            fnode = wrapped
            params = [p.arg for p in wrapped.args.args]
        if fnode is None:
            continue
        statics, snums, dnums = info
        statics |= {params[i] for i in snums if i < len(params)}
        for tgt in node.targets:
            name = dotted_name(tgt)
            if name:
                out[name] = JitFn(name, fnode, params, statics,
                                  {i for i in dnums if i < len(params)})
    return out


def collect_attr_bindings(tree: ast.Module) -> dict[str, str]:
    """`self.X = Y` where Y is a bare local name -> {"self.X": "Y"}: how
    `_build()` publishes its jitted closures as instance attributes."""
    out: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Name):
            for tgt in node.targets:
                name = dotted_name(tgt)
                if name and name.startswith("self."):
                    out[name] = node.value.id
    return out


def dotted_name(node) -> str | None:
    """Name/Attribute chain -> "a.b.c"; None for anything fancier."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def resolve_jit_callee(call: ast.Call, jits: dict[str, JitFn],
                       bindings: dict[str, str]) -> JitFn | None:
    """The JitFn a call dispatches to: a jitted local name, a name bound
    by `name = jax.jit(...)`, or a `self.X` attribute published from
    `_build()`."""
    name = dotted_name(call.func)
    if name is None:
        return None
    if name in jits:
        return jits[name]
    target = bindings.get(name)
    if target is not None and target in jits:
        return jits[target]
    return None
