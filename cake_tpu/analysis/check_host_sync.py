"""Rule `host-sync`: device synchronization in serving hot paths.

A continuous-batching iteration is budgeted for exactly ONE device->host
transfer (the packed sampled-ids fetch); any extra `.item()`,
`np.asarray(device_array)`, `jax.device_get`, `int()/float()/bool()` of a
device value, or Python truthiness on a tracer stalls the dispatch
pipeline for a full link round trip per call — the regression class that
turned the reference's decode loop into a per-token sync storm.

Heuristics (AST only, no type inference):

  * `.item()` and `jax.device_get(...)` always fire;
  * `np.asarray(x)` / `np.array(x)` fire when `x`'s root name is
    DEVICE-TAINTED: assigned (in the same function) from a jitted callable
    (local `_build()` closures via self-attr bindings), a known device
    method (decode_slots, prefill, ...), a sampling op, or a `jnp.*` /
    `jax.*` call. Host-data conversions (lists, np results) stay silent;
  * `int()/float()/bool()` fire only on device-tainted roots — `int()` of
    an already-fetched numpy array is host work;
  * inside jit-traced functions, `if`/`while`/`assert` on a non-static
    parameter is tracer truthiness (a ConcretizationError at best, a
    silent per-trace sync at worst).

Deliberate syncs — the one fetch per engine iteration, the TTFT-honest
first-token sync — carry `# lint: disable=host-sync — <why>`.
"""
from __future__ import annotations

import ast

from .core import Checker, SourceFile, Violation, register
from .hot_paths import is_hot
from .jitinfo import (KNOWN_DONATING_METHODS, collect_attr_bindings,
                      collect_jit_fns, dotted_name, resolve_jit_callee)

# calls that produce device arrays regardless of module knowledge
_DEVICE_FN_NAMES = {"sample", "sample_traced", "push_recent_token",
                    "spec_accept", "embed_tokens", "forward_layers",
                    "lm_head_logits"}
# method attrs that return device arrays on any receiver (model, stage)
_DEVICE_METHOD_ATTRS = set(KNOWN_DONATING_METHODS) | {
    "sample_one", "new_cache", "fwd", "apply"}
_HOST_ROOTS = {"np", "numpy", "os", "math", "sorted", "list", "tuple",
               "len", "min", "max", "sum", "range", "str", "int", "float"}

_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _root_name(node) -> str | None:
    """x / x[i] / x.attr / x[i].attr ... -> "x"."""
    while isinstance(node, (ast.Subscript, ast.Attribute, ast.Starred)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def own_nodes(scope):
    """Every AST node belonging to this function/module scope, NOT
    descending into nested function/class bodies (they get their own
    scope and taint table)."""
    stack = list(getattr(scope, "body", []))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, _SCOPES + (ast.ClassDef,)):
            continue
        stack.extend(ast.iter_child_nodes(node))


class _Taint:
    """Which local names (very likely) hold device arrays."""

    def __init__(self, jits, bindings):
        self.jits = jits
        self.bindings = bindings
        self.tainted: set[str] = set()

    def is_device_call(self, call: ast.Call) -> bool:
        if resolve_jit_callee(call, self.jits, self.bindings) is not None:
            return True
        fn = call.func
        if isinstance(fn, ast.Name):
            return fn.id in _DEVICE_FN_NAMES
        if isinstance(fn, ast.Attribute):
            root = _root_name(fn.value)
            if root in ("jnp", "jax"):
                return fn.attr != "device_get"    # device_get fires itself
            if root in _HOST_ROOTS:
                return False
            return (fn.attr in _DEVICE_METHOD_ATTRS
                    or fn.attr in _DEVICE_FN_NAMES)
        return False

    def feed(self, node):
        if not isinstance(node, ast.Assign):
            return
        if isinstance(node.value, ast.Call):
            device = self.is_device_call(node.value)
        elif isinstance(node.value, (ast.Name, ast.Subscript,
                                     ast.Attribute)):
            device = _root_name(node.value) in self.tainted
        else:
            return
        for tgt in node.targets:
            for sub in ast.walk(tgt):
                if isinstance(sub, ast.Name):
                    if device:
                        self.tainted.add(sub.id)
                    else:
                        self.tainted.discard(sub.id)


class HostSyncChecker(Checker):
    name = "host-sync"
    doc = ("device syncs (.item, np.asarray of device arrays, "
           "int()/float()/bool() of device values, jax.device_get, tracer "
           "truthiness) in the serving hot-path module set")

    def applies(self, sf: SourceFile) -> bool:
        return is_hot(sf.rel)

    def check(self, sf: SourceFile):
        jits = collect_jit_fns(sf.tree)
        bindings = collect_attr_bindings(sf.tree)
        jit_nodes = {id(j.node): j for j in jits.values()}

        scopes = [sf.tree] + [n for n in ast.walk(sf.tree)
                              if isinstance(n, _SCOPES[:2])]
        for scope in scopes:
            taint = _Taint(jits, bindings)
            nodes = list(own_nodes(scope))
            for node in nodes:          # taint pass first: assignments
                taint.feed(node)        # anywhere in the scope count
            for node in nodes:
                if isinstance(node, ast.Call):
                    for v in self._check_call(node, taint):
                        v.rel = sf.rel
                        yield v
            jf = jit_nodes.get(id(scope))
            if jf is not None:
                for v in self._check_truthiness(scope, jf):
                    v.rel = sf.rel
                    yield v

    def _check_call(self, call: ast.Call, taint: _Taint):
        fn = call.func
        if isinstance(fn, ast.Attribute) and fn.attr == "item" \
                and not call.args:
            yield self._v(call, ".item() syncs the device per call — fetch "
                          "once with np.asarray and index on the host")
            return
        name = dotted_name(fn) or ""
        if name == "jax.device_get":
            yield self._v(call, "jax.device_get on a hot path — batch the "
                          "fetch or route it through the packed-ids fetch")
            return
        if name in ("np.asarray", "numpy.asarray", "np.array",
                    "numpy.array"):
            if call.args:
                root = _root_name(call.args[0])
                if root is not None and root in taint.tainted:
                    yield self._v(call, f"np.{fn.attr}({root}) fetches a "
                                  "device array (blocking sync)")
            return
        if isinstance(fn, ast.Name) and fn.id in ("int", "float", "bool") \
                and len(call.args) == 1:
            root = _root_name(call.args[0])
            if root is not None and root in taint.tainted:
                yield self._v(call, f"{fn.id}({root}) forces a device sync "
                              "— keep the value on device or batch the "
                              "fetch")

    def _check_truthiness(self, fn: ast.FunctionDef, jf):
        traced = set(jf.params) - jf.static_names
        for node in own_nodes(fn):
            if isinstance(node, (ast.If, ast.While, ast.Assert)):
                for name in self._bare_refs(node.test):
                    if name in traced:
                        yield self._v(node, "Python truthiness/branch on "
                                      f"traced parameter {name!r} inside a "
                                      "jitted function — use lax.cond/"
                                      "where or make it static")

    @staticmethod
    def _bare_refs(test) -> set[str]:
        """Names referenced by a branch test, minus host-static forms:
        `.shape/.ndim/.dtype/.size` accesses and `is (not) None` checks."""
        out: set[str] = set()

        def walk(node):
            if isinstance(node, ast.Attribute) and node.attr in (
                    "shape", "ndim", "dtype", "size"):
                return                          # static under tracing
            if isinstance(node, ast.Compare) and all(
                    isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                return                          # identity checks are host
            if isinstance(node, ast.Name) and isinstance(node.ctx,
                                                         ast.Load):
                out.add(node.id)
            for child in ast.iter_child_nodes(node):
                walk(child)

        walk(test)
        return out

    def _v(self, node, msg) -> Violation:
        return Violation(self.name, "", getattr(node, "lineno", 0), msg)


register(HostSyncChecker)
