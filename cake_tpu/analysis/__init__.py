"""Static analysis for the serving hot path: an AST checker framework
plus runtime sanitizers.

Five invariants keep the continuous-batching engine fast, and all of
them are invisible to the test suite until they regress in production:

  * `host-sync` — hot loops perform exactly the planned device->host
    fetches and no accidental ones;
  * `recompile-hazard` — steady-state decode never compiles a new
    executable (stable jit cache keys, no shape-branching surprises);
  * `use-after-donate` — buffers passed at donate_argnums positions are
    dead; the name must be rebound before the next read;
  * `knob-registry` — every CAKE_* env read goes through cake_tpu.knobs
    (typed default, generated docs);
  * `metric-registry` — every Counter/Gauge/Histogram name constructed
    under cake_tpu/ appears in the generated metric catalog
    (docs/observability.md);
  * `lock-discipline` — `# guarded-by:` annotated fields are only
    touched under their lock;

plus `hot-timing` (absorbed from PR 1's check_hot_timing.py): wall-clock
calls on hot paths belong to cake_tpu.obs.

Run `python -m cake_tpu.analysis` (or `make lint`); suppress a deliberate
violation in-line with `# lint: disable=<rule> — <reason>` (the reason is
mandatory). The runtime complements live in `analysis.sanitizers`:
`assert_no_recompiles` and `no_implicit_transfers` wrap steady-state
decode in tests. See docs/static_analysis.md.
"""
from __future__ import annotations

from .core import (RULES, Checker, SourceFile, Violation, check_file,
                   iter_py_files, register, run_paths)
from .hot_paths import HOT_PATHS, is_hot

# importing the check_* modules registers the rules
from . import (check_donation, check_host_sync, check_hot_timing,  # noqa: F401,E402
               check_knobs, check_locks, check_metrics, check_recompile)

__all__ = ["RULES", "Checker", "SourceFile", "Violation", "check_file",
           "iter_py_files", "register", "run_paths", "HOT_PATHS", "is_hot"]
