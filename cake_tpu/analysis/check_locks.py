"""Rule `lock-discipline`: `# guarded-by:` annotated fields only touched
under their lock.

The serve engine and the cluster master share mutable state between the
scheduler/request thread and background threads (SSE subscriber bridges,
the degraded-worker restore loop). The convention: the `__init__`
assignment that creates a cross-thread field carries

    self._token_cb = None        # guarded-by: self._sub_lock

and every OTHER method access of that field must sit lexically inside
`with self._sub_lock:`. The checker is what makes the comment load-
bearing — an unguarded access is a build failure, not a data race found
in production.

Scope notes: annotations bind per class; `__init__` itself is exempt
(nothing is shared before construction completes); the guard must be the
annotated lock (a different lock does not count).
"""
from __future__ import annotations

import ast
import re

from .core import Checker, SourceFile, Violation, register

_GUARD_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][\w.]*)")


def _attr_self(node) -> str | None:
    """`self.X` -> "X" (single level only)."""
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _unparse(node) -> str:
    try:
        return ast.unparse(node)
    except Exception:
        return ""


class LockDisciplineChecker(Checker):
    name = "lock-discipline"
    doc = ("fields annotated `# guarded-by: <lock>` accessed outside "
           "`with <lock>:` in methods of their class")

    def check(self, sf: SourceFile):
        for cls in ast.walk(sf.tree):
            if isinstance(cls, ast.ClassDef):
                yield from self._check_class(sf, cls)

    def _guarded_fields(self, sf, cls) -> dict[str, str]:
        """field -> lock expr string, from annotated assignments anywhere
        in the class body (same line or the standalone comment above)."""
        out: dict[str, str] = {}
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AnnAssign):
                targets = [node.target]
            else:
                continue
            fields = [f for f in map(_attr_self, targets) if f]
            if not fields:
                continue
            for line in (node.lineno, node.lineno - 1):
                if 1 <= line <= len(sf.lines):
                    m = _GUARD_RE.search(sf.lines[line - 1])
                    if m and (line == node.lineno
                              or sf.lines[line - 1].strip().startswith("#")):
                        for f in fields:
                            out[f] = m.group(1)
                        break
        return out

    def _check_class(self, sf, cls: ast.ClassDef):
        guarded = self._guarded_fields(sf, cls)
        if not guarded:
            return
        for meth in cls.body:
            if not isinstance(meth, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if meth.name in ("__init__", "__del__"):
                continue
            yield from self._walk(sf, meth.body, guarded, frozenset())

    def _walk(self, sf, body, guarded, held):
        for node in body:
            held_here = held
            if isinstance(node, ast.With):
                locks = {_unparse(item.context_expr)
                         for item in node.items}
                held_here = held | frozenset(locks)
                yield from self._scan_exprs(
                    sf, [i.context_expr for i in node.items], guarded, held)
                yield from self._walk(sf, node.body, guarded, held_here)
                continue
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # nested callback defs: the lock is NOT held when they run
                yield from self._walk(sf, node.body, guarded, frozenset())
                continue
            children = []
            for name in ("body", "orelse", "finalbody"):
                children.extend(getattr(node, name, []))
            for h in getattr(node, "handlers", []):
                children.extend(h.body)
            if children:
                tests = [getattr(node, a) for a in ("test", "iter")
                         if getattr(node, a, None) is not None]
                yield from self._scan_exprs(sf, tests, guarded, held)
                yield from self._walk(sf, children, guarded, held_here)
            else:
                yield from self._scan_exprs(sf, [node], guarded, held)

    def _scan_exprs(self, sf, nodes, guarded, held):
        for top in nodes:
            for node in ast.walk(top):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    continue
                field = _attr_self(node)
                if field is None or field not in guarded:
                    continue
                lock = guarded[field]
                if lock not in held:
                    yield Violation(
                        self.name, sf.rel, node.lineno,
                        f"self.{field} accessed without holding {lock} "
                        f"(declared `# guarded-by: {lock}`)")


register(LockDisciplineChecker)
