"""Rule `recompile-hazard`: unstable jit cache keys.

Steady-state decode must run existing executables — a recompile is a
multi-second stall that shows up as a wedged `/health` and a latency
cliff for every active slot. Two AST-detectable hazard classes:

  1. Unstable static arguments at call sites of jitted functions: an
     f-string, dict/list/set literal, float literal/expression, or a
     wall-clock/random call passed at a `static_argnames`/`static_argnums`
     position keys the executable cache on a value that varies per call
     (or is unhashable). Static args must be drawn from a small closed
     set — ints, enums, quantized buckets.

  2. Shape-dependent Python branching inside a traced function: an
     `if`/`while` on `.shape`/`.ndim` of a NON-static parameter re-traces
     per shape class. Where that is deliberate bucketing (the branch is
     resolved by a static bucket count), say so with
     `# lint: disable=recompile-hazard — <why>`.

The runtime recompile sanitizer (analysis.sanitizers.assert_no_recompiles)
is the dynamic complement: it pins "N steady-state iterations, zero new
executables" in tests.
"""
from __future__ import annotations

import ast

from .core import Checker, SourceFile, Violation, register
from .jitinfo import (collect_attr_bindings, collect_jit_fns, dotted_name,
                      resolve_jit_callee)

_UNSTABLE_CALLS = {"now", "time.time", "time.monotonic",
                   "time.perf_counter", "uuid.uuid4", "id", "hash",
                   "random.random", "random.randint"}


def _unstable_reason(expr) -> str | None:
    """Why this expression is a bad static-arg cache key, or None."""
    if isinstance(expr, ast.JoinedStr):
        return "f-string (new str per call)"
    if isinstance(expr, (ast.Dict, ast.Set, ast.DictComp, ast.SetComp,
                         ast.ListComp, ast.GeneratorExp)):
        return "dict/set/comprehension literal (unhashable or per-call)"
    if isinstance(expr, ast.List):
        return "list literal (unhashable)"
    if isinstance(expr, ast.Constant) and isinstance(expr.value, float):
        return "float literal (cache keyed per exact float)"
    if isinstance(expr, ast.Call):
        name = dotted_name(expr.func) or ""
        if name in _UNSTABLE_CALLS:
            return f"{name}() varies per call"
    if isinstance(expr, ast.BinOp):
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Constant) and isinstance(sub.value,
                                                            float):
                return "float arithmetic (cache keyed per exact float)"
            if isinstance(sub, ast.Div):
                return "float division (cache keyed per exact float)"
    return None


class RecompileChecker(Checker):
    name = "recompile-hazard"
    doc = ("unstable jit cache keys: per-call-varying static args and "
           "shape-dependent Python branching inside traced functions")

    def check(self, sf: SourceFile):
        jits = collect_jit_fns(sf.tree)
        bindings = collect_attr_bindings(sf.tree)
        jit_nodes = {id(j.node): j for j in jits.values()}

        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call):
                jf = resolve_jit_callee(node, jits, bindings)
                if jf is not None and jf.static_names:
                    yield from self._check_static_args(sf, node, jf)
            elif isinstance(node, ast.FunctionDef) \
                    and id(node) in jit_nodes:
                yield from self._check_shape_branches(sf, node,
                                                      jit_nodes[id(node)])

    def _check_static_args(self, sf, call: ast.Call, jf):
        for i, arg in enumerate(call.args):
            if i < len(jf.params) and jf.params[i] in jf.static_names:
                why = _unstable_reason(arg)
                if why:
                    yield Violation(self.name, sf.rel, arg.lineno,
                                    f"static arg {jf.params[i]!r} of jitted "
                                    f"{jf.name!r} is {why}")
        for kw in call.keywords:
            if kw.arg in jf.static_names:
                why = _unstable_reason(kw.value)
                if why:
                    yield Violation(self.name, sf.rel, kw.value.lineno,
                                    f"static arg {kw.arg!r} of jitted "
                                    f"{jf.name!r} is {why}")

    def _check_shape_branches(self, sf, fn: ast.FunctionDef, jf):
        from .check_host_sync import own_nodes
        traced = set(jf.params) - jf.static_names
        for node in own_nodes(fn):
            if not isinstance(node, (ast.If, ast.While)):
                continue
            for sub in ast.walk(node.test):
                if isinstance(sub, ast.Attribute) \
                        and sub.attr in ("shape", "ndim"):
                    base = sub.value
                    if isinstance(base, ast.Name) and base.id in traced:
                        yield Violation(
                            self.name, sf.rel, node.lineno,
                            f"Python branch on {base.id}.{sub.attr} inside "
                            f"jitted {jf.name!r} re-traces per shape — "
                            "bucket the shape statically or mask instead")


register(RecompileChecker)
