"""Rule `hot-timing`: ad-hoc wall-clock calls on serving hot paths.

PR 1's standalone `scripts/check_hot_timing.py`, absorbed into the
framework (same banned-call list, same hot-path scoping, now AST-based so
comments and strings cannot false-positive). cake_tpu/obs is the single
owner of wall-clock deltas on hot paths: stats use `obs.now()`, phase
accounting uses `obs.PhaseTimer` / `RECORDER.span`. Before it existed,
three ad-hoc timing idioms drifted apart; this rule keeps new ones from
creeping back in.

`time.sleep` stays legal — it is a scheduling primitive, not a
measurement.
"""
from __future__ import annotations

import ast

from .core import Checker, SourceFile, Violation, register
from .hot_paths import is_hot

_BANNED_ATTRS = {"monotonic", "time", "perf_counter", "monotonic_ns",
                 "perf_counter_ns", "time_ns"}


class HotTimingChecker(Checker):
    name = "hot-timing"
    doc = ("ad-hoc time.monotonic()/time.time()/time.perf_counter() on "
           "hot paths — route through cake_tpu.obs (now() / PhaseTimer / "
           "RECORDER.span)")

    def applies(self, sf: SourceFile) -> bool:
        return is_hot(sf.rel) and not sf.rel.startswith("cake_tpu/obs/")

    def check(self, sf: SourceFile):
        # names imported straight off the time module also count
        from_time: set[str] = set()
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ImportFrom) and node.module == "time":
                from_time.update(a.asname or a.name for a in node.names
                                 if a.name in _BANNED_ATTRS)
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            hit = None
            if isinstance(fn, ast.Attribute) and fn.attr in _BANNED_ATTRS \
                    and isinstance(fn.value, ast.Name) \
                    and fn.value.id == "time":
                hit = f"time.{fn.attr}"
            elif isinstance(fn, ast.Name) and fn.id in from_time:
                hit = fn.id
            if hit:
                yield Violation(
                    self.name, sf.rel, node.lineno,
                    f"{hit}() on a hot path — use cake_tpu.obs.now() / "
                    "PhaseTimer / RECORDER.span so timings land in the "
                    "metrics rail")


register(HotTimingChecker)
