"""Checker framework: rule registry, per-line suppressions, file walking.

PR 1's `check_hot_timing.py` proved that a 50-line grep can keep an
invariant alive across refactors; this module generalizes it into an
AST-based pass so the serving plane's four load-bearing invariants
(sync-free hot loops, recompile-free steady state, no use-after-donate,
lock-guarded shared state) are enforced by tooling rather than review.

Rules are classes registered with :func:`register`; each sees a parsed
:class:`SourceFile` and yields :class:`Violation`s. A violation is fatal
unless the offending line carries a suppression WITH a written reason:

    x = np.asarray(packed)  # lint: disable=host-sync — the one per-iter fetch

    # lint: disable=host-sync — standalone comments suppress the next line
    x = np.asarray(packed)

A suppression without a reason is itself a violation (`suppression-format`)
— the reason string is the code-review record of why the rule does not
apply, and an unexplained disable is exactly the drift this pass exists to
stop. Run `python -m cake_tpu.analysis` (or `make lint`).
"""
from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field

# rule name -> checker instance; populated by the check_* modules at
# package import (see __init__.py)
RULES: dict[str, "Checker"] = {}


def register(cls):
    inst = cls()
    if inst.name in RULES:
        raise ValueError(f"duplicate rule {inst.name!r}")
    RULES[inst.name] = inst
    return cls


@dataclass
class Violation:
    rule: str
    rel: str                    # repo-relative posix path
    line: int
    msg: str
    suppressed: bool = False
    reason: str = ""

    def render(self) -> str:
        tag = f" [suppressed: {self.reason}]" if self.suppressed else ""
        return f"{self.rel}:{self.line}: {self.rule}: {self.msg}{tag}"


class Checker:
    """One rule. Subclasses set `name`/`doc` and implement `check`."""

    name = ""
    doc = ""

    def applies(self, sf: "SourceFile") -> bool:
        return True

    def check(self, sf: "SourceFile"):
        raise NotImplementedError


# `—`, `--` or `:` separates the rule list from the mandatory reason
_SUPPRESS_RE = re.compile(
    r"#\s*lint:\s*disable\s*=\s*([A-Za-z0-9_,\s-]+?)\s*(?:—|--|:)\s*(\S.*)$")
# require at least one valid rule character after `=` so prose ABOUT the
# syntax (`# lint: disable=<rule> — <reason>` in docstrings) stays inert
_SUPPRESS_ANY_RE = re.compile(r"#\s*lint:\s*disable\s*=\s*[A-Za-z0-9_-]")


class SourceFile:
    """A parsed file plus its suppression table. `rel` is the repo-relative
    posix path — rules scope themselves by it (tests hand in virtual
    paths to place fixture snippets on the hot-path set)."""

    def __init__(self, rel: str, text: str):
        self.rel = rel.replace(os.sep, "/")
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text)
        # line -> {rule: reason}; rule "all" blankets every rule
        self.suppressions: dict[int, dict[str, str]] = {}
        self.format_errors: list[Violation] = []
        self._parse_suppressions()

    def _parse_suppressions(self):
        for i, line in enumerate(self.lines, 1):
            if "lint:" not in line:
                continue
            m = _SUPPRESS_RE.search(line)
            if not m:
                if _SUPPRESS_ANY_RE.search(line):
                    self.format_errors.append(Violation(
                        "suppression-format", self.rel, i,
                        "suppression needs a reason: "
                        "`# lint: disable=<rule> — <why this is ok>`"))
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            reason = m.group(2).strip()
            # a standalone comment line suppresses the next line of code;
            # a trailing comment suppresses its own line
            target = i
            if line.strip().startswith("#"):
                target = i + 1
                while target <= len(self.lines) and (
                        not self.lines[target - 1].strip()
                        or self.lines[target - 1].strip().startswith("#")):
                    target += 1
            tab = self.suppressions.setdefault(target, {})
            for r in rules:
                tab[r] = reason

    def suppression_for(self, rule: str, line: int) -> str | None:
        tab = self.suppressions.get(line)
        if not tab:
            return None
        if rule in tab:
            return tab[rule]
        return tab.get("all")


def check_file(sf: SourceFile, rules: list[str] | None = None
               ) -> list[Violation]:
    """All violations in one file, suppressed ones flagged (never
    dropped — the runner prints them in verbose mode and tests assert
    the roundtrip)."""
    out = list(sf.format_errors)
    selected = RULES if rules is None else {
        r: RULES[r] for r in rules}     # KeyError on unknown rule is right
    for checker in selected.values():
        if not checker.applies(sf):
            continue
        for v in checker.check(sf):
            reason = sf.suppression_for(v.rule, v.line)
            if reason is not None:
                v.suppressed = True
                v.reason = reason
            out.append(v)
    out.sort(key=lambda v: (v.line, v.rule))
    return out


def repo_root() -> str:
    """The directory holding the cake_tpu package."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def iter_py_files(paths: list[str] | None = None):
    """Yield (rel, abspath) for every .py under the given paths (default:
    the cake_tpu package + scripts/), rel computed against the repo root."""
    root = repo_root()
    if not paths:
        paths = [os.path.join(root, "cake_tpu"),
                 os.path.join(root, "scripts")]
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isfile(p):
            yield os.path.relpath(p, root).replace(os.sep, "/"), p
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    ap = os.path.join(dirpath, fn)
                    yield (os.path.relpath(ap, root).replace(os.sep, "/"),
                           ap)


def run_paths(paths: list[str] | None = None,
              rules: list[str] | None = None) -> list[Violation]:
    out = []
    for rel, ap in iter_py_files(paths):
        with open(ap, encoding="utf-8") as f:
            text = f.read()
        try:
            sf = SourceFile(rel, text)
        except SyntaxError as e:
            out.append(Violation("parse-error", rel, e.lineno or 0, str(e)))
            continue
        out.extend(check_file(sf, rules))
    return out
