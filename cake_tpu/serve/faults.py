"""Deterministic fault injection for the serve engine's step path.

The supervisor (serve/supervisor.py) exists because a single bad step used
to kill serving forever — and every one of its recovery paths must be
testable on CPU without real hardware faults. This module is the serve
plane's analog of cluster/faults.py: where that one hooks the wire's
framed read/write, this one hooks the scheduler's device dispatches
(`ServeEngine._step`'s batched decode, every prefill chunk, and the
speculative verify step) through a single module attribute the engine
checks once per dispatch (`faults.FAULT_HOOK` — nothing on import, one
attribute read when disabled).

An "op" is one batched decode (or spec-verify) dispatch — ONE SCHEDULER
ITERATION, not one token: a 3-slot pool emits ~3 tokens per op, so place
kill-steps by iteration count. The counter keeps running across the
rebuilds a fault provokes, which is what makes multi-crash plans
(`times=K`) deterministic.

A fault plan is a comma-separated list of `key=val[;key=val...]` clauses
from the `CAKE_SERVE_FAULT_PLAN` env var (read when this module is first
imported — tests use `install()`/`clear()`). Keys:

    raise_on_step=N     decode dispatch N raises (1-based); with times=K
                        dispatches N..N+K-1 all raise (default K=1 —
                        `kind=oom` + the default times=1 is the oom-once
                        drill)
    times=K             how many consecutive dispatches raise_on_step kills
    kind=K              the injected exception's fault_kind seeding the
                        supervisor's classifier: internal | device | oom
    stall_on_step=N     decode dispatch N stalls stall_step_ms on the
                        scheduler thread BEFORE dispatch, once (the wedge
                        watchdog drill; default N=1)
    stall_step_ms=S     how long that one stall lasts
    delay_ms=D          every decode dispatch sleeps D ms first (gray
                        degradation: slow-but-alive, and a deterministic
                        pace for deadline tests)
    poison_token=T      any dispatch touching a request whose PROMPT
                        contains token id T raises — EVERY time, decode
                        and prefill both, which is what lets the
                        supervisor's replay bisection re-trigger and
                        attribute it (a poisoned request stays poisoned)
    poison_after_ops=N  poison arms only after N decode ops, so the
                        poisoned request can admit cleanly and corrupt
                        the pool MID-generation (the hard case)

The stall sleeps on the scheduler thread by design: a scheduler stuck
inside a device call IS the wedge being simulated.
"""
from __future__ import annotations

import logging
import time
from dataclasses import dataclass

from .. import knobs

log = logging.getLogger("cake_tpu.serve.faults")

__all__ = ["FAULT_HOOK", "InjectedFault", "ServeFaultInjector",
           "parse_plan", "install", "active", "clear"]

# the engine's per-dispatch seam: None (one attribute check) when disabled
FAULT_HOOK = None


class InjectedFault(RuntimeError):
    """A planned failure. `fault_kind` seeds supervisor.classify(), so a
    plan can drill the device/oom/internal recovery paths distinctly."""

    def __init__(self, msg: str, fault_kind: str = "internal"):
        super().__init__(msg)
        self.fault_kind = fault_kind


@dataclass
class ServeFaultInjector:
    """One plan clause; the engine invokes the hooks below per dispatch.
    All state lives here so it survives the rebuilds it provokes."""

    raise_on_step: int | None = None
    times: int = 1
    kind: str = "internal"
    stall_on_step: int = 1
    stall_step_ms: float = 0.0
    delay_ms: float = 0.0
    poison_token: int | None = None
    poison_after_ops: int = 0
    ops: int = 0                # decode dispatches seen (1-based after inc)
    stalled: bool = False

    _INT_KEYS = ("raise_on_step", "times", "stall_on_step", "poison_token",
                 "poison_after_ops")
    _FLOAT_KEYS = ("stall_step_ms", "delay_ms")

    @classmethod
    def parse(cls, clause: str) -> "ServeFaultInjector":
        inj = cls()
        for part in filter(None, (p.strip() for p in clause.split(";"))):
            if "=" not in part:
                raise ValueError(f"fault clause needs key=value: {part!r}")
            k, v = (s.strip() for s in part.split("=", 1))
            if k in cls._INT_KEYS:
                setattr(inj, k, int(v))
            elif k in cls._FLOAT_KEYS:
                setattr(inj, k, float(v))
            elif k == "kind":
                if v not in ("internal", "device", "oom"):
                    raise ValueError(f"unknown fault kind {v!r}")
                inj.kind = v
            else:
                raise ValueError(f"unknown serve fault key {k!r}")
        return inj

    # -- engine seams -------------------------------------------------------

    def on_decode(self, reqs) -> None:
        """Before a batched decode / spec-verify dispatch; `reqs` are the
        active ServeRequests riding it."""
        self.ops += 1
        if self.delay_ms > 0:
            time.sleep(self.delay_ms / 1e3)
        if (self.stall_step_ms > 0 and not self.stalled
                and self.ops >= self.stall_on_step):
            self.stalled = True
            log.warning("serve fault: stalling dispatch %d for %.0f ms",
                        self.ops, self.stall_step_ms)
            time.sleep(self.stall_step_ms / 1e3)
        if (self.raise_on_step is not None
                and self.raise_on_step <= self.ops
                < self.raise_on_step + self.times):
            log.warning("serve fault: raising %s at dispatch %d",
                        self.kind, self.ops)
            raise InjectedFault(
                f"fault injected: step {self.ops} "
                + ("RESOURCE_EXHAUSTED: out of memory"
                   if self.kind == "oom" else f"{self.kind} failure"),
                fault_kind=self.kind)
        self._poison_check(reqs)

    def on_prefill(self, req) -> None:
        """Before one prefill chunk (admission AND rebuild-replay) of
        `req` — poison re-fires here, which is exactly how the
        supervisor's solo replay attributes it."""
        self._poison_check((req,))

    def _poison_check(self, reqs) -> None:
        if self.poison_token is None or self.ops < self.poison_after_ops:
            return
        for r in reqs:
            if self.poison_token in r.prompt_ids:
                raise InjectedFault(
                    f"fault injected: poison token {self.poison_token} "
                    f"in request {r.id}", fault_kind="internal")


def parse_plan(spec: str) -> ServeFaultInjector:
    clauses = [c for c in (s.strip() for s in spec.split(",")) if c]
    if len(clauses) != 1:
        raise ValueError("serve fault plans take exactly one clause")
    return ServeFaultInjector.parse(clauses[0])


def install(spec_or_injector) -> ServeFaultInjector:
    """Activate a fault plan process-wide (faults.FAULT_HOOK)."""
    global FAULT_HOOK
    inj = (spec_or_injector
           if isinstance(spec_or_injector, ServeFaultInjector)
           else parse_plan(spec_or_injector))
    FAULT_HOOK = inj
    log.warning("serve fault plan installed: %s", inj)
    return inj


def active() -> ServeFaultInjector | None:
    return FAULT_HOOK


def clear() -> None:
    global FAULT_HOOK
    FAULT_HOOK = None


# env-driven activation, mirroring cluster/faults.py: the plan takes
# effect the moment the serve plane loads (engine.py imports this module)
_env_plan = knobs.get_str("CAKE_SERVE_FAULT_PLAN")
if _env_plan:
    install(_env_plan)
