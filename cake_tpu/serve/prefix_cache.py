"""Shared-prefix KV cache: device-resident LRU of prefill'd prefix blocks.

Chat traffic shares its system prompt across requests, and the engine used
to recompute the identical prefix KV on every admission. This cache keeps
that work (RadixAttention's insight — SGLang, Zheng et al. 2023 — minus
the radix tree): the prompt's prefix is cut into fixed BLOCK-sized pieces
at chunked-prefill boundaries, each block's KV (plus the linear-attention
conv/recurrent state snapshot at the block's end) is copied out of the
pool row right after the chunk that completed it, and a later admission
whose prompt starts with the same tokens splices the matched chain back
into its row and prefills only the suffix.

Matching is a hash CHAIN, which gives longest-prefix-match without a trie:
block b's key is blake2b(prompt[: (b+1)*block]) — equal key chains iff
equal prefixes — so lookup walks b = 0, 1, ... until the first miss. The
stored token prefix is compared on every hit, so a hash collision can
degrade performance but never output correctness. Reuse is capped at
n-1 tokens: the final prompt token is always prefilled live, because its
logits seed the first sampled token.

Capacity is CAKE_PREFIX_CACHE_MB of device bytes (LRU over blocks; a
middle eviction just shortens the matchable chain). Everything here runs
on the engine's scheduler thread — no locking; the entries are plain jnp
arrays, so eviction is a dict pop and the buffers free with their last
reference.

Greedy outputs are BIT-identical between a hit and a miss: splicing
copies the exact bytes prefill wrote, and the suffix chunks land on the
same chunk-bucket boundaries either way (block size == chunk size), so
every matmul sees the same shapes and inputs.
"""
from __future__ import annotations

import hashlib
from collections import OrderedDict
from dataclasses import dataclass

import numpy as np

from ..obs import (SERVE_PREFIX_BYTES, SERVE_PREFIX_EVICTIONS,
                   SERVE_PREFIX_HITS, SERVE_PREFIX_MISSES)

__all__ = ["PrefixCache", "PagedPrefixCache"]


@dataclass
class _Block:
    tokens: np.ndarray      # the FULL prefix this block completes (verify)
    layers: list            # batch-1 layers pytree, this block's KV + state
    nbytes: int


@dataclass
class _PagedEntry:
    tokens: np.ndarray      # the FULL prefix this unit completes (verify)
    pids: list              # physical block ids this entry PINS (refcount)
    snap: list | None       # boundary row snapshot (SWA rings + linear
                            # state), installed only as a chain's FINAL unit
    nbytes: int


def _tree_bytes(layers) -> int:
    total = 0
    for lc in layers:
        for buf in lc.values():
            total += int(np.prod(buf.shape)) * buf.dtype.itemsize
    return total


class PrefixCache:
    """LRU of prefix blocks for ONE engine (scheduler-thread only)."""

    def __init__(self, model, block: int, capacity_bytes: int):
        self.model = model
        self.block = block
        self.capacity = capacity_bytes
        self._blocks: OrderedDict[bytes, _Block] = OrderedDict()
        self.bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        # membership version: bumped on every insert/evict (NOT on LRU
        # touches) so the kvshare inventory mirror refreshes only when
        # the key set actually changed
        self.version = 0

    @classmethod
    def build(cls, model, ctx: int, block: int,
              capacity_mb: float) -> "PrefixCache | None":
        """None when disabled (capacity <= 0) or structurally unsound: a
        sliding window smaller than the block would evict a block's own
        entries from the ring before they could be extracted, and
        linear-attention snapshots need the block to fit the row."""
        if capacity_mb <= 0 or block > ctx:
            return None
        for spec in model.cfg.layer_specs():
            if spec.window is not None and spec.window < block:
                return None
        return cls(model, block, int(capacity_mb * 1024 * 1024))

    # -- admission-side API -------------------------------------------------

    def chain_keys(self, prompt_ids: list[int]) -> list[bytes]:
        """Key of every block this prompt could match OR contribute
        ((n-1)//block of them — reuse keeps >= 1 live suffix token, and
        the same cap bounds what prefill can capture). One incremental
        blake2b pass per ADMISSION; the engine holds the list for the
        admission's lifetime so match/splice/insert never re-hash."""
        ids = np.asarray(prompt_ids, np.int32)
        h = hashlib.blake2b(digest_size=16)
        keys = []
        for b in range((len(ids) - 1) // self.block):
            h.update(ids[b * self.block:(b + 1) * self.block].tobytes())
            keys.append(h.digest())
        return keys

    def match(self, prompt_ids: list[int], keys: list[bytes]) -> int:
        """Longest cached block chain usable for this prompt, in BLOCKS
        (0 = miss). Refreshes LRU recency of every matched block and
        records the hit/miss counters — except for prompts structurally
        too short to ever hit (<= block tokens, zero keys), which would
        otherwise skew the hit ratio an operator sizes the cache by."""
        if not keys:
            return 0
        ids = np.asarray(prompt_ids, np.int32)
        matched = 0
        for key in keys:
            blk = self._blocks.get(key)
            if blk is None or not np.array_equal(
                    blk.tokens, ids[:len(blk.tokens)]):
                break
            self._blocks.move_to_end(key)
            matched += 1
        if matched:
            self.hits += 1
            SERVE_PREFIX_HITS.inc()
        else:
            self.misses += 1
            SERVE_PREFIX_MISSES.inc()
        return matched

    def splice(self, layers, slot: int, keys: list[bytes], matched: int):
        """Write the matched chain's KV into pool row `slot` (row must be
        freshly wiped). Returns the updated pool layers."""
        for b in range(matched):
            layers = self.model.slot_splice(
                layers, self._blocks[keys[b]].layers, slot,
                final=(b == matched - 1))
        return layers

    def insert(self, layers, slot: int, prompt_ids: list[int],
               block_index: int, keys: list[bytes]) -> None:
        """Capture block `block_index` out of row `slot`. Must be called at
        the chunk boundary that completed the block — the row then holds
        exactly prefix_len tokens, so the linear-attention snapshot is the
        exact prefix state. Dedupes on key; evicts LRU past capacity."""
        end = (block_index + 1) * self.block
        ids = np.asarray(prompt_ids[:end], np.int32)
        key = keys[block_index]
        if key in self._blocks:
            self._blocks.move_to_end(key)
            return
        entry_layers = self.model.slot_extract(
            layers, slot, block_index * self.block, self.block)
        blk = _Block(tokens=ids, layers=entry_layers,
                     nbytes=_tree_bytes(entry_layers))
        if blk.nbytes > self.capacity:
            return                          # could never fit; don't thrash
        while self.bytes + blk.nbytes > self.capacity and self._blocks:
            _, old = self._blocks.popitem(last=False)
            self.bytes -= old.nbytes
            self.evictions += 1
            self.version += 1
            SERVE_PREFIX_EVICTIONS.inc()
        self._blocks[key] = blk
        self.bytes += blk.nbytes
        self.version += 1
        SERVE_PREFIX_BYTES.set(self.bytes)

    # -- introspection ------------------------------------------------------

    def occupancy(self) -> dict:
        return {
            "blocks": len(self._blocks),
            "block_tokens": self.block,
            "bytes": self.bytes,
            "capacity_bytes": self.capacity,
            "utilization": round(self.bytes / self.capacity, 4)
            if self.capacity else 0.0,
            "hits": self.hits,
            "misses": self.misses,
            "evictions": self.evictions,
        }


class PagedPrefixCache(PrefixCache):
    """Prefix index over SHARED paged-pool blocks (the allocator unified
    with the prefix cache): instead of extracting a block's bytes into a
    private copy, an insert PINS the live slot's physical blocks by
    refcount, and a hit maps those same blocks into the new slot's table
    — a prefix hit moves ZERO KV bytes (observable as the
    cake_serve_kv_blocks_shared gauge going positive). Only the boundary
    row snapshot (SWA rings + linear-attention conv/recurrent state, a
    few KB) is copied, because that state is per-slot, not pooled; it is
    installed for the FINAL matched unit exactly like the contiguous
    splice's `final` flag — the same boundary-exact GDN rule.

    The share unit stays one CHUNK of tokens (== chunk // block_tokens
    physical blocks), so the hash chain, match cap (n-1 live tokens) and
    capture boundaries are identical to the contiguous cache — match()
    and chain_keys() are inherited unchanged.

    Cache-held blocks are RECLAIMABLE capacity: the allocator evicts LRU
    units under allocation pressure (evict_for_pressure, wired as
    PagedKV.evictor), so the cache can use every otherwise-idle block
    without ever starving admissions. The contiguous gate "sliding
    window >= block" does not apply here — SWA state rides the boundary
    snapshot, not per-block ring extracts."""

    def __init__(self, model, paged, unit: int, capacity_bytes: int):
        super().__init__(model, unit, capacity_bytes)
        self.paged = paged
        self.bpu = unit // paged.bt           # physical blocks per unit
        self.pinned = 0     # physical blocks currently cache-pinned (a
                            # single int so /health reads it race-free)

    @classmethod
    def build_paged(cls, model, paged, unit: int,
                    capacity_mb: float) -> "PagedPrefixCache | None":
        if capacity_mb <= 0 or unit > paged.ctx or unit % paged.bt:
            return None
        return cls(model, paged, unit, int(capacity_mb * 1024 * 1024))

    # -- admission-side API (paged semantics) -------------------------------

    def splice(self, layers, slot: int, keys: list[bytes], matched: int):
        """Map the matched chain's physical blocks into `slot`'s table
        (refcount bump per block — no KV copy) and install the final
        unit's row snapshot. Refs and mappings are taken host-side and
        the device table row is published ONCE (one scatter + one gauge
        publish per hit, not per block — admission hot path). `layers`
        is ignored (the paged engine keeps no contiguous pool) and
        returned untouched."""
        for b in range(matched):
            entry = self._blocks[keys[b]]
            for j, pid in enumerate(entry.pids):
                self.paged.alloc.ref(pid)
                self.paged.alloc.map(slot, b * self.bpu + j, pid)
        self.paged.sync_table_row(slot)
        final = self._blocks[keys[matched - 1]]
        if final.snap is not None:
            self.paged.rows = self.model.row_install(self.paged.rows,
                                                     final.snap, slot)
        return layers

    def insert(self, layers, slot: int, prompt_ids: list[int],
               block_index: int, keys: list[bytes]) -> None:
        """Pin unit `block_index` of `slot` as a shared entry. Must be
        called at the chunk boundary that completed the unit (the row
        snapshot is exact only there). `layers` is ignored. Dedupes on
        key — a concurrent admission that prefilled its own copy before
        this one captured keeps its private blocks (correct, just
        unshared)."""
        end = (block_index + 1) * self.block
        key = keys[block_index]
        if key in self._blocks:
            self._blocks.move_to_end(key)
            return
        pids = self.paged.alloc.tables[slot][block_index * self.bpu:
                                             (block_index + 1) * self.bpu]
        if self.paged.NULL in pids:
            return                  # row not fully backed (cannot happen
                                    # after a completed chunk; be safe)
        snap = None
        snap_bytes = 0
        if self.paged.has_rows:
            snap = self.model.row_snapshot(self.paged.rows, slot)
            snap_bytes = _tree_bytes(snap)
        nbytes = len(pids) * self.paged.block_bytes + snap_bytes
        if nbytes > self.capacity:
            return                          # could never fit; don't thrash
        while self.bytes + nbytes > self.capacity and self._blocks:
            self._evict_lru()
        for pid in pids:
            self.paged.alloc.ref(pid, cache_pin=True)
        self._blocks[key] = _PagedEntry(
            tokens=np.asarray(prompt_ids[:end], np.int32),
            pids=list(pids), snap=snap, nbytes=nbytes)
        self.bytes += nbytes
        self.version += 1
        self.pinned += len(pids)
        self.paged._publish()
        SERVE_PREFIX_BYTES.set(self.bytes)

    # -- eviction -----------------------------------------------------------

    def _evict_lru(self) -> int:
        """Drop the LRU entry; returns how many device blocks were
        actually FREED (0 when every pinned block is still mapped by a
        live slot)."""
        _, old = self._blocks.popitem(last=False)
        self.bytes -= old.nbytes
        self.evictions += 1
        self.version += 1
        SERVE_PREFIX_EVICTIONS.inc()
        freed = sum(1 for pid in old.pids
                    if self.paged.alloc.deref(pid, cache_pin=True))
        self.pinned -= len(old.pids)
        SERVE_PREFIX_BYTES.set(self.bytes)
        self.paged._publish()
        return freed

    def evict_for_pressure(self) -> int:
        """Allocator pressure hook (PagedKV.evictor): evict LRU entries
        until at least one block frees or the cache is empty. Returns
        blocks freed (0 = nothing reclaimable — escalate to
        preemption)."""
        while self._blocks:
            freed = self._evict_lru()
            if freed:
                return freed
        return 0

    def release_all(self) -> None:
        """Drop every entry and its pins (engine rebuild/shutdown of the
        paged pool; the allocator is being thrown away with us, so only
        the bookkeeping needs to stay consistent)."""
        while self._blocks:
            self._evict_lru()

    def occupancy(self) -> dict:
        out = super().occupancy()
        out["shared_blocks"] = self.paged.alloc.shared_count
        out["unit_blocks"] = self.bpu
        return out
