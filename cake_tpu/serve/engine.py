"""Continuous-batching scheduler: slot-based batched decode for concurrent
text serving.

The reference serializes every request through Arc<RwLock<Master>> (ref:
api/mod.rs:71) and the inherited locked path does the same — request N+1
waits for request N's entire decode. This engine applies iteration-level
scheduling (Orca, OSDI'22) with a fixed slot pool (vLLM's slot idea minus
paging — slots here are whole KV rows of a preallocated batch-B cache):

  * a bounded admission queue feeds a single scheduler thread;
  * admission is CHUNKED (Sarathi-Serve, OSDI'24): a queued request takes
    a free slot immediately (splicing any shared-prefix KV the PrefixCache
    already holds — see prefix_cache.py), then each iteration advances at
    most ONE in-flight admission by one CAKE_PREFILL_CHUNK-token chunk
    (`TextModel.prefill_chunk` scatters straight into the pool row at
    pos0), round-robin over in-flight prefills so a huge prompt cannot
    starve the queue behind it;
  * each iteration also runs ONE batched step over the occupied prefix
    (per-slot positions, RNG keys, recent-token windows, traced sampling
    params, and an `active` mask that freezes rows still mid-prefill):
    a plain `decode_slots` step, or — when a drafter is configured and
    proposed for any slot — a batched multi-token `spec_slots` verify in
    which every slot carries its own draft window and accepts a RAGGED
    per-slot prefix (Leviathan-style speculative decoding folded into
    continuous batching; the paged layout moves each slot's block cursor
    by its accepted length). Either way the iteration fans each slot's
    new tokens out to its request's stream — decode latency under
    admission is bounded by the CHUNK, not the prompt, which kills the
    head-of-line blocking a monolithic prefill imposed on every active
    decode;
  * EOS / budget / client-cancel free the slot for the next admission.

Every jax call happens on the scheduler thread, so the engine needs no
device-side locking; API handlers only touch thread-safe queues/events.
Greedy outputs are bit-identical to the sequential path (masked slots
contribute exactly-zero attention weight; chunked prefill reproduces the
monolithic program's numerics; a prefix-cache hit splices the exact bytes
a miss would recompute), which the tier-1 e2e tests pin.

The engine is CRASH-ONLY (supervisor.py owns the policy): a step failure
no longer kills serving — the supervisor classifies it, reallocates the
pool, and `_rebuild` replays every live slot's prompt+generated tokens
through the chunked-prefill path (the prefix cache makes shared prefixes
nearly free to replay; replay lands on the same chunk buckets admission
compiled). Greedy continuations across a rebuild are bit-identical —
every carry the decode program needs (last token, position, recent
window) is reconstructible from the host-side token record; sampled
(temperature > 0) streams resume on a FRESH rng fold, which is the one
documented parity exception. Repeated failures are budgeted; past the
budget the engine goes honestly DOWN (typed 503s, /health engine block,
restore probe) instead of silently dead — see docs/fault_tolerance.md.
"""
from __future__ import annotations

import logging
import queue as queue_mod
import threading
import time
import uuid

import jax
import jax.numpy as jnp
import numpy as np

from .. import knobs
from ..obs import (RECORDER, SERVE_BATCH_OCCUPANCY, SERVE_E2E_SECONDS,
                   SERVE_ITL_SECONDS, SERVE_PREFILL_CHUNKS, SERVE_POISONED,
                   SERVE_PREEMPTIONS, SERVE_QOS_E2E_SECONDS,
                   SERVE_QOS_TTFT_SECONDS, SERVE_QUEUE_TIMEOUTS,
                   SERVE_QUEUE_WAIT_SECONDS, SERVE_REQUEST_TIMEOUTS,
                   SERVE_SLOTS_BUSY, SERVE_TTFT_SECONDS, TIMELINES, now,
                   set_request_id)
from ..ops.sampling import SamplingConfig, config_has_filters
from ..spec import resolve_drafter
from ..spec.verify import record_step
from . import faults
from .admission import AdmissionQueue, QueueFull
from .admission.classes import class_of, priority
from .flight import FlightRecorder
from .paged import (KVPoolExhausted, PagedKV, PreemptedSlot, choose_victim,
                    victim_rank)
from .prefix_cache import PagedPrefixCache, PrefixCache
from .slots import SlotPool, slot_bucket
from .supervisor import (EngineDown, PoisonedRequest,
                         RequestDeadlineExceeded, Supervisor, classify)

__all__ = ["ServeEngine", "ServeRequest", "QueueFull", "EngineDraining",
           "QueueDeadlineExceeded", "EngineDown", "KVPoolExhausted",
           "PoisonedRequest", "RequestDeadlineExceeded", "maybe_engine"]

log = logging.getLogger("cake_tpu.serve")


class EngineDraining(RuntimeError):
    """Admission refused because the engine is draining for shutdown; the
    API answers 503 + Retry-After so load balancers fail the client over
    instead of letting it wait on a server that is leaving."""

    def __init__(self, retry_after_s: int = 5):
        super().__init__("serve engine draining for shutdown")
        self.retry_after_s = retry_after_s


class QueueDeadlineExceeded(RuntimeError):
    """The request sat in the admission queue past CAKE_QUEUE_DEADLINE_S:
    it is finished with 503 instead of eventually occupying a slot for a
    client that already gave up."""

    def __init__(self, waited_s: float, retry_after_s: int = 1):
        super().__init__(
            f"request expired in admission queue after {waited_s:.1f}s")
        self.waited_s = waited_s
        self.retry_after_s = retry_after_s

# device-resident repeat-penalty window per slot — derived from the
# SamplingConfig default so the engine's window can never silently diverge
# from the sequential path's (the API grid never varies repeat_last_n, so
# one static width serves all)
RECENT_N = SamplingConfig().repeat_last_n

# default pool row length when the model's max_cache_len is unbounded-ish:
# the pool is B x ctx x layers of KV, allocated up front. Derived from
# the registry so ServeEngine callers that pass ctx_len=None without
# going through maybe_engine can never drift from the knob default
DEFAULT_CTX = int(knobs.REGISTRY["CAKE_SERVE_CTX"].default)


def _pow2_chunk(n: int, ctx: int) -> int:
    """Clamp the prefill chunk to a power of two in [16, ctx] — fixed
    chunk buckets keep the per-(bucket, flash_mode) executable count at
    O(log chunk), and block-size == chunk-size keeps prefix-cache splice
    boundaries aligned with chunk boundaries (the bit-parity invariant)."""
    n = max(16, min(int(n), ctx))
    b = 16
    while b * 2 <= n:
        b *= 2
    return b


class _Prefill:
    """Scheduler-private state of one in-flight chunked admission."""

    __slots__ = ("req", "slot", "ids", "n", "pos", "chunks", "next_block",
                 "hit_tokens", "keys")

    def __init__(self, req: "ServeRequest", slot: int):
        self.req = req
        self.slot = slot
        self.ids = req.prompt_ids
        self.n = len(self.ids)
        self.pos = 0            # next prompt position to prefill
        self.chunks = 0         # chunks dispatched so far
        self.next_block = 0     # next prefix-cache block index to capture
        self.hit_tokens = 0     # tokens skipped via prefix-cache splice
        self.keys: list = []    # per-block hash chain (computed once)


class ServeRequest:
    """One submitted generation: token stream + terminal state.

    The engine fills `tokens`/`stats`/`error` (mirroring the legacy
    streamed-path result dict) and feeds `out_q` with Token objects ending
    in DONE. `cancel()` may be called from any thread — the scheduler
    frees the slot on its next iteration.
    """

    DONE = object()

    def __init__(self, prompt_ids: list[int], max_new_tokens: int,
                 sampling: SamplingConfig, request_id: str | None = None,
                 qos: str = "interactive", tenant: str | None = None,
                 continuation: bool = False):
        self.id = request_id or "serve-" + uuid.uuid4().hex[:16]
        self.prompt_ids = list(prompt_ids)
        self.max_new_tokens = max_new_tokens
        self.sampling = sampling or SamplingConfig()
        # QoS class (admission lane, weighted-fair share, preemption
        # rank) + tenant (quota accounting / timeline attribution)
        self.qos = qos
        self.tenant = tenant
        # continuation admission: the prompt's tail is a PARTIAL
        # assistant turn being continued (mid-stream resume splice or
        # client-side finish of a broken stream) — flagged through the
        # enqueue timeline event and stats so operators can tell a
        # splice prefill from a fresh conversation
        self.continuation = continuation
        self.out_q: queue_mod.Queue = queue_mod.Queue()
        self.cancelled = threading.Event()
        self.admitted = threading.Event()   # set when a slot is assigned
        self.done = threading.Event()
        self.result: dict = {}          # tokens / stats / error, like the
                                        # legacy streamed-path result dict
        self.tokens: list[int] = []
        self.stats: dict = {}
        self.t_enqueue = now()
        # delivery handoff state: written by API handler threads
        # registering subscribers, read by the scheduler thread fanning
        # tokens out (the lock-discipline lint enforces the annotations)
        self._sub_lock = threading.Lock()
        self._token_cb = None           # guarded-by: self._sub_lock
        self._done_cbs: list = []       # guarded-by: self._sub_lock
        # scheduler-owned fields
        self.slot: int | None = None
        self.budget = 0                 # decode tokens left after the first
        self.t_first = 0.0              # first-token timestamp (decode t0)
        self._first_pending = False     # first token sampled, not fetched
        self._engine = None

    def cancel(self):
        """Client disconnect: release the slot at the next iteration."""
        self.cancelled.set()
        eng = self._engine
        if eng is not None:
            eng._wake.set()

    def wait(self, timeout: float | None = None) -> bool:
        return self.done.wait(timeout)

    # -- delivery: push subscribers beat thread-parking -------------------
    # API handlers register callbacks instead of blocking an executor
    # thread per in-flight request (the default executor also serves
    # tokenization and every other endpoint — parking a thread per
    # generation would deadlock the server at exactly the concurrency
    # this engine exists to provide).

    def subscribe(self, cb) -> list:
        """Route future token/DONE deliveries through cb (invoked on the
        scheduler thread); returns the backlog accumulated so far."""
        backlog = []
        with self._sub_lock:
            while True:
                try:
                    backlog.append(self.out_q.get_nowait())
                except queue_mod.Empty:
                    break
            self._token_cb = cb
        return backlog

    def add_done_callback(self, cb):
        """cb fires (scheduler thread) when the request completes; fires
        immediately (caller thread) if it already has."""
        with self._sub_lock:
            if not self.done.is_set():
                self._done_cbs.append(cb)
                return
        cb()

    def _deliver(self, item):           # scheduler thread
        with self._sub_lock:
            cb = self._token_cb
            if cb is None:
                self.out_q.put(item)
        if cb is not None:
            try:
                cb(item)
            except Exception:
                pass                    # subscriber's loop may be gone

    def _fire_done(self):               # scheduler thread
        with self._sub_lock:
            self.done.set()
            cbs, self._done_cbs = self._done_cbs, []
        for cb in cbs:
            try:
                cb()
            except Exception:
                pass


class ServeEngine:
    """Owns the slot pool, the admission queue, and the scheduler thread."""

    def __init__(self, model, slots: int = 4, max_queue: int = 64,
                 ctx_len: int | None = None, seed: int = 0,
                 prefill_chunk: int | None = None,
                 prefix_cache_mb: float | None = None,
                 queue_deadline_s: float | None = None,
                 request_deadline_s: float | None = None,
                 spec=None, spec_k: int | None = None,
                 spec_reserve: int | None = None,
                 step_watchdog_s: float | None = None,
                 rebuild_budget: int | None = None,
                 rebuild_window_s: float | None = None,
                 restore_interval_s: float | None = None,
                 kv_blocks: int | None = None,
                 kv_block_tokens: int | None = None,
                 preempt_mode: str | None = None):
        if not hasattr(model, "decode_slots"):
            raise TypeError(
                f"{type(model).__name__} has no batched slot decode; the "
                "engine serves plain TextModels only (distributed/offload "
                "models keep the locked path)")
        self.model = model
        self.slots = slots
        self.ctx = min(ctx_len or DEFAULT_CTX, model.max_cache_len)
        if prefill_chunk is None:
            prefill_chunk = knobs.get("CAKE_PREFILL_CHUNK")
        self.chunk = _pow2_chunk(prefill_chunk, self.ctx)
        if prefix_cache_mb is None:
            prefix_cache_mb = knobs.get("CAKE_PREFIX_CACHE_MB")
        self._prefix_mb = prefix_cache_mb    # rebuilds reconstruct the cache
        # -- paged KV pool (CAKE_KV_BLOCKS > 0) ---------------------------
        # Replaces the worst-case-provisioned slots x ctx rows with a
        # shared pool of fixed-size blocks behind per-slot block tables:
        # memory follows actual sequence length, prefix hits become
        # refcount bumps, and exhaustion preempts a victim (swap or
        # recompute) instead of capping admission. 0 keeps the
        # contiguous pool (see docs/serving.md#paged-kv-pool).
        if kv_blocks is None:
            kv_blocks = knobs.get("CAKE_KV_BLOCKS")
        self.kv_blocks = max(int(kv_blocks), 0)
        if kv_block_tokens is None:
            kv_block_tokens = knobs.get("CAKE_KV_BLOCK_TOKENS")
        self.kv_block_tokens = kv_block_tokens
        if preempt_mode is None:
            preempt_mode = knobs.get("CAKE_PREEMPT_MODE")
        if preempt_mode not in ("swap", "recompute"):
            raise ValueError(
                f"CAKE_PREEMPT_MODE must be 'swap' or 'recompute', got "
                f"{preempt_mode!r}")
        self.preempt_mode = preempt_mode
        self.paged: PagedKV | None = None
        self._preempted: list[PreemptedSlot] = []
        # fleet-shared KV tier hook (fleet/kvshare/KVShareReplica), set
        # by the API server when CAKE_KVSHARE is on. Duck-typed on
        # purpose: serve never imports fleet. When set, _step drains its
        # scheduler-thread mailbox (blob export/import, stream parking)
        # before doing anything else, and health() carries its inventory
        self.kv_share = None
        self.pool = SlotPool(slots)
        self.queue = AdmissionQueue(max_queue)
        # per-request queue deadline (CAKE_QUEUE_DEADLINE_S, 0 disables):
        # a request whose client-side timeout has surely elapsed is 503ed
        # by the sweep instead of admitted into a slot nobody will read
        if queue_deadline_s is None:
            queue_deadline_s = knobs.get("CAKE_QUEUE_DEADLINE_S")
        self.queue_deadline_s = queue_deadline_s
        # per-request TOTAL deadline (CAKE_REQUEST_DEADLINE_S, 0 disables):
        # the queue sweep above only covers waiting — this one cancels
        # ADMITTED slots whose whole-request age expired (504, typed)
        if request_deadline_s is None:
            request_deadline_s = knobs.get("CAKE_REQUEST_DEADLINE_S")
        self.request_deadline_s = request_deadline_s
        # -- speculative decoding: batched over every occupied slot ------
        # CAKE_SPEC names the drafter ("ngram"; unset = off), CAKE_SPEC_K
        # the per-slot draft window. Speculation rides the SAME batched
        # iteration as plain decode: every occupied slot carries its own
        # draft window through one spec_slots dispatch with ragged
        # per-slot acceptance, so there is no occupancy cliff and no
        # paged-mode stand-down — a slot whose drafter abstains simply
        # takes a plain decode step inside the same executable.
        # CAKE_SPEC_RESERVE caps how much speculative frontier a paged
        # slot may reserve ahead of a verify (0 = the full window).
        drafter, k = resolve_drafter(spec, spec_k)
        if drafter is not None and not drafter.shareable:
            raise ValueError(
                f"drafter {drafter.name!r} keeps per-sequence state and "
                "cannot be shared across engine slots — use 'ngram' "
                "(DraftModelDrafter belongs on the generate() path)")
        self.spec_drafter = drafter
        self.spec_k = k
        if spec_reserve is None:
            spec_reserve = knobs.get("CAKE_SPEC_RESERVE")
        self.spec_reserve = max(int(spec_reserve), 0)
        self.spec_steps = self.spec_proposed = self.spec_accepted = 0
        # this iteration's per-slot draft lengths (slot -> n_draft):
        # the speculative-frontier trim must keep blocks the PENDING
        # verify dispatch will write, so rollback reads it
        self._cur_nd: dict[int, int] = {}
        self._draining = threading.Event()

        self._seed = seed
        self._vocab = model.cfg.vocab_size
        self._base_rng = jax.random.PRNGKey(seed)
        self._init_device_state()
        self.prefix_cache = self._build_prefix_cache()
        self._reqs: list[ServeRequest | None] = [None] * slots
        self._prefills: list[_Prefill] = []   # in-flight chunked admissions
        self._rr = 0                          # round-robin cursor over them
        self._seq = 0

        self._wake = threading.Event()
        self._stop = threading.Event()
        self.steps = 0                  # completed scheduler iterations
        self.last_step = now()
        # flight recorder: ring of recent iteration records the
        # supervisor dumps to CAKE_TRACE_DIR on wedge/DOWN — built
        # before the supervisor so the watchdog can always reach it
        self.flight = FlightRecorder()
        self.dead: BaseException | None = None
        # the supervisor needs _stop (watchdog lifetime) — build it after
        # the events, before the scheduler thread can possibly fail
        self.supervisor = Supervisor(
            self, watchdog_s=step_watchdog_s, rebuild_budget=rebuild_budget,
            rebuild_window_s=rebuild_window_s,
            restore_interval_s=restore_interval_s)
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="cake-serve")
        self._thread.start()

    def _init_device_state(self, layers=None, paged=None):
        """(Re)allocate the pool cache and every per-slot carry — called
        at construction and by crash recovery (`_rebuild`/`_revive`),
        which trusts NOTHING device-resident after a failure (donated
        buffers may be consumed, results may be garbage: crash-only).

        ALL per-slot state is device-resident: rows are written at
        admission/release only, and the whole carry (tokens, positions,
        RNG, recent windows) advances inside the batched decode program
        — an iteration ships nothing host->device and fetches only the
        nb sampled ids. In paged mode the pool is a PagedKV (shared
        physical blocks + per-slot tables) instead of B contiguous
        rows; the carries are identical."""
        slots = self.slots
        if self.kv_blocks > 0:
            self.paged = paged or PagedKV.build(
                self.model, slots, self.ctx, self.kv_blocks,
                self.kv_block_tokens, self.chunk)
            self._layers = None
        else:
            if layers is None:
                layers = self.model.new_cache(slots,
                                              kv_len=self.ctx)["layers"]
            self._layers = layers
        self._toks = jnp.zeros((slots,), jnp.int32)
        self._pos = jnp.zeros((slots,), jnp.int32)
        self._temps = jnp.zeros((slots,), jnp.float32)
        self._top_ks = jnp.full((slots,), self._vocab, jnp.int32)
        self._top_ps = jnp.ones((slots,), jnp.float32)
        self._pens = jnp.ones((slots,), jnp.float32)
        self._rngs = jnp.stack([jax.random.PRNGKey(self._seed + i)
                                for i in range(slots)])
        self._recents = jnp.full((slots, RECENT_N), -1, jnp.int32)
        # decode-eligibility mask: True only for slots whose prefill has
        # COMPLETED. Mutated at transitions only (prefill done / release),
        # never donated — the engine keeps its handle across iterations,
        # so steady-state decode still ships nothing host->device
        self._act = jnp.zeros((slots,), jnp.bool_)

    def _build_prefix_cache(self):
        """Mode-matched prefix cache: the paged variant pins shared pool
        blocks by refcount (a hit is a table remap, no KV copy) and is
        wired in as the allocator's under-pressure evictor; the
        contiguous variant keeps private block copies."""
        if self.paged is not None:
            pc = PagedPrefixCache.build_paged(self.model, self.paged,
                                              self.chunk, self._prefix_mb)
            self.paged.evictor = pc.evict_for_pressure if pc else None
            return pc
        return PrefixCache.build(self.model, self.ctx, self.chunk,
                                 self._prefix_mb)

    # -- client surface (any thread) ----------------------------------------

    def submit(self, prompt_ids: list[int], max_new_tokens: int = 256,
               sampling: SamplingConfig | None = None,
               request_id: str | None = None, qos: str = "interactive",
               tenant: str | None = None,
               continuation: bool = False) -> ServeRequest:
        """Enqueue a generation under QoS class `qos` (admission lane,
        weighted-fair share, preemption rank — resolved and clamped by
        the API's admission plane). `continuation` marks a splice
        prefill whose prompt tail is a partial assistant turn being
        continued in place (the prefix cache makes the shared head
        nearly free, so a resume's TTFR is the warm path, not a full
        re-prefill). Raises QueueFull under backpressure
        (class-aware: the 429's Retry-After reflects that class's
        backlog), EngineDown while the engine is dead or in
        budget-exhausted degraded mode (API: 503 + Retry-After),
        PoisonedRequest for quarantined prompts, and ValueError for
        prompts the pool can never hold."""
        if self.dead is not None or not self._thread.is_alive():
            raise EngineDown(f"serve engine is down: {self.dead}",
                             retry_after_s=30)
        down = self.supervisor.down_info()
        if down is not None:
            raise EngineDown(
                "serve engine down for "
                f"{down['down_for_s']}s (rebuild budget exhausted: "
                f"{down.get('last_failure', 'unknown failure')}); "
                "restore loop probing",
                retry_after_s=max(
                    int(self.supervisor.restore_interval_s) + 1, 5))
        if self._draining.is_set():
            raise EngineDraining(retry_after_s=self.retry_after_hint())
        if self.supervisor.is_quarantined(prompt_ids):
            raise PoisonedRequest(
                "request fingerprint quarantined: identical prompt was "
                "implicated in repeated engine crashes")
        n = len(prompt_ids)
        if n < 1:
            raise ValueError("empty prompt")
        if n > self.ctx - 2:
            raise ValueError(
                f"prompt length {n} exceeds the serve context "
                f"({self.ctx} tokens per slot)")
        # bind a local: the scheduler thread nulls self.paged transiently
        # during _rebuild/_fail_all, and submit runs on client threads
        paged = self.paged
        if paged is not None and paged.blocks_for(n + 1) > paged.num_blocks:
            raise ValueError(
                f"prompt needs {paged.blocks_for(n + 1)} KV blocks "
                f"but the pool holds {paged.num_blocks} "
                f"(CAKE_KV_BLOCKS x CAKE_KV_BLOCK_TOKENS tokens total)")
        req = ServeRequest(prompt_ids, max_new_tokens, sampling, request_id,
                           qos=qos, tenant=tenant, continuation=continuation)
        req._engine = self
        # free slots extend the bound: a burst that fits the idle pool is
        # admitted even though the scheduler drains one per iteration
        self.queue.put(req, allow_extra=self.pool.free_count)
        TIMELINES.begin(req.id)
        TIMELINES.event(req.id, "enqueue", depth=self.queue.depth(),
                        qos=req.qos,
                        **({"tenant": req.tenant} if req.tenant else {}),
                        **({"continuation": True} if req.continuation
                           else {}))
        self._wake.set()
        if self.dead is not None or self.supervisor.is_down():
            # the scheduler crashed (or went down) between the liveness
            # check above and the put: its crash drain may have missed
            # this request, so release the waiter ourselves (double-fail
            # is harmless)
            self.queue.purge(lambda r: r is req)
            if self.dead is not None:
                err = EngineDown(f"serve engine is down: {self.dead}",
                                 retry_after_s=30)
            else:
                err = EngineDown(
                    "serve engine down: rebuild budget exhausted; "
                    "restore loop probing",
                    retry_after_s=max(
                        int(self.supervisor.restore_interval_s) + 1, 5))
            self._fail(req, err)
            raise err
        return req

    def stream(self, req: ServeRequest):
        """(async iterator, result dict) over the request's token stream —
        the same contract as the legacy run_generation_streamed, so the SSE
        writer is path-agnostic. Tokens are pushed from the scheduler
        thread straight into an asyncio queue (call_soon_threadsafe): no
        executor thread is parked per stream, and the iterator's finalizer
        cancels the request on abandonment so a client disconnect frees
        the slot instead of leaking it. Must be called on the event loop."""
        import asyncio

        loop = asyncio.get_running_loop()
        aq: asyncio.Queue = asyncio.Queue()

        def pump(item):
            try:
                loop.call_soon_threadsafe(aq.put_nowait, item)
            except RuntimeError:
                pass                    # loop closed; finalizer cancels

        for item in req.subscribe(pump):
            aq.put_nowait(item)

        async def aiter():
            try:
                while True:
                    item = await aq.get()
                    if item is ServeRequest.DONE:
                        break
                    yield item
            finally:
                req.cancel()
            if "error" in req.result:
                raise req.result["error"]

        return aiter(), req.result

    def health(self) -> dict:
        h = {
            "alive": self.dead is None and self._thread.is_alive(),
            "slots": self.slots,
            "slots_busy": self.pool.busy_count,
            "queue_depth": self.queue.depth(),
            "queue_by_class": self.queue.depths(),
            "ctx_len": self.ctx,
            "prefill_chunk": self.chunk,
            "prefilling": len(self._prefills),
            "draining": self._draining.is_set(),
            "steps": self.steps,
            "last_step_age_s": round(now() - self.last_step, 3),
            # supervision: lifetime recovery counters + live wedge flag
            "rebuilds": self.supervisor.rebuild_count,
            "wedged": self.supervisor.wedged(),
        }
        lf = self.supervisor.last_failure()
        if lf is not None:
            h["last_failure"] = lf
        down = self.supervisor.down_info()
        if down is not None:
            h["down"] = down
        q = self.supervisor.quarantined_count()
        if q:
            h["quarantined"] = q
        pc = self.prefix_cache
        if pc is not None:
            h["prefix_cache"] = pc.occupancy()
        # local binding: health() runs on API threads while the scheduler
        # may null self.paged transiently during _rebuild/_fail_all
        paged = self.paged
        if paged is not None:
            live = {}
            for i in self.pool.busy():
                req = self._reqs[i]
                if req is not None:
                    live[i] = len(req.prompt_ids) \
                        + max(len(req.tokens) - 1, 0)
            h["kv_pool"] = {
                **paged.occupancy(live),
                "preempted_slots": len(self._preempted),
            }
            if pc is not None:
                # the peer directory and `cake top` both want the cache
                # size next to pool occupancy, not only in prefix_cache
                h["kv_pool"]["prefix_entries"] = len(pc._blocks)
                h["kv_pool"]["prefix_pinned_blocks"] = getattr(
                    pc, "pinned", 0)
        ks = self.kv_share
        if ks is not None:
            h["kvshare"] = ks.health_view()
        if self.spec_drafter is not None:
            h["spec"] = {
                "drafter": self.spec_drafter.name,
                "k": self.spec_k,
                "mode": "batched",
                "steps": self.spec_steps,
                "proposed": self.spec_proposed,
                "accepted": self.spec_accepted,
            }
        return h

    def begin_drain(self) -> None:
        """Flip the draining flag WITHOUT waiting: new submits raise
        EngineDraining and /health's engine block reports draining
        immediately — a fleet router probing /health stops routing here
        before the first bounced request, instead of discovering the
        drain from 503s. drain() calls this; the API's graceful shutdown
        calls it up front, before handing the blocking wait to an
        executor thread."""
        self._draining.set()
        self._wake.set()

    def retry_after_hint(self) -> int:
        """Seconds a shed/refused client should wait before retrying,
        derived from live state instead of a constant: a DOWN engine
        says the restore-probe interval (the soonest revival can
        happen), a backlogged engine scales with queue depth per slot —
        so routers and clients back off proportionally to the actual
        congestion."""
        down = self.supervisor.down_info()
        if down is not None:
            return max(int(self.supervisor.restore_interval_s) + 1, 5)
        depth = self.queue.depth()
        return max(1, min(30, 1 + (2 * depth) // max(self.slots, 1)))

    def drain(self, timeout: float | None = None) -> bool:
        """Graceful-shutdown phase 1: stop admission (new submits raise
        EngineDraining -> 503 + Retry-After) and wait for in-flight work —
        busy slots AND already-queued requests — to finish, up to timeout
        seconds. Returns True when the engine went idle; False means the
        timeout hit and close() will fail whatever is left. Safe to call
        from any thread; blocks the caller, not the scheduler."""
        self.begin_drain()
        deadline = None if timeout is None else now() + timeout
        while self.pool.busy_count or self.queue.depth() or self._preempted:
            if self.dead is not None or not self._thread.is_alive():
                return False
            if deadline is not None and now() >= deadline:
                return False
            time.sleep(0.02)
        return True

    def close(self, timeout: float = 5.0):
        self._stop.set()
        self._wake.set()
        self._thread.join(timeout=timeout)
        for req in self.queue.drain():
            self._fail(req, EngineDown("serve engine shut down"))
        if self._thread.is_alive():
            # scheduler still inside a device call (e.g. a long compile):
            # release the waiters but do NOT touch pool/_reqs/_layers —
            # racing the live thread's state would crash it mid-step
            # (_fail is benign if the scheduler later finishes the slot)
            self.dead = self.dead or RuntimeError(
                "serve engine shutdown timed out")
            for req in list(self._reqs):
                if req is not None:
                    self._fail(req, EngineDown("serve engine shut down"))
            return
        self._prefills.clear()
        for entry in self._drain_preempted():
            self._fail(entry.req, EngineDown("serve engine shut down"))
        for i, req in enumerate(self._reqs):
            if req is not None:
                self._finish(i, req, cancelled=True)

    def _drain_preempted(self) -> list:
        entries, self._preempted = self._preempted, []
        return entries

    # -- scheduler thread ---------------------------------------------------

    def _loop(self):
        """Supervision shell: the inner `_run` loop does the work; a
        failure escaping it goes to the supervisor's recovery state
        machine (classify -> rebuild-by-replay -> budget -> down). Only
        when the supervisor itself gives up (or breaks) does the engine
        fall to the legacy terminal `dead` state."""
        while not self._stop.is_set():
            try:
                self._run()
                return                      # clean _stop
            except BaseException as e:
                try:
                    recovered = self.supervisor.on_failure(e)
                except BaseException as sup_exc:
                    self._die(sup_exc)      # supervisor bug: last resort
                    return
                if not recovered:
                    self._die(e)
                    return

    def _run(self):
        while not self._stop.is_set():
            if self.supervisor.is_down():
                self._down_cycle()
                continue
            worked = self._step()
            self.supervisor.disarm()
            self.last_step = now()
            if worked:
                self.steps += 1
                self.supervisor.note_ok()
            else:
                # idle: block on the wake event (submit/cancel/close
                # all set it); the 0.5s timeout is only a heartbeat
                # for last_step, not a polling cadence
                self._wake.wait(0.5)
                self._wake.clear()

    def _die(self, e: BaseException):
        """Terminal failure: every waiter is released, loudly."""
        self.dead = e
        self._prefills.clear()      # their reqs are in _reqs below
        for entry in self._drain_preempted():
            self._fail(entry.req, e)
        for req in self.queue.drain():
            self._fail(req, e)
        for i, req in enumerate(self._reqs):
            if req is not None:
                req.result.setdefault("error", e)
                self._finish(i, req, cancelled=True, release=False)

    # -- degraded mode (rebuild budget exhausted) ---------------------------

    def _down_cycle(self):
        """One restore-loop turn while the engine is DOWN: shed whatever
        raced into the queue, wait CAKE_ENGINE_RESTORE_S, then probe the
        device with a trial prefill. Success rebuilds an empty pool and
        resumes serving; failure stays down for the next probe."""
        err = EngineDown("serve engine down: rebuild budget exhausted; "
                         "restore loop probing")
        for req in self.queue.drain():
            self._fail(req, err)
        if self._stop.wait(self.supervisor.restore_interval_s):
            return
        try:
            # recovery-grace watchdog limit: the trial may compile
            self.supervisor.arm("trial", (), grace=True)
            if self.kv_blocks > 0:
                state = PagedKV.build(self.model, self.slots, self.ctx,
                                      self.kv_blocks, self.kv_block_tokens,
                                      self.chunk)
                state.reserve_range(0, 0, 1)
                state.prefill_into(0, [1], 0)
                state.release_slot(0)
                jax.block_until_ready((state.pool, state.rows))
            else:
                layers = self.model.new_cache(self.slots,
                                              kv_len=self.ctx)["layers"]
                _, layers = self.model.prefill_chunk(layers, 0, [1], 0)
                layers = self.model.slot_release(layers, 0)
                state = layers
                # the dispatches above are async — a broken device
                # surfaces its error here, inside the probe's try, not
                # mid-serving
                jax.block_until_ready(layers)
            self.supervisor.disarm()
        except Exception as e:
            self.supervisor.disarm()
            self.supervisor.note_probe_failure(e)
            return
        self._revive(state)

    def _revive(self, state):
        """Trial step succeeded: adopt its (wiped) pool, fresh carries,
        fresh prefix cache, and rejoin the serving loop."""
        if self.kv_blocks > 0:
            self._init_device_state(paged=state)
        else:
            self._init_device_state(state)
        self.prefix_cache = self._build_prefix_cache()
        self.supervisor.clear_down()
        log.warning("serve engine revived: trial step succeeded, pool "
                    "rebuilt empty, admission reopened")

    def _step(self) -> bool:
        # kvshare mailbox FIRST — before the idle early-return below, so
        # an idle engine still serves blob export/import jobs (submit
        # sets _wake, which lands the _run loop here)
        ks = self.kv_share
        if ks is not None:
            ks.run_pending()
        busy = self.pool.busy()
        queued = self.queue.depth() > 0
        if not (busy or queued or self._preempted):
            return False
        with RECORDER.span("serve.step", cat="serve", slots=len(busy),
                           queued=self.queue.depth()):
            # reset failure-attribution context: a crash in the host
            # bookkeeping below must not implicate the PREVIOUS step's
            # request set (the decode/prefill dispatches re-arm with
            # their own sets)
            self.supervisor.arm("step", ())
            # 1. cancel sweeps: decoding slots, mid-prefill slots, and
            # abandoned-while-queued requests (those would otherwise pin
            # queue capacity and 429 live clients while slots sit idle)
            prefilling = {p.slot for p in self._prefills}
            for i in busy:
                req = self._reqs[i]
                if req is not None and req.cancelled.is_set() \
                        and i not in prefilling:
                    self._finish(i, req, cancelled=True)
            for pf in [p for p in self._prefills
                       if p.req.cancelled.is_set()]:
                self._abort_prefill(pf, None)
            for entry in [e for e in self._preempted
                          if e.req.cancelled.is_set()
                          or e.req.done.is_set()]:
                self._preempted.remove(entry)
                self._fail(entry.req, None)
            for req in self.queue.purge(lambda r: r.cancelled.is_set()):
                self._fail(req, None)
            # queue-deadline sweep: a request that has waited past
            # CAKE_QUEUE_DEADLINE_S is 503ed here rather than admitted
            # into a slot for a client that already gave up
            if self.queue_deadline_s > 0:
                cutoff = now() - self.queue_deadline_s
                for req in self.queue.purge(
                        lambda r: r.t_enqueue < cutoff):
                    SERVE_QUEUE_TIMEOUTS.inc()
                    self._fail(req, QueueDeadlineExceeded(
                        now() - req.t_enqueue))
            # request-deadline sweep (CAKE_REQUEST_DEADLINE_S): ADMITTED
            # requests whose TOTAL age expired are cancelled with a typed
            # 504 — the queue sweep above only covers waiting, so without
            # this a slow decode could hold a slot long past the point
            # every client timeout has fired
            if self.request_deadline_s > 0:
                cutoff = now() - self.request_deadline_s
                for i in self.pool.busy():
                    req = self._reqs[i]
                    if req is None or req.t_enqueue >= cutoff:
                        continue
                    SERVE_REQUEST_TIMEOUTS.inc()
                    err = RequestDeadlineExceeded(
                        now() - req.t_enqueue, self.request_deadline_s)
                    pf = next((p for p in self._prefills if p.slot == i),
                              None)
                    if pf is not None:
                        self._abort_prefill(pf, err)
                    else:
                        req.result["error"] = err
                        self._finish(i, req, cancelled=True)
                for entry in [e for e in self._preempted
                              if e.req.t_enqueue < cutoff]:
                    self._preempted.remove(entry)
                    SERVE_REQUEST_TIMEOUTS.inc()
                    self._fail(entry.req, RequestDeadlineExceeded(
                        now() - entry.req.t_enqueue,
                        self.request_deadline_s))
            # 2. preempted slots resume FIRST (oldest-first, as soon as a
            # slot + enough blocks free up — their clients are mid-stream),
            # then every queued request takes a free slot (cheap: at most
            # a prefix-cache splice — the prefill itself is chunked
            # below), so multiple admissions are in flight concurrently
            if self._preempted:
                self._resume_preempted()
            while self.pool.free_count > 0 and self._start_admission():
                pass
            if not (self.pool.busy_count or self.queue.depth()):
                # only parked entries remain and none could resume yet:
                # report idle so _run waits on the wake event (0.5s
                # heartbeat retries the resume) instead of hot-spinning
                return False
            # 3. dispatch ONE batched step over the slots whose prefill
            # has completed (mid-prefill rows ride along frozen under the
            # active mask): a speculative verify step when the drafter
            # proposed for ANY slot — every slot's draft window rides the
            # same dispatch with ragged per-slot acceptance — else a
            # plain batched decode. Both paths cost exactly one device
            # call and one fetch per iteration.
            # 3a. choose the admission to advance this iteration (round-
            # robin) and, in paged mode, reserve its chunk's blocks NOW —
            # BEFORE the decode dispatch. The reservation may preempt a
            # decoding victim, and preemption is only safe pre-dispatch:
            # a swap-out after the decode was dispatched would capture
            # post-step carries holding a sampled token the host never
            # fanned out, silently dropping it from the stream on resume
            self._cur_nd = {}
            pf_job = None
            if self._prefills:
                pf_job = self._prefills[self._rr % len(self._prefills)]
                if self.paged is not None:
                    pf_job = self._prepare_prefill(pf_job)
            prefilling = {p.slot for p in self._prefills}   # post-admission
            active = [i for i in self.pool.busy()
                      if self._reqs[i] is not None and i not in prefilling]
            # 3b. host-side draft building (the n-gram lookup runs while
            # the PREVIOUS iteration's prefill chunk is still on the
            # device — host work here is overlapped, not serialized)
            spec_job = None
            if active and self.spec_drafter is not None:
                spec_job = self._build_drafts(active)
            if self.paged is not None and active:
                # every decoding slot needs blocks for its write frontier
                # — and, when speculating, its whole draft window —
                # mapped BEFORE dispatch; exhaustion preempts a victim
                # (which may shrink `active`) — see _ensure_decode_blocks
                active = self._ensure_decode_blocks(active, spec_job)
            packed = None
            nb = 0
            td0 = now()                 # dispatch + fetch wall clock
            spec_acc0 = self.spec_accepted
            active_ids = tuple(self._reqs[i].id for i in active)
            if active:
                nb = slot_bucket(active[-1] + 1, self.slots)
                SERVE_BATCH_OCCUPANCY.observe(len(active))
                # arm BEFORE the fault hook: an injected stall simulates a
                # dispatch stuck on the device, and the watchdog must see
                # it; real crashes here implicate every active request
                self.supervisor.arm("decode", active_ids)
                hook = faults.FAULT_HOOK
                if hook is not None:
                    hook.on_decode([self._reqs[i] for i in active])
                if spec_job is not None:
                    drafts, n_drafts = spec_job
                    # static no-vocab-filters fast path: when no slot in
                    # the dispatch uses top-k/top-p the accept rule skips
                    # its per-row sorts (at most one extra executable per
                    # bucket — traffic mixes flip between two programs,
                    # both warm in steady state)
                    filt = any(config_has_filters(self._reqs[i].sampling)
                               for i in active)
                    with RECORDER.span("spec.verify", cat="serve",
                                       slots=len(active),
                                       drafts=int(n_drafts.sum())):
                        if self.paged is not None:
                            (packed, self.paged.pool, self.paged.rows,
                             self._toks, self._pos, self._rngs,
                             self._recents) = self.model.spec_slots_paged(
                                self.paged.pool, self.paged.rows,
                                self.paged.tables, self._toks, self._pos,
                                self._rngs, self._recents, self._temps,
                                self._top_ks, self._top_ps, self._pens,
                                self._act, drafts, n_drafts, nb=nb,
                                filt=filt)
                        else:
                            (packed, self._layers, self._toks, self._pos,
                             self._rngs,
                             self._recents) = self.model.spec_slots(
                                self._layers, self._toks, self._pos,
                                self._rngs, self._recents, self._temps,
                                self._top_ks, self._top_ps, self._pens,
                                self._act, drafts, n_drafts, nb=nb,
                                filt=filt)
                elif self.paged is not None:
                    (packed, self.paged.pool, self.paged.rows, self._toks,
                     self._pos, self._rngs,
                     self._recents) = self.model.decode_slots_paged(
                        self.paged.pool, self.paged.rows, self.paged.tables,
                        self._toks, self._pos, self._rngs, self._recents,
                        self._temps, self._top_ks, self._top_ps, self._pens,
                        self._act, nb=nb)
                else:
                    (packed, self._layers, self._toks, self._pos,
                     self._rngs, self._recents) = self.model.decode_slots(
                        self._layers, self._toks, self._pos, self._rngs,
                        self._recents, self._temps, self._top_ks,
                        self._top_ps, self._pens, self._act, nb=nb)
            # 4. ...then advance the chosen admission by one chunk.
            # Dispatch order matters: the decode program is already queued
            # on the device, so the packed-ids fetch below never waits for
            # this chunk — on real hardware the chunk overlaps the host's
            # token fan-out. (Its blocks were reserved in 3a; the job may
            # have been requeued by a decode slot's own preemption since,
            # hence the membership re-check.)
            if pf_job is not None and pf_job in self._prefills:
                idx = self._prefills.index(pf_job)
                if self._advance_prefill(pf_job):
                    self._rr = idx + 1      # still in flight: move past it
                else:
                    self._rr = idx          # removed: next job slid here
            # 5. ONE host fetch per iteration: fan the sampled ids out
            if packed is not None:
                # the fetch is where an async device failure (or a wedge)
                # actually materializes on the host: re-arm with the
                # decode set so the supervisor attributes it correctly
                # even if a prefill chunk was dispatched in between
                self.supervisor.arm("decode", active_ids)
                # lint: disable=host-sync — THE one planned fetch per iteration: the
                # packed ids ([input;sampled], or [input;n_acc;next] on a
                # speculative iteration) for every slot in one transfer,
                # after the next work is already dispatched
                arr = np.asarray(packed)
                if spec_job is not None:
                    self._fanout_spec(active, arr, spec_job[0],
                                      spec_job[1], nb)
                else:
                    self._fanout(active, arr, nb)
            # flight record: one bounded dict per iteration — the black
            # box the supervisor dumps on wedge/DOWN (see flight.py)
            rec = {
                "occupancy": len(active), "bucket": nb,
                "dispatch_ms": round((now() - td0) * 1e3, 3)
                if packed is not None else 0.0,
                "queued": self.queue.depth(),
                "prefilling": len(self._prefills),
                "spec_accepted": self.spec_accepted - spec_acc0,
            }
            if self.paged is not None:
                rec["kv_free"] = self.paged.alloc.free_count
                rec["kv_used"] = self.paged.alloc.used_count
            self.flight.record(**rec)
        return True

    # -- chunked admission --------------------------------------------------

    def _start_admission(self) -> bool:
        """Move the first live queued request into a free slot as an
        in-flight chunked prefill; splice any cached shared prefix so only
        the suffix needs compute. Returns False when the queue is empty."""
        while True:
            req = self.queue.pop()
            if req is None:
                return False
            if req.cancelled.is_set():
                self._fail(req, None)   # abandoned while queued
                continue
            break
        SERVE_QUEUE_WAIT_SECONDS.observe(now() - req.t_enqueue)
        slot = self.pool.alloc()
        # register BEFORE any fallible device work: if anything below (or
        # the loop itself) dies, the crash handler finds the request in
        # _reqs and releases its waiter instead of hanging the client
        self._reqs[slot] = req
        req.slot = slot
        req.admitted.set()
        req.stats = {"queue_wait_s": now() - req.t_enqueue}
        if req.continuation:
            req.stats["continuation"] = True
        TIMELINES.event(req.id, "admit", slot=slot, qos=req.qos,
                        queue_wait_ms=round(
                            req.stats["queue_wait_s"] * 1e3, 3))
        self._begin_prefill(_Prefill(req, slot))
        SERVE_SLOTS_BUSY.set(self.pool.busy_count)
        return True

    def _begin_prefill(self, pf: _Prefill) -> bool:
        """Open a chunked admission for an already-registered request:
        splice any cached shared prefix, then put it in flight. Shared by
        fresh admissions and rebuild restarts. Returns False (request
        failed) when the splice dies."""
        set_request_id(pf.req.id)
        try:
            if self.prefix_cache is not None:
                pf.keys = self.prefix_cache.chain_keys(pf.ids)
                matched = self.prefix_cache.match(pf.ids, pf.keys)
                if matched:
                    self._layers = self.prefix_cache.splice(
                        self._layers, pf.slot, pf.keys, matched)
                    pf.pos = matched * self.chunk
                    pf.next_block = matched
                    pf.hit_tokens = pf.pos
                    TIMELINES.event(pf.req.id, "prefix_hit",
                                    tokens=pf.hit_tokens)
        except Exception as e:
            self._abort_prefill(pf, e, register=False)
            return False
        finally:
            set_request_id(None)
        self._prefills.append(pf)
        return True

    def _advance_prefill(self, pf: _Prefill) -> bool:
        """Prefill ONE chunk of an in-flight admission into its pool row;
        capture any block the chunk completed into the prefix cache; on
        the final chunk, sample the first token and activate the slot for
        decode. Returns True while the job remains in flight."""
        take = min(self.chunk, pf.n - pf.pos)
        set_request_id(pf.req.id)
        try:
            with RECORDER.span("serve.prefill_chunk", cat="serve",
                               tokens=take, pos0=pf.pos, slot=pf.slot):
                self.supervisor.arm("prefill", (pf.req.id,))
                hook = faults.FAULT_HOOK
                if hook is not None:
                    hook.on_prefill(pf.req)
                if self.paged is not None:
                    logits = self.paged.prefill_into(
                        pf.slot, pf.ids[pf.pos:pf.pos + take], pf.pos)
                else:
                    logits, self._layers = self.model.prefill_chunk(
                        self._layers, pf.slot,
                        pf.ids[pf.pos:pf.pos + take], pf.pos)
            pf.pos += take
            pf.chunks += 1
            TIMELINES.event(pf.req.id, "prefill_chunk",
                            pos0=pf.pos - take, tokens=take)
            pf.next_block = self._capture_blocks(pf.ids, pf.slot, pf.pos,
                                                 pf.n, pf.next_block,
                                                 pf.keys)
            if pf.pos >= pf.n:
                self._complete_prefill(pf, logits)
                return False
            return True
        except Exception as e:
            # request-scoped containment first: the admission dies alone
            # (poison isolation for free — a prompt that crashes its own
            # prefill never takes the pool with it)...
            self._abort_prefill(pf, e)
            if classify(e) in ("device", "oom"):
                # ...but a device/oom failure impeaches the WHOLE pool's
                # state, not just this row: escalate to the supervisor
                raise
            return False
        finally:
            set_request_id(None)

    def _complete_prefill(self, pf: _Prefill, logits):
        """Final chunk done: sample the first token (device-resident — it
        rides the next decode iteration's packed fetch) and hand the slot
        to the batched decode."""
        req, slot, scfg = pf.req, pf.slot, pf.req.sampling
        rng = jax.random.fold_in(self._base_rng, self._seq)
        self._seq += 1
        rng, sk = jax.random.split(rng)
        recent = jnp.full((RECENT_N,), -1, jnp.int32)
        tid = self.model.sample_one(
            logits[0], sk, jnp.float32(scfg.temperature),
            jnp.int32(scfg.top_k or self._vocab),
            jnp.float32(scfg.top_p if scfg.top_p is not None else 1.0),
            jnp.float32(scfg.repeat_penalty), recent)
        self._rngs = self._rngs.at[slot].set(rng)
        self._recents = self._recents.at[slot].set(recent.at[-1].set(tid))
        self._toks = self._toks.at[slot].set(tid)
        self._pos = self._pos.at[slot].set(pf.n)
        self._set_slot_sampling(slot, scfg)
        self._act = self._act.at[slot].set(True)
        self._prefills.remove(pf)
        req.budget = min(req.max_new_tokens - 1, self.ctx - pf.n - 1)
        req._first_pending = True       # emitted at the next decode fetch
        # ttft_s is stamped when the first token is FETCHED (everything
        # above is an async dispatch — stamping here would understate the
        # client's real wait)
        req.stats["prefill_chunks"] = pf.chunks
        req.stats["prefix_hit_tokens"] = pf.hit_tokens
        SERVE_PREFILL_CHUNKS.observe(max(pf.chunks, 1))
        TIMELINES.event(req.id, "prefill_done", chunks=pf.chunks,
                        hit_tokens=pf.hit_tokens)

    def _capture_blocks(self, ids, slot: int, pos: int, n: int,
                        next_block: int, keys: list) -> int:
        """Insert every prefix-cache block the chunk that just landed
        completed — captured at the boundary while the row state IS that
        exact prefix (the linear-attention snapshot is only right there).
        The block holding the final token is never cached (its logits
        must be computed live to seed sampling), hence the n-1 cap.
        Shared by admission and crash-replay so the boundary rule cannot
        drift between them. Returns the next uncaptured block index."""
        if self.prefix_cache is None:
            return next_block
        while (next_block + 1) * self.chunk <= min(pos, n - 1):
            self.prefix_cache.insert(self._layers, slot, ids, next_block,
                                     keys)
            next_block += 1
        return next_block

    def _set_slot_sampling(self, slot: int, scfg: SamplingConfig):
        """Write a request's sampling params into the slot's traced
        carries (same disabled-value conventions as sample_traced)."""
        self._temps = self._temps.at[slot].set(scfg.temperature)
        self._top_ks = self._top_ks.at[slot].set(scfg.top_k or self._vocab)
        self._top_ps = self._top_ps.at[slot].set(
            scfg.top_p if scfg.top_p is not None else 1.0)
        self._pens = self._pens.at[slot].set(scfg.repeat_penalty)

    def _abort_prefill(self, pf: _Prefill, error: BaseException | None,
                       register: bool = True):
        """Tear down a mid-prefill admission (client cancel or device
        failure): release the waiter, free the slot, wipe the half-built
        row. The wipe comes LAST and must still escalate on failure —
        splice and prefill_chunk assume a clean row, so a failed wipe
        cannot silently hand ghost KV to the row's next occupant (the
        supervisor's rebuild reallocates the pool). But it must not MASK
        the original error either: the step failure stays the exception
        being raised, the wipe failure rides its __cause__ — first
        exception wins, nothing swallowed or substituted."""
        if register:
            self._prefills.remove(pf)
        self._reqs[pf.slot] = None
        self.pool.free(pf.slot)
        SERVE_SLOTS_BUSY.set(self.pool.busy_count)
        self._fail(pf.req, error)
        try:
            self._release_row(pf.slot)
        except Exception as wipe_exc:
            if error is not None:
                raise error from wipe_exc
            raise

    def _release_row(self, slot: int):
        """Per-request row release, mode-dispatched: contiguous wipes the
        pool row; paged derefs the slot's blocks (shared blocks survive
        under the prefix cache / other slots) and wipes only the SWA/
        linear rows — freed pool blocks need no wipe thanks to the
        gather's stale-tenant pos guard."""
        if self.paged is not None:
            self.paged.release_slot(slot)
        else:
            self._layers = self.model.slot_release(self._layers, slot)

    # -- paged-pool pressure: reserve / preempt / resume --------------------

    def _prepare_prefill(self, pf: _Prefill):
        """Reserve the blocks pf's next chunk will write — called BEFORE
        the decode dispatch so any preemption it triggers sees pre-step
        carries (see _step 3a). Returns pf when the chunk may dispatch;
        None when the admission was failed typed. Reservation failure
        implies the pool is exhausted with pf as the ONLY occupant
        (_reserve_blocks evicts the prefix cache, preempts every
        decoding slot, and requeues every other admission before giving
        up), so the prompt can never fit and parking would hang it."""
        take = min(self.chunk, pf.n - pf.pos)
        got = self._reserve_blocks(pf.slot, pf.pos, take,
                                   requester=pf.req)
        if got == "self":
            # every reclaimable block is held by HIGHER-class work:
            # this admission parks itself (clean restart — nothing
            # emitted) and retries when blocks free, instead of
            # evicting an interactive slot to admit a batch prompt
            self._requeue_admission(pf)
            return None
        if got:
            return pf
        self._abort_prefill(pf, KVPoolExhausted(
            f"KV pool exhausted admitting {pf.req.id}: the prompt needs "
            "more blocks than the pool can ever free"))
        return None

    def _reserve_blocks(self, slot: int, pos0: int, n: int,
                        requester=None):
        """Back positions [pos0, pos0+n) of `slot` with physical blocks,
        evicting prefix-cache LRU (inside the allocator) and then
        preempting victims (QoS policy via _preempt_one) until it fits.
        "self" = only higher-class work holds blocks, the caller must
        park itself; False = nothing left to reclaim."""
        while not self.paged.reserve_range(slot, pos0, n):
            got = self._preempt_one(exclude=slot, requester=requester)
            if got is not True:
                return got
        return True

    def _ensure_decode_blocks(self, active: list[int],
                              spec_job=None) -> list[int]:
        """Back every decoding slot's write reach with physical blocks
        before the batched dispatch: the write-frontier block for a plain
        decode step, the whole speculative frontier [wp, wp + n_draft]
        when the slot carries a draft window (the verify may commit up to
        n_draft + 1 positions — reserving past the frontier is what lets
        the block cursor move by accepted length without a mid-program
        allocation). Exhaustion evicts prefix-cache LRU, then rolls back
        other slots' speculative tails, then preempts a victim; a slot
        that cannot grow with NOTHING left to reclaim is failed typed
        rather than wedging the scheduler. Returns the surviving active
        list (preemption and failure both shrink it)."""
        n_drafts = spec_job[1] if spec_job is not None else None
        for i in active:
            req = self._reqs[i]
            if req is None:
                continue        # preempted by an earlier slot's ensure
            wp = len(req.prompt_ids) + max(len(req.tokens) - 1, 0)
            reach = 1 + (int(n_drafts[i]) if n_drafts is not None else 0)
            while not self.paged.reserve_range(i, wp, reach):
                if reach > 1:
                    # speculation never costs anyone their blocks: under
                    # pressure the slot DROPS its draft window to a plain
                    # decode step (n_drafts gates it out of the dispatch)
                    # and retries with just the write-frontier block —
                    # preemption and typed failure stay reserved for the
                    # growth a non-speculating engine would need too
                    reach = 1
                    n_drafts[i] = 0
                    self._cur_nd[i] = 0
                    continue
                got = self._preempt_one(exclude=i, requester=req)
                if got == "self":
                    # the only reclaimable space is held by HIGHER-class
                    # work: this slot parks itself (swap/recompute — it
                    # resumes bit-identical when blocks free) instead of
                    # kicking an interactive admission back to the queue
                    self._preempt_slot(i, req)
                    break
                if not got:
                    req.result["error"] = KVPoolExhausted(
                        f"KV pool exhausted: request {req.id} cannot "
                        f"grow past {wp} tokens and nothing is left to "
                        "reclaim")
                    self._finish(i, req, cancelled=True)
                    break
        return [i for i in active if self._reqs[i] is not None]

    def _preempt_one(self, exclude: int, requester=None):
        """Free blocks by reclaiming the cheapest thing first: other
        slots' speculative frontier tails (pure rollback — nobody loses
        work), then a DECODING victim (QoS policy: lowest class first,
        LIFO within a class — the cheapest to redo, and the oldest
        request in its class can never be starved by newcomers), else
        an OTHER in-flight admission goes back to readmission (it has
        emitted nothing, so a restart is clean; lowest class, youngest
        first). When the only candidate admission outranks `requester`'s
        class, returns "self": the caller's slot must park itself
        rather than displace higher-class work (a batch decoder never
        requeues an interactive admission). False = nothing left to
        reclaim or preempt."""
        if self.spec_drafter is not None and self._trim_spec_tails(exclude):
            return True

        def outranks(r):
            return requester is not None and \
                priority(class_of(r)) > priority(class_of(requester))
        prefilling = {p.slot for p in self._prefills}
        cands = [(i, self._reqs[i]) for i in self.pool.busy()
                 if i not in prefilling]
        victim = choose_victim(cands, exclude=exclude)
        others = [p for p in self._prefills if p.slot != exclude]
        pick = max(others, key=lambda p: victim_rank(p.req)) \
            if others else None
        # evict in policy order, but never displace strictly-higher-
        # class work: a protected victim falls through to the admission
        # check (a lower-class admission may still be requeued — the
        # review caught the early "self" return inverting priority when
        # e.g. a standard decode was blocked by interactive decodes
        # while a batch prefill held reclaimable blocks)
        if victim is not None and not outranks(victim[1]):
            self._preempt_slot(*victim)
            return True
        if pick is not None and not outranks(pick.req):
            self._requeue_admission(pick)
            return True
        if victim is not None or pick is not None:
            return "self"       # only higher-class work holds blocks
        return False

    def _preempt_slot(self, slot: int, req: ServeRequest):
        """Evict one decoding slot to free its blocks. Swap mode keeps
        the bytes host-side — resume is bit-identical even for SAMPLED
        streams (the RNG carry rides the blob); recompute mode drops
        them and replays at resume (greedy bit-identical, the rebuild
        parity rule)."""
        wp = len(req.prompt_ids) + max(len(req.tokens) - 1, 0)
        if self.preempt_mode == "swap":
            # roll back the speculative frontier first: a swapped-out
            # victim must carry only COMMITTED state — uncommitted
            # draft-window blocks return to the pool instead of riding
            # the blob into host RAM and back
            self.paged.trim_to(slot, wp)
            blob = self.paged.swap_out(
                slot, (self._toks, self._pos, self._rngs, self._recents))
            entry = PreemptedSlot(req, "swap", wp, blob)
        else:
            self.paged.release_slot(slot)
            if not req.tokens:
                req._first_pending = False  # unfetched 1st token is lost
            entry = PreemptedSlot(req, "recompute", wp)
        SERVE_PREEMPTIONS.inc(mode=entry.mode)
        TIMELINES.event(req.id, "preempt", mode=entry.mode, tokens=wp)
        self.pool.free(slot)
        self._reqs[slot] = None
        req.slot = None
        self._act = self._act.at[slot].set(False)
        self._toks = self._toks.at[slot].set(0)
        self._pos = self._pos.at[slot].set(0)
        self._preempted.append(entry)
        SERVE_SLOTS_BUSY.set(self.pool.busy_count)
        log.warning("preempted slot %d (%s, %d tokens): KV pool "
                    "exhausted", slot, entry.mode, wp)

    def _requeue_admission(self, pf: _Prefill):
        """Push a mid-prefill admission back to readmission to free its
        blocks (no tokens emitted yet — a clean restart, ordered ahead
        of every queued request via the preempted list)."""
        self._prefills.remove(pf)
        self.paged.release_slot(pf.slot)
        self.pool.free(pf.slot)
        self._reqs[pf.slot] = None
        pf.req.slot = None
        SERVE_PREEMPTIONS.inc(mode="recompute")
        TIMELINES.event(pf.req.id, "preempt", mode="requeue",
                        tokens=pf.pos)
        # resume gate = the WHOLE prompt's blocks (submit already
        # validated it fits an empty pool): gating on fewer would
        # re-admit the prefill while higher-class work still holds the
        # pool, and the "self" park path would bounce it back every
        # scheduler iteration — preempt/resume churn in the counters,
        # the timeline ring, and the log
        self._preempted.append(
            PreemptedSlot(pf.req, "recompute",
                          max(len(pf.req.prompt_ids) - 1, 0)))
        SERVE_SLOTS_BUSY.set(self.pool.busy_count)
        log.warning("readmitting request %s: KV pool exhausted "
                    "mid-prefill", pf.req.id)

    def _resume_preempted(self):
        """Oldest-first resume of preempted requests: swap entries
        re-allocate blocks and restore bytes + carries; recompute
        entries replay prompt + generated[:-1] through chunked prefill.
        Stops at the first entry that does not fit yet — strict FIFO, so
        a big parked request cannot be starved by smaller ones behind
        it."""
        while self._preempted and self.pool.free_count > 0:
            entry = self._preempted[0]
            req = entry.req
            if entry.mode == "swap":
                slot = self.pool.alloc()
                if not self.paged.swap_in(slot, entry.blob):
                    self.pool.free(slot)
                    self._fail_unresumable(entry)
                    return              # blocks still short; wait
                self._preempted.pop(0)
                toks_b, pos_b, rngs_b, recents_b = entry.blob["carries"]
                self._toks = self._toks.at[slot].set(int(toks_b))
                self._pos = self._pos.at[slot].set(int(pos_b))
                self._rngs = self._rngs.at[slot].set(jnp.asarray(rngs_b))
                self._recents = self._recents.at[slot].set(
                    jnp.asarray(recents_b))
                self._set_slot_sampling(slot, req.sampling)
                self._act = self._act.at[slot].set(True)
                self._reqs[slot] = req
                req.slot = slot
                # a kvshare-adopted stream enters HERE without ever
                # passing _start_admission — its API handler is waiting
                # on the admitted event (no-op for normal preempts,
                # whose admission already set it)
                if not req.admitted.is_set():
                    req.admitted.set()
                TIMELINES.event(req.id, "resume", mode="swap", slot=slot)
            else:
                need = self.paged.blocks_for(entry.tokens_at_preempt + 1)
                # ensure_free counts cache pins as reclaimable: a parked
                # request never reaches the allocation path where lazy
                # eviction runs, so the gate must evict for it
                if not self.paged.ensure_free(need):
                    self._fail_unresumable(entry)
                    return      # replaying now would thrash straight
                                # back into preemption; wait for room
                slot = self.pool.alloc()
                self._preempted.pop(0)
                self._reqs[slot] = req
                req.slot = slot
                # resume stamps BEFORE the replay it triggers, so the
                # timeline reads preempt -> resume -> replay
                TIMELINES.event(req.id, "resume", mode="recompute",
                                slot=slot)
                if req.tokens:
                    self._replay_slot(req, slot)
                else:
                    self._begin_prefill(_Prefill(req, slot))
            SERVE_SLOTS_BUSY.set(self.pool.busy_count)
            log.warning("resumed preempted request %s into slot %d (%s)",
                        req.id, req.slot if req.slot is not None else -1,
                        entry.mode)

    def _fail_unresumable(self, entry: PreemptedSlot):
        """A parked entry whose resume gate failed: if live work still
        holds blocks, more room is coming — leave it parked. With
        NOTHING running and the cache already drained by the gate, no
        future event can free another block, so the request is failed
        typed instead of hanging its client forever."""
        if self.pool.busy_count or self.queue.depth():
            return
        self._preempted.remove(entry)
        self._fail(entry.req, KVPoolExhausted(
            f"KV pool exhausted: preempted request {entry.req.id} needs "
            "more blocks than the pool can ever free"))

    # -- crash recovery (called by the supervisor, scheduler thread) --------

    def _rebuild(self, suspects: frozenset = frozenset()):
        """Rebuild-by-replay after a step failure: trust NOTHING on the
        device (donated inputs may be consumed, results may be garbage) —
        reallocate the pool and prefix cache, then reconstruct every live
        slot from its host-side token record by replaying prompt +
        generated[:-1] through the chunked-prefill path. Replay lands on
        the same chunk buckets admission compiled (usually zero new
        executables), and the fresh prefix cache is repopulated as replay
        runs, so slots sharing prefixes splice instead of recompute.

        Greedy continuations are bit-identical afterwards: position, last
        token, and the repeat-penalty window are all derivable from the
        record (cache rows hold prompt+generated minus the LAST emitted
        token — its KV is appended by the next decode step, exactly as it
        would have been without the crash). Requests that had emitted
        NOTHING yet restart admission from scratch instead.

        Suspects (requests implicated in the triggering crash) replay
        LAST and one at a time — a poisoned request re-crashes on its own
        solo replay, which is how the supervisor attributes it."""
        t0 = now()
        replays: list[ServeRequest] = []
        restarts: list[ServeRequest] = []
        for i, req in enumerate(self._reqs):
            if req is None:
                continue
            if req.cancelled.is_set() or req.done.is_set():
                self._fail(req, None)       # no row left to wipe: gone
                continue
            if req.tokens:
                replays.append(req)
            else:
                req._first_pending = False  # unfetched 1st token is lost
                restarts.append(req)
        self._prefills.clear()
        self.pool = SlotPool(self.slots)
        self._reqs = [None] * self.slots
        # release the impeached device state BEFORE reallocating: the
        # prefix cache's blocks and the old pool (rows or paged blocks)
        # pin HBM, and an oom-classified failure would re-OOM every
        # rebuild attempt if the replacement pool had to coexist with
        # the one it replaces. Preempted entries SURVIVE a rebuild —
        # swap blobs are host memory and recompute entries replay from
        # the host token record either way
        self._layers = None
        self.paged = None
        self.prefix_cache = None
        self._init_device_state()
        self.prefix_cache = self._build_prefix_cache()
        # register EVERY survivor before any device work: if a replay
        # crashes, the next rebuild's harvest must still see the ones
        # that hadn't replayed yet
        replays.sort(key=lambda r: r.id in suspects)    # innocents first
        jobs = []
        for req in replays:
            slot = self.pool.alloc()
            self._reqs[slot] = req
            req.slot = slot
            jobs.append((req, slot))
        for req in restarts:
            slot = self.pool.alloc()
            self._reqs[slot] = req
            req.slot = slot
        for req, slot in jobs:
            self._replay_slot(req, slot)
            # each completed replay is the CONTRAST that lets a later
            # replay crash be attributed to its own request's data
            self.supervisor.note_replay_ok()
        for req in restarts:
            self._begin_prefill(_Prefill(req, slot=req.slot))
        SERVE_SLOTS_BUSY.set(self.pool.busy_count)
        self._wake.set()
        log.warning("serve engine rebuilt in %.0f ms: %d slot(s) replayed, "
                    "%d admission(s) restarted, %d queued untouched",
                    (now() - t0) * 1e3, len(jobs), len(restarts),
                    self.queue.depth())

    def _replay_slot(self, req: ServeRequest, slot: int):
        """Replay one surviving request's recorded tokens into a fresh
        pool row and restore its decode carries bit-exactly (greedy).
        The rng carry is a fresh fold — unused under temperature 0; for
        sampled requests the stream is documented as resuming on a new
        rng after a rebuild."""
        ids = req.prompt_ids + req.tokens[:-1]
        n = len(ids)
        TIMELINES.event(req.id, "replay", tokens=n)
        hook = faults.FAULT_HOOK
        set_request_id(req.id)
        try:
            with RECORDER.span("serve.replay", cat="serve", slot=slot,
                               tokens=n):
                pos = 0
                keys: list = []
                matched = 0
                if self.prefix_cache is not None:
                    keys = self.prefix_cache.chain_keys(ids)
                    matched = self.prefix_cache.match(ids, keys)
                    if matched:
                        self._layers = self.prefix_cache.splice(
                            self._layers, slot, keys, matched)
                        pos = matched * self.chunk
                next_block = matched
                while pos < n:
                    take = min(self.chunk, n - pos)
                    if self.paged is not None and not \
                            self.paged.reserve_range(slot, pos, take):
                        # replay never preempts (it runs inside recovery
                        # / resume, where victim churn would thrash);
                        # cache eviction already happened inside
                        # reserve_range, so this is a genuinely full pool
                        raise KVPoolExhausted(
                            f"KV pool exhausted replaying {req.id}")
                    # recovery-grace watchdog limit: a replay chunk may
                    # carry an in-iteration compile for a bucket fresh
                    # generations never hit
                    self.supervisor.arm("replay", (req.id,), grace=True)
                    if hook is not None:
                        hook.on_prefill(req)
                    if self.paged is not None:
                        self.paged.prefill_into(slot, ids[pos:pos + take],
                                                 pos)
                    else:
                        _, self._layers = self.model.prefill_chunk(
                            self._layers, slot, ids[pos:pos + take], pos)
                    pos += take
                    next_block = self._capture_blocks(ids, slot, pos, n,
                                                      next_block, keys)
        finally:
            set_request_id(None)
        last = req.tokens[-1]
        recent = np.full((RECENT_N,), -1, np.int32)
        tail = req.tokens[-RECENT_N:]
        recent[RECENT_N - len(tail):] = tail
        rng = jax.random.fold_in(self._base_rng, self._seq)
        self._seq += 1
        self._toks = self._toks.at[slot].set(last)
        self._pos = self._pos.at[slot].set(n)
        self._rngs = self._rngs.at[slot].set(rng)
        self._recents = self._recents.at[slot].set(jnp.asarray(recent))
        self._set_slot_sampling(slot, req.sampling)
        self._act = self._act.at[slot].set(True)

    def _drop_poisoned(self, rid: str, err: PoisonedRequest) -> bool:
        """Fail ONE request (attributed poison) with its typed 500 and
        quarantine its fingerprint; the pool lives on for everyone else.
        Row state is not wiped — the caller is about to rebuild."""
        for i, req in enumerate(self._reqs):
            if req is None or req.id != rid:
                continue
            self._reqs[i] = None
            self.pool.free(i)
            self._prefills[:] = [p for p in self._prefills
                                 if p.req.id != rid]
            self.supervisor.quarantine(req.prompt_ids)
            SERVE_POISONED.inc()
            SERVE_SLOTS_BUSY.set(self.pool.busy_count)
            log.error("poisoned request %s dropped and quarantined: %s",
                      rid, err)
            self._fail(req, err)
            return True
        return False

    def _fail_all(self, err: EngineDown):
        """Budget exhausted: every live request is released with the
        typed down error (503 at the API — never a hang), the pool
        bookkeeping resets, and the device pool is dropped (the restore
        trial allocates the replacement)."""
        self._prefills.clear()
        for entry in self._drain_preempted():
            self._fail(entry.req, err)
        for req in self.queue.drain():
            self._fail(req, err)
        for i, req in enumerate(self._reqs):
            if req is not None:
                self._reqs[i] = None
                self._fail(req, err)
        self.pool = SlotPool(self.slots)
        self._act = jnp.zeros((self.slots,), jnp.bool_)
        # drop the device pool AND the prefix cache's blocks: an
        # oom-downed engine must not pin the old HBM while the restore
        # trial tries to allocate its replacement (_revive rebuilds both)
        self._layers = None
        self.paged = None
        self.prefix_cache = None
        SERVE_SLOTS_BUSY.set(0)

    # -- speculative decode (batched, accept-aware) -------------------------

    def _build_drafts(self, active: list[int]):
        """Host-side draft windows for this iteration's batched verify:
        the shared drafter proposes up to spec_k continuation tokens per
        slot from the slot's own committed token history (prompt +
        generated — the drafter-free n-gram mode needs no weights and no
        device work, and the lookup overlaps the previous iteration's
        still-queued prefill chunk). Slots the drafter abstains on, slots
        whose first token the host has not fetched yet, and slots out of
        budget/context headroom get an empty window — they take a plain
        decode step INSIDE the same dispatch. Returns (drafts [B, k]
        int32, n_drafts [B] int32), or None when every window came back
        empty (the iteration then dispatches the cheaper width-1 decode
        program)."""
        k = self.spec_k
        drafts = np.zeros((self.slots, k), np.int32)
        n_drafts = np.zeros((self.slots,), np.int32)
        any_draft = False
        for i in active:
            req = self._reqs[i]
            if req._first_pending:
                continue        # newest token still rides the next fetch
            pos = len(req.prompt_ids) + max(len(req.tokens) - 1, 0)
            ki = min(k, self.ctx - pos - 1, max(req.budget, 0))
            if self.paged is not None and self.spec_reserve > 0:
                # frontier-reservation cap: never back more speculative
                # frontier with blocks than CAKE_SPEC_RESERVE tokens
                ki = min(ki, self.spec_reserve)
            if ki <= 0:
                continue
            d = list(self.spec_drafter.propose(
                req.prompt_ids + req.tokens, ki))[:ki]
            if not d:
                continue
            drafts[i, :len(d)] = d
            n_drafts[i] = len(d)
            self._cur_nd[i] = len(d)
            any_draft = True
        return (drafts, n_drafts) if any_draft else None

    def _trim_spec_tails(self, exclude: int | None = None) -> bool:
        """Pressure-relief ROLLBACK of speculative frontier reservations:
        blocks mapped past what each slot's committed tokens plus its
        PENDING draft window need are returned to the pool — strictly
        cheaper than preempting a victim, so the exhaustion path tries
        this first. Keeps every block the in-flight or about-to-dispatch
        verify may still write (the _cur_nd window). True = at least one
        block freed (the caller retries its allocation)."""
        freed = 0
        for i in self.pool.busy():
            if i == exclude:
                continue
            req = self._reqs[i]
            if req is None:
                continue
            wp = len(req.prompt_ids) + max(len(req.tokens) - 1, 0)
            freed += self.paged.trim_to(
                i, wp + self._cur_nd.get(i, 0) + 1)
        return freed > 0

    def _fanout_spec(self, active: list[int], arr: np.ndarray, drafts,
                     n_drafts, nb: int):
        """Fan one speculative iteration's packed ids out to the streams:
        row 0 carries each slot's input token (a just-activated slot's
        unemitted FIRST token), row 1 its accepted-draft count, row 2 the
        verify step's correction/bonus token. The host already knows the
        drafts it proposed, so n_acc + 1 tokens per slot ride a fetch no
        bigger than the plain decode path's."""
        for i in active:
            req = self._reqs[i]
            if req._first_pending:
                req._first_pending = False
                req.t_first = now()
                req.stats["ttft_s"] = req.t_first - req.t_enqueue
                TIMELINES.event(req.id, "first_token")
                first = int(arr[0, i])
                self._emit(req, first)
                if self.model.cfg.is_eos(first) or req.budget <= 0:
                    self._finish(i, req)
                    continue
            n_prop = int(n_drafts[i])
            n_acc, nxt = int(arr[1, i]), int(arr[2, i])
            if n_prop:
                self.spec_steps += 1
                self.spec_proposed += n_prop
                self.spec_accepted += n_acc
                record_step(n_prop, n_acc, bucket=nb)
                TIMELINES.event(req.id, "spec_verify", bucket=nb,
                                proposed=n_prop, accepted=n_acc)
            else:
                TIMELINES.event(req.id, "decode", bucket=nb)
            for t in list(drafts[i, :n_acc]) + [nxt]:
                req.budget -= 1
                self._emit(req, int(t))
                if self.model.cfg.is_eos(int(t)) or req.budget <= 0:
                    self._finish(i, req)
                    break

    # -- batched decode -----------------------------------------------------

    def _fanout(self, active: list[int], arr: np.ndarray, nb: int):
        """Fan one decode iteration's packed ids out to the streams: row 0
        carries each slot's input token (a just-activated slot's unemitted
        FIRST token), row 1 the token this step sampled."""
        for i in active:
            req = self._reqs[i]
            TIMELINES.event(req.id, "decode", bucket=nb)
            if req._first_pending:
                req._first_pending = False
                req.t_first = now()     # first token actually on host:
                req.stats["ttft_s"] = req.t_first - req.t_enqueue
                TIMELINES.event(req.id, "first_token")
                first = int(arr[0, i])
                self._emit(req, first)
                if self.model.cfg.is_eos(first) or req.budget <= 0:
                    # this step's overshoot token is discarded — one
                    # wasted slot-row step, no recompute
                    self._finish(i, req)
                    continue
            tid = int(arr[1, i])
            req.budget -= 1
            self._emit(req, tid)
            if self.model.cfg.is_eos(tid) or req.budget <= 0:
                self._finish(i, req)

    def _emit(self, req: ServeRequest, tid: int):
        req.tokens.append(tid)
        if not req.cancelled.is_set():
            req._deliver(self.model._mk_token(tid))

    def _finish(self, slot: int, req: ServeRequest, cancelled: bool = False,
                release: bool = True):
        self.pool.free(slot)
        self._reqs[slot] = None
        if release:
            # wipe the row so a cancelled/finished request's KV never
            # lingers into the next occupant's prefix (prefix-cache splice
            # and chunked prefill both assume a clean row), and drop the
            # slot from the active mask — a freed row inside the decode
            # prefix is frozen outright, not stepped
            self._release_row(slot)
            self._toks = self._toks.at[slot].set(0)
            self._pos = self._pos.at[slot].set(0)
            self._act = self._act.at[slot].set(False)
        dt = now() - req.t_first if req.t_first else 0.0
        ndec = max(len(req.tokens) - 1, 0)
        req.stats.update({
            "decode_tokens": ndec, "decode_s": dt,
            "tok_per_s": ndec / dt if dt > 0 and ndec else 0.0,
        })
        req.result["tokens"] = req.tokens
        req.result["stats"] = req.stats
        if not cancelled and req.tokens:
            from ..models.common.text_model import _observe_generation
            _observe_generation(req.stats, len(req.tokens), path="serve")
        # SLO + terminal event only for a request not already finalized:
        # _fail may have released this waiter earlier (close() timeout
        # path), and a second terminal would double-count the histograms
        # and leave two conflicting terminals on the timeline
        if not req.done.is_set():
            outcome = "cancelled" if cancelled and "error" not in req.result \
                else ("error" if cancelled else "ok")
            self._observe_slo(req, outcome)
            TIMELINES.event(
                req.id, "finish", outcome=outcome, tokens=len(req.tokens),
                qos=req.qos,
                ttft_ms=round(req.stats.get("ttft_s", 0.0) * 1e3, 3),
                e2e_ms=round((now() - req.t_enqueue) * 1e3, 3),
                **({"tenant": req.tenant} if req.tenant else {}))
        SERVE_SLOTS_BUSY.set(self.pool.busy_count)
        req._deliver(ServeRequest.DONE)
        req._fire_done()

    def _observe_slo(self, req: ServeRequest, outcome: str):
        """Batched-path SLO decomposition, per terminal request: TTFT /
        mean ITL / e2e histograms labeled by outcome, each observation
        carrying the request id as its exemplar so a bad percentile in a
        scrape links to a concrete /api/v1/requests/<id> timeline."""
        SERVE_E2E_SECONDS.observe(now() - req.t_enqueue, exemplar=req.id,
                                  outcome=outcome)
        SERVE_QOS_E2E_SECONDS.observe(now() - req.t_enqueue,
                                      exemplar=req.id, qos=req.qos,
                                      outcome=outcome)
        if req.t_first:
            SERVE_TTFT_SECONDS.observe(req.t_first - req.t_enqueue,
                                       exemplar=req.id, outcome=outcome)
            SERVE_QOS_TTFT_SECONDS.observe(req.t_first - req.t_enqueue,
                                           exemplar=req.id, qos=req.qos,
                                           outcome=outcome)
            ndec = max(len(req.tokens) - 1, 0)
            if ndec:
                SERVE_ITL_SECONDS.observe(
                    (now() - req.t_first) / ndec, exemplar=req.id,
                    outcome=outcome)

    def _fail(self, req: ServeRequest, error: BaseException | None):
        if error is not None:
            req.result["error"] = error
        req.result.setdefault("tokens", req.tokens)
        # keep whatever stats accrued (queue_wait_s, prefill progress) —
        # failed/cancelled requests are the ones worth diagnosing
        req.result.setdefault("stats", req.stats)
        if not req.done.is_set():
            err = req.result.get("error")
            self._observe_slo(req, "error" if err is not None
                              else "cancelled")
            TIMELINES.event(req.id, "error",
                            type=type(err).__name__ if err is not None
                            else "cancelled")
        req._deliver(ServeRequest.DONE)
        req._fire_done()


def maybe_engine(model, slots: int | None = None,
                 max_queue: int | None = None,
                 ctx_len: int | None = None) -> ServeEngine | None:
    """Engine for serve-capable models, tuned by env: CAKE_SERVE_SLOTS
    (default 4, 0 disables), CAKE_MAX_QUEUE (default 64), CAKE_SERVE_CTX
    (default 4096, capped by the model's max_cache_len), CAKE_PREFILL_CHUNK
    (default 256 — per-iteration chunked-admission token budget),
    CAKE_PREFIX_CACHE_MB (default 256, 0 disables shared-prefix KV reuse),
    the paged-KV knobs CAKE_KV_BLOCKS / CAKE_KV_BLOCK_TOKENS /
    CAKE_PREEMPT_MODE (CAKE_KV_BLOCKS > 0 swaps the contiguous slot rows
    for a shared block pool with refcounted prefix sharing and
    preemption — see docs/serving.md#paged-kv-pool),
    the speculative-decoding knobs CAKE_SPEC / CAKE_SPEC_K /
    CAKE_SPEC_NGRAM / CAKE_SPEC_RESERVE (batched draft/verify/accept
    rides the same slot iteration — see docs/speculative.md), and the
    supervision
    knobs CAKE_STEP_WATCHDOG_S / CAKE_ENGINE_REBUILDS /
    CAKE_ENGINE_REBUILD_WINDOW_S / CAKE_ENGINE_RESTORE_S /
    CAKE_REQUEST_DEADLINE_S (see docs/fault_tolerance.md) — all read
    inside ServeEngine. Distributed / offloaded models return None —
    the API keeps its locked fallback."""
    from ..models.common.text_model import TextModel
    if not isinstance(model, TextModel):
        return None
    if slots is None:
        slots = knobs.get("CAKE_SERVE_SLOTS")
    if slots <= 0:
        return None
    if max_queue is None:
        max_queue = knobs.get("CAKE_MAX_QUEUE")
    if ctx_len is None:
        ctx_len = knobs.get("CAKE_SERVE_CTX")
    return ServeEngine(model, slots=slots, max_queue=max_queue,
                       ctx_len=ctx_len)
