"""Engine flight recorder: a bounded ring of recent scheduler iterations.

The recurring failure mode on this project's hardware is the WEDGE — a
device dispatch that never returns (ROADMAP's TPU caveat, the watchdog in
supervisor.py). When it happens, a gauge flips and /health says wedged,
but the evidence of WHAT the engine was doing in the seconds before is
gone: the span recorder is off by default and metrics are aggregates.
This module is the black box: every completed scheduler iteration
appends one small record (occupancy, dispatch bucket, dispatch+fetch
wall time, spec accept counts, queue depth, KV-pool occupancy) into a
ring of the last `CAKE_FLIGHT_RECORDER` iterations, and the supervisor
dumps the ring to `CAKE_TRACE_DIR` as JSON when the watchdog flags a
wedge or the rebuild budget puts the engine DOWN — the post-mortem an
operator (or the next session's bench triage) replays.

Recording is a dict append under a lock per scheduler iteration — noise
next to the device dispatch the iteration just ran. Dumping is the slow
path and only happens on the two failure classifications.
"""
from __future__ import annotations

import json
import logging
import os
import threading
from collections import deque

from .. import knobs
from ..obs import now

__all__ = ["FlightRecorder"]

log = logging.getLogger("cake_tpu.serve.flight")


class FlightRecorder:
    """Thread-safe iteration ring + dump-to-disk. The scheduler thread
    records; the watchdog thread and the supervisor dump."""

    def __init__(self, capacity: int | None = None):
        if capacity is None:
            capacity = knobs.get("CAKE_FLIGHT_RECORDER")
        self.capacity = max(int(capacity), 1)
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self.capacity)
        self._seq = 0

    def record(self, **fields) -> None:
        """Append one iteration record; `t` (monotonic seconds) and a
        process-lifetime sequence number are stamped here."""
        with self._lock:
            self._seq += 1
            rec = {"seq": self._seq, "t": round(now(), 6)}
            rec.update(fields)
            self._ring.append(rec)

    def snapshot(self) -> list[dict]:
        with self._lock:
            return [dict(r) for r in self._ring]

    def dump(self, reason: str, extra: dict | None = None) -> str | None:
        """Write the ring to CAKE_TRACE_DIR as JSON. Returns the path,
        or None when no trace dir is configured (the record still lives
        in memory for /health debugging via snapshot()). Never raises —
        the dump runs inside failure handling, and a full disk must not
        turn a wedge flag into a supervisor crash."""
        trace_dir = knobs.get_str("CAKE_TRACE_DIR")
        if not trace_dir:
            return None
        try:
            os.makedirs(trace_dir, exist_ok=True)
            with self._lock:
                seq = self._seq
                body = {
                    "reason": reason,
                    "pid": os.getpid(),
                    "iterations": [dict(r) for r in self._ring],
                }
            if extra:
                body.update(extra)
            path = os.path.join(
                trace_dir, f"cake-flight-{os.getpid()}-{seq}-{reason}.json")
            with open(path, "w") as f:
                json.dump(body, f)
            log.warning("flight recorder dumped %d iteration(s) to %s "
                        "(%s)", len(body["iterations"]), path, reason)
            return path
        except Exception:
            log.exception("flight recorder dump failed (%s)", reason)
            return None
