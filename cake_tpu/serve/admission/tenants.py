"""Per-tenant quotas: token-bucket rate limits + concurrent-inflight caps.

Tenancy is resolved per request from the ``X-Cake-Tenant`` header, or —
when basic auth / bearer keys are in play — from the API key, BEFORE any
queue slot is consumed: a tenant over its quota is answered with a typed
429 whose body carries ``"type": "tenant_quota"`` and never touches the
admission queue, the slot pool, or the job executor.

Policies come from the ``CAKE_QOS_TENANTS`` grammar::

    acme:rps=5,burst=10,inflight=4,max_class=standard;free:rps=1,inflight=1

  * entries separated by ``;``, fields by ``,``;
  * ``rps``       — request tokens per second refilled into the bucket
                    (0 / omitted = unlimited rate);
  * ``burst``     — bucket capacity (defaults to max(2*rps, 1));
  * ``inflight``  — max concurrently admitted requests + jobs
                    (0 / omitted = unlimited);
  * ``max_class`` — QoS ceiling: requests asking for a higher class are
                    clamped down (classes.clamp_class);
  * the tenant name ``*`` is a default policy for tenants not named.

DEFAULT-OPEN: a tenant with no matching policy (and no ``*`` entry) is
unlimited — quotas are an operator opt-in, not a deploy-time footgun
that 429s everything the day the knob is misspelled.
"""
from __future__ import annotations

import threading
from collections import OrderedDict

from ... import knobs
from ...obs import SERVE_TENANT_THROTTLES, now

__all__ = ["TenantPolicy", "TenantQuotaExceeded", "TenantRegistry",
           "parse_policies"]


class TenantQuotaExceeded(Exception):
    """Typed 429 answered before any queue slot is consumed. `reason`
    is "rate" (token bucket empty) or "inflight" (concurrency cap)."""

    def __init__(self, tenant: str, reason: str, retry_after_s: int = 1):
        super().__init__(
            f"tenant {tenant!r} over quota ({reason}); retry in "
            f"{retry_after_s}s")
        self.tenant = tenant
        self.reason = reason
        self.retry_after_s = retry_after_s

    def body(self) -> dict:
        """The typed 429 JSON body (the `tenant_quota` error type is
        the machine-readable contract clients key on)."""
        return {"error": str(self), "type": "tenant_quota",
                "tenant": self.tenant, "reason": self.reason}


class TenantPolicy:
    __slots__ = ("rps", "burst", "inflight", "max_class")

    def __init__(self, rps: float = 0.0, burst: float | None = None,
                 inflight: int = 0, max_class: str | None = None):
        self.rps = float(rps)
        self.burst = float(burst) if burst is not None \
            else max(2.0 * self.rps, 1.0)
        self.inflight = int(inflight)
        self.max_class = max_class


def parse_policies(spec: str | None) -> dict[str, TenantPolicy]:
    """CAKE_QOS_TENANTS grammar → {tenant: TenantPolicy}. Bad field
    names raise at parse (engine/server build time), not per request."""
    out: dict[str, TenantPolicy] = {}
    if not spec:
        return out
    for entry in str(spec).split(";"):
        entry = entry.strip()
        if not entry:
            continue
        name, _, fields = entry.partition(":")
        name = name.strip()
        if not name:
            raise ValueError(f"CAKE_QOS_TENANTS: empty tenant name in "
                             f"{entry!r}")
        kw: dict = {}
        for field in fields.split(","):
            field = field.strip()
            if not field:
                continue
            k, _, v = field.partition("=")
            k = k.strip()
            if k == "rps":
                kw["rps"] = float(v)
            elif k == "burst":
                kw["burst"] = float(v)
            elif k == "inflight":
                kw["inflight"] = int(v)
            elif k == "max_class":
                kw["max_class"] = v.strip().lower()
            else:
                raise ValueError(
                    f"CAKE_QOS_TENANTS: unknown field {k!r} (rps, "
                    f"burst, inflight, max_class)")
        out[name] = TenantPolicy(**kw)
    return out


class _Bucket:
    """One tenant's live accounting: token bucket (refilled lazily at
    read time from the monotonic clock) + inflight count."""

    __slots__ = ("policy", "tokens", "t_last", "inflight")

    def __init__(self, policy: TenantPolicy, t0: float):
        self.policy = policy
        self.tokens = policy.burst
        self.t_last = t0        # the registry's clock, not the wall —
                                # tests inject a fake clock
        self.inflight = 0


# live-bucket cap: tenant names are client-controlled when a `*`
# default policy exists, so the accounting dict must be bounded —
# idle buckets evict LRU past this (an evicted bucket refills to full
# burst on return, which only ever FAVORS the client)
MAX_BUCKETS = 4096


class TenantRegistry:
    """Thread-safe tenant admission: acquire() charges the bucket and
    takes an inflight slot, returning a release thunk the caller runs
    when the request/job reaches a terminal state."""

    def __init__(self, spec: str | None = None, clock=now):
        if spec is None:
            spec = knobs.get("CAKE_QOS_TENANTS")
        self.policies = parse_policies(spec)
        self._clock = clock
        self._lock = threading.Lock()
        # guarded-by: self._lock
        self._buckets: "OrderedDict[str, _Bucket]" = OrderedDict()

    def policy(self, tenant: str | None) -> TenantPolicy | None:
        """The policy governing `tenant`: an exact entry, else the `*`
        default, else None (default-open)."""
        if tenant is None:
            return None
        return self.policies.get(tenant) or self.policies.get("*")

    def max_class(self, tenant: str | None) -> str | None:
        pol = self.policy(tenant)
        return pol.max_class if pol is not None else None

    def acquire(self, tenant: str | None):
        """Admit one request/job for `tenant`. Returns a release thunk
        (idempotent); raises TenantQuotaExceeded BEFORE any queue slot
        is consumed. Unconfigured tenants (or tenant None) are
        default-open: the thunk is a no-op."""
        pol = self.policy(tenant)
        if pol is None:
            return lambda: None
        # metric label: tenants matched only by the `*` default report
        # as "*" — the label stays operator-bounded even though the
        # header value is client-controlled
        label = tenant if tenant in self.policies else "*"
        with self._lock:
            b = self._buckets.get(tenant)
            if b is None:
                b = self._buckets[tenant] = _Bucket(pol, self._clock())
                while len(self._buckets) > MAX_BUCKETS:
                    # LRU-evict an idle bucket (never one holding
                    # inflight slots — its release thunk points at it)
                    victim = next((k for k, v in self._buckets.items()
                                   if v.inflight == 0 and v is not b),
                                  None)
                    if victim is None:
                        break
                    del self._buckets[victim]
            else:
                self._buckets.move_to_end(tenant)
            if pol.rps > 0:
                t = self._clock()
                b.tokens = min(pol.burst,
                               b.tokens + (t - b.t_last) * pol.rps)
                b.t_last = t
                if b.tokens < 1.0:
                    wait = (1.0 - b.tokens) / pol.rps
                    SERVE_TENANT_THROTTLES.inc(tenant=label,
                                               reason="rate")
                    raise TenantQuotaExceeded(
                        tenant, "rate",
                        retry_after_s=max(1, int(wait + 0.999)))
            if pol.inflight > 0 and b.inflight >= pol.inflight:
                SERVE_TENANT_THROTTLES.inc(tenant=label,
                                           reason="inflight")
                raise TenantQuotaExceeded(tenant, "inflight",
                                          retry_after_s=1)
            if pol.rps > 0:
                b.tokens -= 1.0
            b.inflight += 1
        released = threading.Event()

        def release():
            # idempotent: terminal paths (done callback, handler
            # finally, submit-failure unwind) may all fire
            if released.is_set():
                return
            released.set()
            with self._lock:
                b.inflight = max(b.inflight - 1, 0)
        return release

    def inflight_of(self, tenant: str) -> int:
        with self._lock:
            b = self._buckets.get(tenant)
            return b.inflight if b is not None else 0
