"""AdmissionPlane: the one front door every generation endpoint shares.

The plane owns what is common to chat, images, and audio BEFORE any
workload-specific scheduling happens:

  * tenancy — resolve the tenant (X-Cake-Tenant header, else the
    Authorization bearer/basic credential), charge its token bucket and
    inflight cap (typed 429 ``tenant_quota`` before any queue slot);
  * class — resolve the QoS class (endpoint default, X-Cake-QoS header
    / ``qos`` body field override, tenant ceiling clamp);
  * heavy jobs — the JobExecutor that runs image/TTS work through the
    same class-aware weighted-fair queue machinery as chat;
  * drain — one switch that refuses new work typed while running work
    finishes, mirrored by the engine's own drain.

Chat requests then flow into the ServeEngine (whose admission queue is
the same class-aware AdmissionQueue), image/audio requests into the
JobExecutor; both populations share the queue-depth gauges, the
timeline store, and the per-class SLO instruments — ONE scheduler
surface, three workloads.
"""
from __future__ import annotations

import hashlib

from .classes import QOS_HEADER, TENANT_HEADER, resolve_class
from .jobs import GenerationJob, JobExecutor
from .tenants import TenantRegistry

__all__ = ["AdmissionPlane", "get_plane", "key_fingerprint"]


def key_fingerprint(credential: str) -> str:
    """Stable non-reversible tenant key for a bearer credential —
    what quotas match on and what observability records."""
    return "key-" + hashlib.blake2b(credential.encode(),
                                    digest_size=6).hexdigest()


class AdmissionPlane:
    def __init__(self, tenants: TenantRegistry | None = None,
                 job_workers: int | None = None):
        self.tenants = tenants if tenants is not None else TenantRegistry()
        self.jobs = JobExecutor(workers=job_workers)
        self.draining = False

    # -- per-request resolution ----------------------------------------------

    @staticmethod
    def tenant_of(headers, authorization: str | None = None) -> str | None:
        """The tenant a request bills against: the explicit header
        wins; otherwise the Authorization bearer credential is
        FINGERPRINTED (``key-<12 hex>`` of its blake2b) and that
        doubles as the tenant key, so keyed deployments get quotas
        without a second header. The raw credential never becomes the
        tenant name: tenant strings flow into timeline events, metric
        labels, and logs — observability surfaces scraped and retained
        with far weaker access control than the auth path. Operators
        key CAKE_QOS_TENANTS policies by the fingerprint (printed by
        ``python -c "from cake_tpu.serve.admission.plane import
        key_fingerprint; print(key_fingerprint('sk-...'))"``).
        None = anonymous (default-open)."""
        t = headers.get(TENANT_HEADER)
        if t:
            return t
        auth = authorization if authorization is not None \
            else headers.get("Authorization", "")
        if auth.startswith("Bearer "):
            cred = auth[7:].strip()
            return key_fingerprint(cred) if cred else None
        return None

    def resolve(self, headers, body: dict | None,
                endpoint_default: str) -> tuple[str, str | None]:
        """(qos, tenant) for one request: endpoint default, overridden
        by X-Cake-QoS / body ``qos``, clamped by the tenant's policy
        ceiling. Raises ValueError on an unknown class name (API: 400)."""
        tenant = self.tenant_of(headers)
        qos = resolve_class(
            endpoint_default, header=headers.get(QOS_HEADER),
            body_value=(body or {}).get("qos"),
            max_class=self.tenants.max_class(tenant))
        return qos, tenant

    def admit(self, tenant: str | None):
        """Charge the tenant's quota; returns an idempotent release
        thunk. Raises TenantQuotaExceeded (typed 429) before any queue
        slot is consumed."""
        return self.tenants.acquire(tenant)

    # -- heavy jobs ----------------------------------------------------------

    def submit_job(self, kind: str, fn, qos: str = "batch",
                   tenant: str | None = None,
                   request_id: str | None = None) -> GenerationJob:
        return self.jobs.submit(
            GenerationJob(kind, fn, qos=qos, tenant=tenant,
                          request_id=request_id))

    # -- lifecycle -----------------------------------------------------------

    def begin_drain(self):
        self.draining = True
        self.jobs.begin_drain()

    def drain(self, timeout: float | None = None) -> bool:
        self.draining = True
        return self.jobs.drain(timeout)

    def close(self):
        self.jobs.close()

    def health(self) -> dict:
        return {
            "draining": self.draining,
            "jobs_running": self.jobs.running_count(),
            "jobs_queued": self.jobs.queue.depth(),
            "job_workers": self.jobs.workers,
            "queue_by_class": self.jobs.queue.depths(),
            "tenant_policies": sorted(self.tenants.policies.keys()),
        }


def get_plane(state) -> AdmissionPlane:
    """The (lazily created) plane attached to an ApiState — handlers
    share one instance so tenant accounting and the job executor span
    every endpoint. Creation is cheap: worker threads start on the
    first job submit."""
    plane = getattr(state, "plane", None)
    if plane is None:
        plane = AdmissionPlane()
        state.plane = plane
    return plane
