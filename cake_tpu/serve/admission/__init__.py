"""Unified admission plane: weighted QoS classes, per-tenant quotas,
and heavy (image/TTS) generation jobs through one scheduler surface.

Grown from PR 2's single bounded FIFO (serve/admission.py) into a
package: every generation endpoint — chat through the serve engine,
images and audio through the JobExecutor — is admitted under a QoS
class with weighted-fair dequeue, per-tenant token-bucket quotas
answered with typed 429s before any queue slot is consumed, shared
queue-depth/SLO instruments, shared timeline events, and one drain
switch. See docs/qos.md.
"""
from .classes import (QOS_CLASSES, QOS_HEADER, TENANT_HEADER, class_bounds,
                      class_of, class_weights, clamp_class, priority,
                      resolve_class, retry_after_for)
from .jobs import GenerationJob, JobCancelled, JobExecutor, JobsDraining
from .plane import AdmissionPlane, get_plane
from .queue import AdmissionQueue, QueueFull
from .tenants import (TenantPolicy, TenantQuotaExceeded, TenantRegistry,
                      parse_policies)

__all__ = [
    "AdmissionPlane", "AdmissionQueue", "GenerationJob", "JobCancelled",
    "JobExecutor", "JobsDraining", "QOS_CLASSES", "QOS_HEADER",
    "QueueFull", "TENANT_HEADER", "TenantPolicy", "TenantQuotaExceeded",
    "TenantRegistry", "class_bounds", "class_of", "class_weights",
    "clamp_class", "get_plane", "parse_policies", "priority",
    "resolve_class", "retry_after_for",
]
