"""Bounded, class-aware admission queue with weighted-fair dequeue.

The PR 2 queue was a single FIFO: one class of traffic, one bound, 429
on overflow. The unified admission plane keeps the same surface (put /
pop / purge / drain, burst-friendly `allow_extra`) but routes items into
per-class deques and dequeues by DEFICIT ROUND ROBIN over the class
weights (Shreedhar & Varghese): each replenish round credits every
backlogged class with its weight, and pop() serves classes with credit
in priority order. Under saturation the service ratio converges to the
weight ratio — interactive chat drains ~8x faster than batch image jobs,
and batch still progresses every round (weights are validated > 0), so
neither side can starve the other. FIFO order is preserved WITHIN a
class, which keeps every existing single-class behavior (and test)
byte-for-byte.

Overflow is per class: a full batch queue sheds batch with a
Retry-After derived from the BATCH backlog and its service share, while
interactive admission stays open — the typed QueueFull carries the
class so the API's 429 can say which lane was full.

Thread-safe: producers are API handler threads (and the job executor's
submitters), consumers are the engine scheduler thread and job worker
threads. Depth transitions publish into cake_serve_queue_depth (total)
and cake_serve_qos_queue_depth{qos} (per class), SUMMED across every
live queue — the engine's request queue and the job executor's queue
count into the same instruments, which is what lets one dashboard see
the whole plane's backlog.
"""
from __future__ import annotations

import threading
import weakref
from collections import deque

from ...obs import SERVE_QOS_QUEUE_DEPTH, SERVE_QUEUE_DEPTH
from .classes import (QOS_CLASSES, class_bounds, class_of, class_weights,
                      merge_bounds, merge_weights, retry_after_for)

__all__ = ["AdmissionQueue", "QueueFull"]


class QueueFull(Exception):
    """Admission queue at capacity for the request's class;
    retry_after_s is the 429 hint, scaled by that class's backlog and
    service share."""

    def __init__(self, depth: int, retry_after_s: int = 1,
                 qos: str = "interactive"):
        super().__init__(
            f"admission queue full for class {qos!r} ({depth} waiting)")
        self.depth = depth
        self.retry_after_s = retry_after_s
        self.qos = qos


# every live AdmissionQueue, so depth transitions can publish the SUM —
# the plane's request queue and job queue share one gauge pair
_BOARD_LOCK = threading.Lock()
_QUEUES: "weakref.WeakSet[AdmissionQueue]" = weakref.WeakSet()


def _publish():
    """Recompute and publish total + per-class depth across live
    queues. Called under no queue lock (depths are read racily — the
    gauges are monitoring, not bookkeeping; every transition republishes
    so they converge immediately)."""
    totals = {c: 0 for c in QOS_CLASSES}
    with _BOARD_LOCK:
        queues = list(_QUEUES)
    for q in queues:
        for c in QOS_CLASSES:
            totals[c] += q.depth_of(c)
    for c, n in totals.items():
        SERVE_QOS_QUEUE_DEPTH.set(n, qos=c)
    SERVE_QUEUE_DEPTH.set(sum(totals.values()))


class AdmissionQueue:
    """Class-aware bounded queue. `maxsize` is the default PER-CLASS
    bound (CAKE_QOS_BOUNDS overrides individual classes); `weights`
    override CAKE_QOS_WEIGHTS (tests)."""

    def __init__(self, maxsize: int = 64, weights: dict | None = None,
                 bounds: dict | None = None):
        if maxsize < 1:
            raise ValueError(f"queue maxsize must be >= 1, got {maxsize}")
        self.maxsize = maxsize
        # constructor overrides go through the SAME merge + validation
        # as the knob path: a partial dict fills from defaults, and a
        # non-positive weight is rejected here rather than hanging
        # pop() in an infinite zero-credit replenish loop (or KeyError-
        # killing the consumer thread on an unlisted class)
        self.weights = class_weights() if weights is None \
            else merge_weights(weights)
        self.bounds = class_bounds(maxsize) if bounds is None \
            else merge_bounds(maxsize, bounds)
        self._lock = threading.Lock()
        self._q: dict[str, deque] = {c: deque() for c in QOS_CLASSES}
        # DRR deficit credit per class; replenished one round at a time
        # when no backlogged class holds credit, reset when a class
        # empties (credit never accumulates across idle periods)
        self._deficit: dict[str, float] = {c: 0.0 for c in QOS_CLASSES}
        with _BOARD_LOCK:
            _QUEUES.add(self)
        # republish after this queue is collected, so a queue GC'd with
        # recently-counted depth cannot leave phantom backlog on the
        # gauges (finalizer holds no reference to self)
        weakref.finalize(self, _publish)
        _publish()

    # -- producers -----------------------------------------------------------

    def put(self, item, allow_extra: int = 0) -> None:
        """allow_extra raises the class bound transiently — the engine
        passes its free-slot count so a BURST against an idle pool is
        never 429ed just because arrivals outpace the one-admission-
        per-iteration drain (the bound counts requests waiting BEYOND
        available slots)."""
        qos = class_of(item)
        with self._lock:
            q = self._q[qos]
            if len(q) >= self.bounds[qos] + max(allow_extra, 0):
                raise QueueFull(
                    len(q), qos=qos,
                    retry_after_s=retry_after_for(len(q), qos,
                                                  self.weights))
            q.append(item)
        _publish()

    # -- consumer (weighted-fair) --------------------------------------------

    def pop(self):
        """Weighted-fair pop; None when empty. Classes holding deficit
        credit are served in priority order (FIFO within a class); when
        no backlogged class holds credit, one replenish round adds each
        backlogged class's weight — so over any saturated window the
        dequeue counts converge to the weight ratio, and every class
        with positive weight is served at least once per round (no
        starvation)."""
        with self._lock:
            if not any(self._q[c] for c in QOS_CLASSES):
                return None
            while True:
                for c in QOS_CLASSES:
                    if not self._q[c]:
                        # empty classes hold no credit: an idle class
                        # must not bank a burst allowance (DRR's
                        # reset-on-empty rule)
                        self._deficit[c] = 0.0
                        continue
                    if self._deficit[c] >= 1.0:
                        self._deficit[c] -= 1.0
                        item = self._q[c].popleft()
                        break
                else:
                    # nobody had credit: one replenish round
                    for c in QOS_CLASSES:
                        if self._q[c]:
                            self._deficit[c] += self.weights[c]
                    continue
                break
        _publish()
        return item

    # -- views / sweeps ------------------------------------------------------

    def depth(self) -> int:
        return sum(len(q) for q in self._q.values())

    def depth_of(self, qos: str) -> int:
        return len(self._q.get(qos, ()))

    def depths(self) -> dict:
        """{class: waiting} snapshot (health / Retry-After surfaces)."""
        return {c: len(self._q[c]) for c in QOS_CLASSES}

    def purge(self, pred) -> list:
        """Remove and return every queued item matching pred — the
        scheduler's per-iteration sweep of requests whose client
        vanished while waiting, so abandoned entries stop pinning queue
        capacity (and 429ing live clients) until they reach the head."""
        dropped = []
        with self._lock:
            for c in QOS_CLASSES:
                hit = [it for it in self._q[c] if pred(it)]
                if hit:
                    dropped.extend(hit)
                    self._q[c] = deque(it for it in self._q[c]
                                       if not pred(it))
        if dropped:
            _publish()
        return dropped

    def drain(self) -> list:
        """Remove and return everything queued (engine shutdown/crash),
        highest class first, FIFO within class."""
        with self._lock:
            items = []
            for c in QOS_CLASSES:
                items.extend(self._q[c])
                self._q[c].clear()
        _publish()
        return items
