"""QoS classes for the unified admission plane.

Every generation workload — chat, image diffusion, TTS — is admitted
under one of three weighted classes:

  * ``interactive`` — latency-sensitive traffic a human is waiting on.
    Chat defaults here.
  * ``standard``    — ordinary API traffic with moderate latency needs.
  * ``batch``       — throughput traffic that tolerates queueing. Image
    and audio generation default here.

Classes are chosen by endpoint default, overridable per request via the
``X-Cake-QoS`` header or a ``qos`` body field, and CLAMPED by the
tenant's policy (a tenant capped at ``standard`` cannot buy its way into
``interactive`` with a header). The weighted-fair queue (queue.py) turns
the class weights into a service ratio under saturation — batch traffic
always progresses but can never starve chat — and the paged preemption
policy (serve/paged/preempt.py) evicts the lowest class first when the
KV pool runs out.
"""
from __future__ import annotations

from ... import knobs

__all__ = ["QOS_CLASSES", "QOS_HEADER", "TENANT_HEADER", "priority",
           "class_of", "clamp_class", "resolve_class", "class_weights",
           "class_bounds", "merge_weights", "merge_bounds",
           "retry_after_for"]

# priority order: higher = served/preserved first. The tuple order is
# also the weighted-fair dequeue's visit order, so under equal credit
# the higher class goes first.
QOS_CLASSES = ("interactive", "standard", "batch")
_PRIORITY = {"interactive": 2, "standard": 1, "batch": 0}
_DEFAULT_WEIGHTS = {"interactive": 8, "standard": 4, "batch": 1}

QOS_HEADER = "X-Cake-QoS"
TENANT_HEADER = "X-Cake-Tenant"


def priority(qos: str) -> int:
    """Numeric priority of a class (higher = more latency-sensitive).
    Unknown strings rank as interactive so a foreign object in the
    victim pool is never preferentially evicted by accident."""
    return _PRIORITY.get(qos, _PRIORITY["interactive"])


def class_of(item) -> str:
    """The QoS class an enqueued item travels under (requests and jobs
    both carry .qos; anything else rides interactive)."""
    qos = getattr(item, "qos", None)
    return qos if qos in _PRIORITY else "interactive"


def clamp_class(qos: str, max_class: str | None) -> str:
    """Clamp a requested class by a tenant policy's ceiling: the result
    never outranks max_class (None = no ceiling)."""
    if max_class is None or max_class not in _PRIORITY:
        return qos
    if priority(qos) > _PRIORITY[max_class]:
        return max_class
    return qos


def resolve_class(default: str, header: str | None = None,
                  body_value=None, max_class: str | None = None) -> str:
    """The class one request is admitted under: the endpoint default
    (chat = interactive, images/audio = batch), overridden by the
    X-Cake-QoS header or the body's ``qos`` field (header wins), then
    clamped by the tenant ceiling. Unknown class names raise ValueError
    (the API answers 400 — a typo must not silently land in a default
    class the client did not ask for)."""
    chosen = default
    for raw in (body_value, header):
        if raw is None or raw == "":
            continue
        val = str(raw).strip().lower()
        if val not in _PRIORITY:
            raise ValueError(
                f"unknown QoS class {raw!r} (one of: "
                f"{', '.join(QOS_CLASSES)})")
        chosen = val
    return clamp_class(chosen, max_class)


def _parse_per_class(spec: str | None, cast, defaults: dict) -> dict:
    """``interactive=8,standard=4,batch=1`` → {class: value}, falling
    back to `defaults` for classes the spec omits. Unknown class names
    raise — a misspelled knob must fail loudly at engine build, not
    silently leave a class on its default."""
    out = dict(defaults)
    if not spec:
        return out
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        name, _, val = part.partition("=")
        name = name.strip().lower()
        if name not in _PRIORITY:
            raise ValueError(f"unknown QoS class {name!r} in {spec!r}")
        out[name] = cast(val.strip())
    return out


def merge_weights(overrides: dict) -> dict:
    """Partial per-class weight dict merged onto the defaults and
    VALIDATED (> 0, known classes only) — the same checks the knob
    path runs, so a constructor override can never hand the queue a
    zero-credit or missing class."""
    w = dict(_DEFAULT_WEIGHTS)
    for cls, val in overrides.items():
        if cls not in _PRIORITY:
            raise ValueError(f"unknown QoS class {cls!r} in weights")
        w[cls] = float(val)
    for cls, val in w.items():
        if val <= 0:
            raise ValueError(
                f"QoS weights: {cls} weight must be > 0 (got {val}) — "
                "a zero-weight class starves")
    return w


def merge_bounds(default: int, overrides: dict) -> dict:
    """Partial per-class bound dict merged onto `default`, validated
    (>= 1, known classes only)."""
    b = {c: int(default) for c in QOS_CLASSES}
    for cls, val in overrides.items():
        if cls not in _PRIORITY:
            raise ValueError(f"unknown QoS class {cls!r} in bounds")
        b[cls] = int(val)
    for cls, val in b.items():
        if val < 1:
            raise ValueError(
                f"QoS bounds: {cls} bound must be >= 1, got {val}")
    return b


def class_weights(spec: str | None = None) -> dict:
    """Weighted-fair dequeue weights per class (CAKE_QOS_WEIGHTS when
    `spec` is None). Weights must be positive: a zero-weight class would
    never accrue deficit credit and starve outright — exactly what the
    weighted queue exists to prevent."""
    if spec is None:
        spec = knobs.get("CAKE_QOS_WEIGHTS")
    return merge_weights(_parse_per_class(spec, float, {}))


def class_bounds(default: int, spec: str | None = None) -> dict:
    """Per-class queue bounds (CAKE_QOS_BOUNDS when `spec` is None);
    classes the spec omits use `default` (the engine's max_queue)."""
    if spec is None:
        spec = knobs.get("CAKE_QOS_BOUNDS")
    return merge_bounds(default, _parse_per_class(spec, int, {}))


def retry_after_for(depth: int, qos: str, weights: dict) -> int:
    """Class-aware Retry-After for a shed request: the wait scales with
    THAT class's backlog divided by its share of service — a shed batch
    request behind a deep batch queue is told to come back much later
    than a shed interactive request behind a shallow one."""
    total = sum(weights.values()) or 1.0
    share = weights.get(qos, 1.0) / total
    # one queue drain ~ a few service rounds; 8 matches the legacy
    # depth//8 heuristic at share=1
    return max(1, min(120, int(depth / max(share, 1e-6)) // 8))
