"""GenerationJob + JobExecutor: non-slot workloads through the plane.

Image diffusion and TTS don't decode through KV slots, so the serve
engine can't batch them — but before this module they also bypassed the
admission queue entirely (the pre-PR-2 one-request lock), invisible to
backpressure, drain, tracing, and the queue-depth gauges. A
GenerationJob wraps one such workload so it flows through the SAME
class-aware weighted-fair queue as chat (its depth counts into
cake_serve_queue_depth / cake_serve_qos_queue_depth), emits the SAME
timeline events (enqueue/admit/finish with class + tenant attrs, so
``GET /api/v1/requests/<id>`` shows an image job's lifecycle), and
respects drain (new jobs are refused typed while running ones finish).

The executor keeps at most CAKE_JOB_WORKERS (default 1) heavy jobs
running. Job functions receive the job and are expected to call
``job.checkpoint()`` between diffusion steps / TTS frames: the
checkpoint raises JobCancelled when the client vanished (the 20-step
FLUX job stops at the next step instead of finishing for nobody) and
briefly yields the thread while interactive requests are queued
anywhere on the plane — so a newly-enqueued chat request is never stuck
behind a long diffusion step loop that hasn't looked up from the
device.
"""
from __future__ import annotations

import logging
import threading
import time
import uuid

from ... import knobs
from ...obs import (SERVE_JOBS_RUNNING, SERVE_QOS_E2E_SECONDS,
                    SERVE_QOS_QUEUE_DEPTH, TIMELINES, now, set_request_id)
from .queue import AdmissionQueue

__all__ = ["GenerationJob", "JobCancelled", "JobExecutor",
           "JobsDraining"]

log = logging.getLogger("cake_tpu.serve.admission")

# seconds a checkpoint yields when interactive work is queued: long
# enough for the engine scheduler thread to win the GIL and dispatch,
# short enough to cost a 20-step job at most ~40ms per pass
_YIELD_S = 0.002


class JobCancelled(Exception):
    """Raised inside job.checkpoint() when the client abandoned the job
    — the step loop unwinds instead of finishing work nobody reads."""


class JobsDraining(RuntimeError):
    """Job admission refused because the plane is draining for
    shutdown; running jobs finish, new ones answer 503 + Retry-After."""

    def __init__(self, retry_after_s: int = 5):
        super().__init__("admission plane draining for shutdown")
        self.retry_after_s = retry_after_s


class GenerationJob:
    """One queued heavy workload (image diffusion, TTS). Mirrors the
    ServeRequest surface the queue, the timelines, and the API waiters
    need: id / qos / tenant / t_enqueue / cancelled / admitted / done /
    result."""

    def __init__(self, kind: str, fn, qos: str = "batch",
                 tenant: str | None = None,
                 request_id: str | None = None):
        self.id = request_id or f"{kind}-" + uuid.uuid4().hex[:16]
        self.kind = kind                # "image" | "audio" | ...
        self.fn = fn                    # fn(job) -> result value
        self.qos = qos
        self.tenant = tenant
        self.t_enqueue = now()
        self.cancelled = threading.Event()
        self.admitted = threading.Event()
        self.done = threading.Event()
        self.result: dict = {}          # "value" | "error"
        self._done_cbs: list = []
        self._cb_lock = threading.Lock()

    # -- client surface ------------------------------------------------------

    def cancel(self):
        self.cancelled.set()

    def wait(self, timeout: float | None = None) -> bool:
        return self.done.wait(timeout)

    def add_done_callback(self, cb):
        """cb fires (worker thread) at the terminal transition; fires
        immediately (caller thread) if the job already finished."""
        with self._cb_lock:
            if not self.done.is_set():
                self._done_cbs.append(cb)
                return
        cb()

    # -- job-function surface ------------------------------------------------

    def checkpoint(self):
        """Call between diffusion steps / TTS frames: aborts a
        cancelled job, and yields the thread while interactive traffic
        is queued anywhere on the plane (the engine's queue and the job
        queue publish into the same per-class gauge) so chat admission
        is never starved by a step loop."""
        if self.cancelled.is_set():
            raise JobCancelled(f"job {self.id} cancelled")
        if SERVE_QOS_QUEUE_DEPTH.value(qos="interactive") > 0:
            time.sleep(_YIELD_S)

    # -- executor internals --------------------------------------------------

    def _finish(self, value=None, error: BaseException | None = None):
        if error is not None:
            self.result["error"] = error
        else:
            self.result["value"] = value
        with self._cb_lock:
            self.done.set()
            cbs, self._done_cbs = self._done_cbs, []
        for cb in cbs:
            try:
                cb()
            except Exception:
                pass                    # waiter's loop may be gone


class JobExecutor:
    """At most `workers` (CAKE_JOB_WORKERS) heavy jobs running at once,
    fed weighted-fair from a class-aware AdmissionQueue. Worker threads
    start lazily on the first submit so embedding an ApiState in a unit
    test costs no threads."""

    def __init__(self, workers: int | None = None,
                 max_queue: int | None = None):
        if workers is None:
            workers = knobs.get("CAKE_JOB_WORKERS")
        self.workers = max(int(workers), 1)
        if max_queue is None:
            max_queue = knobs.get("CAKE_MAX_QUEUE")
        self.queue = AdmissionQueue(max_queue)
        self.running = 0                # guarded-by: self._lock
        self._running_by_kind = {}      # guarded-by: self._lock
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._draining = threading.Event()
        self._threads: list[threading.Thread] = []

    # -- submit / lifecycle --------------------------------------------------

    def submit(self, job: GenerationJob) -> GenerationJob:
        """Enqueue a job. Raises JobsDraining during drain (running
        jobs finish; new ones are refused typed) and QueueFull when the
        job's class lane is at its bound."""
        if self._draining.is_set() or self._stop.is_set():
            raise JobsDraining()
        self.queue.put(job)
        # close() may have drained the queue and joined the workers
        # between the check above and the put: re-check and reclaim, or
        # the job would sit unexecuted forever with its waiter hung
        # (close's own drain catches the put-before-stop ordering)
        if self._stop.is_set() and self.queue.purge(lambda j: j is job):
            raise JobsDraining()
        TIMELINES.begin(job.id)
        # attr named `workload`, not `kind` — event()'s positional
        # parameter is `kind` (the supervisor hit the same collision)
        TIMELINES.event(job.id, "enqueue", qos=job.qos, workload=job.kind,
                        depth=self.queue.depth(),
                        **({"tenant": job.tenant} if job.tenant else {}))
        self._ensure_threads()
        self._wake.set()
        return job

    def _ensure_threads(self):
        with self._lock:
            while len(self._threads) < self.workers:
                t = threading.Thread(
                    target=self._loop, daemon=True,
                    name=f"cake-jobs-{len(self._threads)}")
                self._threads.append(t)
                t.start()

    def begin_drain(self):
        """Refuse new jobs immediately; running jobs keep going."""
        self._draining.set()
        self._wake.set()

    def drain(self, timeout: float | None = None) -> bool:
        """Stop admission and wait for queued + running jobs to finish
        (queued jobs still execute — they were accepted before the
        drain; only NEW submissions are refused). True = went idle."""
        self.begin_drain()
        deadline = None if timeout is None else now() + timeout
        while self.queue.depth() or self.running_count():
            if deadline is not None and now() >= deadline:
                return False
            time.sleep(0.02)
        return True

    def running_count(self) -> int:
        """Locked accessor for out-of-class readers (health views)."""
        with self._lock:
            return self.running

    def close(self, timeout: float = 5.0):
        self._stop.set()
        self._wake.set()
        for job in self.queue.drain():
            job._finish(error=JobsDraining())
        for t in self._threads:
            t.join(timeout=timeout)

    # -- worker loop ---------------------------------------------------------

    def _loop(self):
        while not self._stop.is_set():
            job = self.queue.pop()
            if job is None:
                self._wake.wait(0.2)
                self._wake.clear()
                continue
            if job.cancelled.is_set():
                job._finish(error=JobCancelled(
                    f"job {job.id} cancelled while queued"))
                continue
            self._run_one(job)

    def _run_one(self, job: GenerationJob):
        with self._lock:
            self.running += 1
            self._running_by_kind[job.kind] = \
                self._running_by_kind.get(job.kind, 0) + 1
            kind_running = self._running_by_kind[job.kind]
        # the gauge is per KIND: with >1 worker and mixed workloads the
        # executor-wide count would set the wrong label (and leave a
        # stale non-zero value behind the last finisher)
        SERVE_JOBS_RUNNING.set(kind_running, kind=job.kind)
        job.admitted.set()
        wait_ms = round((now() - job.t_enqueue) * 1e3, 3)
        TIMELINES.event(job.id, "admit", qos=job.qos, workload=job.kind,
                        queue_wait_ms=wait_ms,
                        **({"tenant": job.tenant} if job.tenant else {}))
        set_request_id(job.id)          # spans inside attribute to the job
        try:
            value = job.fn(job)
        except JobCancelled as e:
            TIMELINES.event(job.id, "error", type="cancelled")
            job._finish(error=e)
        except BaseException as e:      # surfaced to the API waiter
            TIMELINES.event(job.id, "error", type=type(e).__name__)
            job._finish(error=e)
        else:
            TIMELINES.event(
                job.id, "finish", outcome="ok", qos=job.qos,
                e2e_ms=round((now() - job.t_enqueue) * 1e3, 3),
                **({"tenant": job.tenant} if job.tenant else {}))
            job._finish(value=value)
        finally:
            set_request_id(None)
            with self._lock:
                self.running -= 1
                self._running_by_kind[job.kind] = max(
                    self._running_by_kind.get(job.kind, 1) - 1, 0)
                kind_running = self._running_by_kind[job.kind]
            SERVE_JOBS_RUNNING.set(kind_running, kind=job.kind)
            SERVE_QOS_E2E_SECONDS.observe(
                now() - job.t_enqueue, exemplar=job.id, qos=job.qos,
                outcome="ok" if "error" not in job.result else (
                    "cancelled" if isinstance(job.result.get("error"),
                                              JobCancelled) else "error"))
