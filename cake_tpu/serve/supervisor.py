"""Crash-only supervision for the serve engine: typed step failures, a
budgeted rebuild state machine, poison attribution, and a wedge watchdog.

Before this module, `ServeEngine._loop` answered every step exception the
same way: set `self.dead`, release the waiters, refuse all future submits
("serve engine is down") until a human restarted the process. That is the
wrong trade on the hardware this project actually runs on — the container
TPU wedges intermittently (BENCH_r04/r05), and PR 4 already proved the
recovery recipe for the cluster plane: classify, rebuild by replay,
budget the retries, degrade honestly. This module applies the same state
machine to the engine itself:

    serving ──step failure──▶ classify ──▶ rebuild-by-replay ──▶ serving
       ▲                         │ budget exhausted                 │
       │                         ▼                                  │
       └──trial step ok── DOWN (503 + Retry-After, /health engine   │
                          block; restore loop probes the device) ◀──┘

  * every failure becomes a `StepFailure(kind ∈ wedge|device|poison|
    oom|internal)` — counted per kind, surfaced in /health;
  * recoverable failures trigger `ServeEngine._rebuild`: reallocate the
    pool and replay every live slot's prompt+generated tokens through
    the chunked-prefill path (see engine.py — greedy continuation is
    bit-identical, pinned by tests/test_serve_faults.py);
  * a request implicated in two consecutive crashes is POISONED: the
    batch crash implicates every active slot, the rebuild replays
    suspects last and one at a time, so a re-crash during a solo replay
    names the culprit — that one request fails with a typed
    `PoisonedRequest` (500) and its prompt fingerprint is quarantined,
    instead of the whole pool crash-looping;
  * rebuilds are budgeted (`CAKE_ENGINE_REBUILDS` per rolling
    `CAKE_ENGINE_REBUILD_WINDOW_S`): past the budget the engine goes
    DOWN — submits answer a typed `EngineDown` (503 + Retry-After,
    never a bare 500), /health carries `engine.down`, and a restore
    loop probes the device every `CAKE_ENGINE_RESTORE_S` with a trial
    prefill until one succeeds, then the pool is rebuilt empty and
    serving resumes. `ServeEngine.dead` remains only as the true last
    resort (the supervisor itself failing).

The wedge watchdog is the serve-plane analog of PR 4's gray-failure
detector: a daemon thread watches the age of the currently-armed device
dispatch against `CAKE_STEP_WATCHDOG_S` (0 disables). It cannot interrupt
a call stuck inside the runtime — nothing can — so it FLAGS: `/health`
reports the engine wedged (503, so the balancer routes away) and
`cake_serve_engine_wedges_total` counts the event; if the dispatch then
dies the failure is classified `wedge`, and if it eventually returns the
flag clears (slow-but-alive, exactly like a gray hop). Recovery work
(replay, trial probes) is armed with a grace limit instead — replay
prefills may carry in-iteration XLA compiles for never-seen buckets, and
flagging the recovery itself as wedged would turn one fault into a
permanent 503 (observed live before the grace existed).

Threading: recovery runs ON the scheduler thread (the engine's device
state is single-threaded by design); the watchdog, API handlers
(submit/health) and this module share only the small annotated state
below, under `self._lock` (the lock-discipline lint enforces it).
"""
from __future__ import annotations

import hashlib
import logging
import threading
from collections import OrderedDict, deque

import numpy as np

from .. import knobs
from ..obs import (SERVE_ENGINE_DOWN, SERVE_ENGINE_REBUILDS,
                   SERVE_ENGINE_WEDGES, SERVE_STEP_FAILURES, TIMELINES,
                   now)

log = logging.getLogger("cake_tpu.serve.supervisor")

__all__ = ["EngineDown", "PoisonedRequest", "RequestDeadlineExceeded",
           "StepFailure", "Supervisor", "classify"]

STEP_KINDS = ("wedge", "device", "poison", "oom", "internal")

# watchdog limit for recovery-phase dispatches (replay / trial): replay
# prefills can compile never-seen chunk buckets in-iteration, and a tight
# CAKE_STEP_WATCHDOG_S would flag the recovery itself as wedged
REBUILD_GRACE_S = 60.0

# consecutive clean steps after a recovery before crash suspects are
# forgotten — two crashes separated by this much progress are treated as
# independent incidents, not a poison pattern
SUSPECT_CLEAR_STEPS = 8

# quarantined prompt fingerprints kept (FIFO past this)
QUARANTINE_CAP = 128


class EngineDown(RuntimeError):
    """The engine cannot take this request: scheduler dead, rebuild
    budget exhausted, or shut down. The API answers 503 + Retry-After on
    every chat path — never a bare 500 and never a hung stream."""

    def __init__(self, msg: str = "serve engine is down",
                 retry_after_s: int = 10):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


class PoisonedRequest(RuntimeError):
    """This request was implicated in consecutive engine crashes (or its
    fingerprint already sits in quarantine): it fails alone with a 500
    while the pool survives for everyone else."""


class RequestDeadlineExceeded(RuntimeError):
    """The request's TOTAL age (queue wait + prefill + decode) passed
    CAKE_REQUEST_DEADLINE_S: it is cancelled with a 504 instead of
    holding a slot for a client that has surely given up."""

    def __init__(self, age_s: float, deadline_s: float):
        super().__init__(
            f"request exceeded its {deadline_s:.1f}s deadline "
            f"(age {age_s:.1f}s)")
        self.age_s = age_s
        self.deadline_s = deadline_s


class StepFailure(RuntimeError):
    """A classified scheduler-step failure (the engine's recovery unit)."""

    def __init__(self, kind: str, cause: BaseException, phase: str,
                 implicated: frozenset):
        assert kind in STEP_KINDS
        super().__init__(
            f"{kind} failure in {phase}: {type(cause).__name__}: {cause}")
        self.kind = kind
        self.cause = cause
        self.phase = phase
        self.implicated = implicated


def classify(exc: BaseException) -> str:
    """Map a raw step exception onto a StepFailure kind. Injected faults
    carry their kind; real jax/XLA runtime errors are `device`; resource
    exhaustion in any spelling is `oom`; everything else is `internal`
    (a scheduler/model bug — still recoverable by rebuild, since the
    per-request state needed for replay lives on the host)."""
    kind = getattr(exc, "fault_kind", None)
    if kind in STEP_KINDS:
        return kind
    if isinstance(exc, MemoryError):
        return "oom"
    text = f"{type(exc).__name__}: {exc}".lower()
    if "resource_exhausted" in text or "resource exhausted" in text \
            or "out of memory" in text:
        return "oom"
    mod = type(exc).__module__ or ""
    if mod.startswith("jaxlib") or "xlaruntime" in type(exc).__name__.lower():
        return "device"
    return "internal"


def fingerprint(prompt_ids) -> bytes:
    """Stable identity of a request's content (quarantine key): a retry
    of a poisoned prompt is refused without crashing the pool again."""
    return hashlib.blake2b(np.asarray(prompt_ids, np.int32).tobytes(),
                           digest_size=16).digest()


class Supervisor:
    """Policy half of the crash-only engine. The engine owns the device
    state and calls in (`arm`/`disarm` around dispatches, `on_failure`
    from its loop's catch); the supervisor owns classification, budget,
    suspects, quarantine, and the down flag."""

    def __init__(self, engine, watchdog_s: float | None = None,
                 rebuild_budget: int | None = None,
                 rebuild_window_s: float | None = None,
                 restore_interval_s: float | None = None):
        self.engine = engine
        if watchdog_s is None:
            watchdog_s = knobs.get("CAKE_STEP_WATCHDOG_S")
        if rebuild_budget is None:
            rebuild_budget = knobs.get("CAKE_ENGINE_REBUILDS")
        if rebuild_window_s is None:
            rebuild_window_s = knobs.get("CAKE_ENGINE_REBUILD_WINDOW_S")
        if restore_interval_s is None:
            restore_interval_s = knobs.get("CAKE_ENGINE_RESTORE_S")
        self.watchdog_s = watchdog_s
        self.rebuild_budget = rebuild_budget
        self.rebuild_window_s = rebuild_window_s
        self.restore_interval_s = restore_interval_s

        # -- cross-thread state (scheduler / watchdog / API handlers) ------
        self._lock = threading.Lock()
        self._inflight_phase = None     # guarded-by: self._lock
        self._inflight_t0 = 0.0         # guarded-by: self._lock
        self._inflight_limit = 0.0      # guarded-by: self._lock
        self._wedge_pending = False     # guarded-by: self._lock
        self._last_phase = "step"       # guarded-by: self._lock
        self._last_ids = ()             # guarded-by: self._lock
        self._down = None               # guarded-by: self._lock
        self._last_failure = None       # guarded-by: self._lock
        self._quarantine = OrderedDict()  # guarded-by: self._lock

        # -- scheduler-thread-only state -----------------------------------
        self._rebuilds: deque = deque()   # rolling-window timestamps
        self._suspects: frozenset | None = None
        self._replay_ok = 0               # successful replays this rebuild
        self._clean_steps = 0
        self.rebuild_count = 0          # lifetime (health counter)
        self.wedge_count = 0            # watchdog thread increments

        self._watchdog = None
        if self.watchdog_s > 0:
            self._watchdog = threading.Thread(
                target=self._watch, daemon=True, name="cake-serve-watchdog")
            self._watchdog.start()

    # -- dispatch tracking (scheduler thread) -------------------------------

    def arm(self, phase: str, req_ids=(), grace: bool = False) -> None:
        """A device dispatch is starting: record phase + the requests it
        could implicate (failure attribution) and start the wedge clock.
        `grace` widens the limit for recovery work that may compile."""
        limit = (max(self.watchdog_s, REBUILD_GRACE_S) if grace
                 else self.watchdog_s)
        with self._lock:
            self._inflight_phase = phase
            self._inflight_t0 = now()
            self._inflight_limit = limit
            self._last_phase = phase
            self._last_ids = tuple(req_ids)

    def disarm(self) -> None:
        """The dispatch came back: stop the wedge clock; a pending wedge
        flag clears (slow-but-alive — the gray-failure outcome)."""
        with self._lock:
            self._inflight_phase = None
            self._wedge_pending = False

    def _watch(self) -> None:
        """Watchdog thread: flag a dispatch stuck past its limit. It
        cannot preempt the runtime — the flag drives /health (503 so the
        balancer routes away) and classification if the step then dies."""
        stop = self.engine._stop
        poll = max(0.02, min(self.watchdog_s / 4.0, 0.5))
        while not stop.wait(poll):
            with self._lock:
                phase = self._inflight_phase
                if phase is None or self._wedge_pending:
                    continue
                age = now() - self._inflight_t0
                if age <= self._inflight_limit:
                    continue
                self._wedge_pending = True
                limit = self._inflight_limit
            self.wedge_count += 1
            SERVE_ENGINE_WEDGES.inc()
            log.error("serve watchdog: %s dispatch in flight %.1fs "
                      "(limit %.1fs) — engine wedged", phase, age, limit)
            # black box out the door while the evidence is fresh: the
            # wedged dispatch may never return, and a later process kill
            # would take the in-memory ring with it
            self._dump_flight("wedge")

    # -- failure handling (scheduler thread) --------------------------------

    def on_failure(self, exc: BaseException) -> bool:
        """Drive the recovery state machine for a loop-escaping failure.
        Returns True when the engine may keep running (recovered, or
        honestly DOWN with the restore loop armed); False means die —
        the engine falls back to the legacy `dead` terminal state."""
        eng = self.engine
        while True:
            if isinstance(exc, (KeyboardInterrupt, SystemExit)):
                return False
            with self._lock:
                phase = self._last_phase
                implicated = frozenset(self._last_ids)
                wedged = self._wedge_pending
                self._inflight_phase = None
                self._wedge_pending = False

            # attribution BEFORE classification: a second consecutive
            # crash pinned on ONE request out of a previously LARGER
            # suspect set makes the failure `poison` — the rebuild
            # replays suspects last and solo, so a data-dependent crash
            # re-fires on exactly the culprit's own replay while the
            # innocents' replays (the contrast) succeeded. A lone busy
            # slot can never be attributed (|prev| must exceed 1): with
            # no other request to contrast against, a repeat crash is
            # indistinguishable from a dying device, and quarantining an
            # innocent prompt forever is worse than letting the rebuild
            # budget handle a crash-loop.
            prev = self._suspects
            narrowed = implicated
            if prev and implicated:
                narrowed = (implicated & prev) or implicated
            poisoned = None
            if prev and len(prev) > 1 and len(narrowed) == 1 \
                    and next(iter(narrowed)) in prev:
                poisoned = next(iter(narrowed))
            if poisoned is not None and phase == "replay" \
                    and self._replay_ok == 0:
                # a replay crash with ZERO successful replays before it is
                # not evidence against the request — a still-broken device
                # kills the FIRST replay too (innocents replay first, so a
                # true poison only crashes after its contrast succeeded)
                poisoned = None

            kind = ("poison" if poisoned
                    else "wedge" if wedged else classify(exc))
            SERVE_STEP_FAILURES.inc(kind=kind)
            for rid in implicated:
                TIMELINES.event(rid, "step_failure", failure=kind,
                                phase=phase)
            summary = (f"{kind} in {phase}: "
                       f"{type(exc).__name__}: {exc}")
            with self._lock:
                self._last_failure = {"kind": kind, "phase": phase,
                                      "error": summary, "at": now()}
            log.error("serve step failure (%s), %d request(s) implicated",
                      summary, len(implicated))

            if poisoned is not None:
                err = PoisonedRequest(
                    f"request {poisoned} implicated in two consecutive "
                    "engine crashes; fingerprint quarantined")
                eng._drop_poisoned(poisoned, err)
                self._suspects = None
            else:
                self._suspects = narrowed or prev
            self._clean_steps = 0

            # rebuild budget: a rolling window, not a lifetime count — a
            # storm is a dying device, an isolated blip years later isn't
            t = now()
            while self._rebuilds and \
                    self._rebuilds[0] < t - self.rebuild_window_s:
                self._rebuilds.popleft()
            if len(self._rebuilds) >= self.rebuild_budget:
                with self._lock:
                    self._down = {"since": t}
                SERVE_ENGINE_DOWN.set(1)
                log.error(
                    "serve engine DOWN: %d rebuilds inside %.0fs exhausted "
                    "the budget (%d); failing live requests, restore loop "
                    "probing every %.1fs", len(self._rebuilds),
                    self.rebuild_window_s, self.rebuild_budget,
                    self.restore_interval_s)
                eng._fail_all(EngineDown(
                    f"serve engine down: rebuild budget exhausted ({summary})",
                    retry_after_s=max(int(self.restore_interval_s) + 1, 5)))
                self._dump_flight("down")
                return True
            self._rebuilds.append(t)
            self.rebuild_count += 1
            SERVE_ENGINE_REBUILDS.inc()
            self._replay_ok = 0
            try:
                eng._rebuild(self._suspects or frozenset())
                return True
            except BaseException as next_exc:  # recovery crashed: re-enter
                exc = next_exc

    def _dump_flight(self, reason: str) -> None:
        """Write the engine's iteration ring to CAKE_TRACE_DIR (no-op
        without a trace dir; never raises — see flight.py). Runs on the
        watchdog thread (wedge) or the scheduler thread (DOWN)."""
        fr = getattr(self.engine, "flight", None)
        if fr is not None:
            fr.dump(reason, extra={"last_failure": self.last_failure()})

    def note_replay_ok(self) -> None:
        """One slot's replay completed — the contrast that makes a later
        replay crash attributable to its own request."""
        self._replay_ok += 1

    def note_ok(self) -> None:
        """One scheduler step completed cleanly; enough of these and the
        suspect set from the last incident is forgotten."""
        if self._suspects is not None:
            self._clean_steps += 1
            if self._clean_steps >= SUSPECT_CLEAR_STEPS:
                self._suspects = None

    def note_probe_failure(self, exc: BaseException) -> None:
        with self._lock:
            self._last_failure = {
                "kind": classify(exc), "phase": "trial",
                "error": f"restore probe failed: "
                         f"{type(exc).__name__}: {exc}",
                "at": now()}
        log.warning("serve restore probe failed: %s", exc)

    def clear_down(self) -> None:
        with self._lock:
            self._down = None
        SERVE_ENGINE_DOWN.set(0)

    # -- quarantine ---------------------------------------------------------

    def quarantine(self, prompt_ids) -> None:
        fp = fingerprint(prompt_ids)
        with self._lock:
            self._quarantine[fp] = now()
            self._quarantine.move_to_end(fp)
            while len(self._quarantine) > QUARANTINE_CAP:
                self._quarantine.popitem(last=False)

    def is_quarantined(self, prompt_ids) -> bool:
        fp = fingerprint(prompt_ids)
        with self._lock:
            return fp in self._quarantine

    # -- introspection (any thread) -----------------------------------------

    def is_down(self) -> bool:
        with self._lock:
            return self._down is not None

    def down_info(self) -> dict | None:
        with self._lock:
            if self._down is None:
                return None
            info = {"down_for_s": round(now() - self._down["since"], 1)}
            if self._last_failure is not None:
                info["last_failure"] = self._last_failure["error"]
            return info

    def wedged(self) -> bool:
        with self._lock:
            return self._wedge_pending

    def last_failure(self) -> dict | None:
        with self._lock:
            if self._last_failure is None:
                return None
            lf = dict(self._last_failure)
            lf["age_s"] = round(now() - lf.pop("at"), 1)
            return lf

    def quarantined_count(self) -> int:
        with self._lock:
            return len(self._quarantine)
