"""Slot bookkeeping for the continuous-batching engine.

A SlotPool tracks which rows of the fixed B-row KV-cache pool are busy.
Allocation always returns the LOWEST free index: occupied slots cluster at
the bottom of the pool, so the batched decode program only has to cover the
prefix 0..highest_busy (power-of-two bucketed by `slot_bucket`) — as load
drops, high slots drain and the decode executable shrinks a bucket at a
time.

Pure host-side bookkeeping (no jax): unit-testable without a model. All
methods are called from the single scheduler thread; no locking.
"""
from __future__ import annotations


def slot_bucket(n: int, cap: int) -> int:
    """Smallest power-of-two >= n, capped at cap — the batched decode
    program's static row count. (PREFILL_BUCKETS starts at 32, so
    text_model.bucket_for would pin every pool <= 32 slots to its full
    size and the occupied-prefix shrink would never engage.)"""
    b = 1
    while b < n:
        b <<= 1
    return min(b, cap)


def slot_buckets(cap: int) -> tuple[int, ...]:
    """The bucket ladder a cap-slot pool can dispatch at: 1, 2, 4, ...
    cap (cap itself included even when not a power of two). Scaling
    CAKE_SERVE_SLOTS from 4 to 8/16 adds exactly ONE rung per doubling —
    a bucket transition compiles only the new bucket's executable, and
    existing rungs keep their compiled programs (pinned in
    tests/test_spec_serve.py). Warmup code and benches iterate this
    ladder instead of hand-rolling powers of two."""
    out = []
    b = 1
    while b < cap:
        out.append(b)
        b <<= 1
    out.append(cap)
    return tuple(out)


class SlotPool:
    def __init__(self, n: int):
        if n < 1:
            raise ValueError(f"slot pool needs >= 1 slot, got {n}")
        self.n = n
        self._busy: set[int] = set()

    @property
    def free_count(self) -> int:
        return self.n - len(self._busy)

    @property
    def busy_count(self) -> int:
        return len(self._busy)

    def busy(self) -> list[int]:
        """Occupied slot indices, ascending."""
        return sorted(self._busy)

    def alloc(self) -> int | None:
        """Claim the lowest free slot; None when the pool is full."""
        for i in range(self.n):
            if i not in self._busy:
                self._busy.add(i)
                return i
        return None

    def free(self, i: int) -> None:
        if i not in self._busy:
            raise ValueError(f"slot {i} is not allocated")
        self._busy.discard(i)

    def prefix_len(self) -> int:
        """Smallest prefix length covering every busy slot (0 when idle) —
        the batched decode program's row count before bucketing."""
        return max(self._busy) + 1 if self._busy else 0
