"""Bounded admission queue for the continuous-batching engine.

The reference (and the inherited locked path) queues unboundedly on an
asyncio.Lock — under overload every client waits forever and memory grows
with the backlog. Here admission is explicit: a bounded FIFO whose
overflow raises QueueFull, which the API layer converts into a 429 with a
Retry-After hint, so clients shed load instead of piling up.

Thread-safe: producers are API handler threads, the consumer is the
scheduler thread. Depth is mirrored into the cake_serve_queue_depth gauge
on every transition.
"""
from __future__ import annotations

import threading
from collections import deque

from ..obs import SERVE_QUEUE_DEPTH


class QueueFull(Exception):
    """Admission queue at capacity; retry_after_s is the 429 hint."""

    def __init__(self, depth: int, retry_after_s: int = 1):
        super().__init__(f"admission queue full ({depth} waiting)")
        self.depth = depth
        self.retry_after_s = retry_after_s


class AdmissionQueue:
    def __init__(self, maxsize: int = 64):
        if maxsize < 1:
            raise ValueError(f"queue maxsize must be >= 1, got {maxsize}")
        self.maxsize = maxsize
        self._items: deque = deque()
        self._lock = threading.Lock()

    def put(self, item, allow_extra: int = 0) -> None:
        """allow_extra raises the bound transiently — the engine passes its
        free-slot count so a BURST against an idle pool is never 429ed
        just because arrivals outpace the one-admission-per-iteration
        drain (maxsize bounds requests waiting BEYOND available slots)."""
        with self._lock:
            if len(self._items) >= self.maxsize + max(allow_extra, 0):
                # hint scales with backlog: a deep queue means a longer wait
                raise QueueFull(len(self._items),
                                retry_after_s=max(1, len(self._items) // 8))
            self._items.append(item)
            SERVE_QUEUE_DEPTH.set(len(self._items))

    def pop(self):
        """FIFO pop; None when empty."""
        with self._lock:
            if not self._items:
                return None
            item = self._items.popleft()
            SERVE_QUEUE_DEPTH.set(len(self._items))
            return item

    def depth(self) -> int:
        return len(self._items)

    def purge(self, pred) -> list:
        """Remove and return every queued item matching pred — the
        scheduler's per-iteration sweep of requests whose client vanished
        while waiting, so abandoned entries stop pinning queue capacity
        (and 429ing live clients) until they reach the head."""
        with self._lock:
            dropped = [it for it in self._items if pred(it)]
            if dropped:
                self._items = deque(it for it in self._items
                                    if not pred(it))
                SERVE_QUEUE_DEPTH.set(len(self._items))
            return dropped

    def drain(self) -> list:
        """Remove and return everything queued (engine shutdown/crash)."""
        with self._lock:
            items = list(self._items)
            self._items.clear()
            SERVE_QUEUE_DEPTH.set(0)
            return items
