"""PagedKV: the device-facing facade of the paged-KV subsystem.

Owns the physical block pool (cache.init_paged_layers), the per-slot
row state (SWA rings + linear-attention conv/recurrent), the DEVICE
block-table array the traced programs read, and the host-side
BlockAllocator that mirrors it. The serve engine talks to this object;
the allocator never touches jax and the engine never touches block ids.

Everything here runs on the engine's scheduler thread. Device/host
mirrors are kept in lockstep: every allocator mutation that changes a
table entry immediately updates the [B, max_blocks] device array (a
scalar scatter — the same cost class as the engine's `active`-mask
flips, and like them it never changes a compiled shape).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...models.common.cache import init_paged_layers
from ...obs import (SERVE_KV_BLOCKS_FREE, SERVE_KV_BLOCKS_SHARED,
                    SERVE_KV_BLOCKS_USED)
from .allocator import BlockAllocator

__all__ = ["PagedKV", "KVPoolExhausted", "pow2_block_tokens"]


class KVPoolExhausted(RuntimeError):
    """The block pool cannot satisfy an allocation even after prefix-
    cache eviction and preemption — the request is failed with a typed
    error instead of wedging the scheduler."""

    def __init__(self, msg: str, retry_after_s: int = 2):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


def pow2_block_tokens(n: int, chunk: int) -> int:
    """Clamp the block size to a power of two in [8, chunk]: chunk %
    block == 0 keeps every chunked-prefill boundary a block boundary
    (the prefix share unit and the GDN boundary-exact snapshot rule both
    hang off that alignment)."""
    n = max(8, min(int(n), chunk))
    b = 8
    while b * 2 <= n:
        b *= 2
    return b


class PagedKV:
    def __init__(self, model, slots: int, ctx: int, num_blocks: int,
                 block_tokens: int):
        self.model = model
        self.slots = slots
        self.ctx = ctx
        self.bt = block_tokens
        self.num_blocks = num_blocks
        self.max_blocks = ctx // block_tokens
        self.alloc = BlockAllocator(num_blocks, block_tokens, slots,
                                    self.max_blocks)
        self.NULL = self.alloc.NULL
        self.pool, self.rows = init_paged_layers(
            model.cfg, num_blocks, block_tokens, slots, ctx, model.dtype)
        self.has_rows = any(r for r in self.rows)
        # device bytes one physical block costs across every pooled layer
        # (the prefix cache's capacity accounting unit)
        self.block_bytes = sum(
            int(np.prod(pl[n].shape[1:])) * pl[n].dtype.itemsize
            for pl in self.pool if pl for n in ("k", "v", "pos"))
        self.tables = jnp.full((slots, self.max_blocks), self.NULL,
                               jnp.int32)
        # eviction hook: () -> int, blocks actually freed (wired to the
        # paged prefix cache's LRU by the engine)
        self.evictor = None
        self.swaps = 0
        self._publish()

    @classmethod
    def build(cls, model, slots: int, ctx: int, num_blocks: int,
              block_tokens: int, chunk: int) -> "PagedKV":
        if chunk & (chunk - 1):
            raise ValueError(
                f"prefill chunk {chunk} must be a power of two — block "
                "boundaries must align with chunk boundaries (the "
                "engine's _pow2_chunk clamp guarantees this; direct "
                "callers must too)")
        bt = pow2_block_tokens(block_tokens, chunk)
        if ctx % bt:
            raise ValueError(
                f"CAKE_KV_BLOCK_TOKENS={bt} must divide the serve context "
                f"{ctx} so the paged view keeps the contiguous row layout")
        if not any(s.kind != "linear" and s.window is None
                   for s in model.cfg.layer_specs()):
            raise ValueError(
                "paged KV needs at least one full-attention layer — "
                "SWA rings and linear state are O(window)/O(1) per slot "
                "and have nothing to page")
        return cls(model, slots, ctx, num_blocks, bt)

    # -- allocation (host) --------------------------------------------------

    def blocks_for(self, tokens: int) -> int:
        """Blocks a sequence of `tokens` tokens occupies (write frontier
        inclusive)."""
        return -(-tokens // self.bt)

    def _alloc_one(self) -> int | None:
        """One free block, evicting prefix-cache LRU units under
        pressure (cache-held blocks are reclaimable capacity, exactly
        like the contiguous prefix cache's LRU — unified here)."""
        pid = self.alloc.alloc()
        while pid is None and self.evictor is not None and self.evictor():
            pid = self.alloc.alloc()
        return pid

    def ensure_free(self, n: int) -> bool:
        """Evict prefix-cache LRU until at least `n` blocks are FREE.
        The allocation path reclaims cache blocks lazily (inside
        _alloc_one), but a PARKED preempted request never reaches an
        allocation — its resume gate must count cache pins as the
        reclaimable capacity they are, or blocks held only by the cache
        would starve it forever. False = short even with the cache
        empty."""
        while self.alloc.free_count < n:
            if self.evictor is None or not self.evictor():
                return False
        return True

    def sync_table_row(self, slot: int) -> None:
        """Publish the slot's host table row to the device in ONE write
        + one gauge publish — the batched companion to ensure()'s
        single-entry scatter, for callers that mapped several entries
        host-side (prefix splice, chunk reservation)."""
        self.tables = self.tables.at[slot].set(
            jnp.asarray(self.alloc.tables[slot], jnp.int32))
        self._publish()

    def ensure(self, slot: int, block_idx: int) -> bool:
        """Back table entry (slot, block_idx) with a physical block;
        False = pool exhausted even after cache eviction (the engine
        escalates to preemption)."""
        if self.alloc.tables[slot][block_idx] != self.NULL:
            return True
        pid = self._alloc_one()
        if pid is None:
            return False
        self.alloc.map(slot, block_idx, pid)
        self.tables = self.tables.at[slot, block_idx].set(pid)
        self._publish()
        return True

    def reserve_range(self, slot: int, pos0: int, n: int) -> bool:
        """Ensure blocks for logical positions [pos0, pos0 + n) — the
        pre-dispatch step of a prefill chunk. All-or-nothing is not
        required: already-mapped entries are kept on failure (they hold
        earlier KV), only the shortfall is reported. The device table
        update is BATCHED: allocations happen host-side first, then one
        row write + one gauge publish regardless of how many blocks the
        chunk spans (ensure()'s per-entry scatter would dispatch a
        device op per block on the admission hot path)."""
        fresh = False
        short = False
        for b in range(pos0 // self.bt, (pos0 + n - 1) // self.bt + 1):
            if self.alloc.tables[slot][b] != self.NULL:
                continue
            pid = self._alloc_one()
            if pid is None:
                short = True
                break
            self.alloc.map(slot, b, pid)
            fresh = True
        if fresh:
            self.sync_table_row(slot)
        return not short

    def trim_to(self, slot: int, tokens: int) -> int:
        """Speculative-frontier ROLLBACK: unmap every table entry past
        the blocks a `tokens`-token sequence occupies. The batched
        verify reserves blocks for the widest possible accept
        ([frontier, frontier + k]); after a rejection — or before a
        swap-out — the tail past the committed frontier is speculative
        over-reservation and this returns it to the pool. Freed blocks
        were exclusively owned (the frontier never maps shared blocks),
        and their bytes need no wipe: the committed write-back already
        masked uncommitted positions, and the gather's stale-tenant
        guard covers recycling. Returns the number of blocks freed."""
        keep = self.blocks_for(max(tokens, 1))
        freed = 0
        touched = False
        for idx in range(keep, self.max_blocks):
            if self.alloc.tables[slot][idx] == self.NULL:
                continue
            touched = True
            if self.alloc.unmap_entry(slot, idx):
                freed += 1
        if touched:
            self.sync_table_row(slot)
        return freed

    def map_shared(self, slot: int, block_idx: int, pid: int) -> None:
        """Point (slot, block_idx) at an existing block, sharing it
        (refcount bump — the paged prefix hit; NO bytes move)."""
        self.alloc.ref(pid)
        self.alloc.map(slot, block_idx, pid)
        self.tables = self.tables.at[slot, block_idx].set(pid)
        self._publish()

    def ensure_writable(self, slot: int, block_idx: int) -> bool:
        """Copy-on-write fork of a shared block before a write into it
        (not reachable from the serve scheduler's own flow — capture
        stops short of the write frontier — but the invariant the
        allocator promises anyone who maps shared blocks)."""
        pid = self.alloc.ensure_writable(slot, block_idx, self._copy_block)
        if pid is None:
            return False
        self.tables = self.tables.at[slot, block_idx].set(pid)
        self._publish()
        return True

    def _copy_block(self, src: int, dst: int) -> None:
        """Device-side copy of one physical block (the CoW fork body) —
        forks the stored KV bytes; the linear-state snapshot rides the
        prefix-cache entry, which the fork's owner re-captures at its
        own boundary (boundary-exact rule)."""
        for pl in self.pool:
            if not pl:
                continue
            for name in ("k", "v", "pos"):
                pl[name] = pl[name].at[dst].set(pl[name][src])

    def release_slot(self, slot: int) -> None:
        """Per-request release: deref every mapped block (shared blocks
        survive under the prefix cache / other slots), clear the device
        table row, wipe the slot's SWA/linear rows. Freed pool blocks
        are NOT wiped — the gather's stale-tenant pos guard makes them
        invisible until a new owner overwrites them."""
        self.alloc.unmap_slot(slot)
        self.tables = self.tables.at[slot].set(self.NULL)
        if self.has_rows:
            self.rows = self.model.row_reset(self.rows, slot)
        self._publish()

    # -- traced-program dispatch -------------------------------------------

    def prefill_into(self, slot: int, ids, pos0: int):
        """One chunk of prompt into the slot's mapped blocks (caller
        reserved them). Returns the chunk's last-position logits."""
        logits, self.pool, self.rows = self.model.prefill_chunk_paged(
            self.pool, self.rows, self.tables, slot, ids, pos0, self.ctx)
        return logits

    # -- preemption transport (slow path: explicit host syncs) --------------

    def swap_out(self, slot: int, carries) -> dict:
        """Preempt-by-swap: fetch the slot's block bytes, row state and
        decode carries to HOST memory, then free its blocks. Returns the
        blob swap_in() restores bit-exactly; the carries tuple is
        (toks, pos, rngs, recents) device arrays indexed [slot]."""
        idx = [i for i, p in enumerate(self.alloc.tables[slot])
               if p != self.NULL]
        ids = jnp.asarray([self.alloc.tables[slot][i] for i in idx],
                          jnp.int32)
        blob = {"idx": idx, "layers": [], "rows": None, "carries": []}
        for pl in self.pool:
            # lint: disable=host-sync — preemption IS the planned swap to host;
            # this whole method is the slow path that frees HBM
            blob["layers"].append(
                {n: np.asarray(pl[n][ids]) for n in ("k", "v", "pos")}
                if pl else {})
        if self.has_rows:
            # lint: disable=host-sync — row state rides the same swap blob
            blob["rows"] = jax.tree_util.tree_map(
                np.asarray, self.model.row_snapshot(self.rows, slot))
        # lint: disable=host-sync — decode carries (a few dozen bytes) complete
        # the bit-exact resume state
        blob["carries"] = [np.asarray(c[slot]) for c in carries]
        self.release_slot(slot)
        self.swaps += 1
        return blob

    def swap_in(self, slot: int, blob: dict) -> bool:
        """Restore a swapped-out slot into freshly allocated blocks.
        False = not enough free blocks yet (caller retries later; the
        blob is untouched). Table indices are restored verbatim, so the
        sequence resumes at its exact logical positions."""
        need = len(blob["idx"])
        if not self.ensure_free(need):
            return False
        pids = []
        for idx in blob["idx"]:
            pid = self._alloc_one()
            assert pid is not None        # guarded by free_count above
            self.alloc.map(slot, idx, pid)
            pids.append(pid)
        dst = jnp.asarray(pids, jnp.int32)
        for pl, saved in zip(self.pool, blob["layers"]):
            if not pl:
                continue
            for name in ("k", "v", "pos"):
                pl[name] = pl[name].at[dst].set(jnp.asarray(saved[name]))
        if self.has_rows and blob["rows"] is not None:
            self.rows = self.model.row_install(
                self.rows, jax.tree_util.tree_map(jnp.asarray,
                                                  blob["rows"]), slot)
        host_row = np.full((self.max_blocks,), self.NULL, np.int32)
        host_row[blob["idx"]] = pids
        self.tables = self.tables.at[slot].set(jnp.asarray(host_row))
        self._publish()
        return True

    # -- observability ------------------------------------------------------

    def _publish(self) -> None:
        SERVE_KV_BLOCKS_FREE.set(self.alloc.free_count)
        SERVE_KV_BLOCKS_USED.set(self.alloc.used_count)
        SERVE_KV_BLOCKS_SHARED.set(self.alloc.shared_count)

    def occupancy(self, live_tokens: dict[int, int] | None = None) -> dict:
        """kv_pool health block. `live_tokens`: slot -> frontier tokens,
        for the fragmentation figure (allocated-but-unfilled tail share
        of live slots' blocks)."""
        out = {
            "blocks": self.num_blocks,
            "block_tokens": self.bt,
            "free": self.alloc.free_count,
            "used": self.alloc.used_count,
            # first-class occupancy in [0, 1]: consumers (the fleet
            # router's probe loop, autoscalers) read this directly
            # instead of re-deriving used/blocks by hand
            "occupancy": round(self.alloc.used_count
                               / max(self.num_blocks, 1), 4),
            "shared": self.alloc.shared_count,
            "cow_forks": self.alloc.cow_forks,
            "swaps": self.swaps,
        }
        if live_tokens:
            alloc_tokens = waste = 0
            for slot, toks in live_tokens.items():
                nblk = len(self.alloc.blocks_of(slot))
                alloc_tokens += nblk * self.bt
                waste += max(nblk * self.bt - toks, 0)
            out["fragmentation"] = round(waste / alloc_tokens, 4) \
                if alloc_tokens else 0.0
        return out
