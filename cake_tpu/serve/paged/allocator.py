"""Host-side block-pool allocator: free list, refcounts, per-slot tables.

Pure bookkeeping (no jax): the physical pool lives on the device
(cache.init_paged_layers); this class decides WHICH physical block backs
which (slot, table-index) pair and when a block is reusable. All methods
run on the engine's scheduler thread — no locking, same discipline as
SlotPool.

Invariants (asserted by check() in the property tests):

  * every block is FREE xor has refcount >= 1;
  * a block's refcount == (#slot-table entries mapping it) + (#prefix
    cache entries pinning it);
  * a slot's table never maps the same physical block at two indices;
  * a block mapped by TWO OR MORE owners is never written — writers call
    ensure_writable() first, which forks a private copy (copy-on-write).

The NULL sentinel (== num_blocks) marks an unmapped table entry; it is
also what the device-side gather/scatter treat as "drop".
"""
from __future__ import annotations

__all__ = ["BlockAllocator"]


class BlockAllocator:
    def __init__(self, num_blocks: int, block_tokens: int, slots: int,
                 max_blocks: int):
        if num_blocks < 1:
            raise ValueError(f"block pool needs >= 1 block, got {num_blocks}")
        self.num_blocks = num_blocks
        self.block_tokens = block_tokens
        self.max_blocks = max_blocks              # table entries per slot
        self.NULL = num_blocks
        self._free: list[int] = list(range(num_blocks - 1, -1, -1))
        self._ref = [0] * num_blocks
        # cache_pins[pid]: how many of pid's refs are prefix-cache pins
        # (reclaimable under pressure) rather than live slot mappings
        self._cache_pins = [0] * num_blocks
        self.tables: list[list[int]] = [[self.NULL] * max_blocks
                                        for _ in range(slots)]
        # lifetime counters (observability)
        self.cow_forks = 0

    # -- core refcounting ---------------------------------------------------

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def used_count(self) -> int:
        return self.num_blocks - len(self._free)

    @property
    def shared_count(self) -> int:
        """Blocks referenced by more than one owner (slot tables and/or
        prefix-cache entries) — the refcount-sharing gauge."""
        return sum(1 for r in self._ref if r >= 2)

    def refcount(self, pid: int) -> int:
        return self._ref[pid]

    def alloc(self) -> int | None:
        """Claim a free block with refcount 1; None when exhausted."""
        if not self._free:
            return None
        pid = self._free.pop()
        assert self._ref[pid] == 0
        self._ref[pid] = 1
        return pid

    def ref(self, pid: int, cache_pin: bool = False) -> None:
        if self._ref[pid] < 1:
            raise ValueError(f"ref of unallocated block {pid}")
        self._ref[pid] += 1
        if cache_pin:
            self._cache_pins[pid] += 1

    def deref(self, pid: int, cache_pin: bool = False) -> bool:
        """Drop one reference; returns True when the block was freed."""
        if self._ref[pid] < 1:
            raise ValueError(f"double free of block {pid}")
        if cache_pin:
            if self._cache_pins[pid] < 1:
                raise ValueError(f"block {pid} has no cache pin to drop")
            self._cache_pins[pid] -= 1
        self._ref[pid] -= 1
        if self._ref[pid] == 0:
            self._free.append(pid)
            return True
        return False

    # -- slot tables --------------------------------------------------------

    def table(self, slot: int) -> list[int]:
        return self.tables[slot]

    def blocks_of(self, slot: int) -> list[int]:
        """Mapped physical ids of a slot, in table order (dense prefix)."""
        return [p for p in self.tables[slot] if p != self.NULL]

    def map(self, slot: int, idx: int, pid: int) -> None:
        """Point table entry (slot, idx) at pid. The caller owns the ref
        being handed over (a fresh alloc(), or a ref() bump for a shared
        block)."""
        if self.tables[slot][idx] != self.NULL:
            raise ValueError(f"slot {slot} table[{idx}] already mapped")
        self.tables[slot][idx] = pid

    def ensure(self, slot: int, idx: int) -> int | None:
        """Return the pid backing (slot, idx), allocating one if the
        entry is unmapped. None = pool exhausted (caller preempts or
        evicts and retries)."""
        pid = self.tables[slot][idx]
        if pid != self.NULL:
            return pid
        pid = self.alloc()
        if pid is None:
            return None
        self.tables[slot][idx] = pid
        return pid

    def unmap_entry(self, slot: int, idx: int) -> bool:
        """Unmap ONE table entry (deref; shared blocks survive under
        their other owners). Returns True when the block was actually
        FREED — the speculative-frontier rollback unit."""
        pid = self.tables[slot][idx]
        if pid == self.NULL:
            return False
        self.tables[slot][idx] = self.NULL
        return self.deref(pid)

    def unmap_slot(self, slot: int) -> list[int]:
        """Release every block the slot maps (deref; shared blocks
        survive under their other owners). Returns the pids that were
        actually FREED."""
        freed = []
        for idx, pid in enumerate(self.tables[slot]):
            if pid == self.NULL:
                continue
            if self.deref(pid):
                freed.append(pid)
            self.tables[slot][idx] = self.NULL
        return freed

    def ensure_writable(self, slot: int, idx: int, copy_block) -> int | None:
        """Copy-on-write guard: make (slot, idx) safe to write. A block
        with refcount 1 is returned as-is; a SHARED block is forked —
        a fresh block is allocated, `copy_block(src_pid, dst_pid)` copies
        the bytes (device-side), the slot's ref moves to the fork.
        None = pool exhausted mid-fork (nothing changed)."""
        pid = self.tables[slot][idx]
        if pid == self.NULL:
            raise ValueError(f"slot {slot} table[{idx}] unmapped")
        if self._ref[pid] == 1:
            return pid
        fork = self.alloc()
        if fork is None:
            return None
        copy_block(pid, fork)
        self.tables[slot][idx] = fork
        self.deref(pid)
        self.cow_forks += 1
        return fork

    # -- invariants (property tests) ----------------------------------------

    def check(self) -> None:
        free = set(self._free)
        assert len(free) == len(self._free), "free list holds duplicates"
        mapped: dict[int, int] = {}
        for t in self.tables:
            seen = set()
            for pid in t:
                if pid == self.NULL:
                    continue
                assert pid not in seen, "slot maps one block twice"
                seen.add(pid)
                mapped[pid] = mapped.get(pid, 0) + 1
        for pid in range(self.num_blocks):
            if pid in free:
                assert self._ref[pid] == 0, f"free block {pid} has refs"
                assert pid not in mapped, f"free block {pid} still mapped"
            else:
                assert self._ref[pid] >= 1, f"used block {pid} unreferenced"
                assert self._ref[pid] == mapped.get(pid, 0) \
                    + self._cache_pins[pid], \
                    f"block {pid}: ref {self._ref[pid]} != " \
                    f"{mapped.get(pid, 0)} mappings + " \
                    f"{self._cache_pins[pid]} cache pins"
