"""Paged KV subsystem: block-pool allocator, refcounted prefix sharing,
preempt-by-swap — the scale refactor that replaces worst-case-provisioned
KV rows (CAKE_SERVE_SLOTS x CAKE_SERVE_CTX) with on-demand fixed-size
blocks behind per-slot indirection tables (vLLM/PagedAttention). Enabled
by CAKE_KV_BLOCKS > 0; see docs/serving.md#paged-kv-pool."""
from .allocator import BlockAllocator
from .pool import KVPoolExhausted, PagedKV, pow2_block_tokens
from .preempt import PreemptedSlot, choose_victim, victim_rank

__all__ = ["BlockAllocator", "KVPoolExhausted", "PagedKV",
           "PreemptedSlot", "choose_victim", "pow2_block_tokens",
           "victim_rank"]
