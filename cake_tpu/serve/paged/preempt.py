"""Preemption policy + parked-request state for the paged serve engine.

When the block pool is exhausted (and the prefix cache has nothing left
to evict), a victim slot is evicted to make room:

  * mode "swap": the slot's blocks, row state and decode carries are
    fetched to host RAM and its blocks freed; resume writes the same
    bytes into fresh blocks — continuation is bit-identical even for
    SAMPLED streams (the RNG carry rides the blob).
  * mode "recompute": the blocks are simply freed; resume replays
    prompt + generated[:-1] through chunked prefill (PR 8's rebuild-by-
    replay machinery) — greedy continuation is bit-identical, sampled
    streams resume on a fresh rng fold (the documented rebuild
    exception).

Victim choice is POLICY-DRIVEN by QoS class: among candidates, the
LOWEST class goes first (batch before standard before interactive — an
interactive admission under pool pressure evicts a batch image/chat
slot, never the other way around while a batch victim exists), and
WITHIN a class latest-admission-first (LIFO, the vLLM rule: the request
that has consumed the least scheduler work is the cheapest to re-run,
and the oldest request in its class can never be starved by
newcomers). Single-class traffic therefore behaves exactly as before
this policy existed. Parked requests resume oldest-first, before any
new admission, as soon as a slot and enough blocks are free.
"""
from __future__ import annotations

from dataclasses import dataclass

from ..admission.classes import class_of, priority

__all__ = ["PreemptedSlot", "choose_victim", "victim_rank"]


@dataclass
class PreemptedSlot:
    req: object                       # ServeRequest
    mode: str                         # "swap" | "recompute"
    tokens_at_preempt: int            # frontier tokens (resume gating)
    blob: dict | None = None          # swap payload (None for recompute;
                                      # sampling params re-derive from
                                      # req.sampling at resume)


def victim_rank(req) -> tuple:
    """Sort key under which the MAX element is the preferred victim:
    lowest QoS class first (negated priority), latest admission within
    a class (LIFO). Shared by slot victim choice and the mid-prefill
    requeue pick so the two paths cannot rank classes differently."""
    return (-priority(class_of(req)), getattr(req, "t_enqueue", 0.0))


def choose_victim(candidates: list[tuple[int, object]],
                  exclude: int | None = None) -> tuple[int, object] | None:
    """(slot, req) to preempt from `candidates` [(slot, req)], or None.
    Lowest class first, LIFO within a class; `exclude` protects the
    slot whose allocation triggered the preemption (a slot cannot make
    room by evicting itself)."""
    pool = [(s, r) for s, r in candidates
            if s != exclude and r is not None]
    if not pool:
        return None
    return max(pool, key=lambda sr: victim_rank(sr[1]))
