"""Continuous-batching serving subsystem: slot-pooled batched decode with
bounded admission, chunked prefill and shared-prefix KV reuse (see
docs/serving.md)."""
from .admission import AdmissionQueue, QueueFull
from .engine import (EngineDraining, QueueDeadlineExceeded, ServeEngine,
                     ServeRequest, maybe_engine)
from .prefix_cache import PrefixCache
from .slots import SlotPool

__all__ = ["AdmissionQueue", "QueueFull", "EngineDraining",
           "QueueDeadlineExceeded", "PrefixCache", "ServeEngine",
           "ServeRequest", "SlotPool", "maybe_engine"]
