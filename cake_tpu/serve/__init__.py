"""Continuous-batching serving subsystem: slot-pooled batched decode with
bounded admission (see docs/serving.md)."""
from .admission import AdmissionQueue, QueueFull
from .engine import ServeEngine, ServeRequest, maybe_engine
from .slots import SlotPool

__all__ = ["AdmissionQueue", "QueueFull", "ServeEngine", "ServeRequest",
           "SlotPool", "maybe_engine"]
