"""Continuous-batching serving subsystem: slot-pooled batched decode with
bounded admission, chunked prefill, shared-prefix KV reuse, and crash-only
supervision (typed step failures, rebuild-by-replay, poison quarantine,
wedge watchdog) — see docs/serving.md and docs/fault_tolerance.md."""
from .admission import (AdmissionPlane, AdmissionQueue, GenerationJob,
                        JobCancelled, JobExecutor, JobsDraining, QueueFull,
                        TenantQuotaExceeded, TenantRegistry)
from .engine import (EngineDraining, QueueDeadlineExceeded, ServeEngine,
                     ServeRequest, maybe_engine)
from .paged import BlockAllocator, KVPoolExhausted, PagedKV
from .prefix_cache import PagedPrefixCache, PrefixCache
from .slots import SlotPool
from .supervisor import (EngineDown, PoisonedRequest,
                         RequestDeadlineExceeded, StepFailure, Supervisor)

__all__ = ["AdmissionPlane", "AdmissionQueue", "GenerationJob",
           "JobCancelled", "JobExecutor", "JobsDraining",
           "TenantQuotaExceeded", "TenantRegistry",
           "QueueFull", "EngineDraining",
           "QueueDeadlineExceeded", "EngineDown", "KVPoolExhausted",
           "PoisonedRequest", "RequestDeadlineExceeded", "StepFailure",
           "Supervisor", "BlockAllocator", "PagedKV", "PagedPrefixCache",
           "PrefixCache", "ServeEngine", "ServeRequest", "SlotPool",
           "maybe_engine"]
