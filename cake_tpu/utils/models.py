"""Local model manager: scan HF + cake caches, report Complete/Partial
status, list/find/delete (ref: utils/models.rs:33-130; `cake list|rm`)."""
from __future__ import annotations

import os
import shutil
from dataclasses import dataclass

from .hub import cake_cache_dir, hf_cache_dir


@dataclass
class ModelEntry:
    repo_id: str
    path: str
    source: str          # "hf" | "cake"
    size_bytes: int
    complete: bool


def _dir_size(path: str) -> int:
    total = 0
    for root, _, files in os.walk(path):
        for f in files:
            fp = os.path.join(root, f)
            try:
                total += os.stat(fp).st_size
            except OSError:
                pass
    return total


def _is_complete(snap: str) -> bool:
    """Complete = has config.json (or gguf) and at least one weight file whose
    blobs resolve (ref: Complete/Partial status in utils/models.rs)."""
    try:
        files = os.listdir(snap)
    except OSError:
        return False
    has_cfg = "config.json" in files or any(f.endswith(".gguf") for f in files)
    weights = [f for f in files if f.endswith((".safetensors", ".gguf"))]
    if not (has_cfg and weights):
        return False
    for w in weights:
        p = os.path.join(snap, w)
        real = os.path.realpath(p)
        if not os.path.exists(real) or os.stat(real).st_size == 0:
            return False
    return True


def list_models() -> list[ModelEntry]:
    out: list[ModelEntry] = []
    hub = hf_cache_dir()
    if os.path.isdir(hub):
        for entry in sorted(os.listdir(hub)):
            if not entry.startswith("models--"):
                continue
            repo_id = entry[len("models--"):].replace("--", "/", 1)
            snap_root = os.path.join(hub, entry, "snapshots")
            snaps = (sorted(os.listdir(snap_root))
                     if os.path.isdir(snap_root) else [])
            for s in reversed(snaps):
                snap = os.path.join(snap_root, s)
                out.append(ModelEntry(
                    repo_id=repo_id, path=snap, source="hf",
                    size_bytes=_dir_size(os.path.join(hub, entry)),
                    complete=_is_complete(snap)))
                break
    cake = cake_cache_dir()
    if os.path.isdir(cake):
        for entry in sorted(os.listdir(cake)):
            p = os.path.join(cake, entry)
            if os.path.isdir(p):
                out.append(ModelEntry(
                    repo_id=entry, path=p, source="cake",
                    size_bytes=_dir_size(p), complete=_is_complete(p)))
    return out


def find_model(repo_id: str) -> ModelEntry | None:
    for m in list_models():
        if m.repo_id == repo_id:
            return m
    return None


def delete_model(repo_id: str) -> bool:
    """Remove a cached model (ref: `cake rm`)."""
    hub = hf_cache_dir()
    target = os.path.join(hub, "models--" + repo_id.replace("/", "--"))
    removed = False
    if os.path.isdir(target):
        shutil.rmtree(target)
        removed = True
    cake_target = os.path.join(cake_cache_dir(), repo_id)
    if os.path.isdir(cake_target):
        shutil.rmtree(cake_target)
        removed = True
    return removed
