"""`cake split`: write per-worker safetensors bundles from layer ranges so
workers can be provisioned out-of-band instead of streaming weights at setup
(ref: utils/split.rs:155).
"""
from __future__ import annotations

import json
import os

from .safetensors_io import TensorStorage, layer_of, save_safetensors


def split_model(model_dir: str, assignments: dict[str, tuple[int, int]],
                out_dir: str, num_layers: int,
                tie_word_embeddings: bool = False) -> dict[str, str]:
    """assignments: worker name -> [lo, hi) layer range. Non-layer tensors
    go to the bundle that needs them: embed with layer 0 (and with the last
    layer too when the head is tied to it), final norm + head with the last
    layer. Returns worker -> bundle path."""
    st = TensorStorage.from_model_dir(model_dir)
    out_paths: dict[str, str] = {}
    os.makedirs(out_dir, exist_ok=True)
    for worker, (lo, hi) in assignments.items():
        tensors = {}
        for name in st.names():
            li = layer_of(name)
            if li is not None:
                keep = lo <= li < hi
            elif "embed_tokens" in name:
                # tied heads read the embedding table from the last bundle too
                keep = lo == 0 or (tie_word_embeddings and hi == num_layers)
            elif "lm_head" in name or ".norm." in name or name.endswith("norm.weight"):
                keep = hi == num_layers  # final norm + head with the last layer
            else:
                keep = True             # unclassified non-layer: every bundle
            if keep:
                tensors[name] = st.read(name)
        wdir = os.path.join(out_dir, worker)
        os.makedirs(wdir, exist_ok=True)
        path = os.path.join(wdir, "model.safetensors")
        save_safetensors(path, tensors,
                         metadata={"layers": f"{lo}-{hi - 1}"})
        # each bundle is a loadable model dir: copy config + tokenizer files
        for aux in ("config.json", "tokenizer.json", "tokenizer_config.json",
                    "generation_config.json"):
            src = os.path.join(model_dir, aux)
            if os.path.exists(src):
                with open(src, "rb") as f:
                    data = f.read()
                with open(os.path.join(wdir, aux), "wb") as f:
                    f.write(data)
        out_paths[worker] = path
    st.close()
    return out_paths
