"""Params pytree -> HF-named tensor dict (inverse of utils/loaders.py).

Used by the splitter (`cake split` — ref: utils/split.rs writes per-worker
safetensors bundles) and by round-trip tests.
"""
from __future__ import annotations

import numpy as np

from ..models.common.config import ModelConfig


def _np(x) -> np.ndarray:
    return np.asarray(x)


def params_to_hf_tensors(cfg: ModelConfig, params: dict,
                         layer_offset: int = 0,
                         fuse_phi: bool = False) -> dict[str, np.ndarray]:
    """fuse_phi: write Phi-style fused qkv_proj/gate_up_proj names."""
    out: dict[str, np.ndarray] = {}
    pre = cfg.model_prefix

    def put_norm(name, w):
        arr = _np(w).astype(np.float32)
        if cfg.residual_rms_norm:
            arr = arr - 1.0     # stored as delta from 0 (ref: config.rs)
        out[name] = arr.astype(_np(w).dtype)

    if "embed_tokens" in params:
        out[f"{pre}.embed_tokens.weight"] = _np(params["embed_tokens"]["weight"])
    if "norm" in params:
        put_norm(f"{pre}.norm.weight", params["norm"]["weight"])
    if "lm_head" in params:
        out["lm_head.weight"] = _np(params["lm_head"]["weight"])

    for j, layer in enumerate(params["layers"]):
        i = layer_offset + j
        lp = f"{pre}.layers.{i}"
        for norm in ("input_layernorm", "post_attention_layernorm",
                     "pre_feedforward_layernorm", "post_feedforward_layernorm"):
            if norm in layer:
                put_norm(f"{lp}.{norm}.weight", layer[norm]["weight"])
        if "self_attn" in layer:
            a = layer["self_attn"]
            if fuse_phi:
                out[f"{lp}.self_attn.qkv_proj.weight"] = np.concatenate([
                    _np(a["q_proj"]["weight"]), _np(a["k_proj"]["weight"]),
                    _np(a["v_proj"]["weight"])], axis=0)
            else:
                for proj in ("q_proj", "k_proj", "v_proj"):
                    out[f"{lp}.self_attn.{proj}.weight"] = _np(a[proj]["weight"])
                    if "bias" in a[proj]:
                        out[f"{lp}.self_attn.{proj}.bias"] = _np(a[proj]["bias"])
            out[f"{lp}.self_attn.o_proj.weight"] = _np(a["o_proj"]["weight"])
            for qk in ("q_norm", "k_norm"):
                if qk in a:
                    put_norm(f"{lp}.self_attn.{qk}.weight", a[qk]["weight"])
        if "linear_attn" in layer:
            from ..models.qwen3_5 import export_gdn_params
            out.update(export_gdn_params(cfg, layer["linear_attn"], lp))
        mlp = layer["mlp"]
        if "experts" in mlp:    # MoE
            out[f"{lp}.mlp.gate.weight"] = _np(mlp["gate"]["weight"])
            for e in range(cfg.num_experts):
                for proj in ("gate_proj", "up_proj", "down_proj"):
                    out[f"{lp}.mlp.experts.{e}.{proj}.weight"] = \
                        _np(mlp["experts"][proj][e])
            if "shared_expert" in mlp:
                for proj in ("gate_proj", "up_proj", "down_proj"):
                    out[f"{lp}.mlp.shared_expert.{proj}.weight"] = \
                        _np(mlp["shared_expert"][proj]["weight"])
                out[f"{lp}.mlp.shared_expert_gate.weight"] = \
                    _np(mlp["shared_expert_gate"]["weight"])
        else:
            if fuse_phi:
                out[f"{lp}.mlp.gate_up_proj.weight"] = np.concatenate([
                    _np(mlp["gate_proj"]["weight"]),
                    _np(mlp["up_proj"]["weight"])], axis=0)
                out[f"{lp}.mlp.down_proj.weight"] = _np(mlp["down_proj"]["weight"])
            else:
                for proj in ("gate_proj", "up_proj", "down_proj"):
                    out[f"{lp}.mlp.{proj}.weight"] = _np(mlp[proj]["weight"])
    return out
