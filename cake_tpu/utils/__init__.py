from .dtypes import (WIRE_DTYPES, WIRE_TAGS, dtype_name, from_numpy_bytes,
                     itemsize, parse_dtype, to_numpy_bytes)
from .export import params_to_hf_tensors
from .gguf import GgufReader, GgufStorage, gguf_config_dict, gguf_to_hf_name
from .hub import (cake_cache_dir, hf_cache_dir, looks_like_repo_id,
                  probe_cached_repo, pull, resolve_model)
from .loaders import ParamLoader, load_model_params
from .models import ModelEntry, delete_model, find_model, list_models
from .quant import (Fp8Quantization, GptqQuantization, NoQuantization,
                    detect_quantization)
from .safetensors_io import (TensorRecord, TensorStorage, index_file,
                             layer_of, save_safetensors)
from .split import split_model
