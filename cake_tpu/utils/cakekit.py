"""ctypes binding for the native cakekit core (csrc/cakekit.cpp).

Builds libcakekit.so on first import if a toolchain is present; every entry
point has a pure-Python fallback, so the package works without a compiler
(the reference gates native code behind build features the same way).
"""
from __future__ import annotations

import ctypes
import logging
import os
import subprocess

import numpy as np

log = logging.getLogger("cake_tpu.cakekit")

_LIB = None
_TRIED = False


def _csrc_dir() -> str:
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))), "csrc")


def _load():
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    so = os.path.join(_csrc_dir(), "libcakekit.so")
    if not os.path.exists(so):
        # build into a process-unique name then rename: concurrent importers
        # must never CDLL a half-written ELF
        tmp = f"{so}.{os.getpid()}.tmp"
        try:
            subprocess.run(
                ["make", "-C", _csrc_dir(), f"TARGET={os.path.basename(tmp)}"],
                capture_output=True, timeout=120, check=True)
            os.replace(tmp, so)
        except Exception as e:
            log.debug("cakekit build unavailable: %s", e)
            if os.path.exists(tmp):
                os.unlink(tmp)
            if not os.path.exists(so):
                return None
    try:
        lib = ctypes.CDLL(so)
        lib.ck_crc32.restype = ctypes.c_uint32
        lib.ck_crc32.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                 ctypes.c_uint32]
        lib.ck_pread.restype = ctypes.c_int64
        lib.ck_pread.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                 ctypes.c_uint64, ctypes.c_void_p]
        lib.ck_pread_fd.restype = ctypes.c_int64
        lib.ck_pread_fd.argtypes = [ctypes.c_int, ctypes.c_uint64,
                                    ctypes.c_uint64, ctypes.c_void_p]
        lib.ck_preadv_fd.restype = ctypes.c_int64
        lib.ck_preadv_fd.argtypes = [ctypes.c_int, ctypes.c_uint64,
                                     ctypes.c_void_p, ctypes.c_void_p,
                                     ctypes.c_void_p, ctypes.c_void_p,
                                     ctypes.c_void_p]
        lib.ck_preadv.restype = ctypes.c_int64
        lib.ck_preadv.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                  ctypes.c_void_p, ctypes.c_void_p,
                                  ctypes.c_void_p, ctypes.c_void_p,
                                  ctypes.c_void_p]
        lib.ck_frame_parse.restype = ctypes.c_int64
        lib.ck_frame_parse.argtypes = [ctypes.c_char_p, ctypes.c_uint32,
                                       ctypes.c_uint32]
        _LIB = lib
    except OSError as e:
        log.debug("cakekit load failed: %s", e)
        _LIB = None
    return _LIB


def available() -> bool:
    return _load() is not None


def crc32(data: bytes, seed: int = 0) -> int:
    lib = _load()
    if lib is None:
        import zlib
        return zlib.crc32(data, seed) & 0xFFFFFFFF
    return int(lib.ck_crc32(data, len(data), seed))


def pread_fd(fd: int, offset: int, length: int) -> bytes:
    """Positioned read on an already-open fd (keeps TensorStorage's fd
    cache effective on the hot path)."""
    lib = _load()
    if lib is None:
        return os.pread(fd, length, offset)
    buf = ctypes.create_string_buffer(length)
    got = lib.ck_pread_fd(fd, offset, length, buf)
    if got < 0:
        raise OSError(f"ck_pread_fd({fd}, {offset}, {length}) -> {got}")
    return buf.raw[:got]


def pread(path: str, offset: int, length: int) -> bytes:
    lib = _load()
    if lib is None:
        fd = os.open(path, os.O_RDONLY)
        try:
            return os.pread(fd, length, offset)
        finally:
            os.close(fd)
    buf = ctypes.create_string_buffer(length)
    got = lib.ck_pread(path.encode(), offset, length, buf)
    if got < 0:
        raise OSError(f"ck_pread({path}, {offset}, {length}) -> {got}")
    return buf.raw[:got]


def preadv(path: str, ranges: list[tuple[int, int]]) -> list[bytes]:
    """Batched positioned reads: [(offset, length), ...] -> chunks."""
    lib = _load()
    if lib is None:
        return [pread(path, off, ln) for off, ln in ranges]
    n = len(ranges)
    offsets = np.asarray([r[0] for r in ranges], np.uint64)
    lens = np.asarray([r[1] for r in ranges], np.uint64)
    out_offsets = np.zeros(n, np.uint64)
    np.cumsum(lens[:-1], out=out_offsets[1:])
    total = int(lens.sum())
    buf = ctypes.create_string_buffer(total)
    got_lens = np.zeros(n, np.uint64)
    got = lib.ck_preadv(path.encode(), n,
                        offsets.ctypes.data_as(ctypes.c_void_p),
                        lens.ctypes.data_as(ctypes.c_void_p),
                        buf,
                        out_offsets.ctypes.data_as(ctypes.c_void_p),
                        got_lens.ctypes.data_as(ctypes.c_void_p))
    if got < 0:
        raise OSError(f"ck_preadv({path}) -> {got}")
    raw = buf.raw
    # slice by ACTUAL lengths: short reads at EOF truncate, same as pread
    return [raw[int(o):int(o + g)] for o, g in zip(out_offsets, got_lens)]


def preadv_fd(fd: int, ranges: list[tuple[int, int]]) -> list[bytes]:
    """Batched positioned reads over a cached fd (expert streaming hot
    path — no per-call open/close)."""
    lib = _load()
    if lib is None:
        return [os.pread(fd, ln, off) for off, ln in ranges]
    n = len(ranges)
    offsets = np.asarray([r[0] for r in ranges], np.uint64)
    lens = np.asarray([r[1] for r in ranges], np.uint64)
    out_offsets = np.zeros(n, np.uint64)
    np.cumsum(lens[:-1], out=out_offsets[1:])
    buf = ctypes.create_string_buffer(int(lens.sum()))
    got_lens = np.zeros(n, np.uint64)
    got = lib.ck_preadv_fd(fd, n,
                           offsets.ctypes.data_as(ctypes.c_void_p),
                           lens.ctypes.data_as(ctypes.c_void_p),
                           buf,
                           out_offsets.ctypes.data_as(ctypes.c_void_p),
                           got_lens.ctypes.data_as(ctypes.c_void_p))
    if got < 0:
        raise OSError(f"ck_preadv_fd({fd}) -> {got}")
    raw = buf.raw
    return [raw[int(o):int(o + g)] for o, g in zip(out_offsets, got_lens)]


def frame_parse(header: bytes, magic: int, max_len: int) -> int:
    if len(header) != 8:
        raise ValueError(f"frame header must be 8 bytes, got {len(header)}")
    lib = _load()
    if lib is None:
        import struct
        m, length = struct.unpack("<II", header)
        if m != magic:
            return -1
        if length > max_len:
            return -2
        return length
    return int(lib.ck_frame_parse(header, magic, max_len))
