"""Tracing / profiling utilities.

Reference parity (SURVEY §5): (a) Chrome-trace export — here the JAX
profiler, whose traces open in Perfetto/TensorBoard (ref: tracing-chrome
behind --sd-tracing); (b) pervasive phase timing at debug level (ref:
text_model.rs:357-365 per-token breakdown, worker.rs:533-543 per-message
read/load/fwd/ser/write).
"""
from __future__ import annotations

import contextlib
import logging
import time

log = logging.getLogger("cake_tpu.trace")


@contextlib.contextmanager
def jax_trace(log_dir: str | None):
    """Wrap a region in a JAX profiler trace (xprof / Perfetto viewable).
    No-op when log_dir is None."""
    if not log_dir:
        yield
        return
    import jax
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        log.info("profiler trace written to %s", log_dir)


class PhaseTimer:
    """Accumulating phase timer for hot loops.

        t = PhaseTimer()
        with t("embed"): ...
        with t("layers"): ...
        log.debug("%s", t)          # embed=0.2ms layers=8.1ms
    """

    def __init__(self):
        self.totals: dict[str, float] = {}
        self.counts: dict[str, int] = {}

    @contextlib.contextmanager
    def __call__(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self.totals[name] = self.totals.get(name, 0.0) + dt
            self.counts[name] = self.counts.get(name, 0) + 1

    def reset(self):
        self.totals.clear()
        self.counts.clear()

    def __str__(self):
        return " ".join(f"{k}={v * 1000:.1f}ms" for k, v in self.totals.items())

    def report(self) -> dict[str, dict]:
        return {k: {"total_ms": round(v * 1000, 3),
                    "count": self.counts[k],
                    "avg_ms": round(v * 1000 / max(self.counts[k], 1), 3)}
                for k, v in self.totals.items()}
