"""GGUF (llama.cpp) checkpoint loading: header/metadata parsing, arch config
extraction, `blk.N.attn_q` -> HF name mapping, and dequantization of the
common K-quant formats at load (ref: utils/gguf.rs:1-26 + dispatch in
cake/mod.rs:237-263).

Supported tensor types: F32, F16, BF16, Q4_0, Q5_0, Q5_1, Q8_0, Q2_K,
Q3_K, Q4_K, Q5_K, Q6_K — covering the llama.cpp quant mixes in common HF
uploads (Q4_K_M, Q5_K_M, Q3_K_M, Q2_K, Q5_0/Q5_1 legacy). Dequant formulas
follow the public ggml block layouts (ggml-common.h / dequantize_row_*),
vectorized with numpy; tests pin each against a literal scalar
transcription of the C loops.
"""
from __future__ import annotations

import struct
from dataclasses import dataclass

import numpy as np

GGUF_MAGIC = 0x46554747   # "GGUF" little-endian

# metadata value type tags
_T_U8, _T_I8, _T_U16, _T_I16, _T_U32, _T_I32, _T_F32, _T_BOOL, _T_STR, \
    _T_ARR, _T_U64, _T_I64, _T_F64 = range(13)

# tensor dtype tags (ggml_type)
GGML_F32, GGML_F16 = 0, 1
GGML_Q4_0, GGML_Q5_0, GGML_Q5_1, GGML_Q8_0 = 2, 6, 7, 8
GGML_Q2_K, GGML_Q3_K, GGML_Q4_K, GGML_Q5_K, GGML_Q6_K = 10, 11, 12, 13, 14
GGML_BF16 = 30

QK_K = 256


@dataclass(frozen=True)
class GgufTensor:
    name: str
    dims: tuple[int, ...]    # ggml order: dims[0] is innermost (in_features)
    ggml_type: int
    offset: int              # relative to data section


class GgufReader:
    def __init__(self, path: str):
        self.path = path
        self.metadata: dict = {}
        self.tensors: dict[str, GgufTensor] = {}
        with open(path, "rb") as f:
            magic, version = struct.unpack("<II", f.read(8))
            if magic != GGUF_MAGIC:
                raise ValueError(f"{path}: not a GGUF file")
            if version < 2:
                raise ValueError(f"GGUF version {version} unsupported")
            n_tensors, n_kv = struct.unpack("<QQ", f.read(16))
            for _ in range(n_kv):
                key = self._read_str(f)
                vtype = struct.unpack("<I", f.read(4))[0]
                self.metadata[key] = self._read_value(f, vtype)
            for _ in range(n_tensors):
                name = self._read_str(f)
                n_dims = struct.unpack("<I", f.read(4))[0]
                dims = struct.unpack(f"<{n_dims}Q", f.read(8 * n_dims))
                ttype, offset = struct.unpack("<IQ", f.read(12))
                self.tensors[name] = GgufTensor(name, dims, ttype, offset)
            align = self.metadata.get("general.alignment", 32)
            pos = f.tell()
            self.data_start = (pos + align - 1) // align * align

    @staticmethod
    def _read_str(f) -> str:
        n = struct.unpack("<Q", f.read(8))[0]
        return f.read(n).decode("utf-8", errors="replace")

    def _read_value(self, f, vtype):
        scalars = {_T_U8: "<B", _T_I8: "<b", _T_U16: "<H", _T_I16: "<h",
                   _T_U32: "<I", _T_I32: "<i", _T_F32: "<f", _T_U64: "<Q",
                   _T_I64: "<q", _T_F64: "<d"}
        if vtype in scalars:
            fmt = scalars[vtype]
            return struct.unpack(fmt, f.read(struct.calcsize(fmt)))[0]
        if vtype == _T_BOOL:
            return bool(f.read(1)[0])
        if vtype == _T_STR:
            return self._read_str(f)
        if vtype == _T_ARR:
            etype, n = struct.unpack("<IQ", f.read(12))
            return [self._read_value(f, etype) for _ in range(n)]
        raise ValueError(f"unknown GGUF metadata type {vtype}")

    # -- tensor data ------------------------------------------------------

    def _raw(self, t: GgufTensor, nbytes: int) -> bytes:
        with open(self.path, "rb") as f:
            f.seek(self.data_start + t.offset)
            return f.read(nbytes)

    def read_tensor(self, name: str) -> np.ndarray:
        """Dequantized f32/f16 tensor in torch layout [out, in]."""
        t = self.tensors[name]
        n = int(np.prod(t.dims))
        if t.ggml_type == GGML_F32:
            data = np.frombuffer(self._raw(t, 4 * n), np.float32)
        elif t.ggml_type == GGML_F16:
            data = np.frombuffer(self._raw(t, 2 * n), np.float16)
        elif t.ggml_type == GGML_BF16:
            import jax.numpy as jnp
            data = np.frombuffer(self._raw(t, 2 * n), jnp.dtype(jnp.bfloat16))
        elif t.ggml_type == GGML_Q4_0:
            data = dequant_q4_0(self._raw(t, n // 32 * 18), n)
        elif t.ggml_type == GGML_Q5_0:
            data = dequant_q5_0(self._raw(t, n // 32 * 22), n)
        elif t.ggml_type == GGML_Q5_1:
            data = dequant_q5_1(self._raw(t, n // 32 * 24), n)
        elif t.ggml_type == GGML_Q8_0:
            data = dequant_q8_0(self._raw(t, n // 32 * 34), n)
        elif t.ggml_type == GGML_Q2_K:
            data = dequant_q2_k(self._raw(t, n // QK_K * 84), n)
        elif t.ggml_type == GGML_Q3_K:
            data = dequant_q3_k(self._raw(t, n // QK_K * 110), n)
        elif t.ggml_type == GGML_Q4_K:
            data = dequant_q4_k(self._raw(t, n // QK_K * 144), n)
        elif t.ggml_type == GGML_Q5_K:
            data = dequant_q5_k(self._raw(t, n // QK_K * 176), n)
        elif t.ggml_type == GGML_Q6_K:
            data = dequant_q6_k(self._raw(t, n // QK_K * 210), n)
        else:
            raise NotImplementedError(f"ggml type {t.ggml_type} for {name}")
        return data.reshape(tuple(reversed(t.dims)))


# -- block dequantizers (vectorized over blocks) ---------------------------

def dequant_q4_0(raw: bytes, n: int) -> np.ndarray:
    """Block = f16 scale + 32x4bit; w = d*(q-8)."""
    nb = n // 32
    b = np.frombuffer(raw, np.uint8).reshape(nb, 18)
    d = b[:, :2].copy().view(np.float16).astype(np.float32)      # [nb,1]
    qs = b[:, 2:]
    lo = (qs & 0xF).astype(np.int8)
    hi = (qs >> 4).astype(np.int8)
    q = np.concatenate([lo, hi], axis=1).astype(np.float32) - 8.0
    return (q * d).reshape(-1)


def dequant_q5_0(raw: bytes, n: int) -> np.ndarray:
    """Block = f16 scale + u32 high-bit mask + 32x4bit; w = d*(q5-16).
    Element j takes qh bit j, element j+16 takes qh bit j+16."""
    nb = n // 32
    b = np.frombuffer(raw, np.uint8).reshape(nb, 22)
    d = b[:, :2].copy().view(np.float16).astype(np.float32)       # [nb,1]
    qh = b[:, 2:6].copy().view(np.uint32)                         # [nb,1]
    qs = b[:, 6:]
    j = np.arange(16, dtype=np.uint32)
    hlo = (((qh >> j) & 1) << 4).astype(np.uint8)                 # [nb,16]
    hhi = (((qh >> (j + 16)) & 1) << 4).astype(np.uint8)
    lo = (qs & 0xF) | hlo
    hi = (qs >> 4) | hhi
    q = np.concatenate([lo, hi], axis=1).astype(np.float32) - 16.0
    return (q * d).reshape(-1)


def dequant_q5_1(raw: bytes, n: int) -> np.ndarray:
    """Block = f16 scale + f16 min + u32 high bits + 32x4bit; w = d*q5 + m."""
    nb = n // 32
    b = np.frombuffer(raw, np.uint8).reshape(nb, 24)
    d = b[:, 0:2].copy().view(np.float16).astype(np.float32)      # [nb,1]
    m = b[:, 2:4].copy().view(np.float16).astype(np.float32)
    qh = b[:, 4:8].copy().view(np.uint32)
    qs = b[:, 8:]
    j = np.arange(16, dtype=np.uint32)
    hlo = (((qh >> j) & 1) << 4).astype(np.uint8)
    hhi = (((qh >> (j + 16)) & 1) << 4).astype(np.uint8)
    lo = (qs & 0xF) | hlo
    hi = (qs >> 4) | hhi
    q = np.concatenate([lo, hi], axis=1).astype(np.float32)
    return (q * d + m).reshape(-1)


def dequant_q8_0(raw: bytes, n: int) -> np.ndarray:
    nb = n // 32
    b = np.frombuffer(raw, np.uint8).reshape(nb, 34)
    d = b[:, :2].copy().view(np.float16).astype(np.float32)
    q = b[:, 2:].copy().view(np.int8).astype(np.float32)
    return (q * d).reshape(-1)


def dequant_q2_k(raw: bytes, n: int) -> np.ndarray:
    """Super-block 256 = 16B scales(4bit sc|min) + 64B 2-bit qs + d + dmin;
    w = d*(sc&0xF)*q - dmin*(sc>>4), 16 groups of 16. The C loop walks two
    128-halves, 4 shift steps of 2 bits, two 16-groups per step."""
    nb = n // QK_K
    b = np.frombuffer(raw, np.uint8).reshape(nb, 84)
    scales = b[:, :16]
    qs = b[:, 16:80].reshape(nb, 2, 1, 32)                        # [nb,half,1,l]
    d = b[:, 80:82].copy().view(np.float16).astype(np.float32)    # [nb,1]
    dmin = b[:, 82:84].copy().view(np.float16).astype(np.float32)
    shift = (np.arange(4, dtype=np.uint8) * 2)[None, None, :, None]
    q = ((qs >> shift) & 3).astype(np.float32)                    # [nb,2,4,32]
    sel = scales.reshape(nb, 2, 4, 2)[..., np.arange(32) // 16]   # [nb,2,4,32]
    dl = d[:, :, None, None] * (sel & 0xF).astype(np.float32)
    ml = dmin[:, :, None, None] * (sel >> 4).astype(np.float32)
    return (dl * q - ml).reshape(-1)


def dequant_q3_k(raw: bytes, n: int) -> np.ndarray:
    """Super-block 256 = 32B hmask + 64B 2-bit qs + 12B 6-bit scales + d;
    w = d*(sc-32)*(q2 - (hmask bit ? 0 : 4)). Scale unpack follows the
    kmask1/kmask2 word shuffle in ggml dequantize_row_q3_K."""
    nb = n // QK_K
    b = np.frombuffer(raw, np.uint8).reshape(nb, 110)
    hm = b[:, :32]                                                # [nb,32]
    qs = b[:, 32:96].reshape(nb, 2, 1, 32)
    a = b[:, 96:108].copy().view(np.uint32)                       # [nb,3]
    d = b[:, 108:110].copy().view(np.float16).astype(np.float32)  # [nb,1]
    k1, k2 = np.uint32(0x03030303), np.uint32(0x0F0F0F0F)
    a0, a1, a2 = a[:, 0], a[:, 1], a[:, 2]
    words = np.stack([
        (a0 & k2) | (((a2 >> np.uint32(0)) & k1) << np.uint32(4)),
        (a1 & k2) | (((a2 >> np.uint32(2)) & k1) << np.uint32(4)),
        ((a0 >> np.uint32(4)) & k2) | (((a2 >> np.uint32(4)) & k1) << np.uint32(4)),
        ((a1 >> np.uint32(4)) & k2) | (((a2 >> np.uint32(6)) & k1) << np.uint32(4)),
    ], axis=1)                                                    # [nb,4] u32
    sc = np.ascontiguousarray(words).view(np.int8).astype(np.float32) - 32.0  # [nb,16]
    shift = (np.arange(4, dtype=np.uint8) * 2)[None, None, :, None]
    q2 = ((qs >> shift) & 3).astype(np.float32)                   # [nb,2,4,32]
    mbit = (np.arange(2)[:, None] * 4 + np.arange(4)[None, :]).astype(np.uint8)
    hbit = (hm[:, None, None, :] >> mbit[None, :, :, None]) & 1   # [nb,2,4,32]
    q = q2 - 4.0 * (1 - hbit).astype(np.float32)
    sel = sc.reshape(nb, 2, 4, 2)[..., np.arange(32) // 16]       # [nb,2,4,32]
    return (d[:, :, None, None] * sel * q).reshape(-1)


def _k4_scale_min(scales: np.ndarray):
    """Unpack the 12-byte 6-bit (scale, min) table of Q4_K -> sc/m [nb, 8]."""
    s = scales.astype(np.uint8)
    sc = np.empty(s.shape[:-1] + (8,), np.uint8)
    m = np.empty_like(sc)
    sc[..., :4] = s[..., 0:4] & 63
    m[..., :4] = s[..., 4:8] & 63
    sc[..., 4:] = (s[..., 8:12] & 0xF) | ((s[..., 0:4] >> 6) << 4)
    m[..., 4:] = (s[..., 8:12] >> 4) | ((s[..., 4:8] >> 6) << 4)
    return sc.astype(np.float32), m.astype(np.float32)


def dequant_q4_k(raw: bytes, n: int) -> np.ndarray:
    """Super-block 256 = d f16 + dmin f16 + 12B scales + 128B qs;
    w = d*sc*q - dmin*m, 8 groups of 32 (low nibbles then high per 64)."""
    nb = n // QK_K
    b = np.frombuffer(raw, np.uint8).reshape(nb, 144)
    d = b[:, 0:2].copy().view(np.float16).astype(np.float32)      # [nb,1]
    dmin = b[:, 2:4].copy().view(np.float16).astype(np.float32)
    sc, mins = _k4_scale_min(b[:, 4:16])                          # [nb,8]
    qs = b[:, 16:]                                                # [nb,128]
    qs4 = qs.reshape(nb, 4, 32)                                   # per 64-pair
    lo = (qs4 & 0xF).astype(np.float32)                           # groups 0,2,4,6
    hi = (qs4 >> 4).astype(np.float32)                            # groups 1,3,5,7
    q = np.stack([lo, hi], axis=2).reshape(nb, 8, 32)
    scale = (d * sc)[:, :, None]
    minv = (dmin * mins)[:, :, None]
    return (scale * q - minv).reshape(-1)


def dequant_q6_k(raw: bytes, n: int) -> np.ndarray:
    """Super-block 256 = 128B ql + 64B qh + 16B scales(i8) + d f16;
    w = d * sc * (q - 32) with the ggml half-block interleave."""
    nb = n // QK_K
    b = np.frombuffer(raw, np.uint8).reshape(nb, 210)
    ql = b[:, 0:128].reshape(nb, 2, 64)
    qh = b[:, 128:192].reshape(nb, 2, 32)
    sc = b[:, 192:208].copy().view(np.int8).astype(np.float32).reshape(nb, 2, 8)
    d = b[:, 208:210].copy().view(np.float16).astype(np.float32)  # [nb,1]

    l0 = ql[:, :, 0:32]
    l32 = ql[:, :, 32:64]
    q1 = (l0 & 0xF) | (((qh >> 0) & 3) << 4)
    q2 = (l32 & 0xF) | (((qh >> 2) & 3) << 4)
    q3 = (l0 >> 4) | (((qh >> 4) & 3) << 4)
    q4 = (l32 >> 4) | (((qh >> 6) & 3) << 4)
    # y[l+0]:sc[l/16], y[l+32]:sc[2+l/16], y[l+64]:sc[4+l/16], y[l+96]:sc[6+l/16]
    q = np.stack([q1, q2, q3, q4], axis=2).astype(np.float32) - 32.0  # [nb,2,4,32]
    idx = np.arange(32) // 16                                     # 0/1 per l
    sel = np.stack([sc[:, :, 0 + idx], sc[:, :, 2 + idx],
                    sc[:, :, 4 + idx], sc[:, :, 6 + idx]], axis=2)
    y = (d[:, :, None, None] * sel * q)
    return y.reshape(-1)


# -- name + config mapping --------------------------------------------------

GGUF_NAME_MAP = {
    "attn_q": "self_attn.q_proj", "attn_k": "self_attn.k_proj",
    "attn_v": "self_attn.v_proj", "attn_output": "self_attn.o_proj",
    "attn_q_norm": "self_attn.q_norm", "attn_k_norm": "self_attn.k_norm",
    "ffn_gate": "mlp.gate_proj", "ffn_up": "mlp.up_proj",
    "ffn_down": "mlp.down_proj",
    "attn_norm": "input_layernorm", "ffn_norm": "post_attention_layernorm",
    "ffn_gate_inp": "mlp.gate",               # MoE router
}

# per-architecture llama.cpp tensor-name overrides: gemma-family sandwich
# norms repurpose ffn_norm as the PRE-feedforward norm, olmo2 is post-norm
# only (llama.cpp LLM_ARCH_GEMMA3 / LLM_ARCH_OLMO2 tensor tables)
_GEMMA_NORMS = {
    "ffn_norm": "pre_feedforward_layernorm",
    "post_attention_norm": "post_attention_layernorm",
    "post_ffw_norm": "post_feedforward_layernorm",
}
GGUF_NAME_OVERRIDES: dict[str, dict[str, str]] = {
    "gemma2": _GEMMA_NORMS,
    "gemma3": _GEMMA_NORMS,
    "olmo2": {"post_attention_norm": "post_attention_layernorm",
              "post_ffw_norm": "post_feedforward_layernorm"},
}

# expert banks: blk.N.ffn_gate_exps.weight holds [n_expert, inter, hidden]
MOE_BANK_STEMS = {
    "ffn_gate_exps": "gate_proj", "ffn_up_exps": "up_proj",
    "ffn_down_exps": "down_proj",
}


def gguf_to_hf_name(name: str, prefix: str = "model",
                    arch: str = "llama") -> str | None:
    """blk.N.attn_q.weight -> model.layers.N.self_attn.q_proj.weight
    (ref: gguf.rs name mapping, plus arch-aware norm/MoE extensions)."""
    if name == "token_embd.weight":
        return f"{prefix}.embed_tokens.weight"
    if name == "output_norm.weight":
        return f"{prefix}.norm.weight"
    if name == "output.weight":
        return "lm_head.weight"
    if name.startswith("blk."):
        _, layer, rest = name.split(".", 2)
        stem, suffix = rest.rsplit(".", 1)
        mapped = GGUF_NAME_OVERRIDES.get(arch, {}).get(stem) \
            or GGUF_NAME_MAP.get(stem)
        if mapped:
            return f"{prefix}.layers.{layer}.{mapped}.{suffix}"
    return None


# Architectures whose tensor set the name maps cover. Qwen3.5 GDN hybrids
# still need linear-attention mappings — rejected with a clear error
# instead of mis-wiring.
GGUF_ARCH_TO_HF = {
    "llama": "LlamaForCausalLM", "qwen2": "Qwen2ForCausalLM",
    "qwen3": "Qwen3ForCausalLM", "qwen3moe": "Qwen3MoeForCausalLM",
    "phi3": "Phi3ForCausalLM", "mistral": "MistralForCausalLM",
    "falcon": "FalconForCausalLM",
    # gemma2 deliberately absent: no QK norms, 1:1 interleave, logit
    # softcapping — the gemma3 adapter would mis-model it
    "gemma3": "Gemma3ForCausalLM",
    "olmo2": "Olmo2ForCausalLM",
}


def gguf_config_dict(reader: GgufReader) -> dict:
    """Build an HF-style config dict from GGUF metadata
    (ref: gguf.rs arch/config extraction)."""
    md = reader.metadata
    arch = md.get("general.architecture", "llama")
    if arch not in GGUF_ARCH_TO_HF:
        raise NotImplementedError(
            f"GGUF architecture {arch!r} not yet supported (needs name-map "
            f"entries beyond the llama layout)")

    def g(key, default=None):
        return md.get(f"{arch}.{key}", default)

    heads = int(g("attention.head_count", 32))
    hidden = int(g("embedding_length", 4096))
    vocab = int(g("vocab_size", 0))
    if not vocab and "token_embd.weight" in reader.tensors:
        vocab = reader.tensors["token_embd.weight"].dims[1]
    d = {
        "architectures": [GGUF_ARCH_TO_HF.get(arch, "LlamaForCausalLM")],
        "hidden_size": hidden,
        "intermediate_size": int(g("feed_forward_length", 11008)),
        "num_hidden_layers": int(g("block_count", 32)),
        "num_attention_heads": heads,
        "num_key_value_heads": int(g("attention.head_count_kv", heads)),
        "vocab_size": int(vocab),
        "rms_norm_eps": float(g("attention.layer_norm_rms_epsilon", 1e-5)),
        "rope_theta": float(g("rope.freq_base", 10000.0)),
        "max_position_embeddings": int(g("context_length", 4096)),
        "tie_word_embeddings": "output.weight" not in reader.tensors,
    }
    if g("attention.key_length"):
        d["head_dim"] = int(g("attention.key_length"))
    if g("attention.sliding_window"):
        d["sliding_window"] = int(g("attention.sliding_window"))
    if arch == "qwen3moe":
        d["num_experts"] = int(g("expert_count", 128))
        d["num_experts_per_tok"] = int(g("expert_used_count", 8))
        d["moe_intermediate_size"] = int(g("expert_feed_forward_length",
                                           d["intermediate_size"]))
        d["norm_topk_prob"] = True
    if arch in ("gemma2", "gemma3"):
        # llama.cpp hardcodes the 5-local:1-global interleave; the adapter's
        # sliding_window_pattern=6 default reproduces it
        d.setdefault("sliding_window", int(g("attention.sliding_window",
                                             1024)))
    eos = md.get("tokenizer.ggml.eos_token_id")
    if eos is not None:
        d["eos_token_id"] = int(eos)
    bos = md.get("tokenizer.ggml.bos_token_id")
    if bos is not None:
        d["bos_token_id"] = int(bos)
    return d


class GgufStorage:
    """TensorStorage-compatible facade over a GGUF file: HF names in,
    dequantized arrays out — so ParamLoader works unchanged.

    MoE expert banks (blk.N.ffn_*_exps, [n_expert, inter, hidden]) are
    exposed as virtual per-expert names matching the HF layout the loader
    expects; a small dequant cache keeps the bank hot while the loader
    iterates experts."""

    def __init__(self, path: str, prefix: str = "model"):
        self.reader = GgufReader(path)
        arch = self.reader.metadata.get("general.architecture", "llama")
        # llama.cpp's gemma converter bakes the (1+w) residual offset INTO
        # every *norm.weight tensor; our loader applies (1+w) itself for
        # residual_rms_norm configs, so undo the baked offset here or every
        # norm would be off by exactly 1
        self._norm_offset = -1.0 if arch.startswith("gemma") else 0.0
        self._map: dict[str, str] = {}
        self._experts: dict[str, tuple[str, int]] = {}
        self._bank_cache: dict[str, np.ndarray] = {}
        for gname, t in self.reader.tensors.items():
            hf = gguf_to_hf_name(gname, prefix, arch)
            if hf:
                self._map[hf] = gname
                continue
            if gname.startswith("blk."):
                _, layer, rest = gname.split(".", 2)
                stem, suffix = rest.rsplit(".", 1)
                proj = MOE_BANK_STEMS.get(stem)
                if proj and suffix == "weight":
                    n_exp = t.dims[-1]     # outermost ggml dim
                    for e in range(n_exp):
                        self._experts[
                            f"{prefix}.layers.{layer}.mlp.experts.{e}."
                            f"{proj}.weight"] = (gname, e)

    def names(self):
        return list(self._map) + list(self._experts)

    def __contains__(self, name):
        return name in self._map or name in self._experts

    def _bank(self, gname: str) -> np.ndarray:
        if gname not in self._bank_cache:
            if len(self._bank_cache) >= 3:   # gate/up/down of current layer
                self._bank_cache.pop(next(iter(self._bank_cache)))
            self._bank_cache[gname] = self.reader.read_tensor(gname)
        return self._bank_cache[gname]

    def read(self, name: str) -> np.ndarray:
        if name in self._experts:
            gname, e = self._experts[name]
            return self._bank(gname)[e]
        arr = self.reader.read_tensor(self._map[name])
        if self._norm_offset and name.endswith("norm.weight"):
            arr = arr + np.asarray(self._norm_offset, arr.dtype)
        return arr

    def close(self):
        self._bank_cache.clear()
