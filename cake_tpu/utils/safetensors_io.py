"""Safetensors reading: header parsing, name->(file, offset) indexing, and
pread-based single-tensor loads without mmap-ing whole checkpoints
(ref: utils/tensor_storage.rs SafetensorsStorage — the foundation of
layer-subset loading and disk expert offload).

Uses the native cakekit C++ pread core when built (csrc/), pure-Python
os.pread otherwise.
"""
from __future__ import annotations

import json
import os
import struct
from dataclasses import dataclass

import numpy as np

from .dtypes import SAFETENSORS_DTYPES, itemsize

# probe the native C++ IO core once at import (csrc/ builds it; optional)
try:
    from . import cakekit as _CAKEKIT
    if not _CAKEKIT.available():
        _CAKEKIT = None
except ImportError:
    _CAKEKIT = None


@dataclass(frozen=True)
class TensorRecord:
    file: str
    dtype: str            # canonical dtype name
    shape: tuple[int, ...]
    start: int            # absolute byte offset in file
    end: int

    @property
    def nbytes(self) -> int:
        return self.end - self.start


def read_header(path: str) -> tuple[dict, int]:
    """Returns (header dict, data_start offset)."""
    with open(path, "rb") as f:
        n = struct.unpack("<Q", f.read(8))[0]
        header = json.loads(f.read(n))
    return header, 8 + n


def index_file(path: str) -> dict[str, TensorRecord]:
    header, base = read_header(path)
    out = {}
    for name, meta in header.items():
        if name == "__metadata__":
            continue
        dt = SAFETENSORS_DTYPES[meta["dtype"]]
        b, e = meta["data_offsets"]
        out[name] = TensorRecord(file=path, dtype=dt,
                                 shape=tuple(meta["shape"]),
                                 start=base + b, end=base + e)
    return out


class TensorStorage:
    """name -> TensorRecord index over one or many .safetensors files;
    reads single tensors by pread (page-cache friendly, no mmap —
    ref: tensor_storage.rs:1-50)."""

    def __init__(self, records: dict[str, TensorRecord]):
        self.records = records
        self._fds: dict[str, int] = {}

    @classmethod
    def from_model_dir(cls, model_dir: str) -> "TensorStorage":
        """Loads model.safetensors.index.json if present, else every
        *.safetensors in the directory (ref: utils/mod.rs load paths)."""
        records: dict[str, TensorRecord] = {}
        idx = os.path.join(model_dir, "model.safetensors.index.json")
        if os.path.exists(idx):
            with open(idx) as f:
                weight_map = json.load(f)["weight_map"]
            for fname in sorted(set(weight_map.values())):
                records.update(index_file(os.path.join(model_dir, fname)))
        else:
            for fname in sorted(os.listdir(model_dir)):
                if fname.endswith(".safetensors"):
                    records.update(index_file(os.path.join(model_dir, fname)))
        if not records:
            raise FileNotFoundError(f"no .safetensors files in {model_dir}")
        return cls(records)

    def names(self):
        return self.records.keys()

    def __contains__(self, name):
        return name in self.records

    def _fd(self, path: str) -> int:
        if path not in self._fds:
            self._fds[path] = os.open(path, os.O_RDONLY)
        return self._fds[path]

    def read_bytes(self, name: str) -> bytes:
        r = self.records[name]
        if _CAKEKIT is not None:
            return _CAKEKIT.pread_fd(self._fd(r.file), r.start, r.nbytes)
        return os.pread(self._fd(r.file), r.nbytes, r.start)

    def read(self, name: str) -> np.ndarray:
        """Read one tensor as a numpy array (bf16/f8 via ml_dtypes)."""
        r = self.records[name]
        import jax.numpy as jnp
        np_dt = jnp.dtype(r.dtype)
        data = self.read_bytes(name)
        return np.frombuffer(bytearray(data), dtype=np_dt).reshape(r.shape)

    def read_many(self, names: list[str]) -> list[np.ndarray]:
        """Read several tensors; same-file groups go through the native
        batched preadv (one syscall round per file — the expert-streaming
        fast path, ref: tensor_storage.rs batched reads)."""
        import jax.numpy as jnp
        out: dict[str, np.ndarray] = {}
        by_file: dict[str, list[str]] = {}
        for n in names:
            by_file.setdefault(self.records[n].file, []).append(n)
        for path, group in by_file.items():
            if _CAKEKIT is not None and len(group) > 1:
                ranges = [(self.records[n].start, self.records[n].nbytes)
                          for n in group]
                blobs = _CAKEKIT.preadv_fd(self._fd(path), ranges)
                for n, blob in zip(group, blobs):
                    r = self.records[n]
                    out[n] = np.frombuffer(bytearray(blob),
                                           dtype=jnp.dtype(r.dtype)
                                           ).reshape(r.shape)
            else:
                for n in group:
                    out[n] = self.read(n)
        return [out[n] for n in names]

    def nbytes(self, name: str) -> int:
        return self.records[name].nbytes

    def close(self):
        for fd in self._fds.values():
            os.close(fd)
        self._fds.clear()


def layer_of(name: str, prefix: str = "model") -> int | None:
    """Extract the decoder-layer index from a weight name, None for
    non-layer tensors (ref: utils/mod.rs layer-subset filters)."""
    marker = ".layers."
    i = name.find(marker)
    if i < 0:
        return None
    rest = name[i + len(marker):]
    head = rest.split(".", 1)[0]
    return int(head) if head.isdigit() else None


def save_safetensors(path: str, tensors: dict[str, np.ndarray],
                     metadata: dict | None = None):
    """Minimal safetensors writer (splitter + tests)."""
    import jax.numpy as jnp
    header: dict = {}
    if metadata:
        header["__metadata__"] = metadata
    offset = 0
    blobs = []
    inv = {v: k for k, v in SAFETENSORS_DTYPES.items()}
    for name, arr in tensors.items():
        dt_name = jnp.dtype(arr.dtype).name
        blob = np.ascontiguousarray(arr).tobytes()
        header[name] = {
            "dtype": inv[dt_name],
            "shape": list(arr.shape),
            "data_offsets": [offset, offset + len(blob)],
        }
        offset += len(blob)
        blobs.append(blob)
    hjson = json.dumps(header).encode()
    pad = (-len(hjson)) % 8
    hjson += b" " * pad
    with open(path, "wb") as f:
        f.write(struct.pack("<Q", len(hjson)))
        f.write(hjson)
        for b in blobs:
            f.write(b)
