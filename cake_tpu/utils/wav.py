"""16-bit PCM WAV encoding (ref: cake-core/src/utils/wav.rs)."""
from __future__ import annotations

import io
import struct

import numpy as np


def f32_to_pcm16(samples: np.ndarray) -> bytes:
    s = np.clip(np.asarray(samples, np.float32), -1.0, 1.0)
    return (s * 32767.0).astype("<i2").tobytes()


def encode_wav(samples: np.ndarray, sample_rate: int = 24000) -> bytes:
    """Mono f32 [-1, 1] samples -> RIFF/WAVE bytes."""
    pcm = f32_to_pcm16(samples)
    buf = io.BytesIO()
    buf.write(b"RIFF")
    buf.write(struct.pack("<I", 36 + len(pcm)))
    buf.write(b"WAVE")
    buf.write(b"fmt ")
    buf.write(struct.pack("<IHHIIHH", 16, 1, 1, sample_rate,
                          sample_rate * 2, 2, 16))
    buf.write(b"data")
    buf.write(struct.pack("<I", len(pcm)))
    buf.write(pcm)
    return buf.getvalue()


def decode_wav(data: bytes) -> tuple[np.ndarray, int]:
    """Minimal RIFF parser -> (f32 mono samples, sample_rate)."""
    if data[:4] != b"RIFF" or data[8:12] != b"WAVE":
        raise ValueError("not a WAV file")
    pos = 12
    fmt = None
    pcm = None
    rate = 24000
    channels = 1
    while pos + 8 <= len(data):
        cid = data[pos:pos + 4]
        size = struct.unpack("<I", data[pos + 4:pos + 8])[0]
        body = data[pos + 8:pos + 8 + size]
        if cid == b"fmt ":
            fmt = struct.unpack("<HHIIHH", body[:16])
            channels, rate = fmt[1], fmt[2]
        elif cid == b"data":
            pcm = body
        pos += 8 + size + (size & 1)
    if pcm is None:
        raise ValueError("no data chunk")
    samples = np.frombuffer(pcm, "<i2").astype(np.float32) / 32767.0
    if channels > 1:
        samples = samples.reshape(-1, channels).mean(axis=1)
    return samples, rate
