"""Quantization strategies: detection from config.json and dequant-at-load
transforms (ref: utils/mod.rs Quantization trait; utils/fp8.rs; utils/gptq.rs).

Each strategy intercepts weight loads by name: given a TensorStorage and a
weight name, it either dequantizes companion tensors (FP8 weight_scale_inv,
GPTQ qweight/scales/qzeros) or falls through to a plain read — exactly the
reference's transparent VarBuilder-backend design.
"""
from __future__ import annotations

import numpy as np

from ..ops.fp8 import FP8_BLOCK


class NoQuantization:
    name = "none"
    vram_factor = 1.0      # ref: utils/mod.rs VRAM expansion estimate

    def load(self, storage, name: str) -> np.ndarray:
        return storage.read(name)

    def has(self, storage, name: str) -> bool:
        return name in storage


class Fp8Quantization:
    """Block-wise FP8 (E4M3) with per-128x128 `weight_scale_inv`
    (ref: utils/fp8.rs). Default: dequant at load. keep_native=True keeps
    weights as f8e4m3 in HBM (1 byte/param — the reference's
    native_dtype_backend, FLUX.1 12 GB vs 24 GB) and the model dequantizes
    per layer inside the jitted forward."""
    name = "fp8"
    vram_factor = 2.0      # f8 -> bf16 doubles bytes when dequantized

    def __init__(self, keep_native: bool = False):
        self.keep_native = keep_native
        if keep_native:
            self.vram_factor = 1.0

    def load(self, storage, name: str):
        scale_name = name.replace(".weight", ".weight_scale_inv")
        if not name.endswith(".weight") or scale_name not in storage:
            return storage.read(name)
        w = storage.read(name)
        s = storage.read(scale_name).astype(np.float32)
        if self.keep_native:
            # marker dict consumed by loaders -> params keep f8 + scales
            return {"__fp8__": w, "scale_inv": s}
        from ..ops.fp8 import dequant_fp8_blockwise
        import jax.numpy as jnp
        return np.asarray(dequant_fp8_blockwise(
            jnp.asarray(w), jnp.asarray(s), out_dtype=jnp.float32))

    def has(self, storage, name: str) -> bool:
        return name in storage


class GptqQuantization:
    """AutoGPTQ 4-bit: qweight int32 [in/8, out] (8x4bit packed along in),
    scales f16 [groups, out], qzeros int32 [groups, out/8].
    weight[o, i] = (q4(i,o) - zero4(g(i),o) - 1) * scale(g(i),o)
    (ref: utils/gptq.rs dequantize_gptq_4bit, incl. the AutoGPTQ -1 zero
    convention)."""
    name = "gptq"
    vram_factor = 4.0

    def __init__(self, group_size: int = 128, desc_act: bool = False):
        self.group_size = group_size
        self.desc_act = desc_act

    def has(self, storage, name: str) -> bool:
        return (name in storage
                or name.replace(".weight", ".qweight") in storage)

    def load(self, storage, name: str) -> np.ndarray:
        qname = name.replace(".weight", ".qweight")
        if not name.endswith(".weight") or qname not in storage:
            return storage.read(name)
        qweight = storage.read(qname).view(np.uint32)
        scales = storage.read(name.replace(".weight", ".scales")).astype(np.float32)
        qzeros = storage.read(name.replace(".weight", ".qzeros")).view(np.uint32)
        # act-order checkpoints permute the group mapping; honor the stored
        # g_idx when present, refuse (instead of silently producing garbage
        # like the reference's gptq.rs would) when it is missing
        gname = name.replace(".weight", ".g_idx")
        g_idx = storage.read(gname).astype(np.int64) if gname in storage \
            else None
        if self.desc_act and g_idx is None:
            raise NotImplementedError(
                f"GPTQ desc_act=true checkpoint without a g_idx tensor for "
                f"{name}: sequential group mapping would silently produce "
                f"wrong weights")
        return dequantize_gptq_4bit(qweight, scales, qzeros, self.group_size,
                                    g_idx)


def unpack_int4(packed: np.ndarray, axis: int) -> np.ndarray:
    """Unpack 8x4-bit nibbles from each uint32 along `axis` (LSB first)."""
    shifts = np.arange(8, dtype=np.uint32) * 4
    nibbles = (packed[..., None] >> shifts) & 0xF          # [..., 8]
    nibbles = np.moveaxis(nibbles, -1, axis + 1 if axis >= 0 else axis)
    shape = list(packed.shape)
    shape[axis] *= 8
    return nibbles.reshape(shape).astype(np.int32)


def dequantize_gptq_4bit(qweight: np.ndarray, scales: np.ndarray,
                         qzeros: np.ndarray, group_size: int = 128,
                         g_idx: np.ndarray | None = None) -> np.ndarray:
    """Returns [out_features, in_features] f32. g_idx (per-in-feature group
    index) overrides the sequential arange//group_size mapping — required
    for act-order (desc_act) checkpoints."""
    q = unpack_int4(qweight, axis=0)                # [in, out]
    zeros = unpack_int4(qzeros, axis=1)             # [groups, out]
    in_features = q.shape[0]
    if g_idx is None:
        g_idx = np.arange(in_features) // group_size
    w = (q - zeros[g_idx] - 1).astype(np.float32) * scales[g_idx]
    return np.ascontiguousarray(w.T)


def fp8_native_quant() -> "Fp8Quantization":
    """The keep-native FP8 strategy (1 byte/param in HBM, per-layer dequant
    fused into the matmuls) — single construction site for the runtime,
    master and worker paths."""
    return Fp8Quantization(keep_native=True)


def detect_quantization(config: dict):
    """From config.json quantization_config (top-level or text_config —
    ref: gptq.rs is_gptq_quantized, utils/mod.rs detection)."""
    for root in (config, config.get("text_config") or {}):
        qc = root.get("quantization_config")
        if not qc:
            continue
        method = qc.get("quant_method", "")
        if method == "gptq" or (qc.get("mode") == "affine"
                                and qc.get("bits") == 4):
            bits = int(qc.get("bits", 4))
            if bits != 4:
                raise NotImplementedError(
                    f"GPTQ {bits}-bit not supported (4-bit only)")
            return GptqQuantization(int(qc.get("group_size", 128)),
                                    desc_act=bool(qc.get("desc_act", False)))
        if method == "fp8" or qc.get("fmt") in ("e4m3", "float8_e4m3fn"):
            return Fp8Quantization()
    return NoQuantization()
