"""Dtype utilities shared by the op library and the wire protocol.

The wire dtype tags cover every dtype the reference protocol ships
(ref: cake-core/src/cake/sharding/proto/message.rs RawTensor dtype:u8),
extended with bfloat16/f8e4m3 which are first-class on TPU.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# Stable u8 wire tags. Never reorder — these are a protocol contract.
WIRE_DTYPES = {
    0: "float32",
    1: "float16",
    2: "bfloat16",
    3: "float64",
    4: "uint8",
    5: "uint32",
    6: "int64",
    7: "int32",
    8: "float8_e4m3fn",
    9: "int8",
    10: "int16",
    11: "uint16",
    12: "bool",
}
WIRE_TAGS = {v: k for k, v in WIRE_DTYPES.items()}

_STR_TO_JNP = {
    "float32": jnp.float32,
    "float16": jnp.float16,
    "bfloat16": jnp.bfloat16,
    "float64": jnp.float64,
    "uint8": jnp.uint8,
    "uint32": jnp.uint32,
    "int64": jnp.int64,
    "int32": jnp.int32,
    "float8_e4m3fn": jnp.float8_e4m3fn,
    "int8": jnp.int8,
    "int16": jnp.int16,
    "uint16": jnp.uint16,
    "bool": jnp.bool_,
}

# safetensors header dtype names -> canonical string
SAFETENSORS_DTYPES = {
    "F64": "float64",
    "F32": "float32",
    "F16": "float16",
    "BF16": "bfloat16",
    "I64": "int64",
    "I32": "int32",
    "I16": "int16",
    "I8": "int8",
    "U8": "uint8",
    "U16": "uint16",
    "U32": "uint32",
    "BOOL": "bool",
    "F8_E4M3": "float8_e4m3fn",
}

_ITEMSIZE = {
    "float64": 8, "float32": 4, "float16": 2, "bfloat16": 2,
    "int64": 8, "int32": 4, "int16": 2, "int8": 1,
    "uint8": 1, "uint16": 2, "uint32": 4, "bool": 1, "float8_e4m3fn": 1,
}


def parse_dtype(s: str):
    """Parse a user dtype string (ref: cake/mod.rs parse_dtype_str)."""
    s = s.lower().strip()
    aliases = {
        "f32": "float32", "f16": "float16", "bf16": "bfloat16",
        "f64": "float64", "u8": "uint8", "u32": "uint32",
        "i64": "int64", "i32": "int32", "f8": "float8_e4m3fn",
        "f8e4m3": "float8_e4m3fn", "half": "float16", "float": "float32",
    }
    s = aliases.get(s, s)
    if s not in _STR_TO_JNP:
        raise ValueError(f"unsupported dtype {s!r}")
    return _STR_TO_JNP[s]


def dtype_name(dt) -> str:
    """Canonical string name for a jnp/np dtype."""
    return jnp.dtype(dt).name


def itemsize(name: str) -> int:
    return _ITEMSIZE[name]


def to_numpy_bytes(arr) -> bytes:
    """Raw little-endian bytes of an array (bf16/f8 via uint16/uint8 views)."""
    a = np.asarray(arr)
    return a.tobytes()


def from_numpy_bytes(data: bytes, dtype_str: str, shape) -> np.ndarray:
    """Inverse of to_numpy_bytes. bfloat16/f8 round-trip via ml_dtypes (numpy
    understands them through jnp.dtype)."""
    np_dt = jnp.dtype(_STR_TO_JNP[dtype_str])  # np.dtype (ml_dtypes-backed for bf16/f8)
    return np.frombuffer(bytearray(data), dtype=np_dt).reshape(shape)
