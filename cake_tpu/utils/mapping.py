"""Checkpoint-name -> parameter-pytree mapped loading.

Release checkpoints (FLUX/SD/VibeVoice/...) store tensors under their
training framework's module names; our models are plain pytrees. A model
family declares a *mapping* {pytree path -> checkpoint tensor name} and
this module does the rest: pread each tensor, validate its shape against
the pytree's expected shape (from jax.eval_shape — no allocation), cast,
and report coverage both ways (missing checkpoint tensors, unused ones).

This replaces the reference's per-model VarBuilder wiring (ref:
models/flux/flux1_model.rs — 1,011 lines of vb.pp(..) calls) with a
declarative table the tests can synthesize checkpoints from.

Path syntax: dotted, with integer segments indexing lists
("double.3.img.qkv.weight" -> params["double"][3]["img"]["qkv"]["weight"]).
"""
from __future__ import annotations

import logging

import jax.numpy as jnp
import numpy as np

log = logging.getLogger("cake_tpu.mapping")


def flatten_tree(tree, prefix: str = "") -> dict[str, object]:
    """Nested dict/list pytree -> {dotted path: leaf}."""
    out: dict[str, object] = {}
    if isinstance(tree, dict):
        items = tree.items()
    elif isinstance(tree, (list, tuple)):
        items = ((str(i), v) for i, v in enumerate(tree))
    elif tree is None:
        return {}          # structural placeholders (e.g. "no upsample here")
    else:
        return {prefix: tree}
    for k, v in items:
        p = f"{prefix}.{k}" if prefix else str(k)
        out.update(flatten_tree(v, p))
    return out


def unflatten_tree(flat: dict[str, object]):
    """Inverse of flatten_tree: contiguous integer keys become lists."""
    root: dict = {}
    for path, leaf in flat.items():
        parts = path.split(".")
        node = root
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = leaf

    def listify(node):
        if not isinstance(node, dict):
            return node
        keys = list(node.keys())
        if keys and all(k.isdigit() for k in keys):
            idx = sorted(int(k) for k in keys)
            if idx == list(range(len(idx))):
                return [listify(node[str(i)]) for i in idx]
        return {k: listify(v) for k, v in node.items()}

    return listify(root)


def load_mapped_params(storage, mapping: dict[str, str], expected,
                       dtype=jnp.bfloat16,
                       transforms: dict[str, object] | None = None,
                       extra: dict[str, object] | None = None,
                       fp8_native: bool = False) -> dict:
    """Load a pytree through a name mapping with full validation.

    storage:   TensorStorage (or anything with read()/__contains__/names()).
    mapping:   {pytree path: checkpoint tensor name}.
    expected:  pytree of arrays or jax.ShapeDtypeStruct (e.g. from
               jax.eval_shape over the family's init_params) — every leaf
               NOT in `extra` must be covered by `mapping`.
    transforms: {pytree path: fn(np.ndarray) -> np.ndarray} applied before
               shape validation (e.g. transpose, split of fused tensors).
    extra:     {pytree path: ready leaf} for computed leaves (rope tables).
    fp8_native: keep 2D float8-stored tensors resident as
               {"fp8", "scale_inv"} marker dicts (1 byte/param in HBM;
               ops/linear.resolve_weight fuses the dequant into the
               consuming matmul — ref: native_dtype_backend.rs keeping
               FLUX.1-dev at ~13 GB instead of ~24). The ComfyUI
               per-tensor `.scale_weight` (or 1.0 for the plain-cast
               flux1-dev-fp8 bundle) broadcasts into the blockwise
               scale_inv grid the text path's resolver already consumes.

    Raises ValueError listing ALL missing tensors / unmapped paths /
    shape mismatches at once — a failed 12 GB load should say everything
    that is wrong, not one name per attempt.
    """
    transforms = transforms or {}
    extra = extra or {}
    flat_expected = flatten_tree(expected)

    problems: list[str] = []
    unmapped = [p for p in flat_expected
                if p not in mapping and p not in extra]
    if unmapped:
        problems.append(f"pytree paths without a mapping entry: "
                        f"{sorted(unmapped)[:8]}"
                        + (f" (+{len(unmapped) - 8} more)"
                           if len(unmapped) > 8 else ""))
    missing = [n for p, n in mapping.items()
               if p in flat_expected and n not in storage]
    if missing:
        problems.append(f"checkpoint tensors not found: {sorted(missing)[:8]}"
                        + (f" (+{len(missing) - 8} more)"
                           if len(missing) > 8 else ""))
    if problems:
        raise ValueError("checkpoint mapping failed:\n  " +
                         "\n  ".join(problems))

    flat_out: dict[str, object] = {}
    for path, exp in flat_expected.items():
        if path in extra:
            flat_out[path] = extra[path]
            continue
        name = mapping[path]
        scale = name[:-len(".weight")] + ".scale_weight" \
            if name.endswith(".weight") else None
        arr = storage.read(name)               # single disk read per tensor
        is_f8 = "float8" in str(arr.dtype)
        if fp8_native and is_f8 and len(exp.shape) == 2:
            if path in transforms:
                arr = transforms[path](arr)    # transpose/split: 1B moves
            if tuple(arr.shape) != tuple(exp.shape):
                problems.append(f"{name} -> {path}: shape {tuple(arr.shape)}"
                                f" != expected {tuple(exp.shape)}")
                continue
            s = (float(storage.read(scale)) if scale and scale in storage
                 else 1.0)
            o, i = arr.shape
            si = jnp.full((-(-o // 128), -(-i // 128)), s, jnp.float32)
            flat_out[path] = {"fp8": jnp.asarray(arr), "scale_inv": si}
            continue
        if is_f8:
            # FP8 (e4m3) dequant on read: plain cast, times the per-tensor
            # `.scale_weight` when the checkpoint has one (Comfy scaled-fp8
            # convention; the flux1-dev-fp8 bundle is plain-cast — ref:
            # flux1_model.rs Fp8Linear F8->F16 dequant)
            arr = arr.astype(np.float32)
            if scale and scale in storage:
                arr = arr * storage.read(scale).astype(np.float32)
        if path in transforms:
            arr = transforms[path](arr)
        if tuple(arr.shape) != tuple(exp.shape):
            problems.append(f"{name} -> {path}: shape {tuple(arr.shape)} "
                            f"!= expected {tuple(exp.shape)}")
            continue
        flat_out[path] = jnp.asarray(arr).astype(dtype)
    if problems:
        raise ValueError("checkpoint mapping failed:\n  " +
                         "\n  ".join(problems))
    return unflatten_tree(flat_out)


def coverage_report(storage, mapping: dict[str, str], prefix: str = "",
                    ignore: tuple[str, ...] = ()) -> list[str]:
    """Checkpoint tensors under `prefix` that no mapping entry consumes
    (and no `ignore` prefix explains). Returned, and warned about, so a
    silently-dropped weight is visible (round-1 lesson: no silent caps)."""
    used = set(mapping.values())
    used |= {n[:-len(".weight")] + ".scale_weight" for n in used
             if n.endswith(".weight")}
    unused = [n for n in storage.names()
              if n.startswith(prefix) and n not in used
              and not any(n.startswith(i) for i in ignore)]
    if unused:
        log.warning("checkpoint tensors not consumed under %r: %s%s",
                    prefix, sorted(unused)[:6],
                    f" (+{len(unused) - 6} more)" if len(unused) > 6 else "")
    return unused
