"""HuggingFace hub integration: repo-id detection, cache probing,
auto-download (ref: utils/hf.rs — repo-id detection, cache probing,
auto-download).

Zero-egress environments: downloads fail fast with a clear message and
local paths always work.
"""
from __future__ import annotations

import os

MODEL_FILE_PATTERNS = ("*.safetensors", "*.json", "tokenizer*", "*.gguf",
                       "*.model")


def looks_like_repo_id(name: str) -> bool:
    """`org/name` that is not an existing path (ref: utils/hf.rs detection)."""
    if os.path.exists(name):
        return False
    parts = name.split("/")
    return len(parts) == 2 and all(p and not p.startswith(".") for p in parts)


def hf_cache_dir() -> str:
    return os.environ.get(
        "HF_HUB_CACHE",
        os.path.join(os.environ.get(
            "HF_HOME", os.path.expanduser("~/.cache/huggingface")), "hub"))


def cake_cache_dir() -> str:
    """Our own worker model-data cache root (ref: sharding/mod.rs cache dir)."""
    from .. import knobs
    return os.path.expanduser(knobs.get("CAKE_TPU_CACHE"))


def probe_cached_repo(repo_id: str) -> str | None:
    """Find an already-downloaded snapshot without network."""
    safe = "models--" + repo_id.replace("/", "--")
    snap_root = os.path.join(hf_cache_dir(), safe, "snapshots")
    if not os.path.isdir(snap_root):
        return None
    snaps = sorted(os.listdir(snap_root))
    for s in reversed(snaps):
        p = os.path.join(snap_root, s)
        if os.path.isdir(p) and any(f.endswith((".safetensors", ".gguf"))
                                    for f in os.listdir(p)):
            return p
    return None  # weightless snapshot (interrupted pull) -> re-download


def resolve_model(name_or_path: str, download: bool = True) -> str:
    """Local dir -> itself; repo id -> cached snapshot or download."""
    if os.path.isdir(name_or_path):
        return name_or_path
    if not looks_like_repo_id(name_or_path):
        raise FileNotFoundError(f"model path {name_or_path!r} does not exist")
    cached = probe_cached_repo(name_or_path)
    if cached:
        return cached
    if not download:
        raise FileNotFoundError(f"{name_or_path} not in HF cache")
    return pull(name_or_path)


def pull(repo_id: str) -> str:
    """Download a repo snapshot (ref: `cake pull`)."""
    try:
        from huggingface_hub import snapshot_download
        return snapshot_download(repo_id, allow_patterns=list(MODEL_FILE_PATTERNS))
    except Exception as e:  # zero-egress / auth failures
        raise RuntimeError(
            f"cannot download {repo_id} (offline environment?): {e}") from e
