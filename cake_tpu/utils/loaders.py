"""Checkpoint -> parameter-pytree loaders with layer-subset support.

The reference's partial VarBuilder loads (full model / master-local-only /
worker-specific-layers — ref: utils/mod.rs:251-333) map to `layer_range` +
include_embed/include_head here; quantization strategies are applied
per-tensor at load (ref: Quantization trait) and Phi-4's pre-fused
qkv_proj/gate_up_proj are split into the TP-alignable separate projections
(see models/common/layers.py init_attention_params docstring).
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..models.common.config import ModelConfig
from ..models.common.layers import make_rope
from ..ops.norms import load_rms_norm_weight
from .quant import NoQuantization
from .safetensors_io import TensorStorage


def _to_dev(arr, dtype):
    if isinstance(arr, dict) and "__fp8__" in arr:
        # native-dtype FP8: weight stays 1 byte/param in HBM; the forward
        # dequantizes per layer (ref: utils/native_dtype_backend.rs)
        return {"fp8": jnp.asarray(arr["__fp8__"]),
                "scale_inv": jnp.asarray(arr["scale_inv"])}
    return jnp.asarray(arr).astype(dtype)


class ParamLoader:
    def __init__(self, cfg: ModelConfig, storage: TensorStorage,
                 dtype=jnp.bfloat16, quant=None,
                 expert_offload: bool = False, expert_lru_size: int = 32):
        self.cfg = cfg
        self.st = storage
        self.dtype = dtype
        self.quant = quant or NoQuantization()
        self.prefix = cfg.model_prefix
        # MoE expert banks stay ON DISK, streamed per selected expert at
        # forward time (ref: --expert-offload / disk_expert_provider.rs) —
        # the storage handle is kept alive by the providers
        self.expert_offload = expert_offload
        self.expert_lru_size = expert_lru_size

    # -- helpers ------------------------------------------------------------

    def _get(self, name: str):
        return self.quant.load(self.st, name)

    _warned_dense_fallback = False

    def _get_dense(self, name: str) -> np.ndarray:
        """Like _get but always a dense ndarray: paths that slice, stack,
        concatenate or consume weights outside linear() (fused qkv/gate_up
        splits, MoE expert stacking + router gate, embeddings, GDN in_proj)
        cannot keep fp8-native marker dicts. Dequantized HOST-side in numpy
        (no device round trip) with a one-time warning that these tensors
        lose the 1 byte/param residency."""
        w = self._get(name)
        if isinstance(w, dict) and "__fp8__" in w:
            if not ParamLoader._warned_dense_fallback:
                import logging
                logging.getLogger("cake_tpu.loaders").warning(
                    "fp8-native: %s loads dense (sliced/stacked/non-matmul "
                    "consumer) — 1 byte/param residency applies to plain "
                    "projections only", name)
                ParamLoader._warned_dense_fallback = True
            f8 = np.asarray(w["__fp8__"])
            si = np.asarray(w["scale_inv"], dtype=np.float32)
            o, i = f8.shape
            full = np.repeat(np.repeat(si, 128, 0), 128, 1)[:o, :i]
            return f8.astype(np.float32) * full
        return w

    def _has(self, name: str) -> bool:
        return self.quant.has(self.st, name)

    def _norm(self, name: str):
        """RMS-norm weight with the (1+w) residual pattern applied in f32 at
        load (ref: config.rs load_rms_norm_weight)."""
        w = _to_dev(self._get(name), self.dtype)
        return load_rms_norm_weight(w, self.cfg.residual_rms_norm)

    # -- sub-loaders --------------------------------------------------------

    def _attention(self, lp: str, spec) -> dict:
        cfg = self.cfg
        sq, skv = cfg.size_q, cfg.size_kv
        p: dict = {}
        if cfg.fused_qkv and self._has(f"{lp}.self_attn.qkv_proj.weight"):
            w = self._get_dense(f"{lp}.self_attn.qkv_proj.weight")
            p["q_proj"] = {"weight": _to_dev(w[:sq], self.dtype)}
            p["k_proj"] = {"weight": _to_dev(w[sq:sq + skv], self.dtype)}
            p["v_proj"] = {"weight": _to_dev(w[sq + skv:], self.dtype)}
        else:
            for proj in ("q_proj", "k_proj", "v_proj"):
                d = {"weight": _to_dev(
                    self._get(f"{lp}.self_attn.{proj}.weight"), self.dtype)}
                bias = f"{lp}.self_attn.{proj}.bias"
                if cfg.qkv_bias and self._has(bias):
                    d["bias"] = _to_dev(self._get(bias), self.dtype)
                p[proj] = d
        p["o_proj"] = {"weight": _to_dev(
            self._get(f"{lp}.self_attn.o_proj.weight"), self.dtype)}
        if cfg.qk_norm:
            p["q_norm"] = {"weight": self._norm(f"{lp}.self_attn.q_norm.weight")}
            p["k_norm"] = {"weight": self._norm(f"{lp}.self_attn.k_norm.weight")}
        return p

    def _mlp(self, mp: str) -> dict:
        cfg = self.cfg
        if cfg.fused_gate_up and self._has(f"{mp}.gate_up_proj.weight"):
            w = self._get_dense(f"{mp}.gate_up_proj.weight")
            i = w.shape[0] // 2
            return {
                "gate_proj": {"weight": _to_dev(w[:i], self.dtype)},
                "up_proj": {"weight": _to_dev(w[i:], self.dtype)},
                "down_proj": {"weight": _to_dev(
                    self._get(f"{mp}.down_proj.weight"), self.dtype)},
            }
        return {proj: {"weight": _to_dev(self._get(f"{mp}.{proj}.weight"),
                                         self.dtype)}
                for proj in ("gate_proj", "up_proj", "down_proj")}

    def _moe(self, mp: str) -> dict:
        cfg = self.cfg
        # router gate feeds a raw einsum (ops/moe.py), not linear(): dense
        p: dict = {"gate": {"weight": _to_dev(
            self._get_dense(f"{mp}.gate.weight"), self.dtype)}}
        if self.expert_offload:
            # experts stream from disk through a dequant-LRU provider
            # instead of residing stacked in HBM; the provider object is a
            # pytree leaf consumed only by the eager offloaded forward
            from ..models.common.expert_provider import DiskExpertProvider
            p["_provider"] = DiskExpertProvider(
                self.st, mp, cfg.num_experts, quant=self.quant,
                dtype=self.dtype, lru_size=self.expert_lru_size,
                name_fmt="{lp}.experts.{e}.{proj}.weight")
        else:
            stacked = {k: [] for k in ("gate_proj", "up_proj", "down_proj")}
            for e in range(cfg.num_experts):
                for proj in stacked:
                    stacked[proj].append(
                        self._get_dense(f"{mp}.experts.{e}.{proj}.weight"))
            p["experts"] = {proj: _to_dev(np.stack(ws), self.dtype)
                            for proj, ws in stacked.items()}
        if cfg.shared_expert_intermediate_size:
            p["shared_expert"] = self._mlp(f"{mp}.shared_expert")
            p["shared_expert_gate"] = {"weight": _to_dev(
                self._get(f"{mp}.shared_expert_gate.weight"), self.dtype)}
        return p

    def _layer(self, i: int) -> dict:
        cfg = self.cfg
        spec = cfg.layer_spec(i)
        lp = f"{self.prefix}.layers.{i}"
        p: dict = {}
        if spec.kind == "linear":
            from ..models.qwen3_5 import load_gdn_params
            p["linear_attn"] = load_gdn_params(self, lp)
        else:
            p["self_attn"] = self._attention(lp, spec)
        p["mlp"] = self._moe(f"{lp}.mlp") if spec.is_moe else self._mlp(f"{lp}.mlp")
        if spec.norm_style == "pre":
            names = ("input_layernorm", "post_attention_layernorm")
        elif spec.norm_style == "post":
            names = ("post_attention_layernorm", "post_feedforward_layernorm")
        else:
            names = ("input_layernorm", "post_attention_layernorm",
                     "pre_feedforward_layernorm", "post_feedforward_layernorm")
        for n in names:
            p[n] = {"weight": self._norm(f"{lp}.{n}.weight")}
        return p

    # -- public -------------------------------------------------------------

    def load(self, layer_range: tuple[int, int] | None = None,
             include_embed: bool | None = None,
             include_head: bool | None = None) -> dict:
        cfg = self.cfg
        lo, hi = layer_range or (0, cfg.num_hidden_layers)
        if include_embed is None:
            include_embed = lo == 0
        if include_head is None:
            include_head = hi == cfg.num_hidden_layers
        if include_head and cfg.tie_word_embeddings:
            include_embed = True
        params: dict = {"layers": [self._layer(i) for i in range(lo, hi)]}
        if include_embed:
            # embeddings feed jnp.take, not linear(): dense
            params["embed_tokens"] = {"weight": _to_dev(
                self._get_dense(f"{self.prefix}.embed_tokens.weight"),
                self.dtype)}
        if include_head:
            params["norm"] = {"weight": self._norm(f"{self.prefix}.norm.weight")}
            if not cfg.tie_word_embeddings:
                head = ("lm_head.weight" if self._has("lm_head.weight")
                        else f"{self.prefix}.lm_head.weight")
                params["lm_head"] = {"weight": _to_dev(self._get(head),
                                                       self.dtype)}
        params["rope"] = make_rope(cfg)
        return params


def load_model_params(cfg: ModelConfig, model_dir: str, dtype=jnp.bfloat16,
                      quant=None, layer_range=None, include_embed=None,
                      include_head=None, expert_offload: bool = False,
                      expert_lru_size: int = 32) -> dict:
    """One-call load: storage + quant detection + pytree assembly."""
    import json
    import os

    from .quant import detect_quantization
    storage = TensorStorage.from_model_dir(model_dir)
    if quant is None:
        cfg_path = os.path.join(model_dir, "config.json")
        with open(cfg_path) as f:
            quant = detect_quantization(json.load(f))
    loader = ParamLoader(cfg, storage, dtype, quant,
                         expert_offload=expert_offload,
                         expert_lru_size=expert_lru_size)
    return loader.load(layer_range, include_embed, include_head)
