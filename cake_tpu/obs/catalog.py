"""Generator for docs/observability.md — the metric / span / timeline
catalog plus the endpoint and tracing prose, all from one source.

The hand-written observability page predated serve/paged/spec/fleet and
went three subsystems stale; like docs/knobs.md it is now GENERATED
(`make metrics-doc`, `python -m cake_tpu.obs`) and pinned to this module
by test. The metric table renders the process-global REGISTRY after the
canonical declarations in obs/__init__.py import, the span table renders
spans.SPAN_CATALOG, and the timeline-event table renders
timeline.EVENT_KINDS — so the `metric-registry` lint (which checks every
constructed instrument name against the generated file) closes the loop:
an instrument cannot ship undocumented, and the doc cannot drift from
the code.
"""
from __future__ import annotations

_HEADER = """\
# Observability

<!-- GENERATED FILE — do not edit. Source of truth is
     cake_tpu/obs/catalog.py (metric table: the canonical declarations
     in cake_tpu/obs/__init__.py; span table: obs/spans.py
     SPAN_CATALOG; timeline events: obs/timeline.py EVENT_KINDS).
     Regenerate with `make metrics-doc`; tests/test_analysis.py pins
     this file, and the `metric-registry` lint checks every
     constructed instrument name against it. -->

`cake_tpu/obs/` is the measurement layer for the whole stack: a metrics
registry (counters / gauges / histograms with Prometheus text
exposition), a span recorder (Chrome-trace / Perfetto JSON export),
request-id propagation, and per-request lifecycle timelines. Every
serving tier records into the same process-global instruments, so one
`/metrics` scrape, one trace export, or one timeline fetch shows the
whole request path — fleet router → replica API → serve engine →
cluster stages.

## Endpoints

| endpoint | serves |
|---|---|
| `GET /metrics` | Prometheus text exposition 0.0.4 of every instrument below (per process; worker-side series live in each worker process) |
| `GET /health` | JSON liveness: worker last-seen ages, gray/hard cluster degradation, the serve-engine block (`alive` / `wedged` / `down` / `draining`, queue depth, `prefilling`, prefix-cache and `kv_pool` occupancy — the paged block carries a first-class `occupancy` field in [0, 1]); 503 while degraded |
| `GET /api/v1/stats` | last generation's timing snapshot (TTFT, tok/s, per-hop RTT split), with its `request_id` (the cross-tier trace id) and `completion_id` |
| `GET /api/v1/trace` | Chrome-trace JSON of the span ring buffer (`?clear=1` drains; 409 while the recorder is disabled) |
| `GET /api/v1/requests` | recent request ids with retrievable timelines |
| `GET /api/v1/requests/<id>` | one request's typed lifecycle timeline (`?format=perfetto` for Chrome-trace instant events); on the fleet router this view STITCHES the router tier's events onto the replica's |
| `GET /api/v1/slo` | the serve TTFT / inter-token / e2e histograms by outcome as JSON, each bucket carrying its sampled exemplar request id |
| `GET /api/v1/flight` | flight-recorder-on-demand: the scheduler-iteration ring as JSON without waiting for a wedge/DOWN dump (`?n=K` for the newest K; 409 without an engine) |
| `GET /api/v1/fleet/telemetry` | ROUTER ONLY: the fleet telemetry rollup — time-series, burn rates, headroom, outliers (see [telemetry.md](telemetry.md)) |
| `GET /api/v1/fleet/autoscale` | ROUTER ONLY: the autoscaler's decision ring, policy, and managed-replica lifecycle state (see [autoscaling.md](autoscaling.md); `{"enabled": false}` when the loop is off) |

## Request-scoped tracing

One id names a request end to end: `cake route` injects an
`X-Cake-Request-Id` header (minting `trace-…` when the client sent
none), the replica API adopts it into the request-id contextvar (spans
and `/api/v1/stats` carry it), the serve engine keys its timeline events
by it, and every response echoes the header back. The OpenAI completion
id (`chatcmpl-…`) is registered as an alias, so either id resolves
`/api/v1/requests/<id>`.

Timelines are ALWAYS recorded (a dict lookup + list append per event):
the last `CAKE_TRACE_REQUESTS` requests are kept, each bounded to 512
events (newest dropped and counted, terminal events always land). The
span recorder stays opt-in (`CAKE_TRACE_DIR` or `RECORDER.enable()`)
and bounded by `CAKE_TRACE_EVENTS`; spans recorded while serving a
request carry the request id in their args, and a timeline's Perfetto
export uses the same perf_counter clock, so both merge on one axis at
<https://ui.perfetto.dev>.

## Engine flight recorder

The serve engine appends one record per scheduler iteration (occupancy,
dispatch bucket, dispatch+fetch wall ms, spec accepts, queue depth,
paged-pool free/used) into a ring of the last `CAKE_FLIGHT_RECORDER`
iterations. The supervisor dumps the ring to `CAKE_TRACE_DIR` as JSON
when the wedge watchdog flags a stuck dispatch or the rebuild budget
puts the engine DOWN — the post-mortem for the wedge failure mode where
the process usually gets killed with the evidence in memory. The same
ring is readable ON DEMAND at `GET /api/v1/flight` (a lock-protected
read-only snapshot) — `cake top` and the profiling workflow inspect a
live engine without waiting for a failure.

## Fleet telemetry plane

The router rolls per-replica signals up into decision-grade series once
per probe cycle: fleet-merged SLO percentiles (bucket-wise histogram
sums), multi-window burn rates (`cake_fleet_slo_burn_rate{window}`),
capacity headroom (`cake_fleet_headroom_tokens_per_s`), and per-replica
anomaly flags (`cake_fleet_replica_outlier`, with
`cake_fleet_replica_stale` marking probe-dead replicas whose mirrored
gauges were retracted). Served at `GET /api/v1/fleet/telemetry` and
rendered live by `cake top`. [telemetry.md](telemetry.md) is the
operator guide (series model, burn-rate formula, headroom model,
outlier rule). With `CAKE_SCALE=1` the rollup also FEEDS the
closed-loop autoscaler: scale actions are counted in
`cake_fleet_scale_actions_total{direction,reason}` with spawn/drain
progress in `cake_fleet_scale_pending_spawns` /
`cake_fleet_scale_managed_replicas`, and the typed decision ring is
served at `GET /api/v1/fleet/autoscale`
([autoscaling.md](autoscaling.md) is the operator guide).

## SLO accounting

The batched engine path decomposes request latency into
`cake_serve_ttft_seconds` / `cake_serve_itl_seconds` /
`cake_serve_e2e_seconds`, labeled by outcome (`ok` / `cancelled` /
`error`) and observed per terminal request. Every observation carries
the request id as a per-bucket sampled exemplar (JSON via
`/api/v1/slo` — the 0.0.4 text format has no exemplar syntax), so a bad
percentile links to the concrete timeline that explains it. The
sequential loops keep feeding `cake_ttft_seconds` /
`cake_decode_token_seconds` as before.

## Wire timing echo

Workers echo `tm = {read_ms, deser_ms, fwd_ms, ser_ms}` in every
`tensor_result`; the master subtracts the echoed phases from its
observed RTT and the remainder is `wire` (TCP + response write +
scheduling). `RemoteStage.rtt_stats()` reports p50/p95/mean/min per
phase, and each successful hop also lands a `cluster_hop` timeline
event against the request in flight.

## Guardrails

`make obs-smoke` runs `make lint` (the static-analysis pass — its
`metric-registry` rule checks every constructed instrument name against
this file, `hot-timing` keeps ad-hoc wall clocks off hot paths), the
`make trace-smoke` cross-tier drive (one request through a real
router + replica must yield a stitched two-tier timeline and non-zero
SLO histograms), and `scripts/obs_smoke.py` (a traced CPU generation
asserting `/metrics` histograms and the Chrome-trace export are live).
The `CAKE_TRACE_*` / `CAKE_FLIGHT_RECORDER` knobs are registered in
`cake_tpu/knobs.py` and listed in the generated [knobs.md](knobs.md).
"""


def generate_doc() -> str:
    """The docs/observability.md body, fully generated."""
    # the canonical instrument declarations live in obs/__init__.py;
    # importing the package populates REGISTRY before we render it
    from . import REGISTRY
    from .spans import SPAN_CATALOG
    from .timeline import EVENT_KINDS

    out = [_HEADER]
    out += ["## Metric catalog", "",
            "Every instrument in the process-global registry, declared "
            "once in", "`cake_tpu/obs/__init__.py`:", "",
            "| metric | type | labels | meaning |", "|---|---|---|---|"]
    for m in sorted(REGISTRY._metrics.values(), key=lambda m: m.name):
        labels = ", ".join(m.labelnames) if m.labelnames else "—"
        out.append(f"| `{m.name}` | {m.typ} | {labels} | {m.help} |")
    out += ["", "## Span catalog", "",
            "Names recorded into the span recorder (RECORDER), by the "
            "layer that records them:", "",
            "| span | recorded by |", "|---|---|"]
    for name, where in SPAN_CATALOG:
        out.append(f"| `{name}` | {where} |")
    out += ["", "## Timeline event catalog", "",
            "Typed per-request lifecycle events "
            "(`/api/v1/requests/<id>`); the store rejects kinds missing "
            "from this table:", "",
            "| event | meaning |", "|---|---|"]
    for kind, doc in EVENT_KINDS.items():
        out.append(f"| `{kind}` | {doc} |")
    out.append("")
    return "\n".join(out)
