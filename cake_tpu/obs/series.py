"""In-process time-series rings: the telemetry plane's storage primitive.

The fleet telemetry rollup (fleet/telemetry.py) needs signals *over
time* — burn rates are windowed deltas of cumulative counters, headroom
is a windowed token rate — but this project deliberately has no external
TSDB. A `Series` is the smallest thing that works instead: a fixed
window of (t, value) samples in a deque, appended once per router probe
cycle, pruned by age on every append, and bounded by a hard sample cap
so a misconfigured window can never grow memory without limit.

Two read idioms cover every consumer:

- gauges (queue depth, occupancy, headroom): `latest()` / `values()`;
- cumulative counters (request totals, token totals, SLO-violation
  counts): `increase(window_s)` — the sum of positive deltas across the
  window, which is Prometheus `increase()` semantics and therefore
  survives a replica restart resetting its counters to zero mid-window
  (the drop is ignored; counting resumes from the new baseline).

The clock is injectable (defaults to obs.now, the monotonic perf
counter) so the burn-rate / rollup math is unit-testable with a fake
clock — no sleeps in tier-1.
"""
from __future__ import annotations

import threading
from collections import deque

from .timing import now

__all__ = ["Series", "SeriesBank"]


class Series:
    """One signal's fixed-window ring of (t, value) samples.

    Thread-safe: the router's probe loop appends and HTTP handlers read,
    and nothing here assumes they share an event loop.
    """

    def __init__(self, name: str, window_s: float, max_samples: int = 4096,
                 clock=now):
        self.name = name
        self.window_s = float(window_s)
        self._clock = clock
        self._lock = threading.Lock()
        # ring of (t, value); guarded-by: self._lock
        self._ring: deque = deque(maxlen=max(int(max_samples), 2))

    def record(self, value: float, t: float | None = None) -> None:
        """Append one sample and prune everything older than the window."""
        t = self._clock() if t is None else float(t)
        with self._lock:
            self._ring.append((t, float(value)))
            cutoff = t - self.window_s
            while len(self._ring) > 1 and self._ring[0][0] < cutoff:
                self._ring.popleft()

    def samples(self) -> list[tuple[float, float]]:
        with self._lock:
            return list(self._ring)

    def values(self, window_s: float | None = None) -> list[float]:
        """Sample values inside the trailing window (newest-clock-relative)."""
        with self._lock:
            if not self._ring:
                return []
            cutoff = self._ring[-1][0] - (self.window_s if window_s is None
                                          else float(window_s))
            return [v for t, v in self._ring if t >= cutoff]

    def latest(self) -> float | None:
        with self._lock:
            return self._ring[-1][1] if self._ring else None

    def latest_t(self) -> float | None:
        with self._lock:
            return self._ring[-1][0] if self._ring else None

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def increase(self, window_s: float | None = None) -> float:
        """Prometheus-style increase of a cumulative counter over the
        trailing window: the sum of positive sample-to-sample deltas,
        starting from the last sample at or before the window boundary
        (so the full span counts). Negative deltas — a replica restart
        resetting its counter — contribute nothing instead of poisoning
        the sum."""
        win = self.window_s if window_s is None else float(window_s)
        with self._lock:
            ring = list(self._ring)
        if len(ring) < 2:
            return 0.0
        cutoff = ring[-1][0] - win
        # baseline: last sample at/before the cutoff, else the oldest
        base_i = 0
        for i, (t, _) in enumerate(ring):
            if t <= cutoff:
                base_i = i
            else:
                break
        total = 0.0
        prev = ring[base_i][1]
        for _, v in ring[base_i + 1:]:
            if v > prev:
                total += v - prev
            prev = v
        return total

    def rate(self, window_s: float | None = None) -> float:
        """increase() divided by the actual covered span (0.0 until two
        samples exist)."""
        win = self.window_s if window_s is None else float(window_s)
        with self._lock:
            ring = list(self._ring)
        if len(ring) < 2:
            return 0.0
        span = min(win, ring[-1][0] - ring[0][0])
        if span <= 0:
            return 0.0
        return self.increase(win) / span


class SeriesBank:
    """Lazily-created named Series sharing one window/cap/clock — the
    telemetry plane keys these by signal name (and per-replica signals
    by "signal/replica")."""

    def __init__(self, window_s: float, max_samples: int = 4096, clock=now):
        self.window_s = float(window_s)
        self.max_samples = int(max_samples)
        self._clock = clock
        self._lock = threading.Lock()
        # name -> Series; guarded-by: self._lock
        self._series: dict[str, Series] = {}

    def series(self, name: str) -> Series:
        with self._lock:
            s = self._series.get(name)
            if s is None:
                s = self._series[name] = Series(
                    name, self.window_s, self.max_samples, self._clock)
            return s

    def record(self, name: str, value: float, t: float | None = None) -> None:
        self.series(name).record(value, t)

    def get(self, name: str) -> Series | None:
        with self._lock:
            return self._series.get(name)

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._series)

    def drop(self, prefix: str) -> None:
        """Forget every series whose name starts with `prefix` — used
        when a replica is removed so its per-replica signals don't
        linger in the bank forever."""
        with self._lock:
            for k in [k for k in self._series if k.startswith(prefix)]:
                del self._series[k]
