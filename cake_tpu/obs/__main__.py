"""`python -m cake_tpu.obs` prints the generated observability catalog
(docs/observability.md) — see catalog.py and `make metrics-doc`."""
from .catalog import generate_doc

if __name__ == "__main__":
    print(generate_doc())
