"""Per-request timelines: a bounded ring of typed lifecycle events.

The metrics registry answers "how is the fleet doing"; the span recorder
answers "where did wall time go inside this process". Neither answers the
operator question this module exists for: *this one request was slow —
which tier ate the time?* A request crosses four tiers (fleet router →
replica API → serve engine → cluster stages), and every hop already
shares one request id (the router injects `X-Cake-Request-Id`, the
replica adopts it into the request-id contextvar, the engine keys its
scheduler bookkeeping by it). This store records that id's lifecycle as
typed events — enqueue, admit, each prefill chunk, each decode/spec
iteration the slot participated in, preemption/swap/resume,
rebuild-replay, router retry/failover/hedge — against monotonic
timestamps, bounded two ways:

  * the store keeps the last `CAKE_TRACE_REQUESTS` request timelines
    (ring: oldest evicted first);
  * each timeline keeps at most `MAX_EVENTS` events (newest dropped,
    counted in `dropped`; terminal events always land so a truncated
    timeline still says how the request ended).

`GET /api/v1/requests/<id>` serves a timeline as JSON; the fleet router's
version of the route stitches its own tier's events onto the replica's.
`to_chrome(rid)` exports one timeline as Chrome-trace instant events on
the SAME perf_counter microsecond clock the span recorder uses, so a
timeline merges with a `RECORDER.export()` in Perfetto.

Recording is always on (one dict lookup + list append per event — the
scheduler iteration doing it also runs a device dispatch), unlike the
span recorder, which buffers far more events and stays opt-in.
"""
from __future__ import annotations

import threading
import time
from collections import OrderedDict

from .. import knobs
from .spans import current_request_id

__all__ = ["EVENT_KINDS", "TIMELINES", "TimelineStore", "TRACE_HEADER",
           "MAX_EVENTS"]

# the one header every tier propagates; the router injects it, the
# replica API adopts it, responses echo it back to the client
TRACE_HEADER = "X-Cake-Request-Id"

# per-timeline event cap: newest events drop past this (counted), except
# terminal kinds, which always land
MAX_EVENTS = 512

# typed event vocabulary — event() rejects unknown kinds, and the
# observability catalog (docs/observability.md) is generated from this
# table, so an event kind cannot ship undocumented. Grouped by the tier
# that records it.
EVENT_KINDS: dict[str, str] = {
    # replica API tier
    "received": "request reached the replica API handler (chat, image, "
                "or audio)",
    "kv_fetch": "fleet-shared KV tier: this replica tried to fetch a "
                "matching prefix blob from a warm peer before "
                "recomputing the prefill (`outcome` = hit | miss | "
                "timeout | error | mismatch, `tokens` installed on a "
                "hit, `peer`)",
    "kv_migrate": "fleet-shared KV tier: a live stream's swap blob "
                  "moved through the router's resume plane (`outcome` "
                  "= shipped | source_miss | ship_error, `from`, `to`)",
    # admission plane + serve engine tier
    "enqueue": "request/job entered the admission queue (`depth` behind "
               "it, `qos` class, `tenant`/`workload` when set)",
    "admit": "slot assigned (chunked prefill opens: `slot`, "
             "`queue_wait_ms`) or heavy job started (`workload`); "
             "carries `qos`",
    "prefix_hit": "prefix-cache splice skipped `tokens` prompt tokens",
    "prefill_chunk": "one chunk scattered into the pool row (`pos0`, "
                     "`tokens`)",
    "prefill_done": "prompt fully prefilled; first token sampled "
                    "(`chunks`, `hit_tokens`)",
    "first_token": "first token fetched to the host (client-visible "
                   "TTFT stamps here)",
    "decode": "one batched decode iteration this slot participated in "
              "(`bucket` = dispatch slot-count bucket)",
    "spec_verify": "one batched speculative verify this slot "
                   "participated in (`bucket`, `proposed`, `accepted`)",
    "preempt": "slot evicted under KV-pool pressure (`mode` = "
               "swap | recompute | requeue, `tokens`)",
    "resume": "preempted request re-entered a slot (`mode`, `slot`)",
    "replay": "prompt+generated replayed through chunked prefill "
              "(crash rebuild or recompute resume; `tokens`)",
    "step_failure": "a scheduler step implicating this request failed "
                    "(`failure` = classified kind, `phase`)",
    "finish": "terminal: generation completed (`outcome`, `tokens`, "
              "`ttft_ms`, `e2e_ms`)",
    "error": "terminal: request failed or was cancelled (`type`)",
    # cluster tier (distributed master, per remote hop)
    "cluster_hop": "one remote-stage forward attributed to this request "
                   "(`worker`, `ms`)",
    # fleet router tier
    "route": "router accepted the request and ordered candidates "
             "(`candidates`, `stream`)",
    "attempt": "one outbound try against a replica (`replica`, "
               "`outcome`, `status`)",
    "retry": "failover: the next candidate gets the request",
    "hedge": "tail hedge fired a duplicate at the next-best replica",
    "shed": "router refused before any replica admitted (`reason`)",
    "commit": "first streamed byte relayed; the request is committed "
              "to `replica`",
    "stream_broken": "stream severed after commit (`replica`, `chunks` "
                     "relayed so far); the resume budget decides what "
                     "happens next",
    "stream_resume": "router began a transparent splice-resume of the "
                     "broken stream (`replica` that broke, `attempt`, "
                     "`sampled` when the rng-fold parity exception "
                     "applies)",
    "resume_spliced": "resumed replica's continuation reached the "
                      "client: first spliced chunk relayed on the same "
                      "socket (`replica`, `overlap_chars` stripped)",
    "done": "terminal: router relayed the final response (`status`)",
    "replica_partition_suspected": "membership ejected a replica on "
                                   "data-path/transport evidence while "
                                   "its probe path may still answer "
                                   "(`replica`, `reason`, `hold_s`); "
                                   "readmit now requires a data-path "
                                   "trial",
    "partition_healed": "a suspected-partition episode ended: the "
                        "replica passed a data-path trial and rejoined "
                        "routing (`replica`, `episode_s`)",
}

# terminal kinds bypass the per-timeline cap: a truncated timeline must
# still say how the request ended
_TERMINAL = frozenset({"finish", "error", "done"})


class _Timeline:
    __slots__ = ("rid", "tier", "start_unix", "t0_us", "events", "dropped")

    def __init__(self, rid: str, tier: str):
        self.rid = rid
        self.tier = tier
        self.start_unix = time.time()
        self.t0_us = time.perf_counter_ns() // 1000
        self.events: list[dict] = []
        self.dropped = 0


class TimelineStore:
    """Thread-safe bounded store. begin() opens a timeline (idempotent),
    event() appends to a known id (unknown ids are a cheap no-op — the
    cluster hop recorder fires for every request, but only requests a
    tier opened a timeline for keep events), alias() lets a second id
    (the OpenAI completion id) resolve to the same timeline."""

    def __init__(self, capacity: int | None = None,
                 max_events: int = MAX_EVENTS):
        if capacity is None:
            capacity = knobs.get("CAKE_TRACE_REQUESTS")
        self.capacity = max(int(capacity), 1)
        self.max_events = max_events
        self._lock = threading.Lock()
        self._by_id: OrderedDict[str, _Timeline] = OrderedDict()
        self._aliases: dict[str, str] = {}

    # -- recording -----------------------------------------------------------

    def begin(self, rid: str, tier: str = "replica") -> None:
        with self._lock:
            if rid in self._by_id or rid in self._aliases:
                return
            self._by_id[rid] = _Timeline(rid, tier)
            while len(self._by_id) > self.capacity:
                old, _ = self._by_id.popitem(last=False)
                self._aliases = {a: r for a, r in self._aliases.items()
                                 if r != old}

    def alias(self, alias_id: str, rid: str) -> None:
        """Make alias_id resolve to rid's timeline (completion id →
        trace id). No-op when rid is unknown or the ids are equal."""
        if alias_id == rid:
            return
        with self._lock:
            if rid in self._by_id:
                self._aliases[alias_id] = rid

    def event(self, rid: str | None, kind: str, **attrs) -> None:
        """Append one typed event. rid=None reads the request-id
        contextvar (the cluster-hop recorder's path). Unknown ids are
        dropped silently: recording is always on, so a tier that never
        opened a timeline (bench scripts, tests driving the model
        directly) costs one dict lookup and nothing else."""
        if kind not in EVENT_KINDS:
            raise ValueError(f"unknown timeline event kind {kind!r} — "
                             "add it to obs.timeline.EVENT_KINDS (and "
                             "regenerate the catalog)")
        if rid is None:
            rid = current_request_id()
            if rid is None:
                return
        t_us = time.perf_counter_ns() // 1000
        with self._lock:
            tl = self._by_id.get(rid)
            if tl is None:
                canon = self._aliases.get(rid)
                tl = self._by_id.get(canon) if canon else None
            if tl is None:
                return
            if len(tl.events) >= self.max_events and kind not in _TERMINAL:
                tl.dropped += 1
                return
            ev = {"t_ms": round((t_us - tl.t0_us) / 1e3, 3), "kind": kind}
            if attrs:
                ev.update(attrs)
            tl.events.append(ev)

    # -- views ---------------------------------------------------------------

    def get(self, rid: str) -> dict | None:
        """JSON-shaped snapshot of one timeline (by id or alias).
        `t_ms` is milliseconds since the timeline opened; `start_unix`
        anchors the monotonic offsets to wall clock so tiers recorded in
        different processes can be laid on one axis."""
        with self._lock:
            tl = self._by_id.get(rid) or self._by_id.get(
                self._aliases.get(rid, ""))
            if tl is None:
                return None
            return {
                "request_id": tl.rid,
                "tier": tl.tier,
                "start_unix": round(tl.start_unix, 6),
                "events": [dict(e) for e in tl.events],
                "dropped": tl.dropped,
            }

    def ids(self) -> list[str]:
        """Known request ids, oldest first."""
        with self._lock:
            return list(self._by_id.keys())

    def to_chrome(self, rid: str) -> dict | None:
        """One timeline as Chrome-trace instant events on the span
        recorder's perf_counter-microsecond clock, so the export merges
        with RECORDER.export() in Perfetto."""
        with self._lock:
            tl = self._by_id.get(rid) or self._by_id.get(
                self._aliases.get(rid, ""))
            if tl is None:
                return None
            events = []
            for e in tl.events:
                args = {k: v for k, v in e.items() if k != "kind"}
                args["request_id"] = tl.rid
                args["tier"] = tl.tier
                events.append(
                    {"name": e["kind"], "cat": "request", "ph": "i",
                     "s": "t", "ts": int(tl.t0_us + e["t_ms"] * 1e3),
                     "pid": 0, "tid": 0, "args": args})
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def clear(self) -> None:
        with self._lock:
            self._by_id.clear()
            self._aliases.clear()


# process-global store: the API handlers, the serve engine, the fleet
# router, and the cluster master all record into this one ring
TIMELINES = TimelineStore()
