"""Metrics registry: counters / gauges / histograms with labels and
Prometheus text exposition (format 0.0.4).

The reference leans on scattered rolling stats (ref: worker.rs:566-578) and
per-token debug prints (ref: text_model.rs:357-365); this registry is the
single pull-based surface replacing those idioms here — instruments are
process-global, cheap to update from hot loops (one dict lookup + float add
under a lock), and rendered on demand by the API's /metrics endpoint.
"""
from __future__ import annotations

import threading

# latency buckets in seconds: sub-ms kernel dispatch through multi-minute
# cluster setup — shared by the TTFT / per-token / hop histograms
LATENCY_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0)


def _fmt(v: float) -> str:
    """Prometheus sample value formatting: integers without the '.0'."""
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _fmt_le(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    return _fmt(v)


def _escape(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n").replace('"', '\\"')


class _Metric:
    typ = "untyped"

    def __init__(self, name: str, help: str, labelnames: tuple[str, ...],
                 lock: threading.RLock):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = lock
        self._values: dict[tuple, object] = {}

    def _key(self, labels: dict) -> tuple:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: labels {sorted(labels)} != declared "
                f"{sorted(self.labelnames)}")
        return tuple(str(labels[k]) for k in self.labelnames)

    def _label_str(self, key: tuple, extra: str = "") -> str:
        parts = [f'{n}="{_escape(v)}"' for n, v in zip(self.labelnames, key)]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""

    def clear(self):
        with self._lock:
            self._values.clear()

    def remove(self, **labels) -> bool:
        """Delete one labelset's samples (Prometheus client `remove()`
        semantics). The fleet tier uses this to retract a dead replica's
        mirrored gauges so scrapes see the series disappear instead of a
        frozen last value. Returns whether the labelset existed."""
        key = self._key(labels)
        with self._lock:
            return self._values.pop(key, None) is not None

    def labelsets(self) -> list[dict]:
        """The label combinations observed so far (empty dict for an
        unlabeled metric with samples) — lets JSON surfaces enumerate a
        metric's series without reaching into _values."""
        with self._lock:
            return [dict(zip(self.labelnames, k)) for k in self._values]

    def samples(self) -> list[str]:
        raise NotImplementedError


class Counter(_Metric):
    typ = "counter"

    def inc(self, amount: float = 1.0, **labels):
        if amount < 0:
            raise ValueError(f"{self.name}: counters only go up")
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        return float(self._values.get(self._key(labels), 0.0))

    def samples(self) -> list[str]:
        with self._lock:
            items = sorted(self._values.items())
        return [f"{self.name}{self._label_str(k)} {_fmt(v)}"
                for k, v in items]


class Gauge(_Metric):
    typ = "gauge"

    def set(self, value: float, **labels):
        key = self._key(labels)
        with self._lock:
            self._values[key] = float(value)

    def inc(self, amount: float = 1.0, **labels):
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels):
        self.inc(-amount, **labels)

    def value(self, **labels) -> float:
        return float(self._values.get(self._key(labels), 0.0))

    def samples(self) -> list[str]:
        with self._lock:
            items = sorted(self._values.items())
        return [f"{self.name}{self._label_str(k)} {_fmt(v)}"
                for k, v in items]


class Histogram(_Metric):
    typ = "histogram"

    def __init__(self, name, help, labelnames, lock,
                 buckets=LATENCY_BUCKETS):
        super().__init__(name, help, labelnames, lock)
        bs = sorted(float(b) for b in buckets)
        if not bs:
            raise ValueError(f"{name}: histogram needs at least one bucket")
        self.buckets = tuple(bs)
        # per-labelset, per-bucket sampled exemplar: the LAST observation
        # that landed in each bucket, as (exemplar_id, value) — a bad
        # percentile in a scrape links to one concrete request id whose
        # timeline (/api/v1/requests/<id>) explains it. Bounded by
        # labelsets x buckets; exposed via exemplars(), not the
        # Prometheus text format (0.0.4 has no exemplar syntax)
        self._exemplars: dict[tuple, dict[int, tuple[str, float]]] = {}

    def observe(self, value: float, exemplar: str | None = None, **labels):
        key = self._key(labels)
        v = float(value)
        with self._lock:
            slot = self._values.get(key)
            if slot is None:
                # per-bucket counts (non-cumulative) + sum + count
                slot = self._values[key] = [[0] * (len(self.buckets) + 1),
                                            0.0, 0]
            counts, _, _ = slot
            idx = len(self.buckets)
            for i, b in enumerate(self.buckets):
                if v <= b:
                    idx = i
                    break
            counts[idx] += 1
            slot[1] += v
            slot[2] += 1
            if exemplar is not None:
                self._exemplars.setdefault(key, {})[idx] = (str(exemplar), v)

    def exemplars(self, **labels) -> dict:
        """{bucket_le: {"exemplar": id, "value": v}} for one labelset —
        each bucket's most recent exemplar-carrying observation."""
        key = self._key(labels)
        edges = [*self.buckets, float("inf")]
        with self._lock:
            ex = dict(self._exemplars.get(key, {}))
        return {_fmt_le(edges[i]): {"exemplar": rid, "value": v}
                for i, (rid, v) in sorted(ex.items())}

    def clear(self):
        with self._lock:
            self._values.clear()
            self._exemplars.clear()

    def remove(self, **labels) -> bool:
        key = self._key(labels)
        with self._lock:
            self._exemplars.pop(key, None)
            return self._values.pop(key, None) is not None

    def count(self, **labels) -> int:
        slot = self._values.get(self._key(labels))
        return 0 if slot is None else int(slot[2])

    def sum(self, **labels) -> float:
        slot = self._values.get(self._key(labels))
        return 0.0 if slot is None else float(slot[1])

    def samples(self) -> list[str]:
        with self._lock:
            items = sorted((k, ([*c], s, n))
                           for k, (c, s, n) in self._values.items())
        out = []
        edges = [*self.buckets, float("inf")]
        for key, (counts, total, n) in items:
            cum = 0
            for edge, c in zip(edges, counts):
                cum += c
                le = f'le="{_fmt_le(edge)}"'
                out.append(f"{self.name}_bucket"
                           f"{self._label_str(key, le)} {cum}")
            out.append(f"{self.name}_sum{self._label_str(key)} {_fmt(total)}")
            out.append(f"{self.name}_count{self._label_str(key)} {n}")
        return out


class MetricsRegistry:
    """Named-instrument registry. Registration is idempotent: asking again
    for the same (name, type, labels) returns the existing instrument, so
    modules can declare their instruments at import time in any order."""

    def __init__(self):
        self._lock = threading.RLock()
        self._metrics: dict[str, _Metric] = {}

    def _get(self, cls, name, help, labelnames, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if type(m) is not cls or m.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name!r} re-registered as {cls.typ} with "
                        f"labels {tuple(labelnames)}, was {m.typ} "
                        f"with {m.labelnames}")
                return m
            m = cls(name, help, tuple(labelnames), self._lock, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "",
                labelnames: tuple[str, ...] = ()) -> Counter:
        return self._get(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: tuple[str, ...] = ()) -> Gauge:
        return self._get(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: tuple[str, ...] = (),
                  buckets=LATENCY_BUCKETS) -> Histogram:
        return self._get(Histogram, name, help, labelnames, buckets=buckets)

    def render(self) -> str:
        """Prometheus text exposition (0.0.4) of every instrument."""
        with self._lock:
            metrics = list(self._metrics.values())
        lines = []
        for m in metrics:
            if m.help:
                lines.append(f"# HELP {m.name} {_escape(m.help)}")
            lines.append(f"# TYPE {m.name} {m.typ}")
            lines.extend(m.samples())
        return "\n".join(lines) + "\n"

    def reset(self):
        """Zero every instrument's samples (registrations survive, so
        module-level instrument handles stay valid) — test isolation."""
        with self._lock:
            for m in self._metrics.values():
                m.clear()


# process-global default registry: hot paths update module-level instruments
# bound to it; the API /metrics endpoint renders it
REGISTRY = MetricsRegistry()
