"""Sanctioned wall-clock helpers for hot paths.

Every monotonic delta taken on a serving hot path goes through this module
(enforced by scripts/check_hot_timing.py): stats code calls now(), phase
accounting goes through PhaseTimer — which also feeds the span recorder, so
one `with timer("fwd"):` yields the rolling average AND a trace event.
"""
from __future__ import annotations

import contextlib
import time

from .spans import RECORDER


def now() -> float:
    """Monotonic seconds (perf_counter): the one clock hot-path deltas use."""
    return time.perf_counter()


class PhaseTimer:
    """Accumulating phase timer for hot loops (ref: worker.rs:533-543
    per-message read/load/fwd/ser/write breakdown).

        t = PhaseTimer()
        with t("embed"): ...
        with t("layers"): ...
        log.debug("%s", t)          # embed=0.2ms layers=8.1ms

    Each timed phase is also recorded as a span in the global RECORDER
    (when enabled), so the same instrumentation produces both the rolling
    log line and the Chrome-trace event.
    """

    def __init__(self, recorder=None):
        self.totals: dict[str, float] = {}
        self.counts: dict[str, int] = {}
        self._rec = RECORDER if recorder is None else recorder

    @contextlib.contextmanager
    def __call__(self, name: str, **span_args):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self.add(name, dt, _span=False)
            if self._rec.enabled:
                t0_us = int(t0 * 1e6)
                self._rec.add(name, t0_us, int(dt * 1e6), **span_args)

    def add(self, name: str, dt: float, t0: float | None = None,
            _span: bool = True):
        """Accumulate an externally measured duration (seconds) — e.g. a
        read timed inside the protocol layer. t0: the phase's real start
        on the perf_counter clock; without it the span is back-dated from
        now, which lays phases logged together on top of each other in the
        exported trace — pass t0 whenever it is known."""
        self.totals[name] = self.totals.get(name, 0.0) + dt
        self.counts[name] = self.counts.get(name, 0) + 1
        if _span and self._rec.enabled:
            if t0 is None:
                t0 = time.perf_counter() - dt
            self._rec.add(name, int(t0 * 1e6), int(dt * 1e6))

    def reset(self):
        self.totals.clear()
        self.counts.clear()

    def __str__(self):
        return " ".join(f"{k}={v * 1000:.1f}ms" for k, v in self.totals.items())

    def report(self) -> dict[str, dict]:
        return {k: {"total_ms": round(v * 1000, 3),
                    "count": self.counts[k],
                    "avg_ms": round(v * 1000 / max(self.counts[k], 1), 3)}
                for k, v in self.totals.items()}
