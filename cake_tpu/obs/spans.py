"""Span recorder: request-scoped phase spans with Chrome-trace export.

The reference exports Chrome traces via tracing-chrome (ref: --sd-tracing,
sd.rs:358-384) and logs per-token phase breakdowns (ref:
text_model.rs:357-365); here both collapse into one recorder: hot paths
record bounded complete ("X") events tagged with the current request id,
and the buffer exports as Perfetto-loadable Chrome-trace JSON
({"traceEvents": [...]}) on demand or into $CAKE_TRACE_DIR.

The recorder is off by default — a disabled span() is one attribute check —
and turns on explicitly (RECORDER.enable()) or via the CAKE_TRACE_DIR env
var. Timestamps are monotonic microseconds (perf_counter_ns), so exported
events always satisfy the Perfetto monotonic-ts requirement.
"""
from __future__ import annotations

import contextlib
import contextvars
import json
import os
import threading
import time
import uuid
from collections import deque

from .. import knobs

# -- request-id propagation --------------------------------------------------

_request_id: contextvars.ContextVar[str | None] = contextvars.ContextVar(
    "cake_request_id", default=None)


def new_request_id() -> str:
    return uuid.uuid4().hex[:16]


def set_request_id(rid: str | None):
    _request_id.set(rid)


def current_request_id() -> str | None:
    return _request_id.get()


@contextlib.contextmanager
def request_scope(rid: str | None = None):
    """Bind a request id for the duration of the block (generates one when
    not given); spans recorded inside carry it in their args."""
    rid = rid or new_request_id()
    token = _request_id.set(rid)
    try:
        yield rid
    finally:
        _request_id.reset(token)


def _now_us() -> int:
    return time.perf_counter_ns() // 1000


class SpanRecorder:
    """Bounded ring buffer of Chrome-trace complete events."""

    def __init__(self, max_events: int | None = None, enabled: bool | None = None):
        if max_events is None:
            max_events = knobs.get("CAKE_TRACE_EVENTS")
        self._events: deque = deque(maxlen=max_events)
        self._lock = threading.Lock()
        self._export_seq = 0
        if enabled is None:
            enabled = bool(knobs.get_str("CAKE_TRACE_DIR"))
        self.enabled = enabled

    def enable(self):
        self.enabled = True

    def disable(self):
        self.enabled = False

    def clear(self):
        with self._lock:
            self._events.clear()

    def __len__(self) -> int:
        return len(self._events)

    # -- recording -----------------------------------------------------------

    def add(self, name: str, ts_us: int, dur_us: int, cat: str = "phase",
            **args):
        """Record a complete event from externally measured timestamps
        (microseconds on the perf_counter clock)."""
        if not self.enabled:
            return
        rid = _request_id.get()
        if rid is not None:
            args.setdefault("request_id", rid)
        ev = {"name": name, "cat": cat, "ph": "X", "ts": int(ts_us),
              "dur": max(int(dur_us), 0), "pid": os.getpid(),
              "tid": threading.get_ident()}
        if args:
            ev["args"] = args
        with self._lock:
            self._events.append(ev)

    @contextlib.contextmanager
    def span(self, name: str, cat: str = "phase", **args):
        """Record the wrapped block as one complete event. Disabled-path
        cost is a single attribute check."""
        if not self.enabled:
            yield
            return
        t0 = _now_us()
        try:
            yield
        finally:
            self.add(name, t0, _now_us() - t0, cat=cat, **args)

    def instant(self, name: str, cat: str = "mark", **args):
        if not self.enabled:
            return
        rid = _request_id.get()
        if rid is not None:
            args.setdefault("request_id", rid)
        ev = {"name": name, "cat": cat, "ph": "i", "ts": _now_us(), "s": "t",
              "pid": os.getpid(), "tid": threading.get_ident()}
        if args:
            ev["args"] = args
        with self._lock:
            self._events.append(ev)

    # -- export --------------------------------------------------------------

    def events(self) -> list[dict]:
        with self._lock:
            return [dict(e) for e in self._events]

    def to_chrome_trace(self) -> dict:
        return {"traceEvents": self.events(), "displayTimeUnit": "ms"}

    def export(self, path: str | None = None) -> str:
        """Write the buffer as Chrome-trace JSON (open in Perfetto /
        chrome://tracing). Default path: $CAKE_TRACE_DIR/cake-trace-<pid>-<n>.json."""
        if path is None:
            trace_dir = knobs.get_str("CAKE_TRACE_DIR") or "."
            os.makedirs(trace_dir, exist_ok=True)
            with self._lock:
                self._export_seq += 1
                seq = self._export_seq
            path = os.path.join(trace_dir,
                                f"cake-trace-{os.getpid()}-{seq}.json")
        with open(path, "w") as f:
            json.dump(self.to_chrome_trace(), f)
        return path


# process-global recorder: every layer (model decode, cluster hops, API,
# bench probe) records into this one buffer so a single export shows the
# whole request path
RECORDER = SpanRecorder()

# span vocabulary: every name recorded into RECORDER, with the layer that
# records it — the observability catalog (docs/observability.md) is
# generated from this table, so a span cannot ship undocumented (the
# metric-registry lint's span analog is this table plus the pinned doc)
SPAN_CATALOG: tuple[tuple[str, str], ...] = (
    ("prefill", "TextModel / offload / distributed generate: prompt "
                "prefill (one device call)"),
    ("decode_segment", "local TextModel: one fused decode segment"),
    ("decode_dispatch", "local TextModel: decode program dispatch"),
    ("decode_wait", "local TextModel: host wait on the fetched token"),
    ("decode_token", "distributed/offload per-token decode loop "
                     "(contains embed/layers/lm_head/sample)"),
    ("embed", "per-token embedding phase (distributed/offload loops)"),
    ("layers", "per-token transformer layers; remote hops carry "
               "worker/start/end args"),
    ("lm_head", "per-token lm_head phase (distributed/offload loops)"),
    ("sample", "per-token sampling phase"),
    ("recover", "cluster master: quarantine->reconnect->replay cycle "
                "after a stage failure"),
    ("replay_prefill", "cluster master: rebuild-by-replay prefill "
                       "reconstructing lost worker KV"),
    ("serve.step", "serve engine: one scheduler iteration (args: "
                   "slots, queued)"),
    ("serve.prefill_chunk", "serve engine: one chunked-admission "
                            "prefill dispatch"),
    ("serve.replay", "serve engine: one slot's crash/preemption replay"),
    ("spec.verify", "speculative verify dispatch (generate path and "
                    "batched serve path)"),
    ("read", "worker wire phase: request frame read (PhaseTimer)"),
    ("deser", "worker wire phase: payload deserialization (PhaseTimer)"),
    ("fwd", "worker wire phase: stage forward compute (PhaseTimer)"),
    ("ser", "worker wire phase: result serialization (PhaseTimer)"),
)


@contextlib.contextmanager
def jax_trace(log_dir: str | None):
    """Wrap a region in a JAX profiler trace (xprof / Perfetto viewable).
    No-op when log_dir is None. Device-side complement to SpanRecorder's
    host-side spans (ref: tracing-chrome behind --sd-tracing)."""
    if not log_dir:
        yield
        return
    import logging

    import jax
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        logging.getLogger("cake_tpu.obs").info(
            "profiler trace written to %s", log_dir)
