"""Observability subsystem: metrics registry, span recorder, request-id
propagation, and the canonical serving instruments.

One import gives a hot path everything it may record into:

    from ..obs import RECORDER, TTFT_SECONDS, now
    t0 = now()
    with RECORDER.span("prefill", cat="gen"):
        ...
    TTFT_SECONDS.observe(now() - t0)

Instruments are process-global: the API server's /metrics endpoint renders
REGISTRY, and a trace export (RECORDER.export()) contains spans from every
layer — model decode phases, cluster hops, API handlers, bench probes.
"""
from __future__ import annotations

from .metrics import (Counter, Gauge, Histogram, LATENCY_BUCKETS,
                      MetricsRegistry, REGISTRY)
from .series import Series, SeriesBank
from .spans import (RECORDER, SPAN_CATALOG, SpanRecorder,
                    current_request_id, jax_trace, new_request_id,
                    request_scope, set_request_id)
from .timeline import (EVENT_KINDS, TIMELINES, TimelineStore, TRACE_HEADER)
from .timing import PhaseTimer, now

# -- canonical serving instruments -------------------------------------------
# Declared once here so every layer shares the same series; registration is
# idempotent, so re-import order never matters.

TTFT_SECONDS = REGISTRY.histogram(
    "cake_ttft_seconds",
    "Time to first token per generation (prefill + first sample + fetch)")

DECODE_TOKEN_SECONDS = REGISTRY.histogram(
    "cake_decode_token_seconds",
    "Mean per-token decode latency per generation")

GENERATED_TOKENS = REGISTRY.counter(
    "cake_generated_tokens_total",
    "Tokens emitted by completed generations",
    labelnames=("path",))           # local | cluster | offload

GENERATIONS = REGISTRY.counter(
    "cake_generations_total",
    "Completed generations by workload kind",
    labelnames=("kind", "status"))  # text | image | audio; ok | error

API_REQUESTS = REGISTRY.counter(
    "cake_api_requests_total",
    "HTTP requests served",
    labelnames=("endpoint", "status"))

API_REQUEST_SECONDS = REGISTRY.histogram(
    "cake_api_request_seconds",
    "HTTP request wall time",
    labelnames=("endpoint",))

WORKER_FWD_SECONDS = REGISTRY.histogram(
    "cake_worker_forward_seconds",
    "Worker-side forward compute time per request (includes any in-band "
    "XLA compile)")

HOP_SECONDS = REGISTRY.histogram(
    "cake_cluster_hop_seconds",
    "Master-observed remote-hop latency split by phase "
    "(rtt | read | deser | fwd | ser | wire)",
    labelnames=("worker", "phase"))

SERVE_QUEUE_DEPTH = REGISTRY.gauge(
    "cake_serve_queue_depth",
    "Requests waiting in the continuous-batching admission queue")

SERVE_SLOTS_BUSY = REGISTRY.gauge(
    "cake_serve_slots_busy",
    "KV-cache slots currently decoding in the serve engine")

SERVE_QUEUE_WAIT_SECONDS = REGISTRY.histogram(
    "cake_serve_queue_wait_seconds",
    "Admission-queue wait per request (enqueue to slot assignment)")

SERVE_BATCH_OCCUPANCY = REGISTRY.histogram(
    "cake_serve_batch_occupancy",
    "Occupied slots per batched decode iteration",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128))

SERVE_PREFILL_CHUNKS = REGISTRY.histogram(
    "cake_serve_prefill_chunks",
    "Prefill chunks per admission (chunked-admission scheduling; 1 = the "
    "whole prompt fit one chunk)",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128))

SERVE_PREFIX_HITS = REGISTRY.counter(
    "cake_serve_prefix_cache_hits_total",
    "Admissions that spliced at least one cached prefix block")

SERVE_PREFIX_MISSES = REGISTRY.counter(
    "cake_serve_prefix_cache_misses_total",
    "Admissions that found no reusable prefix block")

SERVE_PREFIX_EVICTIONS = REGISTRY.counter(
    "cake_serve_prefix_cache_evictions_total",
    "Prefix blocks evicted (LRU) to stay under CAKE_PREFIX_CACHE_MB")

SERVE_PREFIX_BYTES = REGISTRY.gauge(
    "cake_serve_prefix_cache_bytes",
    "Device bytes held by cached prefix blocks")

SPEC_PROPOSED = REGISTRY.counter(
    "cake_serve_spec_proposed_total",
    "Draft tokens proposed to speculative verify steps (local generate "
    "and serve-engine paths)")

SPEC_ACCEPTED = REGISTRY.counter(
    "cake_serve_spec_accepted_total",
    "Draft tokens accepted by speculative verify steps")

SPEC_ACCEPTED_LEN = REGISTRY.histogram(
    "cake_serve_spec_accepted_length",
    "Accepted draft tokens per speculative verify step (0 = every draft "
    "rejected; the step still emits its correction token)",
    buckets=(0, 1, 2, 3, 4, 6, 8, 12, 16))

SPEC_BUCKET_ACCEPTED = REGISTRY.histogram(
    "cake_serve_spec_bucket_accepted_length",
    "Accepted draft tokens per slot verify, labeled by the batched "
    "dispatch's slot-count bucket — the acceptance x occupancy tradeoff "
    "the serve bench reports",
    labelnames=("bucket",),
    buckets=(0, 1, 2, 3, 4, 6, 8, 12, 16))

# -- serve-engine SLO decomposition (batched path) ---------------------------
# The sequential loops already observe cake_ttft_seconds /
# cake_decode_token_seconds; these three cover the continuous-batching
# engine with an outcome label (ok | cancelled | error) so a latency
# regression is attributable to the population that suffered it, and each
# observation carries the request id as a sampled exemplar — a bad
# percentile links to a concrete /api/v1/requests/<id> timeline (the
# /api/v1/slo endpoint renders buckets + exemplars as JSON).

SERVE_TTFT_SECONDS = REGISTRY.histogram(
    "cake_serve_ttft_seconds",
    "Serve-engine time to first token (enqueue to the first token "
    "FETCHED on the host), by request outcome",
    labelnames=("outcome",))        # ok | cancelled | error

SERVE_ITL_SECONDS = REGISTRY.histogram(
    "cake_serve_itl_seconds",
    "Serve-engine mean inter-token latency per request (decode wall "
    "time / decoded tokens), by request outcome",
    labelnames=("outcome",))

SERVE_E2E_SECONDS = REGISTRY.histogram(
    "cake_serve_e2e_seconds",
    "Serve-engine end-to-end request latency (enqueue to terminal "
    "delivery, including queue wait and any preemption/replay), by "
    "request outcome",
    labelnames=("outcome",))

SERVE_QUEUE_TIMEOUTS = REGISTRY.counter(
    "cake_serve_queue_timeouts_total",
    "Requests expired in the admission queue past CAKE_QUEUE_DEADLINE_S "
    "(answered 503 instead of occupying a slot for a client that gave up)")

SERVE_STEP_FAILURES = REGISTRY.counter(
    "cake_serve_step_failures_total",
    "Classified serve-engine step failures handled by the supervisor",
    labelnames=("kind",))           # wedge | device | poison | oom |
                                    # internal

SERVE_ENGINE_REBUILDS = REGISTRY.counter(
    "cake_serve_engine_rebuilds_total",
    "Slot-pool rebuild-by-replay recoveries after a step failure")

SERVE_ENGINE_WEDGES = REGISTRY.counter(
    "cake_serve_engine_wedges_total",
    "Watchdog detections of a device dispatch stuck past "
    "CAKE_STEP_WATCHDOG_S (the engine reports wedged in /health)")

SERVE_ENGINE_DOWN = REGISTRY.gauge(
    "cake_serve_engine_down",
    "1 while the engine's rebuild budget is exhausted (submits answer "
    "503 + Retry-After; the restore loop is probing the device)")

SERVE_POISONED = REGISTRY.counter(
    "cake_serve_poisoned_requests_total",
    "Requests failed as poison (implicated in consecutive engine "
    "crashes) and fingerprint-quarantined")

SERVE_REQUEST_TIMEOUTS = REGISTRY.counter(
    "cake_serve_request_timeouts_total",
    "Admitted requests cancelled because their total age passed "
    "CAKE_REQUEST_DEADLINE_S (answered 504)")

SERVE_KV_BLOCKS_FREE = REGISTRY.gauge(
    "cake_serve_kv_blocks_free",
    "Unallocated physical blocks in the paged KV pool "
    "(CAKE_KV_BLOCKS > 0)")

SERVE_KV_BLOCKS_USED = REGISTRY.gauge(
    "cake_serve_kv_blocks_used",
    "Allocated physical blocks in the paged KV pool (live slots + "
    "prefix-cache pins)")

SERVE_KV_BLOCKS_SHARED = REGISTRY.gauge(
    "cake_serve_kv_blocks_shared",
    "Paged KV blocks with refcount >= 2 — prefix-cache hits share these "
    "by reference instead of copying")

SERVE_PREEMPTIONS = REGISTRY.counter(
    "cake_serve_preemptions_total",
    "Slots evicted because the paged KV pool was exhausted",
    labelnames=("mode",))           # swap | recompute

# -- unified admission plane (QoS classes / tenants / jobs) ------------------
# The class-aware queue publishes per-class depth SUMMED across every
# live queue (engine request queue + job queue), so one scrape sees the
# whole plane's backlog; the SLO pair decomposes latency by class —
# the qos-smoke gate ("interactive TTFT under batch saturation") reads
# these.

SERVE_QOS_QUEUE_DEPTH = REGISTRY.gauge(
    "cake_serve_qos_queue_depth",
    "Queued requests + jobs per QoS class, summed across the admission "
    "plane's queues (chat, image, audio)",
    labelnames=("qos",))            # interactive | standard | batch

SERVE_QOS_TTFT_SECONDS = REGISTRY.histogram(
    "cake_serve_qos_ttft_seconds",
    "Serve-engine time to first token by QoS class and outcome — the "
    "per-class SLO the weighted-fair dequeue exists to protect",
    labelnames=("qos", "outcome"))

SERVE_QOS_E2E_SECONDS = REGISTRY.histogram(
    "cake_serve_qos_e2e_seconds",
    "End-to-end latency by QoS class and outcome, observed for engine "
    "requests AND heavy generation jobs (image/TTS)",
    labelnames=("qos", "outcome"))

SERVE_QOS_SHEDS = REGISTRY.counter(
    "cake_serve_qos_sheds_total",
    "Requests/jobs answered a class-aware 429 because their QoS "
    "class's queue lane was at its bound",
    labelnames=("qos",))

SERVE_TENANT_THROTTLES = REGISTRY.counter(
    "cake_serve_tenant_throttled_total",
    "Requests/jobs refused 429 tenant_quota before any queue slot was "
    "consumed (only configured tenants can throttle, so cardinality is "
    "operator-bounded)",
    labelnames=("tenant", "reason"))    # rate | inflight

SERVE_JOBS_RUNNING = REGISTRY.gauge(
    "cake_serve_jobs_running",
    "Heavy generation jobs (image diffusion / TTS) currently executing "
    "under the admission plane's CAKE_JOB_WORKERS bound",
    labelnames=("kind",))           # image | audio

FLEET_REPLICAS = REGISTRY.gauge(
    "cake_fleet_replicas",
    "Registered replicas by membership state — the primary autoscaling "
    "signal (healthy shrinking or ejected growing means capacity loss)",
    labelnames=("state",))          # healthy | ejected | half_open |
                                    # draining

FLEET_REPLICA_QUEUE_DEPTH = REGISTRY.gauge(
    "cake_fleet_replica_queue_depth",
    "Per-replica admission-queue depth mirrored from the last /health "
    "probe (router-side autoscaling signal: sum across replicas is the "
    "fleet backlog)",
    labelnames=("replica",))

FLEET_REPLICA_OCCUPANCY = REGISTRY.gauge(
    "cake_fleet_replica_occupancy",
    "Per-replica KV occupancy [0, 1] mirrored from the last /health "
    "probe (paged pools report block occupancy, contiguous pools "
    "busy-slot fraction)",
    labelnames=("replica",))

FLEET_REPLICA_INFLIGHT = REGISTRY.gauge(
    "cake_fleet_replica_inflight",
    "Requests the router currently has proxied onto the replica "
    "(bounded by the per-replica in-flight cap)",
    labelnames=("replica",))

FLEET_SHEDS = REGISTRY.counter(
    "cake_fleet_sheds_total",
    "Requests shed 429 AT THE ROUTER before any replica admitted them",
    labelnames=("reason",))         # global | replica_cap | no_replica |
                                    # batch_pressure (QoS early shed)

FLEET_EJECTS = REGISTRY.counter(
    "cake_fleet_ejects_total",
    "Replica ejections from routing membership",
    labelnames=("replica", "reason", "evidence"))
                                        # reason: fails | error_rate |
                                        #   ttft_p95 | health
                                        # evidence: data (transport /
                                        #   request-path) | probe
                                        #   (health-probe-path only)

FLEET_READMITS = REGISTRY.counter(
    "cake_fleet_readmits_total",
    "Replicas readmitted to routing after a half-open trial succeeded",
    labelnames=("replica",))

FLEET_PARTITION_SECONDS = REGISTRY.counter(
    "cake_fleet_partition_seconds_total",
    "Cumulative seconds replicas have spent in a suspected-partition "
    "episode (ejected on data-path/transport evidence, not yet "
    "readmitted through a data-path trial)",
    labelnames=("replica",))

FLEET_RETRIES = REGISTRY.counter(
    "cake_fleet_retries_total",
    "Failover retries: attempts re-routed to another replica after a "
    "retryable failure (transport error, replica 5xx/429)")

FLEET_HEDGES = REGISTRY.counter(
    "cake_fleet_hedges_total",
    "Tail-hedged duplicates fired at a second replica after "
    "CAKE_FLEET_HEDGE_MS without a reply")

FLEET_PROXIED = REGISTRY.counter(
    "cake_fleet_requests_total",
    "Chat requests proxied through the fleet router",
    labelnames=("outcome",))        # ok | failed | shed | broken_stream

FLEET_STREAM_RESUMES = REGISTRY.counter(
    "cake_fleet_stream_resumes_total",
    "Transparent mid-stream resume attempts: streams broken after the "
    "commit point that the router spliced (or tried to) onto another "
    "replica in continuation mode",
    labelnames=("outcome",))        # ok | broken | error | exhausted |
                                    # overflow

# -- fleet-shared KV tier (fleet/kvshare/) -----------------------------------
# Cross-replica prefix-blob fetches and live stream-blob migrations; hit
# ratio is recomputed from the fetch counter each time it moves.

FLEET_KV_FETCHES = REGISTRY.counter(
    "cake_fleet_kv_fetches_total",
    "Cross-replica prefix-blob fetch attempts by a cache-cold replica "
    "before recomputing a prefill (fetch-before-recompute)",
    labelnames=("outcome",))        # hit | miss | timeout | error |
                                    # mismatch

FLEET_KV_FETCH_BYTES = REGISTRY.counter(
    "cake_fleet_kv_fetch_bytes_total",
    "Wire bytes of successfully fetched + installed prefix blobs")

FLEET_KV_MIGRATIONS = REGISTRY.counter(
    "cake_fleet_kv_migrations_total",
    "Live stream-blob migrations attempted by the router's resume plane "
    "(drain/rebalance/failover): shipped = blob installed at the new "
    "owner, source_miss / ship_error = fell back to continuation-mode "
    "re-prefill",
    labelnames=("outcome",))        # shipped | source_miss | ship_error

FLEET_KV_HIT_RATIO = REGISTRY.gauge(
    "cake_fleet_kv_hit_ratio",
    "Fraction of cross-replica prefix fetch attempts that installed a "
    "peer's blob (hit / all outcomes), over this process's lifetime")

# -- fleet telemetry plane (rollups the autoscaler will consume) -------------
# Computed once per probe cycle by fleet/telemetry.py from the in-process
# time-series rings — these are the decision-grade reductions (burn rate,
# headroom, anomaly flags), not raw mirrors.

FLEET_SLO_BURN_RATE = REGISTRY.gauge(
    "cake_fleet_slo_burn_rate",
    "Fleet SLO burn rate per alerting window (fast ~5m, slow ~1h): the "
    "windowed bad-request fraction (TTFT over CAKE_SLO_TTFT_MS, or "
    "errored) divided by the CAKE_SLO_ERR_RATE error budget; > 1 means "
    "the budget is burning faster than it accrues",
    labelnames=("window",))         # fast | slow

FLEET_HEADROOM_TOKENS = REGISTRY.gauge(
    "cake_fleet_headroom_tokens_per_s",
    "Estimated spare fleet decode capacity in tokens/s: per healthy "
    "replica, observed per-slot token rate x free slots x KV-free "
    "fraction, summed fleet-wide — the capacity signal the autoscaler "
    "scales on")

FLEET_REPLICA_OUTLIER = REGISTRY.gauge(
    "cake_fleet_replica_outlier",
    "1 while the replica's TTFT p95 or error rate diverges more than "
    "CAKE_TELEM_OUTLIER_K robust standard deviations from the fleet "
    "median (flagged in /fleet, never auto-ejected)",
    labelnames=("replica",))

FLEET_REPLICA_STALE = REGISTRY.gauge(
    "cake_fleet_replica_stale",
    "1 while the replica's last probe failed, so its mirrored gauges "
    "(queue depth, occupancy) have been retracted and telemetry rollups "
    "exclude it",
    labelnames=("replica",))

# -- fleet autoscale (the closed loop consuming the telemetry plane) ---------
# Written by fleet/autoscale.py (controller) and fleet/lifecycle.py
# (executor) inside the router process; CAKE_SCALE gates the whole loop.

FLEET_SCALE_ACTIONS = REGISTRY.counter(
    "cake_fleet_scale_actions_total",
    "Autoscaler actions EXECUTED (holds are not counted — the decisions "
    "ring at /api/v1/fleet/autoscale carries those): direction out/in, "
    "reason the trigger that fired (burn_fast / headroom_low / "
    "below_min / headroom_high)",
    labelnames=("direction", "reason"))

FLEET_SCALE_PENDING_SPAWNS = REGISTRY.gauge(
    "cake_fleet_scale_pending_spawns",
    "Replica processes spawned by the lifecycle manager still waiting "
    "for their /health to answer 200 (spawn-to-routable window; feeds "
    "the no-replica Retry-After during a cold start)")

FLEET_SCALE_MANAGED_REPLICAS = REGISTRY.gauge(
    "cake_fleet_scale_managed_replicas",
    "Replica processes whose OS lifetime the router's lifecycle manager "
    "owns (spawned by scale-out; retired by scale-in or reaped on "
    "unexpected death)")

CLUSTER_STAGE_FAILURES = REGISTRY.counter(
    "cake_cluster_stage_failures_total",
    "Classified remote-hop failures observed by the master",
    labelnames=("worker", "kind"))  # timeout | eof | conn | corrupt |
                                    # worker_error

CLUSTER_RECONNECTS = REGISTRY.counter(
    "cake_cluster_reconnects_total",
    "Successful master->worker channel re-establishments (reconnect + "
    "re-auth + re-assign) after a stage failure",
    labelnames=("worker",))

CLUSTER_REPLAYS = REGISTRY.counter(
    "cake_cluster_replays_total",
    "Rebuild-by-replay prefills run to reconstruct lost worker KV state "
    "mid-generation")

CLUSTER_DEGRADED = REGISTRY.gauge(
    "cake_cluster_degraded",
    "1 while a worker is quarantined with its retry budget exhausted "
    "(/health answers 503; the restore loop is probing)")

CLUSTER_HOP_DEGRADED = REGISTRY.gauge(
    "cake_cluster_hop_degraded",
    "1 while the hop's rolling RTT p95 exceeds CAKE_HOP_DEGRADED_MS "
    "(gray failure: slow-but-alive)",
    labelnames=("worker",))

WORKER_HEARTBEAT = REGISTRY.gauge(
    "cake_worker_heartbeat_age_seconds",
    "Seconds since the worker last handled any message, at the last "
    "heartbeat tick (worker-process registry)",
    labelnames=("worker",))

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "REGISTRY",
    "LATENCY_BUCKETS", "RECORDER", "SpanRecorder", "PhaseTimer", "now",
    "jax_trace", "new_request_id", "set_request_id", "current_request_id",
    "request_scope", "SPAN_CATALOG", "EVENT_KINDS", "TIMELINES",
    "TimelineStore", "TRACE_HEADER",
    "SERVE_TTFT_SECONDS", "SERVE_ITL_SECONDS", "SERVE_E2E_SECONDS",
    "TTFT_SECONDS", "DECODE_TOKEN_SECONDS", "GENERATED_TOKENS",
    "GENERATIONS", "API_REQUESTS", "API_REQUEST_SECONDS",
    "WORKER_FWD_SECONDS", "HOP_SECONDS", "WORKER_HEARTBEAT",
    "SERVE_QUEUE_DEPTH", "SERVE_SLOTS_BUSY", "SERVE_QUEUE_WAIT_SECONDS",
    "SERVE_BATCH_OCCUPANCY", "SERVE_PREFILL_CHUNKS", "SERVE_PREFIX_HITS",
    "SERVE_PREFIX_MISSES", "SERVE_PREFIX_EVICTIONS", "SERVE_PREFIX_BYTES",
    "SERVE_QUEUE_TIMEOUTS", "SERVE_STEP_FAILURES", "SERVE_ENGINE_REBUILDS",
    "SERVE_ENGINE_WEDGES", "SERVE_ENGINE_DOWN", "SERVE_POISONED",
    "SERVE_QOS_QUEUE_DEPTH", "SERVE_QOS_TTFT_SECONDS",
    "SERVE_QOS_E2E_SECONDS", "SERVE_QOS_SHEDS", "SERVE_TENANT_THROTTLES",
    "SERVE_JOBS_RUNNING",
    "SERVE_REQUEST_TIMEOUTS", "SERVE_KV_BLOCKS_FREE",
    "SERVE_KV_BLOCKS_USED", "SERVE_KV_BLOCKS_SHARED", "SERVE_PREEMPTIONS",
    "CLUSTER_STAGE_FAILURES", "CLUSTER_RECONNECTS",
    "CLUSTER_REPLAYS", "CLUSTER_DEGRADED", "CLUSTER_HOP_DEGRADED",
    "SPEC_PROPOSED", "SPEC_ACCEPTED", "SPEC_ACCEPTED_LEN",
    "SPEC_BUCKET_ACCEPTED",
    "FLEET_REPLICAS", "FLEET_REPLICA_QUEUE_DEPTH",
    "FLEET_REPLICA_OCCUPANCY", "FLEET_REPLICA_INFLIGHT", "FLEET_SHEDS",
    "FLEET_EJECTS", "FLEET_READMITS", "FLEET_PARTITION_SECONDS",
    "FLEET_RETRIES", "FLEET_HEDGES",
    "FLEET_PROXIED", "FLEET_STREAM_RESUMES",
    "FLEET_KV_FETCHES", "FLEET_KV_FETCH_BYTES", "FLEET_KV_MIGRATIONS",
    "FLEET_KV_HIT_RATIO",
    "FLEET_SLO_BURN_RATE", "FLEET_HEADROOM_TOKENS",
    "FLEET_REPLICA_OUTLIER", "FLEET_REPLICA_STALE",
    "FLEET_SCALE_ACTIONS", "FLEET_SCALE_PENDING_SPAWNS",
    "FLEET_SCALE_MANAGED_REPLICAS",
    "Series", "SeriesBank",
]
