"""Master->worker weight streaming with zstd compression, CRC32 integrity,
and a content-keyed worker-side cache.

Reference semantics preserved (ref: cake-core/src/cake/sharding/mod.rs):
  * chunked streaming with per-chunk CRC32 (:697) and zstd level 1 gated by
    a compressibility probe on the first 4 KB (:669-694);
  * worker cache keyed {cluster_hash}-{model_hash} where model_hash =
    sha256(config.json)[:8] (:898-907), validated before re-transfer
    (has_valid_model_cache :768-807);
  * resume support for partial transfers (ModelDataResume).

TPU-first difference: instead of shipping whole checkpoint files, the master
streams a *synthesized* safetensors file containing exactly the worker's
layer subset (built from the pread index — no full-model read), so transfer
bytes == assigned bytes.
"""
from __future__ import annotations

import hashlib
import json
import os
import struct
from typing import Iterable, Iterator

try:
    import zstandard
except ImportError:
    # gated dependency: without zstd the stream degrades to uncompressed
    # chunks (CRC + resume + caching all still work); receiving a
    # compressed chunk without it is a hard protocol error
    zstandard = None

from ..utils.safetensors_io import TensorStorage, layer_of
from . import proto

CHUNK_SIZE = 8 * 1024 * 1024
PROBE_LEN = 4096
_INV_ST_DTYPES = None


def model_hash(model_dir: str) -> str:
    with open(os.path.join(model_dir, "config.json"), "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()[:8]


def cache_key(cluster_key_hash: str, mhash: str) -> str:
    return f"{cluster_key_hash}-{mhash}"


def subset_tensor_names(storage: TensorStorage, start: int, end: int,
                        num_layers: int, include_embed: bool | None = None,
                        include_head: bool | None = None) -> list[str]:
    """Names a worker holding layers [start, end) needs."""
    if include_embed is None:
        include_embed = start == 0
    if include_head is None:
        include_head = end == num_layers
    names = []
    for name in storage.names():
        li = layer_of(name)
        if li is not None:
            if start <= li < end:
                names.append(name)
        elif "embed_tokens" in name:
            if include_embed or include_head:   # tied heads read the table
                names.append(name)
        elif include_head:
            names.append(name)
    return sorted(names)


def synthesize_safetensors(storage: TensorStorage, names: list[str],
                           chunk_size: int = CHUNK_SIZE) -> tuple[int, Iterator[bytes]]:
    """Build a valid safetensors byte stream for a tensor subset without
    materializing it: (total_size, chunk iterator)."""
    global _INV_ST_DTYPES
    if _INV_ST_DTYPES is None:
        from ..utils.dtypes import SAFETENSORS_DTYPES
        _INV_ST_DTYPES = {v: k for k, v in SAFETENSORS_DTYPES.items()}
    header: dict = {}
    offset = 0
    for n in names:
        r = storage.records[n]
        header[n] = {"dtype": _INV_ST_DTYPES[r.dtype], "shape": list(r.shape),
                     "data_offsets": [offset, offset + r.nbytes]}
        offset += r.nbytes
    hjson = json.dumps(header).encode()
    hjson += b" " * ((-len(hjson)) % 8)
    total = 8 + len(hjson) + offset

    def gen() -> Iterator[bytes]:
        # O(n) streaming: accumulate into a bytearray consumed from the
        # front via memoryview offsets (no quadratic re-slicing)
        buf = bytearray(struct.pack("<Q", len(hjson)) + hjson)
        for n in names:
            buf += storage.read_bytes(n)
            view = memoryview(buf)
            off = 0
            while len(buf) - off >= chunk_size:
                yield bytes(view[off:off + chunk_size])
                off += chunk_size
            del view
            if off:
                buf = bytearray(buf[off:])
        if buf:
            yield bytes(buf)

    return total, gen()


def should_compress(sample: bytes) -> bool:
    """zstd only pays off for compressible data — probe the first 4 KB
    (ref: sharding/mod.rs:669-694)."""
    probe = sample[:PROBE_LEN]
    if not probe or zstandard is None:
        return False
    compressed = zstandard.ZstdCompressor(level=1).compress(probe)
    return len(compressed) < int(len(probe) * 0.9)


def encode_chunks(file_name: str, total: int, chunks: Iterable[bytes],
                  start_offset: int = 0) -> Iterator[dict]:
    """bytes chunks -> model_chunk protocol messages."""
    cctx = zstandard.ZstdCompressor(level=1) if zstandard else None
    # the chunk stream always starts at file byte 0 — the running offset
    # must too, or the resume skip below can never fire and the first
    # chunk gets mislabeled with the resume offset (shifted, corrupted
    # file on the worker)
    offset = 0
    n_total = max(1, (total + CHUNK_SIZE - 1) // CHUNK_SIZE)
    i = 0
    for chunk in chunks:
        if offset + len(chunk) <= start_offset:
            offset += len(chunk)       # resume: skip already-sent bytes
            continue
        if offset < start_offset:      # partial overlap
            chunk = chunk[start_offset - offset:]
            offset = start_offset
        compress = should_compress(chunk)
        data = cctx.compress(chunk) if compress else chunk
        yield proto.model_chunk(file_name, i, n_total, data,
                                proto.crc32(data), compress, offset)
        offset += len(chunk)
        i += 1


class ModelReceiver:
    """Worker-side chunk sink: verifies CRC, decompresses, writes into the
    content-keyed cache dir (ref: receive_model_data:940-1099)."""

    def __init__(self, cache_root: str, key: str):
        self.dir = os.path.join(cache_root, key)
        os.makedirs(self.dir, exist_ok=True)
        self._files: dict[str, object] = {}
        self._dctx = zstandard.ZstdDecompressor() if zstandard else None

    def path(self, file_name: str) -> str:
        safe = os.path.basename(file_name)
        return os.path.join(self.dir, safe)

    def resume_offset(self, file_name: str) -> int:
        """How many bytes we already have (partial-transfer resume)."""
        p = self.path(file_name) + ".part"
        return os.path.getsize(p) if os.path.exists(p) else 0

    def on_chunk(self, msg: dict):
        data = msg["d"]
        if proto.crc32(data) != msg["crc"]:
            raise proto.ProtocolError(
                f"CRC mismatch on {msg['file']} chunk {msg['i']}")
        if msg["z"]:
            if self._dctx is None:
                raise proto.ProtocolError(
                    "compressed chunk received but zstandard is unavailable")
            data = self._dctx.decompress(data, max_output_size=2 * CHUNK_SIZE)
        p = self.path(msg["file"]) + ".part"
        f = self._files.get(p)
        if f is None:
            f = open(p, "r+b" if os.path.exists(p) else "wb")
            self._files[p] = f
        f.seek(msg["off"])
        f.write(data)

    def finalize(self):
        for p, f in self._files.items():
            f.close()
            os.replace(p, p[:-len(".part")])
        self._files.clear()

    def write_json(self, name: str, obj: dict):
        with open(os.path.join(self.dir, name), "w") as f:
            json.dump(obj, f)


def has_valid_model_cache(cache_root: str, key: str,
                          expected: dict[str, int]) -> bool:
    """expected: file name -> exact byte size. Validated against the cached
    files before any re-transfer (ref: has_valid_model_cache:768-807)."""
    d = os.path.join(cache_root, key)
    for name, size in expected.items():
        p = os.path.join(d, os.path.basename(name))
        if not os.path.exists(p) or os.path.getsize(p) != size:
            return False
    return True
