"""Worker node: advertises itself, authenticates the master, receives a layer
assignment (+ optionally streamed weights), then serves forward requests —
its whole contiguous layer range executing as ONE jit-compiled device call
per request (ref: cake-core/src/cake/sharding/worker.rs; the reference's
per-op dispatch loop :299-580 collapses into a single compiled range here).

Failure semantics match the reference: a failed forward answers
worker_error and keeps the connection loop alive (:425-431,477-516); a new
layer_assignment on a live socket re-runs setup (master restart, :316-330);
goodbye clears the per-connection cache (:364-384); each connection gets a
fresh KV cache (get_client_context :60-75).
"""
from __future__ import annotations

import asyncio
import json
import logging
import os

import jax.numpy as jnp
import numpy as np

from ..models.common.cache import cache_reset, init_cache
from ..models.common.config import config_from_hf_dict
from ..models.common.text_model import LocalStage, select_flash_mode
from ..obs import PhaseTimer, WORKER_FWD_SECONDS, WORKER_HEARTBEAT, now
from ..utils.dtypes import parse_dtype
from ..utils.hub import cake_cache_dir
from . import faults, proto
from .auth import authenticate_as_worker, cluster_hash
from .discovery import WorkerAdvertiser, detect_capabilities
from .transfer import ModelReceiver, has_valid_model_cache

log = logging.getLogger("cake_tpu.worker")


class WorkerState:
    """Model state shared by all connections after a layer assignment."""

    def __init__(self):
        self.cfg = None
        self.stage: LocalStage | None = None
        self.start = 0
        self.end = 0
        self.dtype = jnp.bfloat16
        self.max_cache_len = 2048
        self.model_id = ""

    @property
    def loaded(self) -> bool:
        return self.stage is not None


class WorkerServer:
    def __init__(self, name: str, cluster_key: str, port: int = 10128,
                 model_dir: str | None = None, cache_root: str | None = None,
                 advertise: bool = True, discovery_port: int | None = None,
                 host: str = "0.0.0.0", tp: int | str | None = None):
        self.name = name
        self.cluster_key = cluster_key
        self.port = port
        self.host = host
        self.model_dir = model_dir          # pre-provisioned weights (cake split)
        self.cache_root = cache_root or os.path.join(cake_cache_dir(), "worker")
        self.advertise = advertise
        self.discovery_port = discovery_port
        self.caps = detect_capabilities()
        # in-host tensor parallelism over this worker's local devices — the
        # TPU-native replacement for the reference's intra-worker multi-GPU
        # layer split (ref: worker.rs:126-229): the assigned range still
        # compiles as ONE program, GSPMD splitting each layer over the mesh
        from ..parallel import serving_mesh
        self.mesh = serving_mesh(tp)
        self.state = WorkerState()
        self._advertiser = None
        self._server: asyncio.AbstractServer | None = None
        self._writers: set = set()       # live connections, closed on stop()
        self.stats = {"ops": 0, "tokens": 0, "fwd_s": 0.0}
        # monotonic liveness: bumped on every handled message, reported as
        # an AGE in worker_info (clocks aren't synchronized across nodes)
        # and exported/logged by the heartbeat loop so /health never has to
        # assume liveness
        self.started = now()
        self.last_heartbeat = now()
        # per-message phase accounting (read/deser/fwd/ser — the obs
        # replacement for the reference's worker.rs:533-543 breakdown);
        # phases also land in the span recorder when tracing is on
        self.phase = PhaseTimer()
        self._hb_task: asyncio.Task | None = None

    # -- lifecycle ---------------------------------------------------------

    async def start(self):
        self._server = await asyncio.start_server(
            self._on_connection, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        if self.advertise:
            kw = {}
            if self.discovery_port is not None:
                kw["discovery_port"] = self.discovery_port
            self._advertiser = WorkerAdvertiser(
                self.name, self.cluster_key, self.port, caps=self.caps,
                **kw).start()
        self._hb_task = asyncio.get_running_loop().create_task(
            self._heartbeat_loop())
        # chaos harness: lets a `@name:crash_after_ops=N` fault plan
        # hard-kill this worker mid-stream (no goodbye, no FIN-wait)
        faults.register_crash("@" + self.name, self._crash)
        log.info("worker %s listening on %s:%d", self.name, self.host, self.port)
        return self

    HEARTBEAT_INTERVAL = 15.0

    async def _heartbeat_loop(self):
        """Periodic liveness export: the gauge carries the monotonic
        last-activity timestamp, the log line the age + phase breakdown —
        a wedged worker is then visible as a growing age, not silence."""
        while True:
            await asyncio.sleep(self.HEARTBEAT_INTERVAL)
            WORKER_HEARTBEAT.set(now() - self.last_heartbeat,
                                 worker=self.name)
            log.debug("worker %s heartbeat: last activity %.1fs ago, "
                      "%d ops [%s]", self.name, now() - self.last_heartbeat,
                      self.stats["ops"], self.phase)

    async def serve_forever(self):
        async with self._server:
            await self._server.serve_forever()

    def _crash(self):
        """Injected hard death (cluster/faults.py crash_after_ops): stop
        accepting and abort every live connection with an RST — the
        ungraceful failure mode recovery must survive. Runs synchronously
        on the event loop thread from inside the fault hook."""
        log.warning("worker %s: injected crash", self.name)
        if self._hb_task:
            self._hb_task.cancel()
        if self._advertiser:
            self._advertiser.stop()
        if self._server:
            self._server.close()
        for w in list(self._writers):
            try:
                w.transport.abort()
            except Exception:
                w.close()

    async def stop(self):
        faults.unregister_crash("@" + self.name)
        if self._hb_task:
            self._hb_task.cancel()
        if self._advertiser:
            self._advertiser.stop()
        if self._server:
            self._server.close()
            # close LIVE connections too: Server.close() only stops
            # accepting, so without this a "stopped" worker keeps serving
            # forwards indefinitely (masters see a healthy worker that the
            # operator believes is down)
            for w in list(self._writers):
                try:
                    w.close()
                except Exception:
                    pass
            # bounded: py3.12 wait_closed blocks until all live master
            # connections drop, which may be never during teardown
            try:
                await asyncio.wait_for(self._server.wait_closed(), 2)
            except (TimeoutError, asyncio.TimeoutError):
                pass

    # -- connection handling -------------------------------------------------

    async def _on_connection(self, reader: asyncio.StreamReader,
                             writer: asyncio.StreamWriter):
        peer = writer.get_extra_info("peername")
        sock = writer.get_extra_info("socket")
        if sock is not None:
            # response frames are latency-critical (one per token): without
            # NODELAY, Nagle + delayed-ACK stalls alternate replies ~40 ms
            # (measured: p50 1 ms / mean 30 ms bimodal RTTs on localhost)
            import socket as _socket
            sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
        # register BEFORE auth: a connection suspended mid-handshake when
        # stop() runs must be closed too, or it survives shutdown and
        # serves forwards on a worker the operator believes is down
        self._writers.add(writer)
        # label the streams so fault plans can target this worker's side
        # of the hop ("@name"; the master's side is plain "name")
        faults.tag(reader, "@" + self.name)
        faults.tag(writer, "@" + self.name)
        try:
            await authenticate_as_worker(reader, writer, self.cluster_key)
        except Exception as e:
            log.warning("auth failed from %s: %s", peer, e)
            self._writers.discard(writer)
            writer.close()
            return
        cache = None
        try:
            while True:
                msg, read_s, decode_s = await proto.read_frame_timed(reader)
                # bump liveness on EVERY received message, before any
                # branch can continue/raise past it; hello reports the age
                # before this message arrived
                prev_heartbeat = self.last_heartbeat
                self.last_heartbeat = now()
                t = msg.get("t")
                if t == "hello":
                    await proto.write_frame(writer, proto.worker_info(
                        self.name,
                        list(range(self.state.start, self.state.end)),
                        self.caps["backend"], self.caps["device"],
                        self.caps["memory_bytes"], self.caps["tflops"],
                        heartbeat_age_s=now() - prev_heartbeat,
                        ops=self.stats["ops"]))
                elif t == "layer_assignment":
                    cache = None
                    await self._handle_assignment(msg, reader, writer)
                elif t == "forward":
                    if not self.state.loaded:
                        await proto.write_frame(writer, proto.worker_error(
                            "no layer assignment"))
                        continue
                    cache = await self._handle_forward(msg, writer, cache,
                                                       read_s, decode_s)
                elif t == "goodbye":
                    # drop (not just zero) the cache: a grown buffer must
                    # not leak its size into the next generation — the next
                    # forward reallocates at the small bucket
                    cache = None
                    await proto.write_frame(writer, proto.ack())
                else:
                    await proto.write_frame(writer, proto.worker_error(
                        f"unexpected message {t!r}"))
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        except Exception as e:
            log.exception("connection error from %s: %s", peer, e)
        finally:
            self._writers.discard(writer)
            writer.close()

    # -- setup ---------------------------------------------------------------

    async def _handle_assignment(self, msg, reader, writer):
        st = self.state
        st.model_id = msg["model_id"]
        st.start, st.end = int(msg["start"]), int(msg["end"])
        st.dtype = parse_dtype(msg["dtype"])
        st.max_cache_len = int(msg.get("max_cache_len", 2048))
        cfg = config_from_hf_dict(msg["config"], msg.get("arch") or None)
        st.cfg = cfg
        key = msg["cache_key"]
        expected = msg.get("expected_files", {})

        # ack tells the master whether weights are already present so it can
        # skip the push (content-keyed cache, ref: has_valid_model_cache)
        model_dir = self.model_dir
        if model_dir is None:
            # empty `expected` cannot validate anything -> treat as uncached
            cached = bool(expected) and has_valid_model_cache(
                self.cache_root, key, expected)
            if not cached and msg["push_weights"]:
                a = proto.ack()
                a["cached"] = False
                # partial-transfer resume offsets (ref: ModelDataResume)
                recv = ModelReceiver(self.cache_root, key)
                a["resume"] = {f: recv.resume_offset(f) for f in expected}
                await proto.write_frame(writer, a)
                model_dir = await self._receive_weights(reader, key, msg, recv)
            elif cached:
                a = proto.ack()
                a["cached"] = True
                await proto.write_frame(writer, a)
                model_dir = os.path.join(self.cache_root, key)
            else:
                await proto.write_frame(writer, proto.worker_error(
                    "no weights: not cached and push disabled"))
                return
        else:
            a = proto.ack()
            a["cached"] = True
            await proto.write_frame(writer, a)

        try:
            t0 = now()
            from ..utils.loaders import load_model_params
            quant = None
            if msg.get("fp8_native"):
                from ..utils.quant import fp8_native_quant
                quant = fp8_native_quant()
            params = load_model_params(
                cfg, model_dir, st.dtype, quant=quant,
                layer_range=(st.start, st.end),
                include_embed=False, include_head=False)
            st.stage = LocalStage(cfg, params, st.start, st.end,
                                  mesh=self.mesh)
            # warm compiles during setup, not on first serve (ref hard-part
            # #7). "decode" warms the 1-token shape at the smallest bucket;
            # "full" (master default) additionally compiles every growth
            # bucket's decode AND fresh-prefill shape, so steady-state
            # serving never pays an in-band compile (VERDICT r4: in-band
            # compiles were the prime suspect for 8x RTT tail stalls)
            # off the event loop: a full warm sweep takes seconds-to-minutes
            # and other connections (another master mid-generation) must
            # keep being served while it runs
            await asyncio.get_running_loop().run_in_executor(
                None, self._warm, msg.get("warm", "decode"))
            log.info("worker %s loaded layers [%d,%d) in %.1fs", self.name,
                     st.start, st.end, now() - t0)
            await proto.write_frame(writer, proto.worker_ready())
        except Exception as e:
            log.exception("assignment failed")
            await proto.write_frame(writer, proto.worker_ready(
                ok=False, error=str(e)))
            st.stage = None

    def _warm(self, mode: str):
        """Compile-warm the shapes serving will hit. All jit caches are
        keyed on array shapes and persist across connections, so this runs
        once per assignment regardless of how many masters connect."""
        if mode == "none":
            return
        from ..models.common.text_model import (PREFILL_BUCKETS,
                                                PREFILL_CHUNK)
        st = self.state
        t0 = now()
        buckets = [b for b in PREFILL_BUCKETS if b <= st.max_cache_len]
        if not buckets or buckets[-1] != st.max_cache_len:
            buckets.append(st.max_cache_len)
        if mode != "full":
            buckets = buckets[:1]
        zero = jnp.asarray(0, jnp.int32)
        x1 = jnp.zeros((1, 1, st.cfg.hidden_size), st.dtype)
        n = 0
        for i, b in enumerate(buckets):
            cache = None     # free bucket i-1 before allocating bucket i
            cache, _ = self._sized_cache(None, b)
            # decode shape at this bucket; reuse the returned cache (same
            # buffers, contents irrelevant) for the prefill warms so the
            # largest bucket never holds two live caches at once
            _, cache = st.stage.forward_hidden(x1, cache, zero, None)
            n += 1
            if mode == "full":
                # fresh full-prompt prefill: the master pads prompts to
                # bucket width w and sends them whole, while the kv hint
                # sizes this cache to w's bucket OR the next one (prompt +
                # DECODE_HEADROOM may spill) — warm both combos
                for w in ([b, buckets[i - 1]] if i > 0 else [b]):
                    xb = jnp.zeros((1, w, st.cfg.hidden_size), st.dtype)
                    _, cache = st.stage.forward_hidden(
                        xb, cache, zero, jnp.asarray(w, jnp.int32),
                        flash_mode=select_flash_mode(0, w, b))
                    n += 1
                # pipelined-prefill chunk shapes: prompts longer than
                # PREFILL_CHUNK arrive as chunk-width slices — fresh for
                # chunk 0, append (pos0 traced, one compile covers all
                # later chunks) for the rest
                # (>= 2*chunk: the master only chunks prompts longer than
                # one chunk, and ceil-to-chunk must fit the bucket — a
                # bucket strictly between chunk and 2*chunk can never
                # receive chunked prefill)
                if b >= 2 * PREFILL_CHUNK:
                    xc = jnp.zeros((1, PREFILL_CHUNK, st.cfg.hidden_size),
                                   st.dtype)
                    vlc = jnp.asarray(PREFILL_CHUNK, jnp.int32)
                    for p0 in (0, PREFILL_CHUNK):
                        _, cache = st.stage.forward_hidden(
                            xc, cache, jnp.asarray(p0, jnp.int32), vlc,
                            flash_mode=select_flash_mode(
                                p0, PREFILL_CHUNK, b))
                        n += 1
        log.info("worker %s warmed %d shapes (%s) in %.1fs", self.name, n,
                 mode, now() - t0)

    async def _receive_weights(self, reader, key: str, assign_msg,
                               recv: ModelReceiver) -> str:
        while True:
            msg = await proto.read_frame(reader)
            if msg["t"] == "model_chunk":
                recv.on_chunk(msg)
            elif msg["t"] == "model_done":
                recv.finalize()
                recv.write_json("config.json", assign_msg["config_raw"]
                                if "config_raw" in assign_msg
                                else assign_msg["config"])
                break
            else:
                raise proto.ProtocolError(
                    f"unexpected {msg['t']!r} during weight transfer")
        return recv.dir

    # -- inference -----------------------------------------------------------

    def _fresh_cache(self, kv_len: int | None = None):
        from ..parallel.sharding import shard_cache
        st = self.state
        return shard_cache(
            init_cache(st.cfg, 1, min(kv_len or st.max_cache_len,
                                      st.max_cache_len), st.dtype,
                       layer_range=(st.start, st.end)), self.mesh)

    def _sized_cache(self, cache, needed: int):
        """Growth-bucketed per-connection cache (mirrors TextModel's
        cache-length bucketing): allocate at the smallest bucket covering
        the request, grow bucket-by-bucket as positions advance — decode
        attends over the allocated buffer, so short generations never pay
        max_cache_len of attention bandwidth per token on workers either."""
        from ..models.common.cache import grow_cache, kv_capacity
        from ..models.common.text_model import bucket_for
        from ..parallel.sharding import shard_cache
        st = self.state
        bkt = bucket_for(needed, st.max_cache_len)
        if cache is None:
            return self._fresh_cache(bkt), bkt
        cap = kv_capacity(st.cfg, cache, (st.start, st.end))
        if cap is None:            # pure SWA/linear range: wraps by design
            return cache, st.max_cache_len
        if needed > cap:
            cache = shard_cache(grow_cache(st.cfg, cache, bkt,
                                           (st.start, st.end)), self.mesh)
            cap = bkt
        return cache, cap

    async def _handle_forward(self, msg, writer, cache, read_s: float = 0.0,
                              decode_s: float = 0.0):
        st = self.state
        t0 = now()
        try:
            # deser: msgpack decode (timed by the framing layer) + raw-buffer
            # unpack + host->device transfer/cast
            t_d = now()
            x = jnp.asarray(proto.unpack_tensor(msg["x"])).astype(st.dtype)
            deser_s = decode_s + (now() - t_d)
            raw_pos0 = int(msg["pos0"])
            pos0 = jnp.asarray(raw_pos0, jnp.int32)
            vl = msg.get("valid_len")
            # kv hint: size the cache to the master's bucket so growth
            # reallocs stay bucket-aligned (and pre-warmed) on every node
            needed = max(raw_pos0 + x.shape[1], int(msg.get("kv") or 0))
            cache, capacity = self._sized_cache(cache, needed)
            # prefill chunks (valid_len present) take the flash path
            # (worker caches are unwrapped while inside the buffer)
            flash_mode = "off"
            if vl is not None:
                flash_mode = select_flash_mode(raw_pos0, x.shape[1],
                                               capacity)
            vl = None if vl is None else jnp.asarray(vl, jnp.int32)
            loop = asyncio.get_running_loop()

            def _run():
                # timing starts INSIDE the executor thread (queueing delay
                # belongs to wire_, not fwd_) and ends after a real fetch
                # (jax dispatch is async; only np.asarray syncs the device)
                t_fwd = now()
                yy, cc = st.stage.forward_hidden(x, cache, pos0, vl,
                                                 flash_mode=flash_mode)
                # lint: disable=host-sync — the stage result is serialized to the wire
                # next; fetching here also keeps fwd_ms honest (dispatch is async)
                yy = np.asarray(yy)
                return yy, cc, t_fwd, (now() - t_fwd) * 1e3

            y, cache, t_fwd0, fwd_ms = await loop.run_in_executor(None, _run)
            # ser timed separately so the echo attributes it: tobytes of
            # the hidden state dominates the response path
            t_s = now()
            packed = proto.pack_tensor(y)
            ser_s = now() - t_s
            # per-phase echo: lets the master split its observed RTT into
            # worker-side read/deser/fwd/ser and attribute the remainder
            # to the wire (ref: worker.rs:533-543)
            tm = {"read_ms": read_s * 1e3, "deser_ms": deser_s * 1e3,
                  "fwd_ms": fwd_ms, "ser_ms": ser_s * 1e3}
            await proto.write_frame(
                writer, proto.tensor_result(packed, msg.get("rid", 0),
                                            fwd_ms=fwd_ms, timing=tm))
        except Exception as e:
            log.exception("forward failed")
            await proto.write_frame(writer, proto.worker_error(str(e)))
            return cache
        dt = now() - t0
        self.stats["ops"] += 1
        self.stats["fwd_s"] += dt
        self.stats["tokens"] += int(np.prod(np.asarray(msg["x"]["sh"][:2])))
        WORKER_FWD_SECONDS.observe(fwd_ms / 1e3)
        # real start timestamps so the exported spans lay out sequentially
        # (read/decode finished just before the handler entered at t0)
        ph = self.phase
        ph.add("read", read_s, t0=t0 - decode_s - read_s)
        ph.add("deser", deser_s, t0=t0 - decode_s)
        ph.add("fwd", fwd_ms / 1e3, t0=t_fwd0)
        ph.add("ser", ser_s, t0=t_s)
        if self.stats["ops"] % 5 == 0:   # rolling stats (ref worker.rs:566-578)
            log.debug("worker %s: %d ops, avg %.1f ms [%s]", self.name,
                      self.stats["ops"],
                      1000 * self.stats["fwd_s"] / self.stats["ops"], ph)
        return cache


def run_worker(name: str, cluster_key: str, port: int = 10128,
               model_dir: str | None = None, tp: int | str | None = None,
               **kw):
    """Blocking entry point (ref: cake-cli run_as_worker)."""
    async def main():
        server = WorkerServer(name, cluster_key, port, model_dir, tp=tp, **kw)
        await server.start()
        await server.serve_forever()
    asyncio.run(main())
