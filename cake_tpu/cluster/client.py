"""Master-side proxy for a remote worker: implements the same
forward_hidden(x, cache, pos0, valid_len) stage interface as LocalStage,
over the framed TCP protocol (ref: cake-core/src/cake/sharding/client.rs:
13-188 — forward_batch ships a contiguous layer range in one round trip;
here every remote call is one round trip by construction).

Sync sockets: the master generation loop is sequential per token (the
pipeline is a chain), so async buys nothing on this path.
"""
from __future__ import annotations

import logging
import socket
import time

import numpy as np

from ..obs import HOP_SECONDS, now
from . import proto
from .auth import AuthError, _mac, CHALLENGE_LEN, MAC_LEN

log = logging.getLogger("cake_tpu.client")

CONNECT_RETRIES = 3          # ref: sharding/mod.rs:385-431 exp backoff
CONNECT_BACKOFF = 1.0


class RemoteStage:
    """A connected, authenticated channel to one worker."""

    SETUP_TIMEOUT = 1800.0   # weight load + whole-range XLA compile

    def __init__(self, host: str, port: int, cluster_key: str,
                 name: str = "?", timeout: float = 120.0):
        self.host, self.port = host, port
        self.cluster_key = cluster_key
        self.name = name
        self.timeout = timeout
        self.sock: socket.socket | None = None
        self.info: dict = {}
        self._rid = 0
        from collections import deque
        # (rtt_s, timing-echo dict in ms: read/deser/fwd/ser — empty for
        # workers predating the echo)
        self.rtts: deque = deque(maxlen=512)
        # monotonic timestamps of the last forward attempt / success on
        # this channel — /health reports the success age and flags a
        # worker only when attempts keep happening without successes
        # (an idle channel is not a dead one)
        self.last_attempt: float | None = None
        self.last_ok: float | None = None
        self.total_ops = 0          # cumulative successes (never cleared)

    # -- connection --------------------------------------------------------

    def connect(self):
        last = None
        for attempt in range(CONNECT_RETRIES):
            try:
                self.sock = socket.create_connection(
                    (self.host, self.port), timeout=self.timeout)
                self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                self._auth()
                proto.write_frame_sync(self.sock, proto.hello("master"))
                self.info = proto.read_frame_sync(self.sock)
                return self
            except (OSError, AuthError) as e:
                last = e
                if self.sock:
                    self.sock.close()
                    self.sock = None
                if attempt == CONNECT_RETRIES - 1:
                    break               # no dead wait after the final attempt
                wait = CONNECT_BACKOFF * (2 ** attempt)
                log.warning("connect to %s:%d failed (%s), retry in %.1fs",
                            self.host, self.port, e, wait)
                time.sleep(wait)
        raise ConnectionError(
            f"cannot reach worker {self.name} at {self.host}:{self.port}: {last}")

    def _auth(self):
        """Master side of the mutual HMAC handshake (sync mirror of
        auth.authenticate_as_master)."""
        import os as _os
        cw = self._recv_exact(CHALLENGE_LEN)
        cm = _os.urandom(CHALLENGE_LEN)
        self.sock.sendall(_mac(self.cluster_key, cw) + cm)
        their = self._recv_exact(MAC_LEN)
        import hmac as _hmac
        if not _hmac.compare_digest(their, _mac(self.cluster_key, cm)):
            raise AuthError("worker failed authentication")

    def _recv_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("socket closed during auth")
            buf += chunk
        return buf

    # -- setup -------------------------------------------------------------

    def assign(self, assignment: dict) -> dict:
        proto.write_frame_sync(self.sock, assignment)
        return proto.read_frame_sync(self.sock)      # ack or worker_error

    def push_weights(self, chunk_msgs) -> None:
        for m in chunk_msgs:
            proto.write_frame_sync(self.sock, m)
        proto.write_frame_sync(self.sock, proto.model_done())

    def wait_ready(self) -> dict:
        # setup (load + compile) can far exceed the per-op forward timeout
        self.sock.settimeout(self.SETUP_TIMEOUT)
        try:
            msg = proto.read_frame_sync(self.sock)
        finally:
            self.sock.settimeout(self.timeout)
        if msg.get("t") != "worker_ready" or not msg.get("ok", False):
            raise RuntimeError(
                f"worker {self.name} setup failed: {msg.get('error', msg)}")
        return msg

    # -- inference (stage interface) ----------------------------------------

    def forward_hidden(self, x, cache, pos0, valid_len, kv_hint=None):
        """cache is managed worker-side per connection; the local `cache`
        slot is passed through untouched (None). kv_hint: master's current
        cache bucket, so the worker sizes its cache to match."""
        self._rid += 1
        t0 = now()
        self.last_attempt = t0
        proto.write_frame_sync(self.sock, proto.forward(
            np.asarray(x), int(pos0),
            None if valid_len is None else int(valid_len), self._rid,
            kv_hint=kv_hint))
        msg = proto.read_frame_sync(self.sock)
        rtt = now() - t0
        if msg.get("t") == "worker_error":
            raise RuntimeError(f"worker {self.name}: {msg['error']}")
        if msg.get("rid", self._rid) != self._rid:
            raise proto.ProtocolError("response id mismatch")
        # successful replies only: error RTTs would pollute the wire stats
        tm = dict(msg.get("tm") or {})
        if "fwd_ms" not in tm and msg.get("fwd_ms"):
            tm["fwd_ms"] = float(msg["fwd_ms"])   # pre-echo workers
        self.rtts.append((rtt, tm))
        self.last_ok = now()
        self.total_ops += 1
        self._observe_hop(rtt, tm)
        return proto.unpack_tensor(msg["x"]), cache

    def _observe_hop(self, rtt: float, tm: dict):
        """Feed the per-hop histograms: whole RTT, each worker-echoed phase,
        and the unattributed remainder (wire = TCP + response write +
        scheduling)."""
        HOP_SECONDS.observe(rtt, worker=self.name, phase="rtt")
        echoed = 0.0
        for k in self._ECHO_PHASES:
            v = tm.get(f"{k}_ms")
            if v is not None:
                HOP_SECONDS.observe(v / 1e3, worker=self.name, phase=k)
                echoed += v / 1e3
        if echoed:
            HOP_SECONDS.observe(max(rtt - echoed, 0.0),
                                worker=self.name, phase="wire")

    _ECHO_PHASES = ("read", "deser", "fwd", "ser")

    def rtt_stats(self) -> dict:
        """Per-hop round-trip accounting (ref: client.rs:96-104 per-client
        send/recv timing). mean vs p50 spread flags bimodal stalls
        (Nagle/delayed-ACK class of bugs). Each RTT splits into the phases
        the worker echoes back (read_/deser_/fwd_/ser_*, with fwd including
        any in-band compile) and the remainder (wire_*: TCP + response
        write + scheduling), so a tail stall is attributable to one side
        of the link AND one phase of the worker's message handling."""
        if not self.rtts:
            return {"count": 0}

        def _stats(vals, prefix):
            arr = sorted(vals)
            return {f"{prefix}p50_ms": round(arr[len(arr) // 2] * 1e3, 2),
                    f"{prefix}p95_ms": round(arr[int(len(arr) * 0.95)] * 1e3, 2),
                    f"{prefix}mean_ms": round(sum(arr) / len(arr) * 1e3, 2),
                    f"{prefix}min_ms": round(arr[0] * 1e3, 2)}

        samples = list(self.rtts)
        rtts = [r for r, _ in samples]
        out = {"count": len(rtts), **_stats(rtts, "")}
        for k in self._ECHO_PHASES:
            vals = [t[f"{k}_ms"] / 1e3 for _, t in samples
                    if t.get(f"{k}_ms")]
            if vals:
                out.update(_stats(vals, f"{k}_"))
        # wire remainder only over samples that carry a worker timing: a
        # worker predating the echo would otherwise have its whole RTT
        # misattributed to the wire
        timed = [(r, t) for r, t in samples if t.get("fwd_ms")]
        if timed:
            out.update(_stats(
                [max(r - sum(t.get(f"{k}_ms", 0.0)
                             for k in self._ECHO_PHASES) / 1e3, 0.0)
                 for r, t in timed], "wire_"))
        return out

    def goodbye(self):
        try:
            proto.write_frame_sync(self.sock, proto.goodbye())
            proto.read_frame_sync(self.sock)
        except OSError:
            pass

    def close(self):
        if self.sock:
            try:
                self.sock.close()
            finally:
                self.sock = None
