"""Master-side proxy for a remote worker: implements the same
forward_hidden(x, cache, pos0, valid_len) stage interface as LocalStage,
over the framed TCP protocol (ref: cake-core/src/cake/sharding/client.rs:
13-188 — forward_batch ships a contiguous layer range in one round trip;
here every remote call is one round trip by construction).

Sync sockets: the master generation loop is sequential per token (the
pipeline is a chain), so async buys nothing on this path.
"""
from __future__ import annotations

import logging
import socket
import time

import numpy as np

from . import proto
from .auth import AuthError, _mac, CHALLENGE_LEN, MAC_LEN

log = logging.getLogger("cake_tpu.client")

CONNECT_RETRIES = 3          # ref: sharding/mod.rs:385-431 exp backoff
CONNECT_BACKOFF = 1.0


class RemoteStage:
    """A connected, authenticated channel to one worker."""

    SETUP_TIMEOUT = 1800.0   # weight load + whole-range XLA compile

    def __init__(self, host: str, port: int, cluster_key: str,
                 name: str = "?", timeout: float = 120.0):
        self.host, self.port = host, port
        self.cluster_key = cluster_key
        self.name = name
        self.timeout = timeout
        self.sock: socket.socket | None = None
        self.info: dict = {}
        self._rid = 0
        from collections import deque
        self.rtts: deque = deque(maxlen=512)       # (rtt_s, worker_fwd_s)

    # -- connection --------------------------------------------------------

    def connect(self):
        last = None
        for attempt in range(CONNECT_RETRIES):
            try:
                self.sock = socket.create_connection(
                    (self.host, self.port), timeout=self.timeout)
                self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                self._auth()
                proto.write_frame_sync(self.sock, proto.hello("master"))
                self.info = proto.read_frame_sync(self.sock)
                return self
            except (OSError, AuthError) as e:
                last = e
                if self.sock:
                    self.sock.close()
                    self.sock = None
                if attempt == CONNECT_RETRIES - 1:
                    break               # no dead wait after the final attempt
                wait = CONNECT_BACKOFF * (2 ** attempt)
                log.warning("connect to %s:%d failed (%s), retry in %.1fs",
                            self.host, self.port, e, wait)
                time.sleep(wait)
        raise ConnectionError(
            f"cannot reach worker {self.name} at {self.host}:{self.port}: {last}")

    def _auth(self):
        """Master side of the mutual HMAC handshake (sync mirror of
        auth.authenticate_as_master)."""
        import os as _os
        cw = self._recv_exact(CHALLENGE_LEN)
        cm = _os.urandom(CHALLENGE_LEN)
        self.sock.sendall(_mac(self.cluster_key, cw) + cm)
        their = self._recv_exact(MAC_LEN)
        import hmac as _hmac
        if not _hmac.compare_digest(their, _mac(self.cluster_key, cm)):
            raise AuthError("worker failed authentication")

    def _recv_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("socket closed during auth")
            buf += chunk
        return buf

    # -- setup -------------------------------------------------------------

    def assign(self, assignment: dict) -> dict:
        proto.write_frame_sync(self.sock, assignment)
        return proto.read_frame_sync(self.sock)      # ack or worker_error

    def push_weights(self, chunk_msgs) -> None:
        for m in chunk_msgs:
            proto.write_frame_sync(self.sock, m)
        proto.write_frame_sync(self.sock, proto.model_done())

    def wait_ready(self) -> dict:
        # setup (load + compile) can far exceed the per-op forward timeout
        self.sock.settimeout(self.SETUP_TIMEOUT)
        try:
            msg = proto.read_frame_sync(self.sock)
        finally:
            self.sock.settimeout(self.timeout)
        if msg.get("t") != "worker_ready" or not msg.get("ok", False):
            raise RuntimeError(
                f"worker {self.name} setup failed: {msg.get('error', msg)}")
        return msg

    # -- inference (stage interface) ----------------------------------------

    def forward_hidden(self, x, cache, pos0, valid_len, kv_hint=None):
        """cache is managed worker-side per connection; the local `cache`
        slot is passed through untouched (None). kv_hint: master's current
        cache bucket, so the worker sizes its cache to match."""
        self._rid += 1
        t0 = time.monotonic()
        proto.write_frame_sync(self.sock, proto.forward(
            np.asarray(x), int(pos0),
            None if valid_len is None else int(valid_len), self._rid,
            kv_hint=kv_hint))
        msg = proto.read_frame_sync(self.sock)
        rtt = time.monotonic() - t0
        if msg.get("t") == "worker_error":
            raise RuntimeError(f"worker {self.name}: {msg['error']}")
        if msg.get("rid", self._rid) != self._rid:
            raise proto.ProtocolError("response id mismatch")
        # successful replies only: error RTTs would pollute the wire stats
        self.rtts.append((rtt, float(msg.get("fwd_ms", 0.0)) / 1e3))
        return proto.unpack_tensor(msg["x"]), cache

    def rtt_stats(self) -> dict:
        """Per-hop round-trip accounting (ref: client.rs:96-104 per-client
        send/recv timing). mean vs p50 spread flags bimodal stalls
        (Nagle/delayed-ACK class of bugs). Each RTT splits into the
        worker-reported compute time (fwd_*, includes any in-band compile)
        and the remainder (wire_*: serialization + TCP + scheduling), so a
        tail stall is attributable to one side."""
        if not self.rtts:
            return {"count": 0}

        def _stats(vals, prefix):
            arr = sorted(vals)
            return {f"{prefix}p50_ms": round(arr[len(arr) // 2] * 1e3, 2),
                    f"{prefix}p95_ms": round(arr[int(len(arr) * 0.95)] * 1e3, 2),
                    f"{prefix}mean_ms": round(sum(arr) / len(arr) * 1e3, 2),
                    f"{prefix}min_ms": round(arr[0] * 1e3, 2)}

        rtts = [r for r, _ in self.rtts]
        out = {"count": len(rtts), **_stats(rtts, "")}
        # split only over samples that carry a worker timing (f > 0): a
        # worker predating fwd_ms would otherwise have its whole RTT
        # misattributed to the wire
        timed = [(r, f) for r, f in self.rtts if f > 0]
        if timed:
            out.update(_stats([f for _, f in timed], "fwd_"))
            out.update(_stats([max(r - f, 0.0) for r, f in timed], "wire_"))
        return out

    def goodbye(self):
        try:
            proto.write_frame_sync(self.sock, proto.goodbye())
            proto.read_frame_sync(self.sock)
        except OSError:
            pass

    def close(self):
        if self.sock:
            try:
                self.sock.close()
            finally:
                self.sock = None
