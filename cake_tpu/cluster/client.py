"""Master-side proxy for a remote worker: implements the same
forward_hidden(x, cache, pos0, valid_len) stage interface as LocalStage,
over the framed TCP protocol (ref: cake-core/src/cake/sharding/client.rs:
13-188 — forward_batch ships a contiguous layer range in one round trip;
here every remote call is one round trip by construction).

Sync sockets: the master generation loop is sequential per token (the
pipeline is a chain), so async buys nothing on this path.
"""
from __future__ import annotations

import logging
import socket
import time

import numpy as np

from .. import knobs
from ..obs import (CLUSTER_HOP_DEGRADED, CLUSTER_STAGE_FAILURES,
                   HOP_SECONDS, TIMELINES, now)
from . import faults, proto
from .auth import AuthError, _mac, CHALLENGE_LEN, MAC_LEN

log = logging.getLogger("cake_tpu.client")

CONNECT_RETRIES = 3          # ref: sharding/mod.rs:385-431 exp backoff
CONNECT_BACKOFF = 1.0

# rolling window the gray-failure detector computes its RTT p95 over, and
# the minimum samples before it may trip (one slow op is noise, not gray)
GRAY_WINDOW = 64
GRAY_MIN_SAMPLES = 4


class StageFailure(RuntimeError):
    """One classified failure of a remote hop. `kind` drives the recovery
    policy and the failure-counter labels:

      timeout       per-op deadline expired (worker stalled or wedged)
      eof           peer closed the connection (worker crash / drop)
      conn          other transport failure (refused, reset, no channel)
      corrupt       undecodable / desynced frame
      worker_error  the worker answered worker_error (op failed in-place;
                    the connection itself stayed up)
    """

    def __init__(self, kind: str, worker: str, detail: str):
        super().__init__(f"worker {worker}: {kind}: {detail}")
        self.kind = kind
        self.worker = worker
        self.detail = detail


class RemoteStage:
    """A connected, authenticated channel to one worker."""

    SETUP_TIMEOUT = 1800.0   # weight load + whole-range XLA compile

    def __init__(self, host: str, port: int, cluster_key: str,
                 name: str = "?", timeout: float | None = None):
        self.host, self.port = host, port
        self.cluster_key = cluster_key
        self.name = name
        # per-op deadline: every forward's socket reads must complete
        # within this, or the op is classified `timeout` and recovery
        # takes over (CAKE_HOP_TIMEOUT_S; generous default — LAN/TPU
        # tunnels sit at 66-90ms RTT, so even seconds is "stalled")
        self.timeout = timeout if timeout is not None \
            else knobs.get("CAKE_HOP_TIMEOUT_S")
        # gray-failure threshold: rolling RTT p95 above this flags the hop
        # degraded in /health WITHOUT failing anything (0 = disabled)
        self.degraded_ms = knobs.get("CAKE_HOP_DEGRADED_MS")
        # the FIRST forward after a reestablish() may include an in-band
        # XLA compile on the freshly re-assigned worker (warm="decode"/
        # "none", or a shape outside the warm sweep) — it gets this grace
        # deadline instead of the per-op one, or a tight CAKE_HOP_TIMEOUT_S
        # would kill every replay and burn the retry budget on a healthy
        # worker
        self.revive_grace_s = knobs.get("CAKE_REVIVE_GRACE_S")
        self._revive_grace = False
        self.sock: socket.socket | None = None
        self.info: dict = {}
        self._rid = 0
        from collections import deque
        # (rtt_s, timing-echo dict in ms: read/deser/fwd/ser — empty for
        # workers predating the echo)
        self.rtts: deque = deque(maxlen=512)
        # monotonic timestamps of the last forward attempt / success on
        # this channel — /health reports the success age and flags a
        # worker only when attempts keep happening without successes
        # (an idle channel is not a dead one)
        self.last_attempt: float | None = None
        self.last_ok: float | None = None
        self.total_ops = 0          # cumulative successes (never cleared)
        # recovery memory, filled in by master_setup: the assignment to
        # replay on reconnect and a weight-repush thunk for the (rare)
        # case the worker lost its content-keyed cache too
        self.assignment: dict | None = None
        self.repush = None

    # -- connection --------------------------------------------------------

    def connect(self, attempts: int | None = None,
                backoff: float | None = None):
        """Connect + mutual auth + hello. Recovery passes attempts=1 and
        runs its own jittered backoff around the call."""
        attempts = CONNECT_RETRIES if attempts is None else max(attempts, 1)
        backoff = CONNECT_BACKOFF if backoff is None else backoff
        last = None
        for attempt in range(attempts):
            try:
                self.sock = socket.create_connection(
                    (self.host, self.port), timeout=self.timeout)
                self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                faults.tag(self.sock, self.name)
                self._auth()
                proto.write_frame_sync(self.sock, proto.hello("master"))
                self.info = proto.read_frame_sync(self.sock)
                return self
            except (OSError, AuthError, proto.ProtocolError) as e:
                last = e
                if self.sock:
                    self.sock.close()
                    self.sock = None
                if attempt == attempts - 1:
                    break               # no dead wait after the final attempt
                wait = backoff * (2 ** attempt)
                log.warning("connect to %s:%d failed (%s), retry in %.1fs",
                            self.host, self.port, e, wait)
                time.sleep(wait)
        raise ConnectionError(
            f"cannot reach worker {self.name} at {self.host}:{self.port}: {last}")

    def _auth(self):
        """Master side of the mutual HMAC handshake (sync mirror of
        auth.authenticate_as_master)."""
        import os as _os
        cw = self._recv_exact(CHALLENGE_LEN)
        cm = _os.urandom(CHALLENGE_LEN)
        self.sock.sendall(_mac(self.cluster_key, cw) + cm)
        their = self._recv_exact(MAC_LEN)
        import hmac as _hmac
        if not _hmac.compare_digest(their, _mac(self.cluster_key, cm)):
            raise AuthError("worker failed authentication")

    def _recv_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                # a truncated handshake IS an auth failure (the worker
                # bailed after a bad MAC) — same mapping as auth._read
                raise AuthError("peer closed during auth handshake")
            buf += chunk
        return buf

    def reestablish(self):
        """One reconnect + re-auth + re-assign + ready cycle from the
        remembered assignment — the recovery path's revive step. The
        weight push is skipped when the worker still acks its
        content-keyed cache (`transfer_cached`); a worker that lost the
        cache too gets the weights re-streamed via the repush thunk."""
        self.close()
        self.connect(attempts=1)
        if self.assignment is None:
            return self
        resp = self.assign(self.assignment)
        if resp.get("t") == "worker_error":
            raise RuntimeError(
                f"worker {self.name} re-assign failed: {resp['error']}")
        if self.assignment.get("push_weights") and not resp.get("cached",
                                                                False):
            if self.repush is None:
                raise RuntimeError(
                    f"worker {self.name} lost its weight cache and no "
                    "repush source is available")
            self.repush(self, resp)
        self.wait_ready()
        self._revive_grace = True
        return self

    # -- setup -------------------------------------------------------------

    def assign(self, assignment: dict) -> dict:
        proto.write_frame_sync(self.sock, assignment)
        return proto.read_frame_sync(self.sock)      # ack or worker_error

    def push_weights(self, chunk_msgs) -> None:
        for m in chunk_msgs:
            proto.write_frame_sync(self.sock, m)
        proto.write_frame_sync(self.sock, proto.model_done())

    def wait_ready(self) -> dict:
        # setup (load + compile) can far exceed the per-op forward timeout
        self.sock.settimeout(self.SETUP_TIMEOUT)
        try:
            msg = proto.read_frame_sync(self.sock)
        finally:
            self.sock.settimeout(self.timeout)
        if msg.get("t") != "worker_ready" or not msg.get("ok", False):
            raise RuntimeError(
                f"worker {self.name} setup failed: {msg.get('error', msg)}")
        return msg

    # -- inference (stage interface) ----------------------------------------

    def forward_hidden(self, x, cache, pos0, valid_len, kv_hint=None):
        """cache is managed worker-side per connection; the local `cache`
        slot is passed through untouched (None). kv_hint: master's current
        cache bucket, so the worker sizes its cache to match.

        Every failure mode surfaces as a classified StageFailure so the
        master's recovery loop (master._recover) can decide policy; after
        a transport-level failure the channel is closed — its stream state
        is unknowable, and a late reply would desync request ids."""
        self._rid += 1
        t0 = now()
        self.last_attempt = t0
        graced = False
        try:
            if self.sock is None:
                raise self._classify("conn", "not connected", close=False)
            if self._revive_grace:
                self._revive_grace = False
                graced = True
                self.sock.settimeout(max(self.timeout, self.revive_grace_s))
            proto.write_frame_sync(self.sock, proto.forward(
                np.asarray(x), int(pos0),
                None if valid_len is None else int(valid_len), self._rid,
                kv_hint=kv_hint))
            msg = proto.read_frame_sync(self.sock)
        except StageFailure:
            raise
        except (socket.timeout, TimeoutError) as e:
            raise self._classify("timeout", e, close=True) from e
        except ConnectionError as e:
            raise self._classify("eof", e, close=True) from e
        except OSError as e:
            raise self._classify("conn", e, close=True) from e
        except proto.ProtocolError as e:
            raise self._classify("corrupt", e, close=True) from e
        finally:
            if graced and self.sock is not None:
                self.sock.settimeout(self.timeout)
        rtt = now() - t0
        if msg.get("t") == "worker_error":
            # the op failed in-place but the connection loop is alive
            # (ref: worker.rs:425-431) — no teardown
            raise self._classify("worker_error", msg["error"], close=False)
        if msg.get("rid", self._rid) != self._rid:
            raise self._classify("corrupt", "response id mismatch",
                                 close=True)
        # successful replies only: error RTTs would pollute the wire stats
        tm = dict(msg.get("tm") or {})
        if "fwd_ms" not in tm and msg.get("fwd_ms"):
            tm["fwd_ms"] = float(msg["fwd_ms"])   # pre-echo workers
        if not graced:
            # the graced post-revive op may carry a multi-second in-band
            # compile — one such sample would pin the rolling p95 and
            # false-flag a freshly recovered hop as gray for a whole
            # window
            self.rtts.append((rtt, tm))
        self.last_ok = now()
        self.total_ops += 1
        self._observe_hop(rtt, tm)
        # per-request timeline: attribute this hop to the generation in
        # flight (request-id contextvar). A no-op dict lookup when no
        # tier opened a timeline for the id (bench scripts, tests)
        TIMELINES.event(None, "cluster_hop", worker=self.name,
                        ms=round(rtt * 1e3, 3))
        if self.degraded_ms > 0:
            CLUSTER_HOP_DEGRADED.set(1.0 if self.gray_degraded else 0.0,
                                     worker=self.name)
        return proto.unpack_tensor(msg["x"]), cache

    def _classify(self, kind: str, detail, close: bool) -> StageFailure:
        CLUSTER_STAGE_FAILURES.inc(worker=self.name, kind=kind)
        if close:
            self.close()
        return StageFailure(kind, self.name, str(detail))

    # -- gray-failure detection --------------------------------------------

    def rtt_p95_ms(self) -> float | None:
        """Rolling p95 over the most recent GRAY_WINDOW successful ops."""
        rtts = [r for r, _ in list(self.rtts)[-GRAY_WINDOW:]]
        if not rtts:
            return None
        arr = sorted(rtts)
        return round(arr[min(int(len(arr) * 0.95), len(arr) - 1)] * 1e3, 2)

    @property
    def gray_degraded(self) -> bool:
        """True while the hop is slow-but-alive: ops succeed, but the
        rolling RTT p95 exceeds CAKE_HOP_DEGRADED_MS. Surfaces in /health
        (and the cake_cluster_hop_degraded gauge) BEFORE a hard per-op
        deadline turns the slowness into a request failure."""
        if self.degraded_ms <= 0 or len(self.rtts) < GRAY_MIN_SAMPLES:
            return False
        p95 = self.rtt_p95_ms()
        return p95 is not None and p95 > self.degraded_ms

    def _observe_hop(self, rtt: float, tm: dict):
        """Feed the per-hop histograms: whole RTT, each worker-echoed phase,
        and the unattributed remainder (wire = TCP + response write +
        scheduling)."""
        HOP_SECONDS.observe(rtt, worker=self.name, phase="rtt")
        echoed = 0.0
        for k in self._ECHO_PHASES:
            v = tm.get(f"{k}_ms")
            if v is not None:
                HOP_SECONDS.observe(v / 1e3, worker=self.name, phase=k)
                echoed += v / 1e3
        if echoed:
            HOP_SECONDS.observe(max(rtt - echoed, 0.0),
                                worker=self.name, phase="wire")

    _ECHO_PHASES = ("read", "deser", "fwd", "ser")

    def rtt_stats(self) -> dict:
        """Per-hop round-trip accounting (ref: client.rs:96-104 per-client
        send/recv timing). mean vs p50 spread flags bimodal stalls
        (Nagle/delayed-ACK class of bugs). Each RTT splits into the phases
        the worker echoes back (read_/deser_/fwd_/ser_*, with fwd including
        any in-band compile) and the remainder (wire_*: TCP + response
        write + scheduling), so a tail stall is attributable to one side
        of the link AND one phase of the worker's message handling."""
        if not self.rtts:
            return {"count": 0}

        def _stats(vals, prefix):
            arr = sorted(vals)
            return {f"{prefix}p50_ms": round(arr[len(arr) // 2] * 1e3, 2),
                    f"{prefix}p95_ms": round(arr[int(len(arr) * 0.95)] * 1e3, 2),
                    f"{prefix}mean_ms": round(sum(arr) / len(arr) * 1e3, 2),
                    f"{prefix}min_ms": round(arr[0] * 1e3, 2)}

        samples = list(self.rtts)
        rtts = [r for r, _ in samples]
        out = {"count": len(rtts), **_stats(rtts, "")}
        for k in self._ECHO_PHASES:
            vals = [t[f"{k}_ms"] / 1e3 for _, t in samples
                    if t.get(f"{k}_ms")]
            if vals:
                out.update(_stats(vals, f"{k}_"))
        # wire remainder only over samples that carry a worker timing: a
        # worker predating the echo would otherwise have its whole RTT
        # misattributed to the wire
        timed = [(r, t) for r, t in samples if t.get("fwd_ms")]
        if timed:
            out.update(_stats(
                [max(r - sum(t.get(f"{k}_ms", 0.0)
                             for k in self._ECHO_PHASES) / 1e3, 0.0)
                 for r, t in timed], "wire_"))
        return out

    def goodbye(self):
        """Best-effort clear of per-connection worker state. Teardown must
        never raise: a timeout, protocol desync, or half-dead socket here
        would otherwise propagate out of master_setup's cleanup (masking
        the original error) or abort an unrelated reset. A channel that
        fails its goodbye is closed — its stream state is unknown, and the
        next forward's `conn` failure routes it into recovery."""
        if self.sock is None:
            return
        try:
            proto.write_frame_sync(self.sock, proto.goodbye())
            proto.read_frame_sync(self.sock)
        except Exception:
            self.close()

    def close(self):
        if self.sock:
            try:
                self.sock.close()
            finally:
                self.sock = None
