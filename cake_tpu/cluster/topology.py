"""Cluster topology: name -> node map with layer ranges.

YAML format (ref: cake-core/src/cake/sharding/topology.rs:17-169, incl. the
`model.layers.0-5` range syntax and auto-assignment when `layers: []`):

    worker-a:
      host: 10.0.0.2:10128
      layers: ["model.layers.0-13"]
      memory_bytes: 17179869184     # optional capability overrides
      tflops: 394.0
      backend: tpu
"""
from __future__ import annotations

import re
from dataclasses import dataclass, field

import yaml

_RANGE_RE = re.compile(r"^(?:model\.)?layers\.(\d+)(?:-(\d+))?$")


@dataclass
class Node:
    name: str
    host: str                      # "ip:port"
    layers: list[int] = field(default_factory=list)
    memory_bytes: int = 0
    tflops: float = 0.0
    backend: str = ""
    hostname: str = ""
    os: str = ""

    @property
    def layer_range(self) -> tuple[int, int] | None:
        if not self.layers:
            return None
        lo, hi = min(self.layers), max(self.layers)
        if sorted(self.layers) != list(range(lo, hi + 1)):
            raise ValueError(f"{self.name}: non-contiguous layers {self.layers}")
        return lo, hi + 1

    @property
    def addr(self) -> tuple[str, int]:
        host, _, port = self.host.partition(":")
        return host, int(port or 10128)


def expand_layer_specs(specs: list) -> list[int]:
    """["model.layers.0-5", "layers.7"] -> [0,1,2,3,4,5,7]
    (ref: topology.rs range regex :13 + from_path expansion)."""
    out: list[int] = []
    for s in specs:
        if isinstance(s, int):
            out.append(s)
            continue
        m = _RANGE_RE.match(str(s).strip())
        if not m:
            raise ValueError(f"bad layer spec {s!r}")
        lo = int(m.group(1))
        hi = int(m.group(2)) if m.group(2) else lo
        if hi < lo:
            raise ValueError(f"descending layer range {s!r}")
        out.extend(range(lo, hi + 1))
    return out


class Topology:
    def __init__(self, nodes: dict[str, Node] | None = None):
        self.nodes: dict[str, Node] = nodes or {}

    @classmethod
    def from_dict(cls, d: dict) -> "Topology":
        nodes = {}
        for name, spec in (d or {}).items():
            nodes[name] = Node(
                name=name,
                host=str(spec.get("host", "")),
                layers=expand_layer_specs(spec.get("layers", []) or []),
                memory_bytes=int(spec.get("memory_bytes",
                                          spec.get("vram_bytes", 0)) or 0),
                tflops=float(spec.get("tflops", 0.0) or 0.0),
                backend=str(spec.get("backend", "")),
                hostname=str(spec.get("hostname", "")),
                os=str(spec.get("os", "")),
            )
        return cls(nodes)

    @classmethod
    def from_path(cls, path: str) -> "Topology":
        with open(path) as f:
            return cls.from_dict(yaml.safe_load(f) or {})

    def to_dict(self) -> dict:
        out = {}
        for name, n in self.nodes.items():
            lr = n.layer_range
            out[name] = {
                "host": n.host,
                "layers": ([f"model.layers.{lr[0]}-{lr[1] - 1}"] if lr else []),
                "memory_bytes": n.memory_bytes,
                "tflops": n.tflops,
                "backend": n.backend,
            }
        return out

    def get_node_for_layer(self, layer: int) -> Node | None:
        """(ref: topology.rs get_node_for_layer:184-193)"""
        for n in self.nodes.values():
            if layer in n.layers:
                return n
        return None

    def assigned_layers(self) -> set[int]:
        out: set[int] = set()
        for n in self.nodes.values():
            overlap = out & set(n.layers)
            if overlap:
                raise ValueError(f"layer(s) {sorted(overlap)} assigned twice")
            out |= set(n.layers)
        return out

    def needs_auto_assignment(self) -> bool:
        return any(not n.layers for n in self.nodes.values())

    def auto_assign_layers(self, strategy, num_layers: int,
                           layer_bytes: list[int]):
        """Fill empty `layers: []` nodes via the Strategy
        (ref: topology.rs auto_assign_layers_with_strategy:225-263)."""
        from .strategy import WorkerCapacity
        caps = [WorkerCapacity(name=n.name, memory_bytes=n.memory_bytes,
                               tflops=n.tflops)
                for n in self.nodes.values() if not n.layers]
        taken = self.assigned_layers()
        free = [i for i in range(num_layers) if i not in taken]
        plan = strategy.assign_layers(caps, free, layer_bytes)
        for name, layers in plan.items():
            self.nodes[name].layers = layers
