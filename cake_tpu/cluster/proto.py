"""Wire protocol: length-prefixed binary framing + msgpack-encoded control
messages + raw-buffer tensor payloads.

Same three-plane shape as the reference's custom protocol (ref:
cake-core/src/cake/sharding/proto/{mod.rs,message.rs}: u32 magic + u32 len
framing with a 512 MB cap, speedy-serialized Message enum, RawTensor with
dtype tag + shape) — re-designed for this stack: msgpack for the control
fields (self-describing, zero-copy bin for tensor bytes) and the TPU dtype
set (bf16, f8e4m3) in the tag table (utils/dtypes.py WIRE_DTYPES).

Message types (parity with ref message.rs:191-247):
  hello, worker_info          - handshake + capability report
  layer_assignment, ack       - setup
  model_chunk, model_done, model_resume - weight streaming (zstd + CRC32)
  worker_ready, worker_error  - readiness / per-op failure
  forward                     - activation shipping for a contiguous layer
                                range in ONE round trip (subsumes the
                                reference's SingleOp + Batch: a worker range
                                is always one jit call here)
  tensor                      - result tensor
  goodbye                     - clear per-connection state

The byte-level framing (pack/unpack, CRC32) also exists natively in
csrc/cakekit.cpp; this module uses it when built.
"""
from __future__ import annotations

import asyncio
import struct
import zlib
from typing import Any

import msgpack
import numpy as np

from ..obs import now
from ..utils.dtypes import WIRE_DTYPES, WIRE_TAGS, from_numpy_bytes

MAGIC = 0x54504B31          # "TPK1"
MAX_FRAME = 512 * 1024 * 1024   # ref: proto/mod.rs 512 MB cap
_HDR = struct.Struct("<II")

# fault-injection seam (cluster/faults.py installs a FaultInjector here;
# None in production — one attribute check per frame). Write hooks fire
# before the frame hits the wire, read hooks see (and may corrupt) the
# raw payload before decode.
FAULT_HOOK = None


class ProtocolError(Exception):
    pass


def _parse_header_py(hdr: bytes) -> int:
    magic, length = _HDR.unpack(hdr)
    if magic != MAGIC:
        raise ProtocolError(f"bad magic {magic:#x}")
    if length > MAX_FRAME:
        raise ProtocolError(f"frame too large: {length}")
    return length


def _parse_header_native(hdr: bytes) -> int:
    from ..utils import cakekit
    n = cakekit.frame_parse(hdr, MAGIC, MAX_FRAME)
    if n == -1:
        raise ProtocolError(f"bad magic {hdr[:4].hex()}")
    if n == -2:
        raise ProtocolError("frame too large")
    return n


# resolve once at import: an 8-byte header parse must not pay a per-frame
# import + availability probe
try:
    from ..utils import cakekit as _ck
    _parse_header = _parse_header_native if _ck.available() else _parse_header_py
except ImportError:
    _parse_header = _parse_header_py


# -- tensors ----------------------------------------------------------------

def pack_tensor(arr) -> dict:
    """numpy/jax array -> wire dict with dtype tag + shape + raw bytes
    (ref: RawTensor::from_tensor, zero-copy where possible)."""
    a = np.asarray(arr)
    name = a.dtype.name if a.dtype.name in WIRE_TAGS else str(a.dtype)
    if name not in WIRE_TAGS:
        raise ProtocolError(f"unsupported wire dtype {a.dtype}")
    return {"dt": WIRE_TAGS[name], "sh": list(a.shape),
            "d": a.tobytes()}


def unpack_tensor(obj: dict) -> np.ndarray:
    dt = WIRE_DTYPES.get(obj["dt"])
    if dt is None:
        raise ProtocolError(f"unknown dtype tag {obj['dt']}")
    return from_numpy_bytes(obj["d"], dt, tuple(obj["sh"]))


# -- framing ----------------------------------------------------------------

def encode_frame(msg: dict) -> bytes:
    payload = msgpack.packb(msg, use_bin_type=True)
    if len(payload) > MAX_FRAME:
        raise ProtocolError(f"frame too large: {len(payload)}")
    return _HDR.pack(MAGIC, len(payload)) + payload


def decode_payload(payload: bytes) -> dict:
    try:
        return msgpack.unpackb(payload, raw=False)
    except Exception as e:
        # garbage on the wire (bit flips, desynced stream) must surface as
        # a classifiable protocol failure, not a raw msgpack internal
        raise ProtocolError(f"undecodable frame: {e}") from e


async def read_frame(reader: asyncio.StreamReader) -> dict:
    return (await read_frame_timed(reader))[0]


async def read_frame_timed(reader: asyncio.StreamReader
                           ) -> tuple[dict, float, float]:
    """read_frame that also reports (payload-read seconds, decode seconds).

    The clock starts AFTER the header arrives, so idle time waiting for the
    next request is excluded — read_s is genuinely "time to pull this
    frame's bytes off the socket" (ref: worker.rs:533-543 per-message
    `read` phase)."""
    hdr = await reader.readexactly(_HDR.size)
    length = _parse_header(hdr)
    t0 = now()
    payload = await reader.readexactly(length)
    t1 = now()
    if FAULT_HOOK is not None:
        payload = FAULT_HOOK.on_read(reader, payload)
    msg = decode_payload(payload)
    return msg, t1 - t0, now() - t1


async def write_frame(writer: asyncio.StreamWriter, msg: dict):
    if FAULT_HOOK is not None:
        FAULT_HOOK.on_write(writer, msg)
    writer.write(encode_frame(msg))
    await writer.drain()


def read_frame_sync(sock) -> dict:
    buf = b""
    while len(buf) < _HDR.size:
        chunk = sock.recv(_HDR.size - len(buf))
        if not chunk:
            raise ConnectionError("socket closed mid-header")
        buf += chunk
    length = _parse_header(buf)
    chunks = []
    got = 0
    while got < length:
        chunk = sock.recv(min(1 << 20, length - got))
        if not chunk:
            raise ConnectionError("socket closed mid-frame")
        chunks.append(chunk)
        got += len(chunk)
    payload = b"".join(chunks)
    if FAULT_HOOK is not None:
        payload = FAULT_HOOK.on_read(sock, payload)
    return decode_payload(payload)


def write_frame_sync(sock, msg: dict):
    if FAULT_HOOK is not None:
        FAULT_HOOK.on_write(sock, msg)
    sock.sendall(encode_frame(msg))


# -- message constructors ---------------------------------------------------

def hello(name: str, version: str = "1") -> dict:
    return {"t": "hello", "name": name, "v": version}


def worker_info(name: str, layers: list[int], backend: str, device: str,
                memory_bytes: int, tflops: float,
                heartbeat_age_s: float | None = None,
                ops: int | None = None) -> dict:
    """heartbeat_age_s: seconds since this worker last handled any message
    on its own monotonic clock (clocks aren't synchronized across nodes, so
    an AGE is the only meaningful cross-node liveness field); ops: forwards
    served since start."""
    out = {"t": "worker_info", "name": name, "layers": layers,
           "backend": backend, "device": device,
           "memory_bytes": memory_bytes, "tflops": tflops}
    if heartbeat_age_s is not None:
        out["heartbeat_age_s"] = round(heartbeat_age_s, 3)
    if ops is not None:
        out["ops"] = int(ops)
    return out


def layer_assignment(model_id: str, arch: str, config: dict,
                     start: int, end: int, dtype: str,
                     cache_key: str, push_weights: bool,
                     fp8_native: bool = False) -> dict:
    return {"t": "layer_assignment", "model_id": model_id, "arch": arch,
            "config": config, "start": start, "end": end, "dtype": dtype,
            "cache_key": cache_key, "push_weights": push_weights,
            "fp8_native": fp8_native}


def model_chunk(file_name: str, index: int, total: int, data: bytes,
                crc32: int, compressed: bool, offset: int) -> dict:
    return {"t": "model_chunk", "file": file_name, "i": index, "n": total,
            "d": data, "crc": crc32, "z": compressed, "off": offset}


def model_done() -> dict:
    return {"t": "model_done"}


def model_resume(file_name: str, offset: int) -> dict:
    """Partial-transfer resume point (ref: ModelDataResume message.rs:238-242)."""
    return {"t": "model_resume", "file": file_name, "off": offset}


def worker_ready(ok: bool = True, error: str | None = None) -> dict:
    return {"t": "worker_ready", "ok": ok, "error": error}


def worker_error(message: str) -> dict:
    return {"t": "worker_error", "error": message}


def forward(x, pos0: int, valid_len: int | None, request_id: int = 0,
            kv_hint: int | None = None) -> dict:
    """kv_hint: the master's current KV bucket — workers size their
    per-connection cache to max(pos0 + width, kv_hint) so growth reallocs
    stay bucket-aligned across all nodes."""
    out = {"t": "forward", "x": pack_tensor(x), "pos0": int(pos0),
           "valid_len": None if valid_len is None else int(valid_len),
           "rid": request_id}
    if kv_hint is not None:
        out["kv"] = int(kv_hint)
    return out


def tensor_result(arr, request_id: int = 0,
                  fwd_ms: float | None = None,
                  timing: dict | None = None) -> dict:
    """fwd_ms: worker-side compute time for this request (includes any
    in-band XLA compile) — lets the master separate wire time from worker
    time in its per-hop RTT stats.

    timing: optional per-phase echo {read_ms, deser_ms, fwd_ms, ser_ms}
    (ref: worker.rs:533-543's read/load/fwd/ser/write breakdown) — the
    master subtracts the echoed phases from its observed RTT to attribute
    the remainder to the wire (TCP + response write + scheduling).

    arr may be a numpy/jax array OR an already-packed wire dict (so the
    worker can time pack_tensor as its `ser` phase without double-packing).
    """
    packed = arr if isinstance(arr, dict) and "dt" in arr else pack_tensor(arr)
    out = {"t": "tensor", "x": packed, "rid": request_id}
    if fwd_ms is not None:
        out["fwd_ms"] = round(fwd_ms, 3)
    if timing:
        out["tm"] = {k: round(float(v), 3) for k, v in timing.items()}
    return out


def goodbye() -> dict:
    return {"t": "goodbye"}


def ack() -> dict:
    return {"t": "ack"}


def crc32(data: bytes) -> int:
    try:
        from ..utils import cakekit
        if cakekit.available():
            return cakekit.crc32(data)
    except ImportError:
        pass
    return zlib.crc32(data) & 0xFFFFFFFF
