"""Layer-assignment strategy: TFLOPS-proportional contiguous ranges capped by
per-device memory, with master-overflow redistribution
(ref: cake-core/src/cake/sharding/default.rs:10-170 DefaultStrategy +
sharding/mod.rs:37-98 Strategy/WorkerCapacity/memory reserves).

Memory reserves by backend (fraction withheld from capacity; ref values:
5% CUDA / 28% unified / 20% CPU — TPU gets 10% for XLA scratch + compiled
program buffers):
"""
from __future__ import annotations

from dataclasses import dataclass

MEMORY_RESERVE = {"tpu": 0.10, "cuda": 0.05, "metal": 0.28, "cpu": 0.20}
DEFAULT_RESERVE = 0.15


@dataclass
class WorkerCapacity:
    name: str
    memory_bytes: int
    tflops: float
    backend: str = "tpu"

    @property
    def usable_bytes(self) -> int:
        r = MEMORY_RESERVE.get(self.backend, DEFAULT_RESERVE)
        return int(self.memory_bytes * (1.0 - r))


class Strategy:
    """Pluggable assignment interface (ref: sharding/mod.rs:37-52)."""

    def assign_layers(self, workers: list[WorkerCapacity], layers: list[int],
                      layer_bytes: list[int]) -> dict[str, list[int]]:
        raise NotImplementedError


class DefaultStrategy(Strategy):
    """Contiguous ranges proportional to TFLOPS, each capped by the worker's
    usable memory; layers that fit nowhere stay unassigned (the master keeps
    them — ref: default.rs master-overflow redistribution)."""

    def assign_layers(self, workers, layers, layer_bytes):
        plan: dict[str, list[int]] = {w.name: [] for w in workers}
        if not workers or not layers:
            return plan
        total_tflops = sum(max(w.tflops, 1e-9) for w in workers)
        remaining = list(layers)
        # strongest workers first: they take their proportional share from
        # the front so ranges stay contiguous
        order = sorted(workers, key=lambda w: -w.tflops)
        n_total = len(layers)
        for idx, w in enumerate(order):
            if not remaining:
                break
            share = max(w.tflops, 1e-9) / total_tflops
            want = max(1, round(share * n_total))
            if idx == len(order) - 1:
                want = len(remaining)          # last worker offered the rest
            take: list[int] = []
            used = 0
            budget = w.usable_bytes if w.memory_bytes else None
            for li in remaining[:want]:
                b = layer_bytes[li] if li < len(layer_bytes) else 0
                if budget is not None and used + b > budget:
                    break
                take.append(li)
                used += b
            plan[w.name] = take
            remaining = remaining[len(take):]
        return plan


def estimate_layer_bytes(storage, num_layers: int,
                         quant_factor: float = 1.0) -> list[int]:
    """Per-layer parameter bytes from safetensors headers — no tensor data
    read (ref: default.rs:189-307 layer-size estimation; quant_factor is the
    dequant VRAM expansion, ref: sharding/mod.rs:262-273)."""
    from ..utils.safetensors_io import layer_of
    sizes = [0] * num_layers
    for name in storage.names():
        li = layer_of(name)
        if li is not None and li < num_layers:
            sizes[li] += storage.nbytes(name)
    return [int(s * quant_factor) for s in sizes]
