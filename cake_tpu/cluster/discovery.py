"""Zero-config cluster discovery over UDP broadcast.

Protocol (same shape as ref: cake-core/src/cake/sharding/discovery.rs —
magic-tagged JSON query filtered by a SHA-256(cluster_key) prefix, unicast
JSON reply with device capabilities; ref lines 13-16, 75-84, 370-495):

  master -> broadcast:  {"magic": "CTPU", "hash": <8-hex>, "q": "discover"}
  worker -> unicast:    {"magic": "CTPU", "hash": ..., "name": ...,
                         "port": <service port>, "caps": {...}}

Capability detection is TPU-first: chip kind -> (TFLOPS, HBM) table via
jax.devices(), CPU fallback from /proc/meminfo (ref detect_gpus:91-162
does the same with nvidia-smi / sysctl).
"""
from __future__ import annotations

import json
import os
import socket
import threading
import time

from .auth import cluster_hash

DISCOVERY_PORT = 18337
MAGIC = "CTPU"
MAX_DATAGRAM = 4096

# chip kind -> (bf16 TFLOPS, HBM bytes) — public spec numbers
TPU_SPECS = {
    "TPU v2": (46.0, 8 << 30),
    "TPU v3": (123.0, 16 << 30),
    "TPU v4": (275.0, 32 << 30),
    "TPU v5 lite": (394.0, 16 << 30),
    "TPU v5e": (394.0, 16 << 30),
    "TPU v5p": (459.0, 95 << 30),
    "TPU v6 lite": (918.0, 32 << 30),
    "TPU v6e": (918.0, 32 << 30),
}


def detect_capabilities() -> dict:
    """Report backend/devices/memory/tflops for this host."""
    try:
        import jax
        devs = jax.devices()
        kind = devs[0].device_kind
        if devs[0].platform == "tpu":
            for prefix, (tf, hbm) in TPU_SPECS.items():
                if kind.startswith(prefix):
                    return {"backend": "tpu", "device": kind,
                            "n_devices": len(devs),
                            "memory_bytes": hbm * len(devs),
                            "tflops": tf * len(devs)}
            return {"backend": "tpu", "device": kind, "n_devices": len(devs),
                    "memory_bytes": (16 << 30) * len(devs),
                    "tflops": 200.0 * len(devs)}
    except Exception:
        pass
    return {"backend": "cpu", "device": "cpu", "n_devices": 1,
            "memory_bytes": _host_memory_bytes(), "tflops": 1.0}


def _host_memory_bytes() -> int:
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    return 8 << 30


def get_broadcast_addresses() -> list[str]:
    """Interface-directed broadcast addresses + limited broadcast + loopback
    (ref: get_broadcast_addresses:499-592). Parsed from /proc/net/route +
    per-interface ioctl-free heuristics; always includes the fallbacks."""
    addrs = {"255.255.255.255", "127.0.0.1"}
    try:
        import subprocess
        out = subprocess.run(["ip", "-json", "addr"], capture_output=True,
                             timeout=2, text=True)
        if out.returncode == 0:
            for iface in json.loads(out.stdout):
                for a in iface.get("addr_info", []):
                    if a.get("family") == "inet" and a.get("broadcast"):
                        addrs.add(a["broadcast"])
    except Exception:
        pass
    return sorted(addrs)


class WorkerAdvertiser:
    """Background UDP listener answering discovery queries
    (ref: advertise_worker:429-495)."""

    def __init__(self, name: str, cluster_key: str, service_port: int,
                 discovery_port: int = DISCOVERY_PORT, caps: dict | None = None):
        self.name = name
        self.hash = cluster_hash(cluster_key)
        self.service_port = service_port
        self.discovery_port = discovery_port
        self.caps = caps or detect_capabilities()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._sock: socket.socket | None = None

    def start(self):
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        except OSError:
            pass
        self._sock.bind(("0.0.0.0", self.discovery_port))
        self._sock.settimeout(0.5)
        self._thread = threading.Thread(target=self._serve, daemon=True,
                                        name=f"advertiser-{self.name}")
        self._thread.start()
        return self

    def _serve(self):
        while not self._stop.is_set():
            try:
                data, addr = self._sock.recvfrom(MAX_DATAGRAM)
            except socket.timeout:
                continue
            except OSError:
                break
            try:
                msg = json.loads(data)
            except ValueError:
                continue
            if msg.get("magic") != MAGIC or msg.get("hash") != self.hash \
                    or msg.get("q") != "discover":
                continue
            reply = {"magic": MAGIC, "hash": self.hash, "name": self.name,
                     "port": self.service_port, "caps": self.caps,
                     "hostname": socket.gethostname(), "os": os.uname().sysname}
            try:
                self._sock.sendto(json.dumps(reply).encode(), addr)
            except OSError:
                pass

    def stop(self):
        self._stop.set()
        if self._sock:
            self._sock.close()
        if self._thread:
            self._thread.join(timeout=2)


def discover_workers(cluster_key: str, timeout: float = 2.0,
                     discovery_port: int = DISCOVERY_PORT,
                     expected: int | None = None) -> list[dict]:
    """Broadcast a query and collect worker replies
    (ref: discover_workers:604+). Returns a list of reply dicts with the
    sender ip added as "host"."""
    h = cluster_hash(cluster_key)
    query = json.dumps({"magic": MAGIC, "hash": h, "q": "discover"}).encode()
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_BROADCAST, 1)
    sock.settimeout(0.25)
    found: dict[tuple, dict] = {}
    baddrs = get_broadcast_addresses()      # once: spawns an `ip` subprocess
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        for baddr in baddrs:
            try:
                sock.sendto(query, (baddr, discovery_port))
            except OSError:
                continue
        while True:
            try:
                data, addr = sock.recvfrom(MAX_DATAGRAM)
            except socket.timeout:
                break
            except OSError:
                break
            try:
                msg = json.loads(data)
            except ValueError:
                continue
            if msg.get("magic") != MAGIC or msg.get("hash") != h \
                    or "name" not in msg:
                continue
            msg["host"] = addr[0]
            found[(msg["name"],)] = msg
        if expected is not None and len(found) >= expected:
            break
    sock.close()
    return list(found.values())
