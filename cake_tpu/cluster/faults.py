"""Deterministic fault injection for the cluster wire.

Chaos testing needs failures that land on an exact operation, not "pull a
cable and hope": this module installs a hook into proto's framed
read/write (`proto.FAULT_HOOK` — a single attribute check per frame when
disabled, nothing on import) that can kill, stall, delay, or corrupt any
hop after a chosen number of forward ops.

Channels are labeled when they are created (client.RemoteStage tags its
socket, worker.WorkerServer tags each connection's streams):

    w0      the master's channel TO worker w0 (master side)
    @w0     a connection AT worker w0 (worker side)

A fault plan is a comma-separated list of `target:key=val[;key=val...]`
clauses; `target` is an fnmatch pattern over labels (omitted = `*`).
Plans come from the `CAKE_FAULT_PLAN` env var (read when this module is
first imported) or `install()` in tests. Keys:

    drop_after_ops=N     ops 1..N succeed; op N+1 severs the connection
    delay_ms=D           every op sleeps D ms (gray failure)
    stall_after_ops=N    ops 1..N clean; op N+1 stalls (default 0: the
                         first op) — same after-N semantics as drop/crash
    stall_once_ms=S      ONE op stalls S ms (per-op-deadline trip), once
    corrupt_after_ops=N  op N+1's response frame is corrupted, once
    crash_after_ops=N    op N+1 hard-kills the whole worker (worker-side
                         labels only), once

An "op" is one forward request crossing the channel (master write of a
`forward` frame / worker read of one); one-shot faults (drop, stall,
corrupt, crash) fire exactly once per plan entry, so a recovered channel
is not re-killed — the deterministic single-fault the bit-identical
recovery tests pin. delay_ms keeps applying across reconnects (a gray
worker stays gray until the plan is cleared).

The worker-side sleep blocks the worker's event loop by design: a stalled
event loop IS the gray failure being simulated.
"""
from __future__ import annotations

import fnmatch
import logging
import time
import weakref
from dataclasses import dataclass, field

from .. import knobs
from . import proto

log = logging.getLogger("cake_tpu.faults")

# channel object -> label; weak so dead sockets/streams don't accumulate
_labels: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
# worker-side crash callbacks, keyed by worker-side label ("@name")
_crash_cbs: dict[str, object] = {}


def tag(channel, label: str) -> None:
    """Label a channel (socket / StreamReader / StreamWriter) so fault
    plans can target it. Always safe to call; one weak-dict write."""
    try:
        _labels[channel] = label
    except TypeError:
        pass        # non-weakref-able channel: untargetable, not an error


def register_crash(label: str, callback) -> None:
    """Register the hard-kill callback for a worker-side label; invoked
    (on the worker's event loop thread) when crash_after_ops trips."""
    _crash_cbs[label] = callback


def unregister_crash(label: str) -> None:
    _crash_cbs.pop(label, None)


@dataclass
class HopFaults:
    """Fault state for one plan clause (one target pattern)."""

    target: str = "*"
    drop_after_ops: int | None = None
    delay_ms: float = 0.0
    stall_once_ms: float = 0.0
    stall_after_ops: int = 0
    corrupt_after_ops: int | None = None
    crash_after_ops: int | None = None
    ops: int = 0
    fired: set = field(default_factory=set)

    _INT_KEYS = ("drop_after_ops", "corrupt_after_ops", "crash_after_ops",
                 "stall_after_ops")
    _FLOAT_KEYS = ("delay_ms", "stall_once_ms")

    @classmethod
    def parse(cls, clause: str) -> "HopFaults":
        """`[target:]k=v[;k=v...]` — target omitted means every hop."""
        clause = clause.strip()
        target = "*"
        if ":" in clause.split("=", 1)[0]:
            target, clause = clause.split(":", 1)
        hf = cls(target=target.strip() or "*")
        for part in filter(None, (p.strip() for p in clause.split(";"))):
            if "=" not in part:
                raise ValueError(f"fault clause needs key=value: {part!r}")
            k, v = (s.strip() for s in part.split("=", 1))
            if k in cls._INT_KEYS:
                setattr(hf, k, int(v))
            elif k in cls._FLOAT_KEYS:
                setattr(hf, k, float(v))
            else:
                raise ValueError(f"unknown fault key {k!r}")
        return hf

    def matches(self, label: str) -> bool:
        return fnmatch.fnmatch(label, self.target)


class FaultInjector:
    """The installed proto hook: dispatches frames to matching plan
    clauses. State (op counters, one-shot flags) lives here, so it
    survives the reconnects it provokes."""

    def __init__(self, plans: list[HopFaults]):
        self.plans = plans

    def _plans_for(self, channel):
        label = _labels.get(channel)
        if label is None:
            return label, ()
        return label, [p for p in self.plans if p.matches(label)]

    # -- proto seam ---------------------------------------------------------

    def on_write(self, channel, msg: dict) -> None:
        """Before a frame is written. Master-side data-plane ops are
        counted here (one `forward` per op)."""
        if msg.get("t") != "forward":
            return
        label, plans = self._plans_for(channel)
        for p in plans:
            p.ops += 1
            self._apply(p, label, channel)

    def on_read(self, channel, payload: bytes) -> bytes:
        """After a frame's payload is read, before decode. Worker-side
        ops are counted here; corruption happens here on either side."""
        label, plans = self._plans_for(channel)
        if not plans:
            return payload
        t = None
        if label.startswith("@"):
            # only worker-side op counting needs the message type — don't
            # pay a second full msgpack decode of every multi-MB tensor
            # frame on master-side channels
            try:
                t = proto.decode_payload(payload).get("t")
            except Exception:
                t = None
        for p in plans:
            if label.startswith("@") and t == "forward":
                p.ops += 1
                self._apply(p, label, channel)
            if (p.corrupt_after_ops is not None
                    and p.ops > p.corrupt_after_ops
                    and "corrupt" not in p.fired):
                p.fired.add("corrupt")
                log.warning("fault[%s]: corrupting frame after op %d",
                            label, p.ops)
                payload = bytes(b ^ 0xFF for b in payload[:16]) + payload[16:]
        return payload

    # -- fault actions ------------------------------------------------------

    def _apply(self, p: HopFaults, label: str, channel) -> None:
        if p.delay_ms > 0:
            time.sleep(p.delay_ms / 1e3)
        if (p.stall_once_ms > 0 and p.ops > p.stall_after_ops
                and "stall" not in p.fired):
            p.fired.add("stall")
            log.warning("fault[%s]: stalling %.0f ms at op %d", label,
                        p.stall_once_ms, p.ops)
            time.sleep(p.stall_once_ms / 1e3)
        if (p.crash_after_ops is not None and p.ops > p.crash_after_ops
                and "crash" not in p.fired):
            p.fired.add("crash")
            log.warning("fault[%s]: crashing worker at op %d", label, p.ops)
            cb = _crash_cbs.get(label)
            if cb is not None:
                cb()
            raise ConnectionError(f"fault injected: worker {label} crashed")
        if (p.drop_after_ops is not None and p.ops > p.drop_after_ops
                and "drop" not in p.fired):
            p.fired.add("drop")
            log.warning("fault[%s]: dropping connection at op %d", label,
                        p.ops)
            self._sever(channel)
            raise ConnectionError(f"fault injected: {label} connection "
                                  "dropped")

    @staticmethod
    def _sever(channel) -> None:
        try:
            channel.close()
        except Exception:
            pass


def parse_plan(spec: str) -> FaultInjector:
    clauses = [c for c in (s.strip() for s in spec.split(",")) if c]
    if not clauses:
        raise ValueError("empty fault plan")
    return FaultInjector([HopFaults.parse(c) for c in clauses])


def install(spec_or_injector) -> FaultInjector:
    """Activate a fault plan process-wide (proto.FAULT_HOOK)."""
    inj = (spec_or_injector if isinstance(spec_or_injector, FaultInjector)
           else parse_plan(spec_or_injector))
    proto.FAULT_HOOK = inj
    log.warning("fault plan installed: %d clause(s)", len(inj.plans))
    return inj


def active() -> FaultInjector | None:
    return proto.FAULT_HOOK


def clear() -> None:
    proto.FAULT_HOOK = None


# env-driven activation: `CAKE_FAULT_PLAN="w0:drop_after_ops=5"` takes
# effect the moment the cluster plane loads (client.py and worker.py both
# import this module to tag their channels)
_env_plan = knobs.get_str("CAKE_FAULT_PLAN")
if _env_plan:
    install(_env_plan)
