"""Mutual HMAC-SHA256 challenge-response over the raw socket, BEFORE any
protocol framing (ref: cake-core/src/cake/sharding/auth.rs:1-118).

Both sides prove knowledge of the cluster pre-shared key without sending it:
  worker  -> master: 32-byte random challenge Cw
  master  -> worker: HMAC(key, Cw) || 32-byte challenge Cm
  worker  -> master: HMAC(key, Cm)          (after verifying, constant-time)
No confidentiality — like the reference, this authenticates membership only.
"""
from __future__ import annotations

import asyncio
import hashlib
import hmac
import os

CHALLENGE_LEN = 32
MAC_LEN = 32
AUTH_TIMEOUT = 10.0


class AuthError(Exception):
    pass


def _mac(key: str, challenge: bytes) -> bytes:
    return hmac.new(key.encode(), challenge, hashlib.sha256).digest()


def cluster_hash(cluster_key: str) -> str:
    """8-hex-char cluster id derived from the key — used as the discovery
    filter and cache-key component (ref: discovery.rs cluster_hash:75-84)."""
    return hashlib.sha256(cluster_key.encode()).hexdigest()[:8]


async def _read(reader, n: int, what: str) -> bytes:
    """Read exactly n bytes; EOF/timeout during the handshake IS an auth
    failure (the peer bailed after a bad MAC)."""
    try:
        return await asyncio.wait_for(reader.readexactly(n), AUTH_TIMEOUT)
    except (asyncio.IncompleteReadError, ConnectionError) as e:
        raise AuthError(f"peer closed during {what}") from e
    except (TimeoutError, asyncio.TimeoutError) as e:
        raise AuthError(f"timeout waiting for {what}") from e


async def authenticate_as_worker(reader: asyncio.StreamReader,
                                 writer: asyncio.StreamWriter,
                                 cluster_key: str):
    """Worker side: challenge the master, answer the master's challenge."""
    cw = os.urandom(CHALLENGE_LEN)
    writer.write(cw)
    await writer.drain()
    data = await _read(reader, MAC_LEN + CHALLENGE_LEN, "master response")
    their_mac, cm = data[:MAC_LEN], data[MAC_LEN:]
    if not hmac.compare_digest(their_mac, _mac(cluster_key, cw)):
        raise AuthError("master failed authentication")
    writer.write(_mac(cluster_key, cm))
    await writer.drain()


async def authenticate_as_master(reader: asyncio.StreamReader,
                                 writer: asyncio.StreamWriter,
                                 cluster_key: str):
    """Master side: answer the worker's challenge, then challenge back."""
    cw = await _read(reader, CHALLENGE_LEN, "worker challenge")
    cm = os.urandom(CHALLENGE_LEN)
    writer.write(_mac(cluster_key, cw) + cm)
    await writer.drain()
    their_mac = await _read(reader, MAC_LEN, "worker MAC")
    if not hmac.compare_digest(their_mac, _mac(cluster_key, cm)):
        raise AuthError("worker failed authentication")
