"""Master node: cluster bring-up and distributed text generation.

Bring-up (ref: cake-core/src/cake/sharding/mod.rs master_setup:162-506):
discover workers -> estimate per-layer bytes from safetensors headers ->
TFLOPS-proportional assignment -> connect + authenticate + assign ->
stream the worker's layer-subset weights (zstd+CRC32, content-keyed cache)
-> await worker_ready. The master keeps unassigned layers, the embeddings
and the head (ref: Context VarBuilder excluding worker layers).

Generation (ref: master.rs:109-171 + text_model.rs forward loop): the stage
chain [local ranges | remote workers] runs per token; each local range is
one jit call, each remote range one TCP round trip; embeddings, head and
sampling stay on the master device.
"""
from __future__ import annotations

import functools
import logging
import os
import random
import threading
import time
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from .. import knobs
from ..models.common.cache import init_cache
from ..models.common.config import ModelConfig
from ..models.common.text_model import (PREFILL_BUCKETS, PREFILL_CHUNK,
                                        LocalStage, Token,
                                        _observe_generation, bucket_for,
                                        check_prefill_bounds,
                                        initial_kv_bucket,
                                        select_flash_mode)
from ..models.common.layers import (embed_tokens, forward_layers,
                                    lm_head_logits)
from ..obs import (CLUSTER_DEGRADED, CLUSTER_RECONNECTS, CLUSTER_REPLAYS,
                   RECORDER, now)
from ..ops.sampling import SamplingConfig, push_recent_token, sample
from .auth import cluster_hash
from .client import RemoteStage, StageFailure
from .strategy import DefaultStrategy, WorkerCapacity, estimate_layer_bytes
from .topology import Topology
from . import proto, transfer

log = logging.getLogger("cake_tpu.master")

# cap on the recovery reconnect backoff — failures past the first few
# retries are probed by the background restore loop instead
RECOVERY_BACKOFF_CAP_S = 10.0


class ClusterDegradedError(RuntimeError):
    """A worker is down and the recovery retry budget is exhausted: the
    request fails FAST (instead of hanging on reconnect loops), /health
    answers 503, and the background restore loop keeps probing the dead
    worker so a later request can succeed."""


@dataclass
class Stage:
    kind: str                  # "local" | "remote"
    start: int
    end: int
    runner: object             # LocalStage or RemoteStage
    cache: object = None       # local KV cache (remote keeps its own)


class DistributedTextModel:
    """TextModel over a stage chain. Single local stage == plain TextModel
    semantics; remote stages hop hidden states over the wire."""


    def __init__(self, cfg: ModelConfig, master_params: dict,
                 stages: list[Stage], tokenizer=None, dtype=jnp.bfloat16,
                 max_cache_len: int = 2048, seed: int = 42, mesh=None,
                 prefill_chunk: int | None = None,
                 recovery_retries: int | None = None,
                 recovery_backoff_s: float | None = None,
                 restore_interval_s: float | None = None):
        self.cfg = cfg
        self.stages = stages
        # mid-stream fault tolerance: how many quarantine->reconnect->
        # replay cycles one generation may spend before failing fast
        # (CAKE_RECOVERY_RETRIES), the base of the capped-exponential
        # jittered reconnect backoff (CAKE_RECOVERY_BACKOFF_S), and the
        # background restore loop's probe interval once degraded
        # (CAKE_RESTORE_INTERVAL_S)
        self.recovery_retries = recovery_retries if recovery_retries \
            is not None else knobs.get("CAKE_RECOVERY_RETRIES")
        self.recovery_backoff_s = recovery_backoff_s if recovery_backoff_s \
            is not None else knobs.get("CAKE_RECOVERY_BACKOFF_S")
        self.restore_interval_s = restore_interval_s if restore_interval_s \
            is not None else knobs.get("CAKE_RESTORE_INTERVAL_S")
        # serializes channel revival: _recover and the restore loop must
        # not reestablish() the same worker concurrently. NEVER guards
        # the flags below — reestablish() spans reconnect + weight
        # re-push + wait_ready (minutes), and a flag read blocking on it
        # would break generate()'s fail-fast contract
        self._revive_lock = threading.Lock()
        # guards the degraded flag + restore-thread handle (request
        # threads flip the flag, the restore loop clears it; the
        # lock-discipline lint enforces the guarded-by annotations).
        # Held only for flag reads/writes — always cheap, never across
        # network or device work
        self._degraded_lock = threading.Lock()
        # {worker, since, error} while a worker is quarantined with the
        # retry budget exhausted; /health 503s on it and generate() fails
        # fast until the restore loop revives the worker. Out-of-class
        # readers go through degraded_info()
        self.degraded: dict | None = None           # guarded-by: self._degraded_lock
        self._restore_thread: threading.Thread | None = None  # guarded-by: self._degraded_lock
        self._recoveries = 0            # per-generation, surfaced in stats
        self._replays = 0
        self._gen_prompt: list[int] = []   # recorded token sequence the
        self._gen_out: list[int] = []      # rebuild-by-replay replays
        self.tokenizer = tokenizer
        self.dtype = dtype
        # clamp like TextModel: positions past max_seq_len would silently
        # mis-index the rope tables (out-of-range gathers clamp, not raise)
        self.max_cache_len = min(max_cache_len, cfg.max_seq_len)
        self.mesh = mesh
        # pipelined-prefill chunk width; PREFILL_CHUNK is what workers
        # compile-warm, so overriding trades a first-request in-band
        # compile for the chosen width
        self.prefill_chunk = prefill_chunk or PREFILL_CHUNK
        self._last_prefill: dict = {}
        self._kv_len = self.max_cache_len   # reset()/generate() re-bucket
        # embed + head replicate over the in-host tp mesh so the hidden
        # state entering/leaving the sharded local stages is replicated
        from ..parallel.sharding import shard_params
        self.params = shard_params(master_params, mesh)  # embed + head
        self._rng = jax.random.PRNGKey(seed)

        @jax.jit
        def _embed(params, tokens):
            return embed_tokens(cfg, params, tokens)

        @jax.jit
        def _head(params, x_last):
            return lm_head_logits(cfg, params, x_last)[:, 0]

        self._embed = _embed
        self._head = _head
        self._sample = jax.jit(
            lambda l, k, rec, scfg: sample(l, k, scfg, rec),
            static_argnames=("scfg",))

    # -- lifecycle ----------------------------------------------------------

    def reset(self, kv_len: int | None = None):
        """Fresh caches everywhere; local stage caches start at the given
        cache-length bucket and grow bucket-by-bucket during decode (same
        lever as TextModel's growth bucketing — short generations never
        attend over max_cache_len of mostly-empty buffer)."""
        from ..parallel.sharding import shard_cache
        self._kv_len = min(kv_len or self.max_cache_len, self.max_cache_len)
        for s in self.stages:
            if s.kind == "local":
                s.cache = shard_cache(
                    init_cache(self.cfg, 1, self._kv_len,
                               self.dtype, (s.start, s.end)), self.mesh)
            else:
                s.runner.goodbye()

    def _grow_local(self, new_len: int):
        from ..models.common.cache import grow_cache
        from ..parallel.sharding import shard_cache
        new_len = min(new_len, self.max_cache_len)
        if new_len <= self._kv_len:
            return
        for s in self.stages:
            if s.kind == "local":
                s.cache = shard_cache(
                    grow_cache(self.cfg, s.cache, new_len,
                               (s.start, s.end)), self.mesh)
        self._kv_len = new_len

    # -- forward ------------------------------------------------------------

    def _stage_forward(self, s: Stage, x, pos0: int, valid_len: int | None):
        """One stage hop — the single definition of local/remote dispatch
        (dtype cast, flash-mode selection, kv hint) shared by the
        sequential chain and the pipelined prefill threads."""
        with RECORDER.span("layers", cat="phase", kind=s.kind,
                           start=s.start, end=s.end,
                           worker=getattr(s.runner, "name", "")):
            return self._stage_forward_inner(s, x, pos0, valid_len)

    def _stage_forward_inner(self, s: Stage, x, pos0: int,
                             valid_len: int | None):
        if s.kind == "local":
            # local prefill stages flash like TextModel.prefill
            # (full-length unwrapped caches)
            flash_mode = "off"
            if valid_len is not None:
                flash_mode = select_flash_mode(pos0, x.shape[1],
                                               self._kv_len)
            x, s.cache = s.runner.forward_hidden(
                jnp.asarray(x).astype(self.dtype), s.cache,
                jnp.asarray(pos0, jnp.int32),
                None if valid_len is None
                else jnp.asarray(valid_len, jnp.int32),
                flash_mode=flash_mode)
            return x
        # kv hint keeps the worker's per-connection cache bucket aligned
        # with the master's, so growth reallocs land on the same
        # (pre-warmed) bucket boundaries on every node
        # lint: disable=host-sync — remote hop: the hidden state must become
        # host bytes to cross the wire (this IS the pipeline's transfer point)
        x, _ = s.runner.forward_hidden(np.asarray(x), None, pos0, valid_len,
                                       kv_hint=self._kv_len)
        return x

    def _run_stages(self, x, pos0: int, valid_len: int | None):
        for s in self.stages:
            x = self._stage_forward(s, x, pos0, valid_len)
        return x

    def prefill_logits(self, token_ids: list[int], pos0: int = 0):
        n = len(token_ids)
        bkt = check_prefill_bounds(n, pos0, self._kv_len, self.max_cache_len)
        # pipelined chunked prefill when the chain has remote hops and the
        # prompt spans >= 2 chunks: decode is irreducibly sequential (token
        # t+1 needs token t's sample) but prefill is not — chunk c+1 runs
        # on stage s while chunk c is on stage s+1, hiding wire+compute of
        # every stage but the slowest
        cw = self.prefill_chunk
        if (pos0 == 0 and n > cw
                and (-(-n // cw)) * cw <= self._kv_len  # padded chunks fit
                and any(s.kind == "remote" for s in self.stages)):
            return self._prefill_pipelined(token_ids)
        self._last_prefill = {"pipelined": False, "chunks": 1, "width": bkt}
        padded = np.zeros((1, bkt), np.int32)
        padded[0, :n] = token_ids
        x = self._embed(self.params, jnp.asarray(padded))
        x = self._run_stages(x, pos0, n)
        x = jnp.asarray(x)[:, n - 1:n]
        return self._head(self.params, x.astype(self.dtype))

    def _prefill_pipelined(self, token_ids: list[int]):
        """Stream the prompt through the stage chain in PREFILL_CHUNK-token
        slices, one thread per stage (plus a feeder): the blocking remote
        round trips of different stages overlap, so long-prompt TTFT
        approaches max-stage time instead of sum-of-stages. Queues are
        unbounded — a failed stage can then never deadlock its upstream;
        in-flight memory is bounded by n_chunks hidden-state slices."""
        import queue as _queue
        import threading

        cw = self.prefill_chunk
        n = len(token_ids)
        n_chunks = -(-n // cw)
        self._last_prefill = {"pipelined": True, "chunks": n_chunks,
                              "width": cw}
        qs = [_queue.Queue() for _ in range(len(self.stages) + 1)]
        errs: list[Exception] = []

        def feed():
            try:
                for ci in range(n_chunks):
                    lo = ci * cw
                    ids = token_ids[lo:lo + cw]
                    padded = np.zeros((1, cw), np.int32)
                    padded[0, :len(ids)] = ids
                    x = self._embed(self.params, jnp.asarray(padded))
                    qs[0].put((x, lo, len(ids)))
            except Exception as e:     # noqa: BLE001 — surfaced below
                errs.append(e)
            finally:
                qs[0].put(None)

        def run_stage(i: int, s: Stage):
            try:
                while True:
                    item = qs[i].get()
                    if item is None:
                        break
                    x, p0, vl = item
                    qs[i + 1].put((self._stage_forward(s, x, p0, vl), p0, vl))
            except Exception as e:     # noqa: BLE001 — surfaced below
                errs.append(e)
            finally:
                qs[i + 1].put(None)

        threads = [threading.Thread(target=feed, daemon=True)] + [
            threading.Thread(target=run_stage, args=(i, s), daemon=True)
            for i, s in enumerate(self.stages)]
        for t in threads:
            t.start()
        last = None
        while True:
            item = qs[-1].get()
            if item is None:
                break
            last = item
        for t in threads:
            t.join()
        if errs:
            raise errs[0]
        if last is None:
            raise RuntimeError("pipelined prefill produced no output")
        x, _, vl = last
        x = jnp.asarray(x)[:, vl - 1:vl]
        return self._head(self.params, x.astype(self.dtype))

    def decode_logits(self, token_id: int, pos: int):
        with RECORDER.span("embed", cat="phase"):
            x = self._embed(self.params, jnp.asarray([[token_id]], jnp.int32))
        x = self._run_stages(x, pos, None)
        with RECORDER.span("lm_head", cat="phase"):
            return self._head(self.params,
                              jnp.asarray(x)[:, -1:].astype(self.dtype))

    # -- generation ---------------------------------------------------------

    def generate(self, prompt_ids: list[int], max_new_tokens: int = 256,
                 sampling: SamplingConfig | None = None, on_token=None,
                 rng=None, **_):
        # a degraded cluster fails FAST: the retry budget was already
        # spent, and the background restore loop owns the dead worker —
        # burning every request's latency on doomed reconnects would turn
        # one dead node into a full outage
        d = self.degraded_info()
        if d is not None:
            raise ClusterDegradedError(
                f"cluster degraded: worker {d['worker']} down for "
                f"{now() - d['since']:.0f}s ({d['error']}); "
                "restore loop is probing")
        scfg = sampling or SamplingConfig()
        rng = self._rng if rng is None else rng
        # initial bucket covers prompt + first sampled token + a short run
        # of decode (same sizing idea as TextModel's first_span): the first
        # growth — a realloc on master AND every worker — should not land
        # within the opening tokens of decode
        self.reset(kv_len=initial_kv_bucket(len(prompt_ids), max_new_tokens,
                                            self.max_cache_len))
        # per-generation RTT windows: the stats this generate returns (and
        # /api/v1/stats re-serves as "last generation") must not blend in
        # samples from earlier generations
        for s in self.stages:
            if s.kind == "remote":
                s.runner.rtts.clear()
        out: list[int] = []
        # recovery bookkeeping: the recorded token sequence is exactly
        # what rebuild-by-replay prefills after a worker loss (`out` is
        # aliased, so appends below keep the record current)
        self._gen_prompt = list(prompt_ids)
        self._gen_out = out
        self._recoveries = self._replays = 0
        recent = jnp.full((max(scfg.repeat_last_n, 1),), -1, jnp.int32)

        t0 = now()
        with RECORDER.span("prefill", cat="gen", tokens=len(prompt_ids)):
            try:
                logits = self.prefill_logits(prompt_ids)
            except StageFailure as e:
                logits = self._recover(e, max_new_tokens)
        with RECORDER.span("sample", cat="phase"):
            rng, sk = jax.random.split(rng)
            tok = self._sample(logits[0], sk, recent, scfg)
            recent = push_recent_token(recent, tok)
        ttft = now() - t0

        pos = len(prompt_ids)
        # lint: disable=host-sync — first-token fetch keeps TTFT honest (same
        # contract as TextModel.generate)
        tid = int(tok)
        out.append(tid)
        if on_token:
            on_token(self._mk_token(tid))

        t1 = now()
        budget = self.max_cache_len - len(prompt_ids) - 1
        max_new_tokens = min(max_new_tokens, max(budget, 1))
        while not self.cfg.is_eos(tid) and len(out) < max_new_tokens:
            if pos + 1 > self._kv_len:
                self._grow_local(bucket_for(pos + 2, self.max_cache_len))
            with RECORDER.span("decode_token", cat="gen", pos=pos):
                try:
                    logits = self.decode_logits(tid, pos)
                except StageFailure as e:
                    # replay leaves every cache holding positions
                    # 0..pos and returns exactly the logits this failed
                    # decode owed — the loop continues none the wiser
                    logits = self._recover(e, max_new_tokens - len(out))
                with RECORDER.span("sample", cat="phase"):
                    rng, sk = jax.random.split(rng)
                    tok = self._sample(logits[0], sk, recent, scfg)
                    recent = push_recent_token(recent, tok)
                    # lint: disable=host-sync — the distributed loop is host-driven by
                    # design: the sampled id must reach the host to feed the next hop's
                    # wire frame (one small fetch per token, measured in BENCH_CLUSTER)
                    tid = int(tok)
            pos += 1
            out.append(tid)
            if on_token:
                on_token(self._mk_token(tid))
        dt = now() - t1
        stats = {"ttft_s": ttft, "decode_tokens": len(out) - 1,
                 "decode_s": dt, "prefill": dict(self._last_prefill),
                 "tok_per_s": (len(out) - 1) / dt if dt > 0 else 0.0,
                 "recoveries": self._recoveries, "replays": self._replays,
                 "stage_rtts": {
                     f"{s.runner.name}[{s.start}:{s.end}]":
                         s.runner.rtt_stats()
                     for s in self.stages if s.kind == "remote"}}
        _observe_generation(stats, len(out), path="cluster")
        return out, stats

    # -- mid-stream fault recovery ------------------------------------------

    def _remote_stage(self, worker: str) -> Stage | None:
        return next((s for s in self.stages
                     if s.kind == "remote" and s.runner.name == worker), None)

    def _recover(self, failure: StageFailure, remaining_new: int):
        """Quarantine the failed stage, reconnect with capped exponential
        backoff + jitter (re-auth + re-assign; weight push skipped while
        the worker acks its content-keyed cache), then rebuild ALL stage
        caches with one replay prefill. Returns the logits the failed op
        owed. Retry budget exhausted => mark the cluster degraded and
        raise ClusterDegradedError."""
        worker = failure.worker
        last: Exception = failure
        log.warning("stage failure (%s): %s — starting recovery",
                    failure.kind, failure)
        for attempt in range(self.recovery_retries):
            if attempt:
                wait = min(self.recovery_backoff_s * (2 ** (attempt - 1)),
                           RECOVERY_BACKOFF_CAP_S)
                # jitter so a fleet of masters doesn't reconnect-stampede
                # a worker that just came back
                time.sleep(wait * random.uniform(0.75, 1.25))
            if isinstance(last, StageFailure):
                worker = last.worker
            try:
                stage = self._remote_stage(worker)
                if stage is not None:
                    with RECORDER.span("recover", cat="gen", worker=worker,
                                       attempt=attempt):
                        with self._revive_lock:
                            stage.runner.reestablish()
                    CLUSTER_RECONNECTS.inc(worker=worker)
                    log.info("worker %s reconnected (attempt %d)", worker,
                             attempt + 1)
                logits = self._replay(remaining_new)
                self._recoveries += 1
                return logits
            except (StageFailure, OSError, RuntimeError,
                    proto.ProtocolError) as e:
                log.warning("recovery attempt %d/%d for %s failed: %s",
                            attempt + 1, self.recovery_retries, worker, e)
                last = e
        self._mark_degraded(worker, last)
        raise ClusterDegradedError(
            f"worker {worker} unrecoverable after "
            f"{self.recovery_retries} attempts: {last}") from last

    def _replay(self, remaining_new: int):
        """Rebuild-by-replay: worker KV is per-connection and died with
        the socket, so every stage cache is reset and the recorded token
        sequence (prompt + everything generated so far) is replayed
        through ONE pipeline prefill. The final position's logits are
        exactly what the failed op would have produced — greedy
        continuation is bit-identical to an unfailed run, and recovery
        costs one prefill no matter when the failure hit."""
        seq = self._gen_prompt + self._gen_out
        self.reset(kv_len=initial_kv_bucket(len(seq), remaining_new,
                                            self.max_cache_len))
        with RECORDER.span("replay_prefill", cat="gen", tokens=len(seq)):
            logits = self.prefill_logits(seq)
        self._replays += 1
        CLUSTER_REPLAYS.inc()
        return logits

    def degraded_info(self) -> dict | None:
        """Locked read of the degraded flag for out-of-class readers
        (/health, generate()'s fail-fast check) — the lock is only ever
        held for flag flips, so this never blocks on recovery work."""
        with self._degraded_lock:
            return self.degraded

    def _mark_degraded(self, worker: str, error: Exception):
        with self._degraded_lock:
            self.degraded = {"worker": worker, "since": now(),
                             "error": str(error)}
            if self._restore_thread is None \
                    or not self._restore_thread.is_alive():
                # started under the lock: the loop's first read blocks
                # until this block publishes the flag, never deadlocks
                self._restore_thread = threading.Thread(
                    target=self._restore_loop, daemon=True,
                    name="cake-restore")
                self._restore_thread.start()
        CLUSTER_DEGRADED.set(1.0)
        log.error("cluster degraded: worker %s unrecoverable (%s); "
                  "restore loop probing every %.1fs", worker, error,
                  self.restore_interval_s)

    def _restore_loop(self):
        """Background probe of the quarantined worker: on success the
        degraded flag clears and the NEXT request proceeds normally (its
        reset/prefill rebuilds all state — no replay needed between
        requests)."""
        while True:
            with self._degraded_lock:
                info = self.degraded
            if info is None:
                return
            time.sleep(self.restore_interval_s)
            with self._degraded_lock:
                info = self.degraded
            if info is None:
                return
            stage = self._remote_stage(info["worker"])
            if stage is None:
                with self._degraded_lock:
                    self.degraded = None
                CLUSTER_DEGRADED.set(0.0)
                return
            try:
                with self._revive_lock:
                    stage.runner.reestablish()
                CLUSTER_RECONNECTS.inc(worker=info["worker"])
                with self._degraded_lock:
                    self.degraded = None
                CLUSTER_DEGRADED.set(0.0)
                log.info("worker %s restored; cluster healthy again",
                         info["worker"])
                return
            except Exception as e:
                log.debug("restore probe for %s failed: %s",
                          info["worker"], e)

    def _mk_token(self, tid: int) -> Token:
        text = None
        if self.tokenizer is not None:
            try:
                text = self.tokenizer.decode([tid])
            except Exception:
                pass
        return Token(id=tid, text=text, is_end_of_stream=self.cfg.is_eos(tid))

    def chat_generate(self, messages: list[dict], **kw):
        from ..models.common.text_model import chat_prompt_ids
        return self.generate(chat_prompt_ids(self.tokenizer, messages), **kw)


# ---------------------------------------------------------------------------
# Cluster bring-up
# ---------------------------------------------------------------------------


@dataclass
class MasterSetup:
    cfg: ModelConfig
    topology: Topology
    stages: list[Stage]
    master_params: dict
    clients: list[RemoteStage] = field(default_factory=list)


def plan_assignments(cfg: ModelConfig, storage, workers: list[dict],
                     quant_factor: float = 1.0) -> dict[str, tuple[int, int]]:
    """TFLOPS-proportional contiguous ranges from discovery replies."""
    caps = [WorkerCapacity(name=w["name"],
                           memory_bytes=w["caps"]["memory_bytes"],
                           tflops=w["caps"]["tflops"],
                           backend=w["caps"].get("backend", "tpu"))
            for w in workers]
    layer_bytes = estimate_layer_bytes(storage, cfg.num_hidden_layers,
                                       quant_factor)
    plan = DefaultStrategy().assign_layers(
        caps, list(range(cfg.num_hidden_layers)), layer_bytes)
    out = {}
    for name, layers in plan.items():
        if layers:
            out[name] = (min(layers), max(layers) + 1)
    return out


def master_setup(model_dir: str, cluster_key: str, cfg: ModelConfig,
                 workers: list[dict],
                 assignments: dict[str, tuple[int, int]] | None = None,
                 dtype_str: str = "bf16", max_cache_len: int = 2048,
                 push_weights: bool = True,
                 master_device_fraction_reserved: float = 0.1,
                 fp8_native: bool = False, mesh=None,
                 warm: str = "full") -> MasterSetup:
    """Connect/auth/assign/push to each worker; build the stage chain.

    workers: discovery replies ({"name", "host", "port", "caps"}).
    fp8_native: stream the checkpoint's f8e4m3 tensors verbatim (the wire
    already carries raw safetensors bytes, so FP8 stays 1 byte/param in
    transit) and have every node keep them native in HBM with per-layer
    dequant fused into the matmuls (ref: native_dtype_backend.rs through
    sharding/mod.rs push_model_data).
    """
    import json
    import os

    from ..utils.loaders import load_model_params
    from ..utils.safetensors_io import TensorStorage

    storage = TensorStorage.from_model_dir(model_dir)
    if assignments is None:
        assignments = plan_assignments(cfg, storage, workers)
    with open(os.path.join(model_dir, "config.json")) as f:
        config_raw = json.load(f)
    mhash = transfer.model_hash(model_dir)
    ckey = transfer.cache_key(cluster_hash(cluster_key), mhash)

    # workers sorted by their range start -> stage order
    ordered = sorted(((name, rng) for name, rng in assignments.items()),
                     key=lambda kv: kv[1][0])
    clients: list[RemoteStage] = []
    worker_by_name = {w["name"]: w for w in workers}
    n = cfg.num_hidden_layers

    try:
        for name, (start, end) in ordered:
            w = worker_by_name[name]
            client = RemoteStage(w["host"], w["port"], cluster_key,
                                 name).connect()
            # registered immediately: a failure anywhere below (this worker
            # or a later one) must not leak the already-open sockets and
            # their per-connection server state
            clients.append(client)
            names = transfer.subset_tensor_names(storage, start, end, n,
                                                 include_embed=False,
                                                 include_head=False)
            # expected sizes always sent so the worker can validate its
            # cache even when pushing is disabled (header-only synthesis:
            # no data read)
            total, _ = transfer.synthesize_safetensors(storage, names)
            expected = {"model.safetensors": total}
            assignment = proto.layer_assignment(
                model_id=mhash, arch=cfg.arch, config=config_raw,
                start=start, end=end, dtype=dtype_str, cache_key=ckey,
                push_weights=push_weights, fp8_native=fp8_native)
            assignment["max_cache_len"] = max_cache_len
            assignment["expected_files"] = expected
            # "full": workers compile every growth bucket's decode + prefill
            # shape during setup so serving never pays an in-band compile;
            # "decode": smallest-bucket decode only (fast setup); "none"
            assignment["warm"] = warm
            # recovery memory: a mid-generation reconnect replays this
            # exact assignment (the worker's content-keyed weight cache
            # makes the push a no-op; the repush thunk covers a worker
            # that lost the cache too, e.g. a rebuilt host)
            client.assignment = assignment
            client.repush = functools.partial(_repush_weights, model_dir,
                                              names)
            resp = client.assign(assignment)
            if resp.get("t") == "worker_error":
                raise RuntimeError(f"worker {name}: {resp['error']}")
            if push_weights and not transfer_cached(resp):
                start_off = (resp.get("resume") or {}).get(
                    "model.safetensors", 0)
                total, chunks = transfer.synthesize_safetensors(storage,
                                                                names)
                client.push_weights(
                    transfer.encode_chunks("model.safetensors", total,
                                           chunks, start_offset=start_off))
            client.wait_ready()
            log.info("worker %s ready with layers [%d,%d)", name, start, end)

        # master keeps the unassigned layers
        assigned = set()
        for start, end in assignments.values():
            assigned |= set(range(start, end))
        master_layers = [i for i in range(n) if i not in assigned]

        # build the ordered stage chain
        stages: list[Stage] = []
        ranges: list[tuple[str, int, int, object]] = []
        for name, (start, end) in ordered:
            ranges.append(("remote", start, end,
                           clients[[nm for nm, _ in ordered].index(name)]))
        for lo, hi in _contiguous(master_layers):
            ranges.append(("local", lo, hi, None))
        ranges.sort(key=lambda r: r[1])

        dtype = {"bf16": jnp.bfloat16, "f32": jnp.float32,
                 "f16": jnp.float16}.get(dtype_str, jnp.bfloat16)
        quant = None
        if fp8_native:
            from ..utils.quant import fp8_native_quant
            quant = fp8_native_quant()
        master_params = load_model_params(cfg, model_dir, dtype, quant=quant,
                                          layer_range=(0, 0),
                                          include_embed=True, include_head=True)
        for kind, lo, hi, runner in ranges:
            if kind == "local":
                p = load_model_params(cfg, model_dir, dtype, quant=quant,
                                      layer_range=(lo, hi),
                                      include_embed=False, include_head=False)
                from ..parallel.sharding import shard_cache
                runner = LocalStage(cfg, p, lo, hi, mesh=mesh)
                cache = shard_cache(init_cache(cfg, 1, max_cache_len, dtype,
                                               (lo, hi)), mesh)
                stages.append(Stage("local", lo, hi, runner, cache))
            else:
                stages.append(Stage("remote", lo, hi, runner))

        topo = Topology.from_dict({
            name: {"host": f"{worker_by_name[name]['host']}:"
                           f"{worker_by_name[name]['port']}",
                   "layers": [f"model.layers.{s}-{e - 1}"],
                   "memory_bytes": worker_by_name[name]["caps"]["memory_bytes"],
                   "tflops": worker_by_name[name]["caps"]["tflops"],
                   "backend": worker_by_name[name]["caps"].get("backend", "")}
            for name, (s, e) in assignments.items()})
        storage.close()
        return MasterSetup(cfg=cfg, topology=topo, stages=stages,
                           master_params=master_params, clients=clients)
    except BaseException:
        # a failure ANYWHERE in setup (worker connect/assign/push, master
        # local-stage load, cache init) must not leak the already-open
        # worker sockets or the checkpoint storage handles
        for c in clients:
            try:
                c.close()
            except Exception:
                pass
        try:
            storage.close()
        except Exception:
            pass
        raise


def transfer_cached(ack_msg: dict) -> bool:
    return bool(ack_msg.get("cached", False))


def _repush_weights(model_dir: str, names: list[str], client: RemoteStage,
                    ack: dict) -> None:
    """Recovery-path weight re-stream for a worker that lost its content-
    keyed cache: reopen the checkpoint and synthesize the client's layer
    subset again (master_setup's storage handle is long closed by the
    time a mid-generation reconnect needs this)."""
    from ..utils.safetensors_io import TensorStorage
    storage = TensorStorage.from_model_dir(model_dir)
    try:
        start_off = (ack.get("resume") or {}).get("model.safetensors", 0)
        total, chunks = transfer.synthesize_safetensors(storage, names)
        client.push_weights(transfer.encode_chunks(
            "model.safetensors", total, chunks, start_offset=start_off))
    finally:
        storage.close()


def _contiguous(layers: list[int]) -> list[tuple[int, int]]:
    if not layers:
        return []
    out = []
    lo = prev = layers[0]
    for i in layers[1:]:
        if i != prev + 1:
            out.append((lo, prev + 1))
            lo = i
        prev = i
    out.append((lo, prev + 1))
    return out
