"""Distributed runtime: discovery, auth, wire protocol, topology/strategy,
weight streaming, master/worker/client (ref: cake-core/src/cake/sharding/).

Pipeline-style layer sharding over the LAN — the reference's core strategy
(SURVEY §2g) — with each node's contiguous range compiled to one XLA call.
"""
from . import faults
from .auth import AuthError, cluster_hash
from .client import RemoteStage, StageFailure
from .discovery import (WorkerAdvertiser, detect_capabilities,
                        discover_workers)
from .master import (ClusterDegradedError, DistributedTextModel, MasterSetup,
                     Stage, master_setup, plan_assignments)
from .strategy import DefaultStrategy, WorkerCapacity, estimate_layer_bytes
from .topology import Node, Topology, expand_layer_specs
from .worker import WorkerServer, run_worker
