"""Deterministic fault injection for the fleet router's outbound path.

The membership state machine and failover retry logic exist to survive
replica failure — and, like the serve supervisor (serve/faults.py) and
the cluster wire (cluster/faults.py), every recovery path must be
drillable on one CPU without real crashes. This module hooks the
router's per-attempt seam: before the router opens an HTTP attempt
against a replica it consults `faults.FAULT_HOOK` (one attribute read
when disabled), and while relaying an SSE stream it asks the hook
whether to sever the relay mid-stream.

A fault plan is one `key=val[;key=val...]` clause from the
`CAKE_FLEET_FAULT_PLAN` env var (tests use `install()`/`clear()`). Keys:

    replica=NAME        target replica (required — fleet faults are
                        always per-replica; the point is asymmetry)
    refuse_after_ops=N  outbound attempt N (1-based, counted per target)
                        and later raise a simulated connection refusal —
                        the black-hole/kill drill (default 1 when
                        `refuse=1` alone is given)
    refuse_times=K      only attempts N..N+K-1 refuse (default: forever,
                        i.e. the replica stays dark until clear())
    stall_ms=S          every attempt against the target reports a stall
                        of S ms first (the router awaits it — gray
                        slow-but-alive, drives the TTFB p95 detector)
    break_stream_after=N  sever the SSE relay after N forwarded chunks —
                        the mid-stream failure drill (transparent
                        splice-resume under CAKE_FLEET_STREAM_RESUMES,
                        typed error event past the budget — never a
                        silent hang)
    break_times=K       sever only the first K streams to the target
                        (default: every stream) — lets a resume drill
                        break the owner once and then prove the SAME
                        replica serves clean splices afterwards

An "op" is one outbound ATTEMPT against the target replica (retries and
hedges count separately); the counter survives ejection/readmission
cycles, which is what makes eject -> half-open -> readmit drills
deterministic.
"""
from __future__ import annotations

import logging
from dataclasses import dataclass

from .. import knobs

log = logging.getLogger("cake_tpu.fleet.faults")

__all__ = ["FAULT_HOOK", "InjectedFleetFault", "FleetFaultInjector",
           "parse_plan", "install", "active", "clear"]

# the router's per-attempt seam: None (one attribute check) when disabled
FAULT_HOOK = None


class InjectedFleetFault(ConnectionError):
    """A planned outbound failure — a ConnectionError subclass so the
    router's transport-failure classification treats it exactly like a
    real refused/reset connection."""


@dataclass
class FleetFaultInjector:
    """One plan clause; the router invokes the hooks below per attempt.
    All state lives here so it survives the ejections it provokes."""

    replica: str = ""
    refuse_after_ops: int | None = None
    refuse_times: int | None = None     # None = refuse forever once armed
    stall_ms: float = 0.0
    break_stream_after: int | None = None
    break_times: int | None = None      # None = sever every stream
    ops: int = 0                        # attempts seen against the target
    streams_broken: int = 0             # severs already delivered

    _INT_KEYS = ("refuse_after_ops", "refuse_times", "break_stream_after",
                 "break_times")

    @classmethod
    def parse(cls, clause: str) -> "FleetFaultInjector":
        inj = cls()
        for part in filter(None, (p.strip() for p in clause.split(";"))):
            if "=" not in part:
                raise ValueError(f"fault clause needs key=value: {part!r}")
            k, v = (s.strip() for s in part.split("=", 1))
            if k == "replica":
                inj.replica = v
            elif k == "refuse":
                inj.refuse_after_ops = 1
            elif k in cls._INT_KEYS:
                setattr(inj, k, int(v))
            elif k == "stall_ms":
                inj.stall_ms = float(v)
            else:
                raise ValueError(f"unknown fleet fault key {k!r}")
        if not inj.replica:
            raise ValueError("fleet fault plans require replica=NAME")
        return inj

    # -- router seams --------------------------------------------------------

    def on_attempt(self, replica: str) -> float:
        """Before one outbound attempt. Returns a stall in SECONDS the
        router must await (0 = none); raises InjectedFleetFault to
        simulate a refused connection."""
        if replica != self.replica:
            return 0.0
        self.ops += 1
        if (self.refuse_after_ops is not None
                and self.ops >= self.refuse_after_ops
                and (self.refuse_times is None
                     or self.ops < self.refuse_after_ops
                     + self.refuse_times)):
            log.warning("fleet fault: refusing attempt %d to %s",
                        self.ops, replica)
            raise InjectedFleetFault(
                f"fault injected: connection to {replica} refused "
                f"(attempt {self.ops})")
        return self.stall_ms / 1e3

    def break_stream(self, replica: str, chunks_sent: int) -> bool:
        """True when the SSE relay to this replica must sever now; each
        True consumes one of the break_times window (None = sever every
        stream to the target forever)."""
        if (replica != self.replica
                or self.break_stream_after is None
                or chunks_sent < self.break_stream_after):
            return False
        if (self.break_times is not None
                and self.streams_broken >= self.break_times):
            return False
        self.streams_broken += 1
        return True


def parse_plan(spec: str) -> FleetFaultInjector:
    clauses = [c for c in (s.strip() for s in spec.split(",")) if c]
    if len(clauses) != 1:
        raise ValueError("fleet fault plans take exactly one clause")
    return FleetFaultInjector.parse(clauses[0])


def install(spec_or_injector) -> FleetFaultInjector:
    """Activate a fault plan process-wide (faults.FAULT_HOOK)."""
    global FAULT_HOOK
    inj = (spec_or_injector
           if isinstance(spec_or_injector, FleetFaultInjector)
           else parse_plan(spec_or_injector))
    FAULT_HOOK = inj
    log.warning("fleet fault plan installed: %s", inj)
    return inj


def active() -> FleetFaultInjector | None:
    return FAULT_HOOK


def clear() -> None:
    global FAULT_HOOK
    FAULT_HOOK = None


# env-driven activation, mirroring serve/faults.py: the plan takes effect
# the moment the fleet plane loads (router.py imports this module)
_env_plan = knobs.get_str("CAKE_FLEET_FAULT_PLAN")
if _env_plan:
    install(_env_plan)
