"""`cake top` — live fleet dashboard over GET /api/v1/fleet/telemetry.

Renders the router's telemetry rollup (fleet/telemetry.py) as a
terminal dashboard: a fleet header (SLO burn rates, capacity headroom,
sheds/s, merged percentiles) over one row per replica (state, queue
depth, occupancy, TTFT p95, error rate, tok/s, speculative accept
rate, headroom, stale/outlier flags). Interactive mode is curses
(q quits, refreshes every --interval); `--once` / `--plain` / a
non-tty stdout fall back to plain text so the same command works in a
pipe or a cron job. Rendering is pure text-from-dict (render_screen),
so tests drive it with canned bodies and never need a terminal.
"""
from __future__ import annotations

import json
import sys
import time
import urllib.error
import urllib.request

TELEMETRY_PATH = "/api/v1/fleet/telemetry"


def fetch_telemetry(base_url: str, timeout_s: float = 3.0) -> dict:
    """One GET of the router's telemetry snapshot. Raises OSError (or a
    urllib subclass of it) when the router is unreachable."""
    url = base_url.rstrip("/") + TELEMETRY_PATH
    with urllib.request.urlopen(url, timeout=timeout_s) as r:
        return json.loads(r.read().decode("utf-8", "replace"))


def _fmt(v, spec: str = ".1f", dash: str = "-") -> str:
    """Format a maybe-None number; telemetry rows use None for 'no
    window data yet', which renders as a dash rather than 0 (a real
    zero is information; absence is not)."""
    if v is None:
        return dash
    return format(v, spec)


def _pct(v) -> str:
    return "-" if v is None else f"{v * 100.0:.0f}%"


def render_screen(body: dict, base_url: str = "",
                  width: int = 100) -> list[str]:
    """The dashboard as a list of lines (curses and plain mode both
    draw exactly these)."""
    burn = body.get("burn_rate", {})
    pct = body.get("percentiles", {})
    ttft = pct.get("ttft", {})
    lines = [
        f"cake top — {base_url or 'fleet'}   cycle {body.get('cycles', 0)}"
        f"   slo ttft {body.get('slo', {}).get('ttft_ms', 0):.0f}ms"
        f" err {body.get('slo', {}).get('err_rate', 0):.2%}",
        f"burn fast {burn.get('fast', 0.0):.2f}x"
        f"  slow {burn.get('slow', 0.0):.2f}x"
        f"   headroom {body.get('headroom_tokens_per_s', 0.0):.0f} tok/s"
        f"   sheds {body.get('sheds_per_s', 0.0):.2f}/s"
        f"   queue {body.get('fleet_queue_depth', 0)}",
    ]
    if ttft:
        lines.append(
            f"fleet ttft p50 {ttft.get('p50', 0) * 1000:.0f}ms"
            f"  p95 {ttft.get('p95', 0) * 1000:.0f}ms"
            f"  p99 {ttft.get('p99', 0) * 1000:.0f}ms"
            f"  (n={ttft.get('count', 0):.0f}, fast window)")
    else:
        lines.append("fleet ttft percentiles: no window data yet")
    scale = body.get("autoscale")
    if scale:
        last = scale.get("last") or {}
        if last:
            age = last.get("age_s")
            what = f"last {last.get('kind', '?')}"
            if last.get("reason"):
                what += f"({last['reason']})"
            if last.get("replica"):
                what += f" {last['replica']}"
            if age is not None:
                what += f" {age:.0f}s ago"
        else:
            what = "no decisions yet"
        lines.append(
            f"autoscale [{scale.get('min', '?')}"
            f"..{scale.get('max', '?')}]"
            f"   managed {scale.get('managed', 0)}"
            f"   pending {scale.get('pending_spawns', 0)}"
            f"   {what}")
    lines.append("")
    hdr = (f"{'REPLICA':<14} {'STATE':<9} {'DEPTH':>5} {'OCC':>5} "
           f"{'INFL':>5} {'TTFTp95':>8} {'ERR':>6} {'TOK/S':>8} "
           f"{'ACC':>5} {'HDRM':>7}  FLAGS")
    lines.append(hdr[:width])
    replicas = body.get("replicas", {})
    for name in sorted(replicas):
        row = replicas[name]
        flags = []
        if row.get("stale"):
            flags.append("stale")
        if row.get("partition_s") is not None:
            flags.append(f"partition({row['partition_s']:.0f}s)")
        if row.get("outlier"):
            reason = row.get("outlier_reason")
            flags.append(f"outlier({reason})" if reason
                         and reason != "stale" else "outlier")
        line = (f"{name[:14]:<14} {str(row.get('state', '?'))[:9]:<9} "
                f"{row.get('queue_depth', 0):>5} "
                f"{_pct(row.get('occupancy')):>5} "
                f"{row.get('inflight', 0):>5} "
                f"{_fmt(row.get('ttft_p95_ms'), '.0f'):>8} "
                f"{_pct(row.get('err_rate')):>6} "
                f"{_fmt(row.get('tokens_per_s'), '.1f'):>8} "
                f"{_pct(row.get('accept_rate')):>5} "
                f"{_fmt(row.get('headroom_tokens_per_s'), '.0f'):>7}  "
                f"{' '.join(flags)}")
        lines.append(line[:width])
    if not replicas:
        lines.append("(no replicas registered yet)")
    return lines


def _plain_once(base_url: str, timeout_s: float) -> int:
    try:
        body = fetch_telemetry(base_url, timeout_s)
    except OSError as e:
        print(f"cake top: {base_url}{TELEMETRY_PATH}: {e}",
              file=sys.stderr)
        return 1
    for line in render_screen(body, base_url):
        print(line)
    return 0


def _curses_loop(base_url: str, interval_s: float,
                 timeout_s: float) -> int:
    import curses

    def loop(scr):
        curses.curs_set(0)
        scr.timeout(int(interval_s * 1000))
        err = None
        body = {}
        while True:
            try:
                body = fetch_telemetry(base_url, timeout_s)
                err = None
            except OSError as e:
                err = str(e)
            h, w = scr.getmaxyx()
            scr.erase()
            lines = render_screen(body, base_url, width=w - 1)
            if err:
                lines.insert(0, f"[unreachable: {err}]"[:w - 1])
            for y, line in enumerate(lines[:h - 1]):
                scr.addstr(y, 0, line)
            scr.addstr(h - 1, 0, "q to quit"[:w - 1])
            scr.refresh()
            ch = scr.getch()      # doubles as the refresh sleep
            if ch in (ord("q"), ord("Q")):
                return 0

    return curses.wrapper(loop)


def run_top(base_url: str, interval_s: float = 2.0, once: bool = False,
            plain: bool = False, timeout_s: float = 3.0) -> int:
    """CLI entry. Curses when interactive; plain text when --once,
    --plain, or stdout is not a tty (pipes, CI)."""
    if once:
        return _plain_once(base_url, timeout_s)
    try:
        if plain or not sys.stdout.isatty():
            while True:
                rc = _plain_once(base_url, timeout_s)
                if rc != 0:
                    return rc
                print()
                time.sleep(interval_s)
        return _curses_loop(base_url, interval_s, timeout_s)
    except KeyboardInterrupt:
        return 0
