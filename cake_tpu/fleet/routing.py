"""Prefix-affinity routing: which replica owns this conversation?

Each replica's serve engine keeps a shared-prefix KV cache keyed by an
incremental blake2b hash chain over the prompt (serve/prefix_cache.py) —
a follow-up that lands on the replica holding its prefix blocks skips
most of its prefill (warm TTFT). Round-robin throws that away: N
replicas means a 1/N chance of landing warm. This module gives the
router the same chain, one tier up (SGLang's cache-aware routing
insight, minus the remote radix trees):

  * the CONVERSATION HEAD — the leading system message plus the first
    non-system message — is rendered to canonical bytes and hashed with
    the same incremental blake2b(digest_size=16) chain over fixed
    256-byte blocks that the prefix cache uses over token blocks. The
    head is what identifies a conversation: every follow-up request
    carries it verbatim at messages[0..], so the key is STABLE across
    turns, while two different conversations diverge in their first user
    message and spread. The chain depth cap
    (CAKE_FLEET_AFFINITY_BLOCKS, default 64 blocks = 16KB) is a COST
    backstop against pathological first messages, not a tuning knob: it
    must comfortably cover the system prompt + first message, because a
    cap that truncates inside a fleet-wide shared system prompt would
    hash every conversation to one key and melt a single replica.

  * the key is placed on replicas by RENDEZVOUS (highest-random-weight)
    hashing: every replica draws a uniform u = blake2b(key || name) in
    (0, 1) and candidates are ranked by -w / ln(u), the logarithmic
    weighted-rendezvous score — a replica with twice the probed
    capacity (slots from /health) owns twice the conversations in
    expectation, so heterogeneous fleets place load proportionally.
    With equal weights the score is monotone in u, which makes the
    ranking IDENTICAL to the classic unweighted digest sort (placement
    is backward-compatible; benches stay comparable). Adding or
    ejecting a replica reshuffles only the conversations it owned,
    changing ONE replica's weight remaps only conversations moving to
    or from it, and the failover order is DETERMINISTIC — when the
    owner is ejected, every router instance agrees on the same
    next-best replica, so the reroute itself stays cache-friendly.

Pure functions, no I/O: the router feeds them membership and bodies.
"""
from __future__ import annotations

import hashlib
import math

__all__ = ["affinity_key", "rank_replicas", "conversation_head",
           "AFFINITY_BLOCK"]

# bytes per chain block — the router-tier analog of the prefix cache's
# block_tokens (tokens hash here would need the tokenizer the router
# deliberately does not load)
AFFINITY_BLOCK = 256


def conversation_head(messages: list) -> bytes:
    """Canonical bytes of the conversation's identity: leading system
    message(s) + the first non-system message. Follow-up turns append to
    the END of messages, so this prefix is verbatim-stable for the whole
    conversation — the property the affinity key needs."""
    parts = []
    for m in messages:
        role = str(m.get("role", ""))
        content = m.get("content")
        if not isinstance(content, str):
            content = str(content)
        parts.append(f"{role}\x1f{content}\x1e")
        if role != "system":
            break                   # first non-system message ends the head
    return "".join(parts).encode("utf-8", "surrogatepass")


def affinity_key(data: bytes, max_blocks: int = 4) -> bytes:
    """Chain digest over `data` in AFFINITY_BLOCK-byte pieces, capped at
    `max_blocks` — the same incremental blake2b(digest_size=16) chain
    construction as PrefixCache.chain_keys, over bytes instead of token
    ids. Equal capped prefixes <=> equal keys."""
    h = hashlib.blake2b(digest_size=16)
    cap = max(max_blocks, 1) * AFFINITY_BLOCK
    view = data[:cap]
    for b in range(0, len(view), AFFINITY_BLOCK):
        h.update(view[b:b + AFFINITY_BLOCK])
    return h.digest()


def rank_replicas(key: bytes, names: list,
                  weights: dict | None = None) -> list:
    """Weighted rendezvous order of `names` for `key`: descending
    -w / ln(u) with u uniform in (0, 1) from blake2b(key || name),
    name-tiebroken. rank[0] is the owner; rank[1] is the deterministic
    next-best every router agrees on when the owner is ejected.
    `weights` maps name -> capacity (missing or non-positive = 1.0);
    a replica's expected share of keys is proportional to its weight,
    and equal weights reproduce the unweighted digest ordering exactly
    (the score is monotone in u)."""
    def score(name: str) -> float:
        h = hashlib.blake2b(
            key + name.encode("utf-8", "surrogatepass"),
            digest_size=8).digest()
        # (h + 0.5) / 2^64 keeps u strictly inside (0, 1) in exact
        # arithmetic, but digests within ~1024 of 2^64 ROUND to 1.0 in
        # float64 — and ln(1) = 0 would make the score a deterministic
        # ZeroDivisionError for that (key, name) pair forever; clamp to
        # the largest float64 below 1.0 (ties broken by name as usual)
        u = min((int.from_bytes(h, "big") + 0.5) / 2.0 ** 64,
                1.0 - 2.0 ** -53)
        w = float((weights or {}).get(name, 1.0))
        if w <= 0.0:
            w = 1.0
        return -w / math.log(u)
    return sorted(names, key=lambda n: (score(n), n), reverse=True)
