"""Replica lifecycle manager: the autoscaler's hands.

The controller (fleet/autoscale.py) decides; this module executes
against real OS processes from inside the router's event loop:

  SCALE-OUT  spawn a `cake serve --announce` subprocess from the
             CAKE_SCALE_SPAWN_CMD template ({port} and {name} filled
             per spawn, port allocated from the OS), then poll the
             child's /health until it answers 200 — only THEN is the
             replica admitted to the routing registry, so a cold
             replica (model load, XLA compile) never takes traffic.
             With a cluster key set, UDP discovery admits announced
             replicas through the existing path too; the direct
             health-poll admission is what makes same-host fleets
             deterministic (same-host SO_REUSEPORT advertisers share
             one UDP port, so a discovery query reaches only one).

  SCALE-IN   cordon the victim in the registry (the router stops
             routing NEW requests immediately), SIGTERM the process —
             which triggers the replica's own graceful drain: /health
             flips to draining, in-flight requests and live streams
             finish — then wait for the exit up to the drain budget
             and reap. SIGKILL only fires after the budget; a replica
             with live streams is never killed by plan (PR 15's
             self-healing resume is the backstop, not the plan).

  SWEEP      each probe cycle, managed processes that exited
             UNEXPECTEDLY (crash, kill -9) are reaped and removed from
             routing; the controller's below-min rule then decides the
             replacement.

Spawn-to-routable durations feed a rolling estimate the router's
no-replica 503 uses for Retry-After during a cold start — a client
arriving mid-scale-out should wait out the spawn, not give up on the
static backlog formula.

Every transition lands on the autoscale decisions ring (spawned /
admitted / spawn_failed / retire / reaped / died). The spawn and probe
seams are injectable so tier-1 tests drive the whole state machine with
stub processes and a fake prober — no model, no sockets.
"""
from __future__ import annotations

import asyncio
import logging
import os
import shlex
import signal
import socket
import subprocess
from collections import deque

from .. import knobs
from ..obs import (FLEET_SCALE_MANAGED_REPLICAS, FLEET_SCALE_PENDING_SPAWNS,
                   now)

log = logging.getLogger("cake_tpu.fleet")

__all__ = ["ReplicaLifecycle", "ManagedReplica"]

# spawn-to-routable estimate before any spawn has completed (seconds);
# replaced by the rolling mean as soon as one admission lands
DEFAULT_SPAWN_ETA_S = 10.0

# rolling window of completed spawn durations the ETA averages
_SPAWN_HISTORY = 8

# grace past CAKE_DRAIN_TIMEOUT_S before a retiring replica that never
# exited is SIGKILLed (the drain budget is the replica's; this covers
# process teardown after it)
_REAP_GRACE_S = 10.0


class ManagedReplica:
    """One process the router owns: spawn identity + Popen handle +
    admission bookkeeping. Event-loop-confined, like all lifecycle
    state."""

    def __init__(self, name: str, port: int, proc, spawned_at: float):
        self.name = name
        self.port = port
        self.base_url = f"http://127.0.0.1:{port}"
        self.proc = proc
        self.spawned_at = spawned_at
        self.admitted_at: float | None = None
        self.retiring = False

    @property
    def pending(self) -> bool:
        return self.admitted_at is None and not self.retiring

    def snapshot(self, t: float) -> dict:
        return {"name": self.name, "port": self.port,
                "pid": getattr(self.proc, "pid", None),
                "age_s": round(t - self.spawned_at, 3),
                "admitted": self.admitted_at is not None,
                "retiring": self.retiring}


class ReplicaLifecycle:
    """Owns every replica process the autoscaler creates. All methods
    run on the router's event loop; blocking waits are poll loops with
    asyncio sleeps, and process I/O is non-blocking (Popen + poll())."""

    def __init__(self, registry, *,
                 spawn_cmd: str | None = None,
                 spawn_timeout_s: float | None = None,
                 drain_timeout_s: float | None = None,
                 record=None, clock=now, spawner=None, prober=None):
        self.registry = registry
        self.spawn_cmd = spawn_cmd if spawn_cmd is not None \
            else (knobs.get_str("CAKE_SCALE_SPAWN_CMD") or None)
        self.spawn_timeout_s = spawn_timeout_s \
            if spawn_timeout_s is not None \
            else knobs.get("CAKE_SCALE_SPAWN_TIMEOUT_S")
        self.drain_timeout_s = drain_timeout_s \
            if drain_timeout_s is not None \
            else knobs.get("CAKE_DRAIN_TIMEOUT_S")
        # decisions-ring hook (DecisionLog.record); a no-op default
        # keeps the manager usable standalone in tests
        self._record = record if record is not None \
            else (lambda kind, **fields: None)
        self._clock = clock
        # test seams: spawner(cmd_list) -> Popen-like (poll/terminate/
        # kill/pid), prober(base_url) -> awaitable bool (one /health try)
        self._spawner = spawner or self._default_spawner
        self._prober = prober or self._default_prober
        self._managed: dict[str, ManagedReplica] = {}
        self._tasks: set = set()
        self._seq = 0
        self._spawn_secs: deque = deque(maxlen=_SPAWN_HISTORY)

    # -- spawn (scale-out) ---------------------------------------------------

    @staticmethod
    def _default_spawner(cmd: list):
        # own session: the router's SIGTERM must not blanket-kill the
        # fleet it manages — close() retires children deliberately
        return subprocess.Popen(cmd, start_new_session=True)

    async def _default_prober(self, base_url: str) -> bool:
        try:
            import aiohttp
            tmo = aiohttp.ClientTimeout(total=2.0)
            async with aiohttp.ClientSession() as s:
                async with s.get(base_url + "/health", timeout=tmo) as r:
                    return r.status == 200
        except asyncio.CancelledError:
            raise
        except Exception:
            return False

    @staticmethod
    def _free_port() -> int:
        with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    def _next_name(self) -> str:
        taken = set(self.registry.names()) | set(self._managed)
        while True:
            self._seq += 1
            name = f"scale-{self._seq}"
            if name not in taken:
                return name

    def spawn(self, reason: str = "") -> str | None:
        """Launch one replica process and start its admission poll.
        Returns the managed name, or None when no spawn template is
        configured (the decision still logged upstream — an operator
        running without CAKE_SCALE_SPAWN_CMD gets advisory decisions)."""
        if not self.spawn_cmd:
            log.warning("scale-out decided (%s) but CAKE_SCALE_SPAWN_CMD "
                        "is unset; not spawning", reason or "?")
            return None
        t = self._clock()
        name = self._next_name()
        port = self._free_port()
        cmd = shlex.split(self.spawn_cmd.format(port=port, name=name))
        try:
            proc = self._spawner(cmd)
        except OSError as e:
            log.error("spawn failed to launch %r: %s", cmd, e)
            self._record("spawn_failed", replica=name,
                         error=f"{type(e).__name__}: {e}")
            return None
        mr = ManagedReplica(name, port, proc, t)
        self._managed[name] = mr
        self._record("spawned", replica=name, port=port,
                     pid=getattr(proc, "pid", None), reason=reason)
        self._publish()
        self._track(self._admit(mr))
        return name

    async def _admit(self, mr: ManagedReplica) -> None:
        """Poll the child's /health until 200, then join the routing
        registry. A child that dies or never answers within the spawn
        timeout is killed and recorded spawn_failed."""
        deadline = mr.spawned_at + self.spawn_timeout_s
        while True:
            if mr.retiring:
                return
            if mr.proc.poll() is not None:
                self._record("spawn_failed", replica=mr.name,
                             error="process exited before healthy")
                self._drop(mr)
                return
            if await self._prober(mr.base_url):
                break
            if self._clock() >= deadline:
                self._record("spawn_failed", replica=mr.name,
                             error=f"no healthy /health within "
                                   f"{self.spawn_timeout_s:g}s")
                self._kill(mr)
                self._drop(mr)
                return
            await asyncio.sleep(0.25)
        t = self._clock()
        mr.admitted_at = t
        self._spawn_secs.append(t - mr.spawned_at)
        self.registry.add(mr.name, mr.base_url)
        self._record("admitted", replica=mr.name,
                     spawn_s=round(t - mr.spawned_at, 3))
        self._publish()

    # -- retire (scale-in) ---------------------------------------------------

    def retire(self, name: str, reason: str = "") -> bool:
        """Begin a graceful scale-in of a managed replica. Returns False
        when the name is not managed (the controller only selects
        managed victims; this guards direct callers)."""
        mr = self._managed.get(name)
        if mr is None or mr.retiring:
            return False
        mr.retiring = True
        rep = self.registry.get(name)
        if rep is not None:
            rep.cordon()            # stop NEW routing immediately
        self._record("retire", replica=name, reason=reason)
        self._publish()
        self._track(self._drain_and_reap(mr))
        return True

    async def _drain_and_reap(self, mr: ManagedReplica) -> None:
        """SIGTERM triggers the replica's own graceful drain (in-flight
        requests and live streams finish); wait for the exit up to the
        drain budget + grace, SIGKILL as the backstop, then drop it
        from routing."""
        try:
            if mr.proc.poll() is None:
                mr.proc.terminate()
        except OSError:
            pass
        deadline = self._clock() + self.drain_timeout_s + _REAP_GRACE_S
        killed = False
        while mr.proc.poll() is None:
            if self._clock() >= deadline:
                self._kill(mr)
                killed = True
                break
            await asyncio.sleep(0.1)
        # reap the zombie without blocking the loop (the process is
        # already dead or just SIGKILLed)
        try:
            mr.proc.wait(timeout=5.0)
        except Exception:
            pass
        self._record("reaped", replica=mr.name, forced=killed)
        self._drop(mr)

    # -- sweep (unexpected deaths) -------------------------------------------

    def sweep(self) -> list:
        """Reap managed processes that exited OUTSIDE a retire (crash,
        kill -9): remove them from routing so their gauges retract and
        the controller's below-min rule sees the hole. Returns the
        reaped names. Called once per probe cycle."""
        dead = [mr for mr in list(self._managed.values())
                if not mr.retiring and mr.proc.poll() is not None]
        for mr in dead:
            self._record("died", replica=mr.name,
                         exit_code=mr.proc.poll())
            self._drop(mr)
        return [mr.name for mr in dead]

    # -- views ---------------------------------------------------------------

    def is_managed(self, name: str) -> bool:
        return name in self._managed

    def managed_names(self) -> list:
        return list(self._managed)

    def pending_count(self) -> int:
        return sum(1 for mr in self._managed.values() if mr.pending)

    def pending_spawn_eta(self) -> int | None:
        """Seconds until the oldest pending spawn is expected routable
        (rolling mean of completed spawn durations), or None when no
        spawn is in flight — the cold-start Retry-After."""
        pending = [mr for mr in self._managed.values() if mr.pending]
        if not pending:
            return None
        expected = (sum(self._spawn_secs) / len(self._spawn_secs)) \
            if self._spawn_secs else DEFAULT_SPAWN_ETA_S
        t = self._clock()
        remaining = max(expected - (t - min(mr.spawned_at
                                            for mr in pending)), 1.0)
        return int(remaining + 0.999)

    def snapshot(self) -> dict:
        t = self._clock()
        return {"managed": [mr.snapshot(t)
                            for mr in self._managed.values()],
                "pending_spawns": self.pending_count(),
                "spawn_eta_s": self.pending_spawn_eta(),
                "spawn_cmd_set": bool(self.spawn_cmd)}

    # -- teardown ------------------------------------------------------------

    async def close(self) -> None:
        """Router shutdown: cancel admission/drain tasks and terminate
        every managed process (the router spawned them; an exiting
        router must not orphan a fleet nothing owns)."""
        for task in list(self._tasks):
            task.cancel()
        for task in list(self._tasks):
            try:
                await task
            except (asyncio.CancelledError, Exception):
                pass
        self._tasks.clear()
        for mr in list(self._managed.values()):
            try:
                if mr.proc.poll() is None:
                    mr.proc.terminate()
            except OSError:
                pass
        deadline = self._clock() + self.drain_timeout_s + _REAP_GRACE_S
        for mr in list(self._managed.values()):
            while mr.proc.poll() is None:
                if self._clock() >= deadline:
                    self._kill(mr)
                    break
                await asyncio.sleep(0.1)
            self._drop(mr)

    # -- internals -----------------------------------------------------------

    def _track(self, coro) -> None:
        task = asyncio.create_task(coro)
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    def _kill(self, mr: ManagedReplica) -> None:
        try:
            if mr.proc.poll() is None:
                # the whole session: spawn templates may wrap the serve
                # process in a shell, and an orphaned grandchild would
                # keep the port
                try:
                    os.killpg(os.getpgid(mr.proc.pid), signal.SIGKILL)
                except (OSError, AttributeError):
                    mr.proc.kill()
        except OSError:
            pass

    def _drop(self, mr: ManagedReplica) -> None:
        self._managed.pop(mr.name, None)
        self.registry.remove(mr.name)
        self._publish()

    def _publish(self) -> None:
        FLEET_SCALE_PENDING_SPAWNS.set(self.pending_count())
        FLEET_SCALE_MANAGED_REPLICAS.set(len(self._managed))
