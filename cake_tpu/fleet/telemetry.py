"""Fleet telemetry plane: time-series rollups the autoscaler will consume.

The router's probe loop already sees every replica every cycle; this
module turns that stream into DECISION-GRADE signals instead of raw
mirrors. Once per cycle the router scrapes each replica's Prometheus
`/metrics` text (the SLO histograms live there with their buckets —
/api/v1/slo renders counts and exemplars but not bucket boundaries),
parses out the handful of families the rollup needs, and feeds
fixed-window rings (obs/series.py). On top of the rings it computes:

  * fleet-level SLO percentiles — bucket-wise SUMS of the per-replica
    cake_serve_{ttft,itl,e2e}_seconds histograms (identical boundaries,
    enforced by the metric-registry lint) interpolated the
    histogram_quantile way;
  * multi-window BURN RATES — the windowed bad-request fraction (TTFT
    over CAKE_SLO_TTFT_MS, or outcome=error) divided by the
    CAKE_SLO_ERR_RATE budget, over a fast (~5m, page-worthy) and a slow
    (~1h, ticket-worthy) window — the Google SRE multi-window
    multi-burn-rate alert shape;
  * capacity HEADROOM — per replica, the observed per-slot token rate x
    free slots x KV-free fraction, summed over live replicas: an
    estimate in tokens/s of how much more decode the fleet could absorb
    right now;
  * per-replica ANOMALIES — a replica whose windowed TTFT p95 or error
    rate sits more than CAKE_TELEM_OUTLIER_K robust standard deviations
    (MAD-scaled) from the fleet median is flagged `outlier` in /fleet
    WITHOUT being ejected (the gray-failure detector generalized from
    RTT to every signal; ejection stays the membership machine's call).
    An unreachable (stale) replica is the degenerate outlier and is
    flagged immediately.

Stale replicas (last probe failed) are EXCLUDED from every rollup — the
registry retracts their mirrored gauges (see Replica.observe_health), so
a dead replica's frozen numbers can never average into fleet signals.

Everything is pure-math testable: `ingest()` takes raw scrape texts and
an optional timestamp, the clock is injectable, and the network lives
only in `collect()`. docs/telemetry.md is the operator guide.
"""
from __future__ import annotations

import asyncio
import re
from collections import deque

from .. import knobs
from ..obs import (FLEET_HEADROOM_TOKENS, FLEET_SHEDS, FLEET_SLO_BURN_RATE,
                   SeriesBank, now)

__all__ = ["FleetTelemetry", "parse_prom_text", "replica_signals",
           "merge_histograms", "bucket_quantile", "detect_outliers"]

# robust-scale floors: with a homogeneous fleet the MAD is ~0 and any
# jitter would divide by nothing — the scale never drops below these
# (TTFT also keeps a 10%-of-median relative floor), so only divergence
# an operator would call real trips the flag
_TTFT_SCALE_FLOOR_S = 0.005
_ERR_SCALE_FLOOR = 0.02

# rollup-overhead ring length (the < 5ms bench gate averages these)
_OVERHEAD_SAMPLES = 128


# -- Prometheus text parsing -------------------------------------------------

# one compiled pass over the label block: quoted values may hold commas
# and escaped quotes, which rules out a naive split — this parser runs
# per scrape line per replica per probe cycle, so it has to be cheap
_LABEL_RE = re.compile(r'([A-Za-z_][A-Za-z0-9_:]*)="((?:\\.|[^"\\])*)"')


def parse_prom_text(text: str, prefix="cake_"):
    """Minimal Prometheus 0.0.4 sample parser: yields
    (name, labels_dict, value) for every sample line whose metric name
    starts with `prefix` (a str or tuple of strs). Tolerates anything it
    cannot parse (a replica mid rolling-upgrade must not break the
    whole rollup)."""
    out = []
    append = out.append
    for line in text.splitlines():
        if not line or line[0] == "#" or not line.startswith(prefix):
            continue
        try:
            brace = line.find("{")
            if brace >= 0:
                labelstr, _, valstr = line[brace + 1:].rpartition("}")
                name = line[:brace]
                labels = {}
                for k, v in _LABEL_RE.findall(labelstr):
                    if "\\" in v:
                        v = v.replace('\\"', '"').replace("\\n", "\n") \
                             .replace("\\\\", "\\")
                    labels[k] = v
            else:
                name, _, valstr = line.partition(" ")
                labels = {}
            append((name, labels, float(valstr)))
        except (ValueError, IndexError):
            continue
    return out


def _le(v: str) -> float:
    return float("inf") if v == "+Inf" else float(v)


def replica_signals(text: str) -> dict:
    """Reduce one replica's /metrics text to the signal dict the rollup
    consumes:

      hist[sem]     = (edges, cumulative_counts) for outcome=ok of
                      cake_serve_{sem}_seconds, sem in ttft/itl/e2e
      requests      = total finished requests (e2e _count, all outcomes)
      errors        = finished requests with outcome=error
      tokens        = cake_generated_tokens_total summed over paths
      queue_depth / slots_busy / kv_free / kv_used   = gauges (or None)
      spec_proposed / spec_accepted                  = counters
      qos_depth     = {class: queued depth} from the admission plane
                      (the autoscaler's QoS view: batch backlog is
                      visible but deliberately not a scale trigger)
    """
    sig = {"hist": {}, "requests": 0.0, "errors": 0.0, "tokens": 0.0,
           "queue_depth": None, "slots_busy": None,
           "kv_free": None, "kv_used": None,
           "spec_proposed": 0.0, "spec_accepted": 0.0,
           "qos_depth": {}}
    buckets: dict[str, dict[float, float]] = {}
    # only two families feed the rollup — skipping the rest at the
    # startswith check keeps the per-cycle parse cost flat no matter how
    # many instrument families a replica exports
    for name, labels, value in parse_prom_text(
            text, prefix=("cake_serve_", "cake_generated_tokens_total")):
        if name.startswith("cake_serve_") and name.endswith("_seconds_bucket"):
            sem = name[len("cake_serve_"):-len("_seconds_bucket")]
            if sem in ("ttft", "itl", "e2e") \
                    and labels.get("outcome") == "ok":
                buckets.setdefault(sem, {})[_le(labels["le"])] = value
        elif name == "cake_serve_e2e_seconds_count":
            sig["requests"] += value
            if labels.get("outcome") == "error":
                sig["errors"] += value
        elif name == "cake_generated_tokens_total":
            sig["tokens"] += value
        elif name == "cake_serve_queue_depth":
            sig["queue_depth"] = value
        elif name == "cake_serve_qos_queue_depth":
            cls = labels.get("qos") or "?"
            sig["qos_depth"][cls] = sig["qos_depth"].get(cls, 0.0) + value
        elif name == "cake_serve_slots_busy":
            sig["slots_busy"] = value
        elif name == "cake_serve_kv_blocks_free":
            sig["kv_free"] = value
        elif name == "cake_serve_kv_blocks_used":
            sig["kv_used"] = value
        elif name == "cake_serve_spec_proposed_total":
            sig["spec_proposed"] += value
        elif name == "cake_serve_spec_accepted_total":
            sig["spec_accepted"] += value
    for sem, by_le in buckets.items():
        edges = tuple(sorted(by_le))
        sig["hist"][sem] = (edges, tuple(by_le[e] for e in edges))
    return sig


# -- histogram math ----------------------------------------------------------

def merge_histograms(hists) -> tuple[tuple, tuple] | None:
    """Bucket-wise sum of cumulative histograms sharing identical
    boundaries. Histograms with mismatched edges are SKIPPED (and the
    caller reports how many) — summing misaligned buckets silently
    produces garbage percentiles, which is exactly what the
    metric-registry lint exists to prevent in-tree."""
    ref = None
    acc = None
    for edges, counts in hists:
        if ref is None:
            ref = edges
            acc = list(counts)
        elif edges == ref:
            for i, c in enumerate(counts):
                acc[i] += c
        else:
            continue
    if ref is None:
        return None
    return ref, tuple(acc)


def bucket_quantile(edges, cum_counts, q: float) -> float | None:
    """histogram_quantile over one cumulative histogram: find the bucket
    the q-th observation falls in and interpolate linearly inside it.
    The +Inf bucket clamps to the last finite edge (there is no upper
    boundary to interpolate toward). None when the histogram is empty."""
    if not cum_counts:
        return None
    total = cum_counts[-1]
    if total <= 0:
        return None
    target = q * total
    lo = 0.0
    prev_cum = 0.0
    for edge, cum in zip(edges, cum_counts):
        if cum >= target:
            if edge == float("inf"):
                # clamp: the observation is beyond the last finite edge
                return lo
            in_bucket = cum - prev_cum
            if in_bucket <= 0:
                return edge
            frac = (target - prev_cum) / in_bucket
            return lo + (edge - lo) * frac
        lo = edge if edge != float("inf") else lo
        prev_cum = cum
    return lo


def ttft_over_slo(edges, cum_counts, slo_s: float) -> float:
    """How many of the histogram's observations exceeded the objective,
    at bucket resolution: total minus the cumulative count at the first
    edge >= slo_s (conservative — an observation in the straddling
    bucket counts as GOOD, so a bucket boundary sitting exactly on the
    objective behaves like Prometheus `le`)."""
    if not cum_counts:
        return 0.0
    total = cum_counts[-1]
    for edge, cum in zip(edges, cum_counts):
        if edge >= slo_s:
            return max(total - cum, 0.0)
    return 0.0


def detect_outliers(stats: dict, k: float, min_n: int) -> dict:
    """name -> reason for replicas whose TTFT p95 or error rate diverges
    > k robust standard deviations (1.4826 x MAD, floored) from the
    fleet median. Needs >= min_n replicas reporting the signal — a
    median over two cannot say which one is wrong."""
    flags: dict[str, str] = {}
    for key, reason, floor_abs, floor_rel in (
            ("ttft_p95_s", "ttft_p95", _TTFT_SCALE_FLOOR_S, 0.1),
            ("err_rate", "err_rate", _ERR_SCALE_FLOOR, 0.0)):
        pts = [(name, s[key]) for name, s in stats.items()
               if s.get(key) is not None]
        if len(pts) < max(min_n, 2):
            continue
        values = sorted(v for _, v in pts)
        med = _median(values)
        mad = _median(sorted(abs(v - med) for v in values))
        scale = max(1.4826 * mad, floor_abs, floor_rel * abs(med))
        for name, v in pts:
            if abs(v - med) > k * scale:
                flags.setdefault(name, reason)
    return flags


def _median(sorted_values) -> float:
    n = len(sorted_values)
    mid = n // 2
    if n % 2:
        return float(sorted_values[mid])
    return (sorted_values[mid - 1] + sorted_values[mid]) / 2.0


def _counter_total(metric) -> float:
    """Sum a labeled counter across every labelset (router-local sheds
    feed the dashboard's sheds/s)."""
    return sum(metric.value(**ls) for ls in metric.labelsets())


class _HistRing:
    """Fixed-window ring of one replica histogram's CUMULATIVE bucket
    vectors, so the rollup can compute windowed bucket deltas (what the
    fleet percentile is actually over). Counter resets (replica restart)
    are handled the Prometheus-increase way: a drop in the total count
    starts a fresh baseline instead of producing negative buckets.
    Event-loop-confined like the telemetry plane that owns it."""

    def __init__(self, window_s: float, max_samples: int, clock):
        self.window_s = float(window_s)
        self._clock = clock
        self._ring: deque = deque(maxlen=max(int(max_samples), 2))
        self.edges: tuple = ()

    def record(self, edges, cum_counts, t: float | None = None) -> None:
        t = self._clock() if t is None else float(t)
        if edges != self.edges:
            # boundary change = replica upgrade: old vectors are
            # incomparable, start over
            self._ring.clear()
            self.edges = tuple(edges)
        self._ring.append((t, tuple(cum_counts)))
        cutoff = t - self.window_s
        while len(self._ring) > 1 and self._ring[0][0] < cutoff:
            self._ring.popleft()

    def window_delta(self, window_s: float) -> tuple[tuple, tuple] | None:
        """(edges, windowed cumulative-count deltas) over the trailing
        window, reset-safe; None before the first sample."""
        if not self._ring:
            return None
        ring = list(self._ring)
        cutoff = ring[-1][0] - float(window_s)
        base_i = 0
        for i, (t, _) in enumerate(ring):
            if t <= cutoff:
                base_i = i
            else:
                break
        # fast path: no counter reset inside the window (the running
        # totals are monotone), so the windowed delta is simply
        # last - baseline per bucket — O(samples) on one scalar instead
        # of O(samples x buckets)
        base = ring[base_i][1]
        last = ring[-1][1]
        prev_total = base[-1] if base else 0.0
        reset = False
        for _, counts in ring[base_i + 1:]:
            if counts[-1] < prev_total:
                reset = True
                break
            prev_total = counts[-1]
        if not reset:
            acc = [max(c - b, 0.0) for c, b in zip(last, base)]
        else:
            acc = [0.0] * len(base)
            prev = base
            for _, counts in ring[base_i + 1:]:
                if counts[-1] < prev[-1]:   # reset: restart from zero
                    prev = tuple(0.0 for _ in counts)
                for i, c in enumerate(counts):
                    d = c - prev[i]
                    if d > 0:
                        acc[i] += d
                prev = counts
        if base_i == 0 and len(ring) >= 1 and sum(acc) == 0.0:
            # nothing but the first sample in the window: its cumulative
            # counts ARE the delta from the implicit zero baseline
            acc = list(ring[-1][1])
        return self.edges, tuple(acc)


# -- the plane ---------------------------------------------------------------

class FleetTelemetry:
    """The router's telemetry plane. `collect()` scrapes (async, network),
    `ingest()` is the pure rollup (sync, fake-clock testable), and
    `snapshot()` is what GET /api/v1/fleet/telemetry returns. All state
    is event-loop-confined to the router loop, matching the router's own
    handler state; the Series rings underneath carry their own locks."""

    def __init__(self, registry, *, clock=now,
                 fast_window_s: float | None = None,
                 slow_window_s: float | None = None,
                 slo_ttft_ms: float | None = None,
                 slo_err_rate: float | None = None,
                 outlier_k: float | None = None,
                 outlier_min_n: int | None = None,
                 ring: int | None = None):
        self.registry = registry
        self._clock = clock
        self.fast_window_s = fast_window_s if fast_window_s is not None \
            else knobs.get("CAKE_TELEM_FAST_WINDOW_S")
        self.slow_window_s = slow_window_s if slow_window_s is not None \
            else knobs.get("CAKE_TELEM_SLOW_WINDOW_S")
        self.slo_ttft_ms = slo_ttft_ms if slo_ttft_ms is not None \
            else knobs.get("CAKE_SLO_TTFT_MS")
        self.slo_err_rate = slo_err_rate if slo_err_rate is not None \
            else knobs.get("CAKE_SLO_ERR_RATE")
        self.outlier_k = outlier_k if outlier_k is not None \
            else knobs.get("CAKE_TELEM_OUTLIER_K")
        self.outlier_min_n = outlier_min_n if outlier_min_n is not None \
            else knobs.get("CAKE_TELEM_OUTLIER_MIN_N")
        ring = ring if ring is not None else knobs.get("CAKE_TELEM_RING")
        # rings retain the slow window: the slow burn rate needs it, and
        # everything faster reads a sub-window of the same samples
        self.bank = SeriesBank(self.slow_window_s, ring, clock)
        self._hists: dict[tuple[str, str], _HistRing] = {}
        self._per_slot: dict[str, float] = {}   # tok/s per busy slot
        self._overhead_ms: deque = deque(maxlen=_OVERHEAD_SAMPLES)
        self._last: dict = {}
        self._cycles = 0

    # -- scrape (network) ----------------------------------------------------

    async def collect(self, session, timeout_s: float = 2.0) -> dict:
        """Scrape every registered replica's /metrics concurrently.
        name -> text, or None when the replica was unreachable."""
        import aiohttp
        tmo = aiohttp.ClientTimeout(total=max(timeout_s, 0.2))

        async def scrape(rep):
            try:
                async with session.get(rep.base_url + "/metrics",
                                       timeout=tmo) as r:
                    if r.status != 200:
                        return rep.name, None
                    return rep.name, await r.text()
            except asyncio.CancelledError:
                raise
            except Exception:
                return rep.name, None
        pairs = await asyncio.gather(
            *(scrape(r) for r in self.registry.replicas()))
        return dict(pairs)

    async def step(self, session) -> None:
        """One probe-cycle turn: scrape, then roll up."""
        self.ingest(await self.collect(session))

    # -- rollup (pure) -------------------------------------------------------

    def ingest(self, scrapes: dict, t: float | None = None) -> dict:
        """Fold one cycle of raw scrape texts ({name: text|None}) into
        the rings and recompute every rollup. Returns (and caches) the
        snapshot body. Pure math on its inputs — tests drive it with
        synthetic texts and a fake clock."""
        t0 = now()
        t = self._clock() if t is None else float(t)
        self._cycles += 1
        live: dict[str, dict] = {}
        for name, text in scrapes.items():
            if text is None:
                continue
            sig = replica_signals(text)
            live[name] = sig
            self.bank.record(f"req/{name}", sig["requests"], t)
            self.bank.record(f"tok/{name}", sig["tokens"], t)
            self.bank.record(f"spec_prop/{name}", sig["spec_proposed"], t)
            self.bank.record(f"spec_acc/{name}", sig["spec_accepted"], t)
            if sig["slots_busy"] is not None:
                self.bank.record(f"busy/{name}", sig["slots_busy"], t)
            bad = sig["errors"]
            h = sig["hist"].get("ttft")
            if h is not None:
                bad += ttft_over_slo(*h, self.slo_ttft_ms / 1000.0)
            self.bank.record(f"bad/{name}", bad, t)
            for sem, (edges, counts) in sig["hist"].items():
                ring = self._hists.get((name, sem))
                if ring is None:
                    ring = self._hists[(name, sem)] = _HistRing(
                        self.slow_window_s, self.bank.max_samples,
                        self._clock)
                ring.record(edges, counts, t)

        body = self._rollup(scrapes, live, t)
        ms = (now() - t0) * 1000.0
        self._overhead_ms.append(ms)
        body["rollup_ms"] = {
            "last": round(ms, 3),
            "mean": round(sum(self._overhead_ms)
                          / len(self._overhead_ms), 3),
            "max": round(max(self._overhead_ms), 3)}
        self._last = body
        return body

    def _rollup(self, scrapes: dict, live: dict, t: float) -> dict:
        reps = {r.name: r for r in self.registry.replicas()}
        snaps = {name: rep.snapshot() for name, rep in reps.items()}
        # stale = this cycle's scrape failed OR the probe side already
        # marked it (either way its numbers must not enter the rollup)
        stale = {name for name in reps
                 if scrapes.get(name) is None or snaps[name].get("stale")}
        # ejected replicas drop out of the rollup like dead ones even
        # when their scrape/probe path still answers — the asymmetric
        # partition case (probe-alive, data-dead) would otherwise keep
        # contributing headroom the router cannot actually route to
        ejected = {name for name in reps
                   if snaps[name].get("state") == "ejected"}
        usable = [n for n in live if n not in stale and n not in ejected]

        # fleet percentiles: bucket-wise sums of windowed deltas
        percentiles: dict[str, dict] = {}
        skipped_mismatched = 0
        for sem in ("ttft", "itl", "e2e"):
            deltas, ref_edges = [], None
            for name in usable:
                ring = self._hists.get((name, sem))
                d = ring.window_delta(self.fast_window_s) if ring else None
                if d is None:
                    continue
                if ref_edges is None:
                    ref_edges = d[0]
                elif d[0] != ref_edges:
                    skipped_mismatched += 1
                    continue
                deltas.append(d)
            merged = merge_histograms(deltas)
            if merged is None:
                continue
            edges, counts = merged
            percentiles[sem] = {
                "p50": bucket_quantile(edges, counts, 0.50),
                "p95": bucket_quantile(edges, counts, 0.95),
                "p99": bucket_quantile(edges, counts, 0.99),
                "count": counts[-1] if counts else 0}

        # burn rates: windowed bad fraction / error budget
        burn = {}
        for label, win in (("fast", self.fast_window_s),
                           ("slow", self.slow_window_s)):
            req = bad = 0.0
            for name in usable:
                s_req = self.bank.get(f"req/{name}")
                s_bad = self.bank.get(f"bad/{name}")
                if s_req is not None:
                    req += s_req.increase(win)
                if s_bad is not None:
                    bad += s_bad.increase(win)
            frac = (bad / req) if req > 0 else 0.0
            burn[label] = round(frac / max(self.slo_err_rate, 1e-9), 4)
            FLEET_SLO_BURN_RATE.set(burn[label], window=label)

        # headroom: per-slot token rate x free slots x KV-free fraction
        headroom = 0.0
        replicas_out: dict[str, dict] = {}
        per_rep_stats: dict[str, dict] = {}
        for name, rep in reps.items():
            snap = snaps[name]
            sig = live.get(name)
            row = {"state": snap["state"],
                   "stale": name in stale,
                   "queue_depth": snap["queue_depth"],
                   "occupancy": snap["occupancy"],
                   "inflight": snap["inflight"],
                   "eject_evidence": snap.get("eject_evidence"),
                   "partition_s": snap.get("partition_s"),
                   "ttft_p95_ms": None, "err_rate": None,
                   "tokens_per_s": None, "accept_rate": None,
                   "headroom_tokens_per_s": 0.0}
            if sig is not None and name not in stale \
                    and name not in ejected:
                tok = self.bank.get(f"tok/{name}")
                rate = tok.rate(self.fast_window_s) if tok else 0.0
                row["tokens_per_s"] = round(rate, 3)
                busy_s = self.bank.get(f"busy/{name}")
                busy_vals = busy_s.values(self.fast_window_s) \
                    if busy_s else []
                busy_avg = (sum(busy_vals) / len(busy_vals)) \
                    if busy_vals else 0.0
                if rate > 0 and busy_avg > 0:
                    self._per_slot[name] = rate / max(busy_avg, 1.0)
                slots = reps[name].weight()    # probed engine slots
                busy_now = sig["slots_busy"] or 0.0
                free_slots = max(slots - busy_now, 0.0)
                if sig["kv_free"] is not None and sig["kv_used"] is not None \
                        and (sig["kv_free"] + sig["kv_used"]) > 0:
                    kv_free_frac = sig["kv_free"] / (sig["kv_free"]
                                                     + sig["kv_used"])
                else:
                    kv_free_frac = max(1.0 - snap["occupancy"], 0.0)
                hr = self._per_slot.get(name, 0.0) * free_slots \
                    * kv_free_frac
                row["headroom_tokens_per_s"] = round(hr, 3)
                headroom += hr
                # windowed per-replica SLO stats for the outlier detector
                ring = self._hists.get((name, "ttft"))
                d = ring.window_delta(self.fast_window_s) if ring else None
                p95 = bucket_quantile(*d, 0.95) if d else None
                if p95 is not None:
                    row["ttft_p95_ms"] = round(p95 * 1000.0, 3)
                s_req = self.bank.get(f"req/{name}")
                s_bad = self.bank.get(f"bad/{name}")
                inc_req = s_req.increase(self.fast_window_s) \
                    if s_req else 0.0
                inc_bad = s_bad.increase(self.fast_window_s) \
                    if s_bad else 0.0
                err = (inc_bad / inc_req) if inc_req > 0 else None
                if err is not None:
                    row["err_rate"] = round(err, 4)
                sp = self.bank.get(f"spec_prop/{name}")
                sa = self.bank.get(f"spec_acc/{name}")
                inc_p = sp.increase(self.fast_window_s) if sp else 0.0
                inc_a = sa.increase(self.fast_window_s) if sa else 0.0
                if inc_p > 0:
                    row["accept_rate"] = round(inc_a / inc_p, 4)
                per_rep_stats[name] = {"ttft_p95_s": p95, "err_rate": err}
            replicas_out[name] = row
        FLEET_HEADROOM_TOKENS.set(headroom)

        # anomalies: statistical outliers among the live, plus every
        # stale replica (unreachable is the degenerate outlier)
        flags = detect_outliers(per_rep_stats, self.outlier_k,
                                self.outlier_min_n)
        for name in stale:
            flags.setdefault(name, "stale")
        for name, rep in reps.items():
            reason = flags.get(name)
            rep.set_outlier(reason is not None, reason)
            replicas_out[name]["outlier"] = reason is not None
            replicas_out[name]["outlier_reason"] = reason

        # per-class backlog across usable replicas: the autoscaler reads
        # this for its decision detail — batch backlog is VISIBLE here but
        # never a scale trigger (interactive burn/headroom are; a deep
        # batch queue is exactly what the batch class is for)
        qos_backlog: dict[str, float] = {}
        for name in usable:
            sig = live.get(name)
            for cls, depth in (sig.get("qos_depth") or {}).items():
                qos_backlog[cls] = qos_backlog.get(cls, 0.0) + depth

        # fleet-level rings for dashboards (`cake top` sparklines)
        fleet_depth = sum(s["queue_depth"] for n, s in snaps.items()
                          if n not in stale)
        self.bank.record("fleet/headroom", headroom, t)
        self.bank.record("fleet/burn_fast", burn["fast"], t)
        self.bank.record("fleet/burn_slow", burn["slow"], t)
        self.bank.record("fleet/queue_depth", fleet_depth, t)
        self.bank.record("fleet/sheds", _counter_total(FLEET_SHEDS), t)
        sheds_s = self.bank.series("fleet/sheds").rate(self.fast_window_s)

        series = {}
        for key in ("fleet/headroom", "fleet/burn_fast",
                    "fleet/burn_slow", "fleet/queue_depth"):
            s = self.bank.get(key)
            if s is not None:
                # ages relative to now: the monotonic clock means
                # nothing across processes, an age does
                series[key] = [[round(t - st, 3), round(v, 4)]
                               for st, v in s.samples()]

        return {
            "cycles": self._cycles,
            "slo": {"ttft_ms": self.slo_ttft_ms,
                    "err_rate": self.slo_err_rate},
            "windows": {"fast_s": self.fast_window_s,
                        "slow_s": self.slow_window_s},
            "burn_rate": burn,
            "headroom_tokens_per_s": round(headroom, 3),
            "sheds_per_s": round(sheds_s, 4),
            "fleet_queue_depth": fleet_depth,
            "qos_backlog": {c: round(v, 1)
                            for c, v in sorted(qos_backlog.items())},
            "percentiles": percentiles,
            "mismatched_histograms_skipped": skipped_mismatched,
            "stale": sorted(stale),
            "outliers": {n: r for n, r in sorted(flags.items())},
            "replicas": replicas_out,
            "series": series,
        }

    # -- views ---------------------------------------------------------------

    def snapshot(self) -> dict:
        """Last rollup (what /api/v1/fleet/telemetry returns); an empty
        body with the configuration before the first cycle."""
        if self._last:
            return self._last
        return {"cycles": 0,
                "slo": {"ttft_ms": self.slo_ttft_ms,
                        "err_rate": self.slo_err_rate},
                "windows": {"fast_s": self.fast_window_s,
                            "slow_s": self.slow_window_s},
                "burn_rate": {"fast": 0.0, "slow": 0.0},
                "headroom_tokens_per_s": 0.0, "sheds_per_s": 0.0,
                "fleet_queue_depth": 0, "qos_backlog": {},
                "percentiles": {}, "stale": [],
                "outliers": {}, "replicas": {}, "series": {},
                "rollup_ms": {"last": 0.0, "mean": 0.0, "max": 0.0}}
