"""Fleet-shared KV tier: prefix-blob export/fetch between replicas, a
probe-piggybacked peer directory, and live stream blob migration (see
docs/kv_sharing.md).

Module map:

  * blob.py      — versioned + checksummed wire format (KVBlobMismatch
                   is the typed reject; fallback is always recompute);
  * directory.py — the X-Cake-KV-Peers header codec (router builds it
                   from registry-mirrored inventories per attempt);
  * replica.py   — the per-engine agent: scheduler-thread mailbox for
                   export/import/park/adopt, fetch-before-recompute on
                   admission, and the StreamMigrated severing signal.
"""
from .blob import (KVBlobMismatch, MAGIC, VERSION, decode_blob,
                   encode_blob, pool_signature)
from .directory import encode_directory, parse_directory

# replica.py imports jax (it manipulates pool arrays); the ROUTER tier
# imports this package for the directory codec alone and deliberately
# stays model-free / import-light, so the replica-side names resolve
# lazily (PEP 562) instead of pulling jax into the router process
_REPLICA_NAMES = ("KVShareReplica", "StreamMigrated", "KV_DIR_HEADER",
                  "KV_RESUME_HEADER", "KV_RESUMED_HEADER")


def __getattr__(name):
    if name in _REPLICA_NAMES:
        from . import replica as _replica
        return getattr(_replica, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

__all__ = [
    "KVBlobMismatch", "MAGIC", "VERSION", "encode_blob", "decode_blob",
    "pool_signature", "encode_directory", "parse_directory",
    "KVShareReplica", "StreamMigrated", "KV_DIR_HEADER",
    "KV_RESUME_HEADER", "KV_RESUMED_HEADER",
]
