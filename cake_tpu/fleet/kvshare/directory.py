"""Peer directory for the fleet-shared KV tier.

Each replica's /health already rides the router's probe scrape; with
kvshare on, the health body carries a `kvshare.chains` inventory (the
hex chain keys the replica can export, newest-first, capped by
CAKE_KVSHARE_INVENTORY). The registry mirrors that inventory per
replica, and the router injects a compact JSON directory of WARM peers
into each forwarded request (the X-Cake-KV-Peers header, built here) —
exactly the piggyback pattern the QoS/tenant headers use, so the
directory is never more stale than one probe interval, and a stale or
ejected replica's inventory is retracted with its probe state.

Wire shape (compact on purpose — it lives in a request header, and
aiohttp caps header lines at ~8 KB):

    {"p": [{"u": "http://host:port", "k": ["<hex>", ...]}, ...]}
"""
from __future__ import annotations

import json

__all__ = ["encode_directory", "parse_directory"]


def encode_directory(peers: list) -> str | None:
    """Header value for a list of (base_url, chain_hex_iterable) pairs;
    None when no peer has anything to advertise (the header is simply
    not injected)."""
    out = []
    for url, chains in peers:
        chains = list(chains)
        if not url or not chains:
            continue
        out.append({"u": url, "k": chains})
    if not out:
        return None
    return json.dumps({"p": out}, separators=(",", ":"))


def parse_directory(header: str) -> list:
    """(base_url, frozenset(chain_hex)) pairs out of a header value;
    malformed input parses as empty (the fetch path treats that as "no
    warm peers" and recomputes)."""
    try:
        doc = json.loads(header)
        peers = []
        for p in doc.get("p") or []:
            url = p.get("u")
            keys = p.get("k") or []
            if isinstance(url, str) and url and isinstance(keys, list):
                peers.append((url, frozenset(
                    k for k in keys if isinstance(k, str))))
        return peers
    except Exception:
        return []
