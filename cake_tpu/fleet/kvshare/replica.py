"""Replica side of the fleet-shared KV tier.

One KVShareReplica rides each serve engine (wired by the API server when
CAKE_KVSHARE is on; the engine holds it duck-typed as `kv_share` so the
serve package never imports fleet). It owns three jobs:

  * prefix blob plane — export a prefix-cache chain's pinned blocks as a
    wire blob (GET /api/v1/kv/prefix/<chain>) and install a fetched blob
    into the local PagedPrefixCache through the same pin/map machinery a
    local capture uses, so a fetched chain is indistinguishable from a
    locally-computed one (greedy outputs stay bit-identical);
  * fetch-before-recompute — on admission, consult the router-injected
    peer directory header and fetch the longest matching chain from a
    warm peer instead of re-prefilling, bounded by
    CAKE_KVSHARE_FETCH_TIMEOUT_S; every failure mode degrades to honest
    recompute;
  * live stream migration — park a draining/migrating slot's swap blob
    (PagedKV.swap_out: KV bytes + row state + decode carries + the
    generated-token record) for the router's resume plane to ship to a
    new owner, which adopts it through the engine's swap-resume path and
    continues the stream bit-exactly (the rng carry rides the blob).

Threading model: the prefix cache and the paged pool are scheduler-thread
-only state, so every mutation runs as a mailbox job drained by
run_pending() at the top of each engine iteration (the engine calls it
before its idle early-return, and submit_job sets the engine's wake
event, so an idle engine still serves blobs promptly). API threads block
on a per-job event with a deadline. The only cross-thread reads outside
the mailbox are the inventory mirror (an atomically swapped tuple) and
the parked/inbound stores (dict ops under self._lock).
"""
from __future__ import annotations

import asyncio
import hashlib
import logging
import threading
from collections import deque

import jax
import jax.numpy as jnp
import numpy as np

from ... import knobs
from ...obs import (FLEET_KV_FETCH_BYTES, FLEET_KV_FETCHES,
                    FLEET_KV_HIT_RATIO, SERVE_PREFIX_BYTES,
                    SERVE_SLOTS_BUSY, TIMELINES, now)
from ...serve.engine import ServeRequest
from ...serve.paged import PreemptedSlot
from ...serve.prefix_cache import _PagedEntry
from .blob import KVBlobMismatch, decode_blob, encode_blob, pool_signature

__all__ = ["KVShareReplica", "StreamMigrated", "KV_DIR_HEADER",
           "KV_RESUME_HEADER", "KV_RESUMED_HEADER"]

log = logging.getLogger("cake.fleet.kvshare")

# router -> replica: the peer directory (compact JSON of warm peers and
# their advertised chain keys), injected per attempt like the QoS header
KV_DIR_HEADER = "X-Cake-KV-Peers"
# router -> replica: adopt the posted stream blob for this request id
# before treating the body as a plain continuation
KV_RESUME_HEADER = "X-Cake-KV-Resume"
# replica -> router: this response replays the stream from token 0 out
# of an adopted blob — strip everything the client already saw
KV_RESUMED_HEADER = "X-Cake-KV-Resumed"

# parked stream blobs nobody fetched are dropped after this many
# seconds (host RAM; the client's own retry has long moved on)
_PARKED_TTL_S = 60.0


class StreamMigrated(RuntimeError):
    """This live stream's KV state was parked for migration: the slot is
    gone and the blob is waiting for the router's resume plane. The SSE
    handler severs the socket mid-body (NO clean finish) so the router
    classifies the leg as broken and runs its resume machinery."""

    def __init__(self, rid: str):
        super().__init__(
            f"stream {rid} migrated: swap blob parked for the fleet "
            "resume plane")
        self.rid = rid


def _chain_of(ids: np.ndarray, block: int) -> list[bytes]:
    """Unit keys of a token record that is an exact multiple of the unit
    size — one key per STORED unit. (PrefixCache.chain_keys caps at
    (n-1)//block because an admission must keep one live suffix token;
    an exported entry's record covers exactly its units, so the export
    and import sides hash the full record with this instead.)"""
    h = hashlib.blake2b(digest_size=16)
    keys = []
    for b in range(len(ids) // block):
        h.update(ids[b * block:(b + 1) * block].tobytes())
        keys.append(h.digest())
    return keys


class KVShareReplica:
    """Per-replica kvshare agent (see module docstring)."""

    def __init__(self, engine):
        self.engine = engine
        self.fetch_timeout = float(knobs.get("CAKE_KVSHARE_FETCH_TIMEOUT_S"))
        self.inventory_cap = int(knobs.get("CAKE_KVSHARE_INVENTORY"))
        # mailbox: (kind, payload, box) appended by API threads, drained
        # on the scheduler thread; deque append/popleft are atomic
        self._jobs: deque = deque()
        self._lock = threading.Lock()
        self._parked: dict = {}     # guarded-by: self._lock
        self._inbound: dict = {}    # guarded-by: self._lock
        # inventory mirror: hex chain keys this replica can export,
        # newest-first. Rebuilt on the scheduler thread whenever the
        # prefix cache's membership version moves, swapped atomically so
        # API threads read it lock-free
        self._inventory: tuple = ()
        self._pc_version = -1
        self._drain_swept = False
        # lifetime fetch accounting behind the hit-ratio gauge
        self._fetches = 0
        self._fetch_hits = 0

    # -- scheduler-thread side ---------------------------------------------

    def run_pending(self) -> None:
        """Drain the mailbox + housekeeping. Called at the top of every
        engine scheduler iteration (and on wake): everything in here runs
        on the scheduler thread, where the prefix cache and paged pool
        are safe to touch."""
        eng = self.engine
        try:
            self._sweep_drain()
            self._sweep_parked_ttl()
        except Exception:
            log.exception("kvshare housekeeping failed")
        while True:
            try:
                kind, payload, box = self._jobs.popleft()
            except IndexError:
                break
            try:
                box["result"] = self._execute(kind, payload)
            except BaseException as e:   # the submitter re-raises it
                box["error"] = e
            box["event"].set()
        pc = eng.prefix_cache
        if pc is not None and pc.version != self._pc_version:
            self._pc_version = pc.version
            cap = max(self.inventory_cap, 0)
            keys = list(pc._blocks)[-cap:] if cap else []
            self._inventory = tuple(k.hex() for k in reversed(keys))

    def submit_job(self, kind: str, payload, timeout: float):
        """API-thread entry: enqueue a scheduler job and block on its
        completion (the engine's wake event lands the _run loop in
        run_pending even when idle). Raises TimeoutError past the
        deadline and re-raises whatever the job raised."""
        box = {"event": threading.Event()}
        self._jobs.append((kind, payload, box))
        self.engine._wake.set()
        if not box["event"].wait(timeout):
            raise TimeoutError(f"kvshare {kind} job timed out after "
                               f"{timeout:.1f}s")
        if "error" in box:
            raise box["error"]
        return box.get("result")

    def _execute(self, kind: str, payload):
        if kind == "export_prefix":
            return self._export_prefix(payload)
        if kind == "import_prefix":
            return self._import_prefix(payload)
        if kind == "export_stream":
            return self._export_stream(payload)
        if kind == "adopt":
            return self._adopt(payload)
        raise ValueError(f"unknown kvshare job kind {kind!r}")

    # -- drain parking -------------------------------------------------------

    def _sweep_drain(self) -> None:
        """On drain, park every live STREAMED decode once: the router
        resumes each one on a peer from the shipped blob instead of the
        continuation re-prefill. Mid-prefill slots and subscriber-less
        (blocking) requests finish normally under the old drain path; a
        slot whose first token is sampled but unfetched is skipped too —
        parking it would lose that token."""
        eng = self.engine
        if not eng._draining.is_set():
            self._drain_swept = False
            return
        if self._drain_swept or eng.paged is None:
            return
        self._drain_swept = True
        prefilling = {p.slot for p in eng._prefills}
        for i in eng.pool.busy():
            req = eng._reqs[i]
            if req is None or i in prefilling or not req.tokens \
                    or req._first_pending or req.cancelled.is_set() \
                    or req.done.is_set():
                continue
            with req._sub_lock:
                live = req._token_cb is not None
            if not live:
                continue
            self._park_slot(i, req)

    def _sweep_parked_ttl(self) -> None:
        cutoff = now() - _PARKED_TTL_S
        with self._lock:
            stale = [rid for rid, p in self._parked.items()
                     if p["t"] < cutoff]
            for rid in stale:
                del self._parked[rid]
        for rid in stale:
            log.warning("kvshare: dropped unclaimed parked stream %s", rid)

    def _park_slot(self, slot: int, req: ServeRequest) -> dict:
        """Swap a live decode out of its slot and park the blob for the
        resume plane — the migration-flavored _preempt_slot: same
        committed-frontier trim + swap_out, but the request FAILS typed
        (StreamMigrated) instead of joining the resume queue, because its
        next owner is another replica."""
        eng = self.engine
        wp = len(req.prompt_ids) + max(len(req.tokens) - 1, 0)
        eng.paged.trim_to(slot, wp)
        blob = eng.paged.swap_out(
            slot, (eng._toks, eng._pos, eng._rngs, eng._recents))
        parked = {"blob": blob, "gen_ids": list(req.tokens),
                  "prompt_ids": list(req.prompt_ids),
                  "budget": req.budget, "wp": wp, "t": now()}
        with self._lock:
            self._parked[req.id] = parked
        TIMELINES.event(req.id, "preempt", mode="swap", tokens=wp)
        eng.pool.free(slot)
        eng._reqs[slot] = None
        req.slot = None
        eng._act = eng._act.at[slot].set(False)
        eng._toks = eng._toks.at[slot].set(0)
        eng._pos = eng._pos.at[slot].set(0)
        SERVE_SLOTS_BUSY.set(eng.pool.busy_count)
        log.info("kvshare: parked stream %s (%d prompt + %d generated "
                 "tokens) for migration", req.id, len(req.prompt_ids),
                 len(req.tokens))
        eng._fail(req, StreamMigrated(req.id))
        return parked

    # -- prefix export/import (scheduler thread) -----------------------------

    def _export_prefix(self, chain_hex: str) -> bytes | None:
        """Wire blob of the longest CONTIGUOUS cached chain head ending
        at (or before) the requested chain key; None = not exportable
        here. Serves the prefix GET route."""
        eng = self.engine
        pc, paged = eng.prefix_cache, eng.paged
        if pc is None or paged is None:
            return None
        try:
            want = bytes.fromhex(chain_hex)
        except ValueError:
            return None
        tip = pc._blocks.get(want)
        if tip is None:
            return None
        ids = np.asarray(tip.tokens, np.int32)
        keys = _chain_of(ids, pc.block)
        entries = []
        for k in keys:                  # stop at the first gap: the blob
            e = pc._blocks.get(k)       # must stay a contiguous head
            if e is None:
                break
            entries.append(e)
        if not entries:
            return None
        all_pids = [pid for e in entries for pid in e.pids]
        arrays = {"tokens": np.asarray(entries[-1].tokens, np.int32)}
        pid_idx = jnp.asarray(all_pids, jnp.int32)
        for li, pl in enumerate(paged.pool):
            if not pl:
                continue
            for name in ("k", "v", "pos"):
                # lint: disable=host-sync — the export IS the planned copy to
                # host; this runs on the explicit blob-request path, not per
                # decode iteration
                arrays[f"layers/{li}/{name}"] = np.asarray(pl[name][pid_idx])
        has_snap = entries[0].snap is not None
        if has_snap:
            for u, e in enumerate(entries):
                leaves = jax.tree_util.tree_leaves(e.snap)
                for j, leaf in enumerate(leaves):
                    # lint: disable=host-sync — boundary row snapshots (a few
                    # KB) ride the same export blob
                    arrays[f"snap/{u}/{j}"] = np.asarray(leaf)
        header = {
            "kind": "prefix",
            "chain": keys[len(entries) - 1].hex(),
            "units": len(entries),
            "unit_tokens": pc.block,
            "block_tokens": paged.bt,
            "bpu": pc.bpu,
            "pool": pool_signature(paged),
            "has_snap": has_snap,
        }
        return encode_blob(header, arrays)

    def _import_prefix(self, data: bytes) -> dict:
        """Install a fetched prefix blob into the local PagedPrefixCache:
        fresh physical blocks, cache-pin ownership, per-unit boundary
        snapshots — after this, match()/splice() treat the chain exactly
        like a local capture. Installs the longest contiguous head that
        fits (capacity/pool pressure can shorten it — still valid).
        Raises KVBlobMismatch when the blob cannot apply here at all."""
        eng = self.engine
        pc, paged = eng.prefix_cache, eng.paged
        if pc is None or paged is None:
            raise KVBlobMismatch("replica has no paged prefix cache")
        header, arrays = decode_blob(data)
        if header.get("kind") != "prefix":
            raise KVBlobMismatch("not a prefix blob")
        if header.get("pool") != pool_signature(paged):
            raise KVBlobMismatch("pool shape signature mismatch")
        if header.get("unit_tokens") != pc.block \
                or header.get("block_tokens") != paged.bt \
                or header.get("bpu") != pc.bpu:
            raise KVBlobMismatch("prefix geometry mismatch")
        units = int(header.get("units") or 0)
        tokens = arrays.get("tokens")
        if units < 1 or tokens is None \
                or len(tokens) != units * pc.block:
            raise KVBlobMismatch("prefix blob token record inconsistent")
        ids = np.asarray(tokens, np.int32)
        keys = _chain_of(ids, pc.block)     # never trust the sender's keys
        rows = {}
        for li, pl in enumerate(paged.pool):
            if not pl:
                continue
            for name in ("k", "v", "pos"):
                a = arrays.get(f"layers/{li}/{name}")
                if a is None or a.shape[0] != units * pc.bpu:
                    raise KVBlobMismatch(
                        f"prefix blob layer {li}/{name} rows missing or "
                        "short")
                rows[(li, name)] = a
        snaps = self._decode_snaps(header, arrays, units)
        installed = 0
        for u in range(units):
            key = keys[u]
            if key in pc._blocks:           # dedupe, refresh recency
                pc._blocks.move_to_end(key)
                installed = u + 1
                continue
            snap = snaps[u] if snaps is not None else None
            snap_nbytes = sum(a.nbytes for a in
                              jax.tree_util.tree_leaves(snap)) \
                if snap is not None else 0
            nbytes = pc.bpu * paged.block_bytes + snap_nbytes
            if nbytes > pc.capacity:
                break
            while pc.bytes + nbytes > pc.capacity and pc._blocks:
                pc._evict_lru()
            if not paged.ensure_free(pc.bpu):
                break                       # partial contiguous head: valid
            pids = []
            for _ in range(pc.bpu):
                pid = paged._alloc_one()
                assert pid is not None      # guarded by ensure_free above
                pids.append(pid)
            dst = jnp.asarray(pids, jnp.int32)
            sl = slice(u * pc.bpu, (u + 1) * pc.bpu)
            for (li, name), arr in rows.items():
                pl = paged.pool[li]
                pl[name] = pl[name].at[dst].set(jnp.asarray(arr[sl]))
            # cache-pin ownership: alloc() granted ref=1; convert it to a
            # pure pin (ref == mappings + cache_pins stays balanced)
            for pid in pids:
                paged.alloc.ref(pid, cache_pin=True)
                paged.alloc.deref(pid)
            pc._blocks[key] = _PagedEntry(
                tokens=ids[:(u + 1) * pc.block], pids=pids, snap=snap,
                nbytes=nbytes)
            pc.bytes += nbytes
            pc.version += 1
            pc.pinned += len(pids)
            installed = u + 1
        paged._publish()
        SERVE_PREFIX_BYTES.set(pc.bytes)
        if installed == 0:
            raise KVBlobMismatch("no room to install any prefix unit")
        log.info("kvshare: installed %d/%d prefix units (%d tokens)",
                 installed, units, installed * pc.block)
        return {"installed_units": installed,
                "tokens": installed * pc.block}

    def _decode_snaps(self, header: dict, arrays: dict, units: int):
        """Rebuild per-unit boundary row snapshots against the LOCAL row
        treedef (the blob carries leaves only: treedefs don't serialize,
        and shape-checking against a locally-derived reference is the
        honest compatibility gate)."""
        eng = self.engine
        paged = eng.paged
        if not header.get("has_snap"):
            if paged.has_rows:
                raise KVBlobMismatch(
                    "prefix blob has no row snapshots but this pool "
                    "keeps per-slot rows")
            return None
        if not paged.has_rows:
            raise KVBlobMismatch(
                "prefix blob carries row snapshots but this pool is "
                "rowless")
        ref = eng.model.row_snapshot(paged.rows, 0)
        leaves, treedef = jax.tree_util.tree_flatten(ref)
        snaps = []
        for u in range(units):
            got = []
            for j, leaf in enumerate(leaves):
                a = arrays.get(f"snap/{u}/{j}")
                if a is None or tuple(a.shape) != tuple(leaf.shape) \
                        or str(a.dtype) != str(leaf.dtype):
                    raise KVBlobMismatch(
                        f"row snapshot {u}/{j} missing or shaped wrong")
                got.append(jnp.asarray(a))
            if f"snap/{u}/{len(leaves)}" in arrays:
                raise KVBlobMismatch("row snapshot has extra leaves")
            snaps.append(jax.tree_util.tree_unflatten(treedef, got))
        return snaps

    # -- stream export/adopt (scheduler thread) ------------------------------

    def export_stream(self, rid: str, timeout: float) -> bytes | None:
        """API-thread entry for the stream GET route. An ALREADY-parked
        blob encodes directly (host memory + static pool shapes — no
        engine state; this keeps drain-parked blobs fetchable even while
        the scheduler is busy tearing down). A live stream goes through
        the mailbox so the park runs on the scheduler thread."""
        with self._lock:
            parked = self._parked.get(rid)
        if parked is not None:
            return self._encode_stream(rid, parked)
        return self.submit_job("export_stream", rid, timeout)

    def _export_stream(self, rid: str) -> bytes | None:
        """Wire blob of a parked stream; a LIVE stream is parked on the
        spot (the resume plane's fetch IS the migration signal — covers
        planned rebalance and post-commit failover where the source
        still answers). None = unknown stream."""
        with self._lock:
            parked = self._parked.get(rid)
        if parked is None:
            parked = self._park_live(rid)
        if parked is None:
            return None
        return self._encode_stream(rid, parked)

    def _park_live(self, rid: str) -> dict | None:
        eng = self.engine
        if eng.paged is None:
            return None
        prefilling = {p.slot for p in eng._prefills}
        for i in eng.pool.busy():
            req = eng._reqs[i]
            if req is None or req.id != rid:
                continue
            if i in prefilling or not req.tokens or req._first_pending \
                    or req.cancelled.is_set() or req.done.is_set():
                return None     # not migratable in this state
            return self._park_slot(i, req)
        return None

    def _encode_stream(self, rid: str, parked: dict) -> bytes:
        eng = self.engine
        paged = eng.paged
        blob = parked["blob"]
        arrays = {
            "idx": np.asarray(blob["idx"], np.int32),
            "gen_ids": np.asarray(parked["gen_ids"], np.int32),
            "prompt_ids": np.asarray(parked["prompt_ids"], np.int32),
        }
        for li, saved in enumerate(blob["layers"]):
            if not saved:
                continue
            for name in ("k", "v", "pos"):
                arrays[f"layers/{li}/{name}"] = saved[name]
        has_rows = blob["rows"] is not None
        if has_rows:
            for j, leaf in enumerate(
                    jax.tree_util.tree_leaves(blob["rows"])):
                arrays[f"rows/{j}"] = np.asarray(leaf)
        for ci, c in enumerate(blob["carries"]):
            arrays[f"carries/{ci}"] = np.asarray(c)
        header = {
            "kind": "stream", "rid": rid, "budget": parked["budget"],
            "wp": parked["wp"], "block_tokens": paged.bt,
            "pool": pool_signature(paged), "has_rows": has_rows,
        }
        return encode_blob(header, arrays)

    def store_inbound(self, rid: str, data: bytes) -> dict:
        """Decode + stage a stream blob shipped by the router (any
        thread: decode touches no engine state). The adopt job installs
        it when the resumed request arrives."""
        header, arrays = decode_blob(data)
        if header.get("kind") != "stream":
            raise KVBlobMismatch("not a stream blob")
        with self._lock:
            self._inbound[rid] = (header, arrays, now())
        return {"rid": rid, "gen_tokens": int(arrays["gen_ids"].shape[0])}

    def _adopt(self, payload: dict):
        """Adopt a staged stream blob: rebuild the swap-blob dict against
        the local pool and enter the engine through the swap-resume path
        (_resume_preempted swap_in's it and the decode carries continue
        the sampled sequence bit-exactly). Returns the live ServeRequest,
        or None = cannot adopt (caller falls back to the plain
        continuation re-prefill)."""
        eng = self.engine
        paged = eng.paged
        rid = payload["rid"]
        with self._lock:
            staged = self._inbound.pop(rid, None)
        if staged is None or paged is None:
            return None
        header, arrays, _ = staged
        if header.get("pool") != pool_signature(paged) \
                or header.get("block_tokens") != paged.bt:
            log.warning("kvshare: staged blob for %s does not match this "
                        "pool; falling back to continuation", rid)
            return None
        idx = [int(i) for i in arrays["idx"]]
        if not idx or max(idx) >= paged.max_blocks:
            return None
        layers = []
        for li, pl in enumerate(paged.pool):
            if not pl:
                layers.append({})
                continue
            d = {}
            for name in ("k", "v", "pos"):
                a = arrays.get(f"layers/{li}/{name}")
                if a is None or a.shape[0] != len(idx):
                    return None
                d[name] = a
            layers.append(d)
        rows = None
        if header.get("has_rows"):
            if not paged.has_rows:
                return None
            ref = eng.model.row_snapshot(paged.rows, 0)
            leaves, treedef = jax.tree_util.tree_flatten(ref)
            got = []
            for j, leaf in enumerate(leaves):
                a = arrays.get(f"rows/{j}")
                if a is None or tuple(a.shape) != tuple(leaf.shape):
                    return None
                got.append(a)
            rows = jax.tree_util.tree_unflatten(treedef, got)
        elif paged.has_rows:
            return None
        try:
            carries = [arrays[f"carries/{i}"] for i in range(4)]
        except KeyError:
            return None
        gen_ids = [int(t) for t in arrays["gen_ids"]]
        prompt_ids = [int(t) for t in arrays["prompt_ids"]]
        if not gen_ids or not prompt_ids:
            return None
        blob = {"idx": idx, "layers": layers, "rows": rows,
                "carries": carries}
        req = ServeRequest(prompt_ids, max(len(gen_ids) + 1, 2),
                           payload.get("sampling"), request_id=rid,
                           qos=payload.get("qos", "interactive"),
                           tenant=payload.get("tenant"),
                           continuation=True)
        req._engine = eng
        req.tokens = list(gen_ids)
        req.budget = max(int(header.get("budget") or 0), 0)
        req.t_first = now()
        req.stats["ttft_s"] = 0.0
        req.stats["kv_migrated"] = True
        wp = int(header.get("wp") or 0)
        eng._preempted.append(PreemptedSlot(req, "swap", wp, blob))
        eng._wake.set()
        log.info("kvshare: adopted migrated stream %s (%d generated "
                 "tokens, budget %d)", rid, len(gen_ids), req.budget)
        return req

    # -- fetch-before-recompute (API thread, async) --------------------------

    async def fetch_before_prefill(self, rid: str, prompt_ids: list,
                                   peers_header: str) -> None:
        """Consult the router-injected peer directory and try ONE fetch
        of the longest chain a warm peer advertises beyond what the local
        cache already holds. Best-effort by construction: every failure
        (no match, HTTP error, timeout, geometry mismatch) returns with
        the cache unchanged and the admission recomputes honestly."""
        eng = self.engine
        pc = eng.prefix_cache
        if pc is None or eng.paged is None or not peers_header:
            return
        from .directory import parse_directory
        peers = parse_directory(peers_header)
        if not peers:
            return
        keys = pc.chain_keys(prompt_ids)
        if not keys:
            return
        hexkeys = [k.hex() for k in keys]
        local = 0
        for i in range(len(keys) - 1, -1, -1):
            if keys[i] in pc._blocks:   # racy read, advisory only: a
                local = i + 1           # stale answer costs one redundant
                break                   # fetch or one missed one
        best = None
        for i in range(len(hexkeys) - 1, local - 1, -1):
            for url, advertised in peers:
                if hexkeys[i] in advertised:
                    best = (i + 1, url, hexkeys[i])
                    break
            if best is not None:
                break
        if best is None:
            if local < len(keys):
                self._account_fetch("miss", rid, None)
            return
        units, url, chain_hex = best
        import aiohttp
        t0 = now()
        deadline = max(self.fetch_timeout, 0.1)
        try:
            timeout = aiohttp.ClientTimeout(total=deadline)
            async with aiohttp.ClientSession(timeout=timeout) as sess:
                async with sess.get(
                        url.rstrip("/") + "/api/v1/kv/prefix/"
                        + chain_hex) as r:
                    if r.status != 200:
                        self._account_fetch("miss", rid, url)
                        return
                    data = await r.read()
        except asyncio.TimeoutError:
            self._account_fetch("timeout", rid, url)
            return
        except Exception:
            self._account_fetch("error", rid, url)
            return
        remaining = max(deadline - (now() - t0), 0.2)
        loop = asyncio.get_running_loop()
        try:
            res = await loop.run_in_executor(
                None, lambda: self.submit_job("import_prefix", data,
                                              remaining))
        except KVBlobMismatch:
            self._account_fetch("mismatch", rid, url)
            return
        except Exception:
            self._account_fetch("error", rid, url)
            return
        self._account_fetch("hit", rid, url, tokens=res["tokens"])
        FLEET_KV_FETCH_BYTES.inc(len(data))

    def _account_fetch(self, outcome: str, rid: str, peer: str | None,
                       **attrs) -> None:
        FLEET_KV_FETCHES.inc(outcome=outcome)
        self._fetches += 1
        if outcome == "hit":
            self._fetch_hits += 1
        FLEET_KV_HIT_RATIO.set(self._fetch_hits / self._fetches)
        ev = {"outcome": outcome}
        if peer:
            ev["peer"] = peer
        ev.update(attrs)
        TIMELINES.event(rid, "kv_fetch", **ev)

    # -- views ---------------------------------------------------------------

    def health_view(self) -> dict:
        """The kvshare block /health carries — the registry mirrors
        `chains` into the peer directory on every probe scrape."""
        with self._lock:
            parked = len(self._parked)
            inbound = len(self._inbound)
        return {"chains": list(self._inventory), "parked": parked,
                "inbound": inbound}
