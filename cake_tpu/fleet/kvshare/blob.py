"""Fleet-shared KV wire format: versioned, checksummed blobs of paged
KV state.

One codec carries both transfer kinds of the kvshare tier:

  * kind="prefix" — a prefix-cache chain's pinned blocks (per-layer
    block arrays + the boundary row snapshot), exported by a warm
    replica and installed into a cold peer's PagedPrefixCache so the
    peer's next admission splices instead of re-prefilling;
  * kind="stream" — a live slot's swap blob (PagedKV.swap_out layout:
    block arrays + row snapshot + decode carries + the generated-token
    record), shipped to a new owner on drain/rebalance so a sampled
    stream resumes bit-exactly (the rng carry rides the blob).

Layout: MAGIC + version byte + blake2b-16 digest of the payload +
payload, where payload = 4-byte big-endian header length + compact JSON
header + the concatenated raw array bytes in header-manifest order. The
header's "arrays" manifest records each array's key/dtype/shape; every
other header field is kind-specific (pool signature, chain key, token
counts, budget).

Every failure mode — bad magic, version skew, checksum mismatch, a
manifest that disagrees with the body, or a pool-shape signature that
does not match the importing replica — raises the typed KVBlobMismatch.
Callers treat that as "recompute honestly": a fetched blob can never
corrupt a pool, only fail to help.
"""
from __future__ import annotations

import hashlib
import json

import numpy as np

__all__ = ["KVBlobMismatch", "MAGIC", "VERSION", "encode_blob",
           "decode_blob", "pool_signature"]

MAGIC = b"CAKEKV"
VERSION = 1
_DIGEST = 16


class KVBlobMismatch(ValueError):
    """Typed reject: this blob cannot be installed here. The fallback is
    ALWAYS honest recompute — never a partial or corrupted install."""


def encode_blob(header: dict, arrays: dict) -> bytes:
    """Serialize `arrays` (name -> np.ndarray, order-significant) under
    `header` (JSON-safe dict; an "arrays" manifest is added here)."""
    manifest = []
    chunks = []
    for key, arr in arrays.items():
        src = np.asarray(arr)
        a = np.ascontiguousarray(src)
        # record the SOURCE shape: ascontiguousarray promotes 0-d
        # scalars (the toks/pos decode carries) to (1,), and a carry
        # must come back with the exact shape the engine swapped out
        manifest.append({"key": key, "dtype": str(a.dtype),
                         "shape": list(src.shape)})
        chunks.append(a.tobytes())
    head = dict(header)
    head["arrays"] = manifest
    hj = json.dumps(head, separators=(",", ":"), sort_keys=True).encode()
    payload = len(hj).to_bytes(4, "big") + hj + b"".join(chunks)
    digest = hashlib.blake2b(payload, digest_size=_DIGEST).digest()
    return MAGIC + bytes([VERSION]) + digest + payload


def decode_blob(data: bytes) -> tuple[dict, dict]:
    """Verify + parse a wire blob; returns (header, arrays). Raises
    KVBlobMismatch on any structural problem — the checksum covers the
    whole payload, so a passing decode is byte-exact."""
    pre = len(MAGIC) + 1 + _DIGEST
    if not isinstance(data, (bytes, bytearray)) or len(data) < pre + 4:
        raise KVBlobMismatch("kv blob truncated")
    data = bytes(data)
    if data[:len(MAGIC)] != MAGIC:
        raise KVBlobMismatch("kv blob: bad magic")
    ver = data[len(MAGIC)]
    if ver != VERSION:
        raise KVBlobMismatch(f"kv blob version {ver} != {VERSION}")
    digest = data[len(MAGIC) + 1:pre]
    payload = data[pre:]
    if hashlib.blake2b(payload, digest_size=_DIGEST).digest() != digest:
        raise KVBlobMismatch("kv blob checksum mismatch")
    hlen = int.from_bytes(payload[:4], "big")
    if 4 + hlen > len(payload):
        raise KVBlobMismatch("kv blob header truncated")
    try:
        header = json.loads(payload[4:4 + hlen].decode())
    except Exception as e:
        raise KVBlobMismatch(f"kv blob header unreadable: {e}")
    body = payload[4 + hlen:]
    arrays = {}
    pos = 0
    for m in header.get("arrays") or []:
        try:
            dt = np.dtype(m["dtype"])
            shape = tuple(int(s) for s in m["shape"])
        except Exception as e:
            raise KVBlobMismatch(f"kv blob manifest unreadable: {e}")
        n = int(np.prod(shape, dtype=np.int64)) * dt.itemsize
        if pos + n > len(body):
            raise KVBlobMismatch("kv blob body truncated")
        arrays[m["key"]] = np.frombuffer(
            body[pos:pos + n], dtype=dt).reshape(shape).copy()
        pos += n
    if pos != len(body):
        raise KVBlobMismatch("kv blob: trailing bytes past manifest")
    return header, arrays


def pool_signature(paged) -> list:
    """JSON-safe shape/dtype signature of a PagedKV pool's per-layer
    block arrays (batch dim excluded — block COUNT may differ between
    peers; per-block geometry and dtype must not). Import refuses any
    blob whose recorded signature differs from the local one."""
    sig = []
    for pl in paged.pool:
        if not pl:
            sig.append(None)
        else:
            sig.append({n: [list(pl[n].shape[1:]), str(pl[n].dtype)]
                        for n in ("k", "v", "pos")})
    return sig
