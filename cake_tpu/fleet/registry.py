"""Replica registry: health-driven membership for the fleet router.

One `cake serve` replica survives crashes (serve/supervisor.py) and
worker death (cluster/master.py) — but a fleet of N replicas needs a
tier that stops ROUTING to a sick one. This module owns that decision:
every replica the router fronts is a :class:`Replica` whose membership
state machine is driven by two signal streams,

  * the router's own request outcomes (transport failures, replica 5xx,
    time-to-first-byte), fed through :meth:`Replica.record_result`; and
  * the replica's /health engine block (down / wedged / draining, queue
    depth, kv_pool occupancy), fed through :meth:`Replica.observe_health`
    by the router's probe loop.

The gray-failure detector is the cluster hop detector's shape
(cluster/client.py: rolling window, p95 vs threshold, minimum samples
before it may trip) applied to routing: a replica whose rolling error
rate or TTFB p95 crosses its threshold is EJECTED even though TCP still
connects — slow-but-alive is the failure mode that burns tail latency
("The Tail at Scale", Dean & Barroso).

State machine (docs/fleet.md has the diagram):

    HEALTHY --(consecutive transport fails >= eject_fails,
               error rate >= err_rate over the window,
               TTFB p95 > degraded_ttft_ms,
               or /health says down/wedged)--> EJECTED
    EJECTED --(hold expires AND a probe succeeds)--> HALF_OPEN
    HALF_OPEN --(one successful trial request,
                 or two consecutive healthy probes
                 *probe-evidence ejects only*)--> HEALTHY
    HALF_OPEN --(any failure)--> EJECTED (hold doubles, capped 8x)

Every eject carries an EVIDENCE dimension: "data" when the router's own
request path produced the evidence (transport failures, error rate,
TTFB p95 — including the half-open trial failing), "probe" when only
the /health probe path did. Data evidence is sticky for the episode and
gates readmission: probe successes alone can NEVER clear a
data-evidence eject — only the half-open data-path trial lease can.
This kills the asymmetric-partition flap, where a replica whose probe
path is alive but whose data path is partitioned would otherwise
readmit on two healthy probes, fail its next real request, re-eject,
and loop. While a data-evidence eject is open the replica is in a
suspected-PARTITION episode (surfaced in /fleet, the replica
pseudo-timeline, and cake_fleet_partition_seconds_total). Readmission
does not reset the hold-doubling streak — repeated partition/heal
cycles find their re-eject hold doubled each round (no reputation
laundering); the streak expires only after a quiet forget window.

DRAINING is orthogonal: a replica whose engine block says draining keeps
its machine state but stops taking NEW requests (in-flight ones finish)
— mirroring how the engine itself drains.

Thread model: the probe loop and the request path touch the same fields,
so every mutable field is `# guarded-by:` its owner's lock and the
lock-discipline lint (cake_tpu/analysis) enforces the annotation.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass

from .. import knobs
from ..obs import (FLEET_EJECTS, FLEET_PARTITION_SECONDS, FLEET_READMITS,
                   FLEET_REPLICAS, FLEET_REPLICA_INFLIGHT,
                   FLEET_REPLICA_OCCUPANCY, FLEET_REPLICA_OUTLIER,
                   FLEET_REPLICA_QUEUE_DEPTH, FLEET_REPLICA_STALE, now)

__all__ = ["Replica", "ReplicaRegistry", "MembershipPolicy",
           "discover_replicas", "HEALTHY", "EJECTED", "HALF_OPEN"]

HEALTHY = "healthy"
EJECTED = "ejected"
HALF_OPEN = "half_open"

# minimum rolling-window samples before the error-rate / TTFB detectors
# may trip (one bad response is noise, not gray failure) — same guard as
# the cluster hop detector's GRAY_MIN_SAMPLES
GRAY_MIN_SAMPLES = 8

# ejection hold multiplier cap: repeated re-ejects back off the half-open
# probe exponentially, but a replica is never held out longer than 8x the
# base hold (a flapping replica should still get probed, just less often)
MAX_EJECT_BACKOFF = 8

# per-replica in-flight fallback before the first health probe reports a
# slot count (auto cap = 2x slots once known)
DEFAULT_INFLIGHT_CAP = 8

# eject-streak forget window, in multiples of the FULLY BACKED-OFF hold:
# a replica that stays out of trouble this long after its last eject has
# its hold-doubling reputation expired (readmission alone never resets
# the streak — see _readmit)
EJECT_FORGET_HOLDS = 2


@dataclass(frozen=True)
class MembershipPolicy:
    """Ejection thresholds, snapshotted from knobs at registry build time
    (tests construct their own)."""

    eject_fails: int = 3        # consecutive transport failures
    err_window: int = 32        # rolling result window length
    err_rate: float = 0.5       # error fraction over the window
    degraded_ttft_ms: float = 0.0   # TTFB p95 gray threshold (0 = off)
    eject_s: float = 5.0        # base ejection hold before half-open
    replica_inflight: int = 0   # per-replica cap (0 = auto from health)

    @classmethod
    def from_knobs(cls) -> "MembershipPolicy":
        return cls(
            eject_fails=max(knobs.get("CAKE_FLEET_EJECT_FAILS"), 1),
            err_window=max(knobs.get("CAKE_FLEET_ERR_WINDOW"), 4),
            err_rate=knobs.get("CAKE_FLEET_ERR_RATE"),
            degraded_ttft_ms=knobs.get("CAKE_FLEET_DEGRADED_TTFT_MS"),
            eject_s=knobs.get("CAKE_FLEET_EJECT_S"),
            replica_inflight=knobs.get("CAKE_FLEET_REPLICA_INFLIGHT"))


class Replica:
    """One `cake serve` replica: identity + membership state + the live
    load view the router routes on. All mutable state is guarded by
    `self._lock` — the probe loop and every concurrent request handler
    share these fields."""

    def __init__(self, name: str, base_url: str,
                 policy: MembershipPolicy | None = None):
        self.name = name
        self.base_url = base_url.rstrip("/")
        self.policy = policy or MembershipPolicy()
        # reentrant: the state-machine helpers (_eject/_readmit/_cap/...)
        # re-acquire under their callers so the lock-discipline lint can
        # verify every guarded access lexically, in helpers included
        self._lock = threading.RLock()
        # membership state machine (probe loop + request path)
        self.state = HEALTHY            # guarded-by: self._lock
        self.state_since = now()        # guarded-by: self._lock
        self.consec_fails = 0           # guarded-by: self._lock
        self.results: list = []         # guarded-by: self._lock
        self.eject_until = 0.0          # guarded-by: self._lock
        self.eject_streak = 0           # guarded-by: self._lock
        self.probe_ok_streak = 0        # guarded-by: self._lock
        self.trial_inflight = False     # guarded-by: self._lock
        # live load view, mirrored from /health by the probe loop
        self.inflight = 0               # guarded-by: self._lock
        self.draining = False           # guarded-by: self._lock
        self.slots_hint = 0             # guarded-by: self._lock
        self.queue_depth = 0            # guarded-by: self._lock
        self.occupancy = 0.0            # guarded-by: self._lock
        self.last_probe_ok = None       # guarded-by: self._lock
        self.ejects = 0                 # guarded-by: self._lock
        self.readmits = 0               # guarded-by: self._lock
        # evidence behind the OPEN eject episode: "data" (request-path
        # transport/error evidence) or "probe" (/health evidence only);
        # None = no open episode. Data evidence is sticky and gates
        # readmission to the data-path trial (see module docstring).
        self.eject_evidence = None      # guarded-by: self._lock
        # suspected-partition episode (open while eject_evidence is
        # "data"): wall-clock start + last accrual point feeding
        # cake_fleet_partition_seconds_total incrementally
        self.partition_since = None     # guarded-by: self._lock
        self._partition_accrued_at = 0.0  # guarded-by: self._lock
        # eject-streak decay clock (EJECT_FORGET_HOLDS)
        self._last_eject_at = 0.0       # guarded-by: self._lock
        # membership events pending pickup by the router probe loop
        # into the replica:<name> pseudo-timelines
        self._pending_events = []       # guarded-by: self._lock
        # warm-up clock: when THIS router first saw this replica (reset
        # on re-registration and on detected in-place restart). The
        # autoscaler holds while any replica is younger than
        # CAKE_SCALE_WARMUP_S — a cold replica's empty histograms would
        # misread as zero headroom and re-trigger the scale-out that
        # just ran.
        self.first_seen = now()         # guarded-by: self._lock
        self._last_started_age = None   # guarded-by: self._lock
        # lifecycle cordon: the router stops routing NEW requests here
        # while the lifecycle manager drains + reaps it (scale-in);
        # unlike `draining` (mirrored from the replica's own /health)
        # this is the ROUTER's decision and survives probe updates
        self.cordoned = False           # guarded-by: self._lock
        # telemetry-plane anomaly flag (fleet/telemetry.py writes it
        # once per rollup cycle; /fleet surfaces it without ejecting)
        self.outlier = False            # guarded-by: self._lock
        self.outlier_reason = None      # guarded-by: self._lock
        # kvshare inventory: hex prefix-chain keys this replica's last
        # healthy /health advertised (kvshare.chains). Retracted the
        # instant a probe goes stale/sick — a peer directory must never
        # point a fetch at a replica whose cache state is unknown
        self.kv_chains = ()             # guarded-by: self._lock

    # -- capacity -----------------------------------------------------------

    def _cap(self) -> int:
        """Per-replica in-flight cap: the knob when set, else 2x the slot
        count the last health probe reported, else the pre-probe
        fallback."""
        with self._lock:
            if self.policy.replica_inflight > 0:
                return self.policy.replica_inflight
            if self.slots_hint > 0:
                return 2 * self.slots_hint
            return DEFAULT_INFLIGHT_CAP

    def cap(self) -> int:
        with self._lock:
            return self._cap()

    def weight(self) -> float:
        """Rendezvous placement weight: the replica's probed capacity
        (engine slots from its last /health), 1.0 before the first
        probe lands — so a heterogeneous fleet places conversations
        proportionally to real slot counts while a fresh fleet starts
        uniform."""
        with self._lock:
            return float(self.slots_hint) if self.slots_hint > 0 else 1.0

    def try_acquire(self) -> str | None:
        """Reserve one routing slot on this replica. Returns a truthy
        lease token — "slot" for a normal reservation, "trial" for THE
        one half-open probation request — or None when the replica
        refuses (draining, ejected, at cap, trial already in flight).
        The caller passes the token back to release(), which is what
        keeps a pre-eject request's release from clearing the trial
        flag of a probation request still running."""
        with self._lock:
            if self.draining or self.cordoned:
                return None
            if self.state == HEALTHY:
                if self.inflight >= self._cap():
                    return None
                self.inflight += 1
                FLEET_REPLICA_INFLIGHT.set(self.inflight,
                                           replica=self.name)
                return "slot"
            if self.state == HALF_OPEN and not self.trial_inflight:
                self.trial_inflight = True
                self.inflight += 1
                FLEET_REPLICA_INFLIGHT.set(self.inflight,
                                           replica=self.name)
                return "trial"
            return None

    def release(self, lease: str = "slot") -> None:
        with self._lock:
            self.inflight = max(self.inflight - 1, 0)
            if lease == "trial":
                self.trial_inflight = False
            FLEET_REPLICA_INFLIGHT.set(self.inflight, replica=self.name)

    # -- outcome stream (request path) --------------------------------------

    def record_result(self, ok: bool, ttfb_ms: float | None = None,
                      transport: bool = False,
                      lease: str = "slot") -> str | None:
        """Feed one routed-request outcome into the detector. `transport`
        marks connect/read failures (the replica never answered) —
        these drive the consecutive-failure eject; HTTP-level errors
        (replica 5xx) ride the rolling error rate instead. `lease` is
        the token try_acquire issued for this request: only the TRIAL
        request's outcome may move a HALF_OPEN replica (readmit or
        re-eject) — a request that started before the ejection and
        finished during probation is STALE evidence (its failure is the
        old incident, not the probe), and an EJECTED replica ignores
        outcomes entirely. Returns the eject reason when this result
        ejected the replica, else None."""
        with self._lock:
            if self.state == EJECTED:
                return None                 # stale pre-eject outcome
            if ok:
                self.consec_fails = 0
                self.results.append((True, ttfb_ms))
                del self.results[:-self.policy.err_window]
                if self.state == HALF_OPEN:
                    if lease == "trial":
                        self._readmit()
                    return None
                return self._check_gray()
            # failure
            if self.state == HALF_OPEN:
                if lease == "trial":
                    return self._eject("fails")
                return None                 # stale pre-eject failure
            self.results.append((False, None))
            del self.results[:-self.policy.err_window]
            if transport:
                self.consec_fails += 1
            if transport and self.consec_fails >= self.policy.eject_fails:
                return self._eject("fails")
            return self._check_gray()

    def _check_gray(self) -> str | None:
        """Rolling-window detectors: error rate, then TTFB p95 — the hop
        detector's shape, pointed at routing outcomes."""
        with self._lock:
            if (self.state != HEALTHY
                    or len(self.results) < GRAY_MIN_SAMPLES):
                return None
            errs = sum(1 for ok, _ in self.results if not ok)
            if errs / len(self.results) >= self.policy.err_rate:
                return self._eject("error_rate")
            if self.policy.degraded_ttft_ms > 0:
                ms = sorted(t for ok, t in self.results
                            if ok and t is not None)
                if len(ms) >= GRAY_MIN_SAMPLES:
                    p95 = ms[min(int(len(ms) * 0.95), len(ms) - 1)]
                    if p95 > self.policy.degraded_ttft_ms:
                        return self._eject("ttft_p95")
            return None

    # -- health stream (probe loop) ------------------------------------------

    def observe_health(self, status: int | None,
                       body: dict | None) -> str | None:
        """Consume one /health probe. `status` None = unreachable (counts
        like a transport failure). A 503 whose engine block says down or
        wedged ejects immediately — the replica itself is reporting it
        cannot serve. Healthy probes drive the ejected -> half_open ->
        readmit side of the machine, so an idle fleet still readmits
        without waiting for live traffic to gamble on the replica.
        A data-evidence (suspected-partition) eject is the exception:
        healthy probes can advance it to HALF_OPEN but never readmit it
        — the probe path answering says nothing about the data path
        that produced the evidence; only the trial lease does.
        Returns an eject reason when the probe ejected, else None."""
        self._accrue_partition()
        with self._lock:
            if status is None:
                self.last_probe_ok = False
                self.probe_ok_streak = 0
                self.consec_fails += 1
                # stale-mirror retraction: the queue-depth / occupancy
                # gauges mirror a /health body that no longer exists —
                # delete the labelsets (a scrape sees the series
                # DISAPPEAR, not freeze) and raise the companion stale
                # flag so rollups/dashboards exclude this replica
                # instead of averaging its last numbers forever. The
                # inflight gauge stays: it counts the router's OWN
                # proxied requests, which are real until they fail.
                FLEET_REPLICA_QUEUE_DEPTH.remove(replica=self.name)
                FLEET_REPLICA_OCCUPANCY.remove(replica=self.name)
                FLEET_REPLICA_STALE.set(1, replica=self.name)
                self.kv_chains = ()     # retract: inventory is stale too
                if self.state == HALF_OPEN:
                    return self._eject("health")
                if (self.state == HEALTHY
                        and self.consec_fails >= self.policy.eject_fails):
                    return self._eject("health")
                return None
            engine = (body or {}).get("engine") or {}
            # in-place restart detection: /health carries a monotonic
            # process age (started_at_age_s); the age moving BACKWARD
            # means a new process answers behind the same URL — reset
            # the warm-up clock so the autoscaler grants it the same
            # grace as a freshly spawned replica
            age = (body or {}).get("started_at_age_s")
            if age is not None:
                try:
                    age = float(age)
                except (TypeError, ValueError):
                    age = None
            if age is not None:
                if self._last_started_age is not None \
                        and age < self._last_started_age:
                    self.first_seen = now()
                self._last_started_age = age
            self.draining = bool((body or {}).get("draining")
                                 or engine.get("draining"))
            if engine.get("slots"):
                self.slots_hint = int(engine["slots"])
            self.queue_depth = int(engine.get("queue_depth") or 0)
            self.occupancy = self._occupancy_of(engine)
            FLEET_REPLICA_QUEUE_DEPTH.set(self.queue_depth,
                                          replica=self.name)
            FLEET_REPLICA_OCCUPANCY.set(self.occupancy, replica=self.name)
            FLEET_REPLICA_STALE.set(0, replica=self.name)
            kvshare = engine.get("kvshare") or {}
            chains = kvshare.get("chains") or []
            self.kv_chains = tuple(
                c for c in chains if isinstance(c, str))
            sick = bool(engine.get("down") or engine.get("wedged")
                        or engine.get("alive") is False)
            self.last_probe_ok = not sick
            if sick:
                self.kv_chains = ()     # retract with the sick verdict
                self.probe_ok_streak = 0
                if self.state in (HEALTHY, HALF_OPEN):
                    return self._eject("health")
                return None
            # healthy probe
            self.consec_fails = 0
            if self.state == EJECTED and now() >= self.eject_until:
                self._transition(HALF_OPEN)
                self.probe_ok_streak = 1
            elif self.state == HALF_OPEN:
                self.probe_ok_streak += 1
                if (self.probe_ok_streak >= 2
                        and self.eject_evidence != "data"):
                    self._readmit()
            return None

    @staticmethod
    def _occupancy_of(engine: dict) -> float:
        """KV occupancy in [0, 1]: the kv_pool block's first-class
        `occupancy` field for paged pools (the producer computes
        used/blocks — block occupancy matters: a paged replica can have
        95% of its KV spoken for with only half its slots busy), with
        the hand-derivation kept only for pre-occupancy replicas mid
        rolling upgrade; contiguous pools fall back to busy-slot
        fraction — the autoscaling signal either way."""
        kv = engine.get("kv_pool") or {}
        if "occupancy" in kv:
            return round(float(kv["occupancy"]), 4)
        if kv.get("blocks"):                # pre-occupancy replica
            return round((kv.get("used") or 0) / kv["blocks"], 4)
        slots = engine.get("slots") or 0
        if slots:
            return round((engine.get("slots_busy") or 0) / slots, 4)
        return 0.0

    # -- transitions (lock held) --------------------------------------------

    def _transition(self, state: str) -> None:
        with self._lock:
            self.state = state
            self.state_since = now()

    def _eject(self, reason: str) -> str:
        with self._lock:
            evidence = "probe" if reason == "health" else "data"
            if self.eject_evidence == "data":
                evidence = "data"   # sticky across the open episode: a
                                    # probe-reason re-eject mid-episode
                                    # must not downgrade the readmit gate
            self.eject_evidence = evidence
            forget_s = (self.policy.eject_s * MAX_EJECT_BACKOFF
                        * EJECT_FORGET_HOLDS)
            if (forget_s > 0 and self._last_eject_at
                    and now() - self._last_eject_at > forget_s):
                self.eject_streak = 0   # reputation expired: quiet since
            self._last_eject_at = now()
            self.eject_streak += 1
            hold = self.policy.eject_s * min(2 ** (self.eject_streak - 1),
                                             MAX_EJECT_BACKOFF)
            self.eject_until = now() + hold
            self.probe_ok_streak = 0
            self.trial_inflight = False
            self.results.clear()
            self.ejects += 1
            if evidence == "data" and self.partition_since is None:
                self.partition_since = now()
                self._partition_accrued_at = self.partition_since
                self._pending_events.append(
                    ("replica_partition_suspected",
                     {"replica": self.name, "reason": reason,
                      "hold_s": round(hold, 3)}))
            self._transition(EJECTED)
        FLEET_EJECTS.inc(replica=self.name, reason=reason,
                         evidence=evidence)
        return reason

    def _readmit(self) -> None:
        with self._lock:
            # eject_streak intentionally SURVIVES readmission: a
            # partition/heal flap must find its re-eject hold doubled
            # each round; the streak expires only after the quiet
            # forget window (_eject)
            self.consec_fails = 0
            self.probe_ok_streak = 0
            self.trial_inflight = False
            self.readmits += 1
            if self.partition_since is not None:
                self._accrue_partition()
                self._pending_events.append(
                    ("partition_healed",
                     {"replica": self.name,
                      "episode_s": round(now() - self.partition_since,
                                         3)}))
                self.partition_since = None
            self.eject_evidence = None
            self._transition(HEALTHY)
        FLEET_READMITS.inc(replica=self.name)

    def _accrue_partition(self) -> None:
        """Feed the open partition episode's elapsed time into
        cake_fleet_partition_seconds_total incrementally (each probe
        cycle), so the counter climbs DURING an episode instead of
        jumping at heal."""
        with self._lock:
            if self.partition_since is None:
                return
            t = now()
            delta = t - self._partition_accrued_at
            self._partition_accrued_at = t
        if delta > 0:
            FLEET_PARTITION_SECONDS.inc(delta, replica=self.name)

    def drain_events(self) -> list:
        """Pop pending membership events as (kind, attrs) tuples — the
        router probe loop records them into the replica:<name>
        pseudo-timelines so partition episodes show up in the stitched
        two-tier timeline."""
        with self._lock:
            ev = self._pending_events
            self._pending_events = []
            return ev

    def history(self) -> dict:
        """The membership reputation that outlives removal (registry
        tombstones): eject counts, backoff streak, and any running
        ejection hold — what restore_history re-applies on re-announce."""
        with self._lock:
            return {"ejects": self.ejects,
                    "eject_streak": self.eject_streak,
                    "readmits": self.readmits,
                    "eject_until": self.eject_until,
                    "eject_evidence": self.eject_evidence,
                    "last_eject_at": self._last_eject_at}

    def cordon(self) -> None:
        """Router-side drain mark (lifecycle scale-in): stop routing NEW
        requests here; in-flight ones finish. One-way — a cordoned
        replica is on its way out of the registry."""
        with self._lock:
            self.cordoned = True

    def warm_age_s(self) -> float:
        """Seconds since this router first saw the replica (re-joins and
        detected restarts reset it) — the autoscaler's warm-up input."""
        with self._lock:
            return now() - self.first_seen

    def restore_history(self, hist: dict) -> None:
        """Re-apply a removed replica's eject history on re-announce
        (registry tombstones): counts and streak carry over so the
        backoff ladder is not laundered, and a still-running ejection
        hold is resumed — while first_seen stays FRESH (set by
        __init__), because the warm-up clock is about this process
        instance, not the name's reputation."""
        with self._lock:
            self.ejects = int(hist.get("ejects") or 0)
            self.eject_streak = int(hist.get("eject_streak") or 0)
            self.readmits = int(hist.get("readmits") or 0)
            self._last_eject_at = float(hist.get("last_eject_at") or 0.0)
            until = float(hist.get("eject_until") or 0.0)
            if until > now():
                self.eject_until = until
                # the evidence gate survives re-announce with the hold:
                # a data-evidence eject still demands a data-path trial
                self.eject_evidence = hist.get("eject_evidence")
                if self.eject_evidence == "data":
                    self.partition_since = now()
                    self._partition_accrued_at = self.partition_since
                self._transition(EJECTED)

    def set_outlier(self, flag: bool, reason: str | None = None) -> None:
        """Telemetry-plane anomaly flag (fleet/telemetry.py, once per
        rollup cycle): surfaced in /fleet and the outlier gauge, but
        NEVER a membership input — flagging is advisory, ejection stays
        the state machine's call."""
        with self._lock:
            self.outlier = bool(flag)
            self.outlier_reason = reason if flag else None
        FLEET_REPLICA_OUTLIER.set(1 if flag else 0, replica=self.name)

    # -- views ---------------------------------------------------------------

    def routable(self) -> bool:
        """Eligible for NEW requests right now (half-open counts — the
        acquire path limits it to one trial)."""
        with self._lock:
            return (not self.draining and not self.cordoned
                    and self.state in (HEALTHY, HALF_OPEN))

    def kv_inventory(self) -> tuple:
        """Hex chain keys from the last HEALTHY probe (empty once
        retracted). The router builds X-Cake-KV-Peers from this.
        Empty while EJECTED — probe retraction can lag a data-evidence
        eject by one probe interval, and a directory must never point a
        fetch at a replica the router itself refuses to route to.
        DRAINING/CORDONED replicas keep advertising on purpose: their
        cache is exactly what peers should siphon before they go."""
        with self._lock:
            if self.state == EJECTED:
                return ()
            return self.kv_chains

    def snapshot(self) -> dict:
        with self._lock:
            state = "draining" if ((self.draining or self.cordoned)
                                   and self.state == HEALTHY) else self.state
            return {
                "name": self.name,
                "base_url": self.base_url,
                "state": state,
                "state_age_s": round(now() - self.state_since, 3),
                "inflight": self.inflight,
                "cap": self._cap(),
                "queue_depth": self.queue_depth,
                "occupancy": self.occupancy,
                "consec_fails": self.consec_fails,
                "eject_streak": self.eject_streak,
                "ejects": self.ejects,
                "readmits": self.readmits,
                "eject_evidence": self.eject_evidence,
                "partition_s": (round(now() - self.partition_since, 3)
                                if self.partition_since is not None
                                else None),
                "last_probe_ok": self.last_probe_ok,
                "stale": self.last_probe_ok is False,
                "warm_age_s": round(now() - self.first_seen, 3),
                "cordoned": self.cordoned,
                "outlier": self.outlier,
                "outlier_reason": self.outlier_reason,
            }


class ReplicaRegistry:
    """Thread-safe membership set. Join/leave mutate the map under the
    registry lock; per-replica state lives in each Replica under its own
    lock, so the probe loop and request handlers never serialize on one
    global lock for outcome recording."""

    def __init__(self, policy: MembershipPolicy | None = None):
        self.policy = policy or MembershipPolicy.from_knobs()
        self._lock = threading.Lock()
        self._replicas: dict = {}       # guarded-by: self._lock
        self._rr = 0                    # guarded-by: self._lock
        # eject-history tombstones: a replica that leaves and
        # re-announces under the same name must NOT launder its
        # membership reputation (the backoff ladder restarts otherwise)
        self._history: dict = {}        # guarded-by: self._lock

    # -- membership ----------------------------------------------------------

    def add(self, name: str, base_url: str) -> Replica:
        """Join (idempotent on name: re-announcement refreshes the URL
        but keeps membership state — a re-registered replica does not
        launder its ejection history). A name that LEFT and re-announces
        gets a fresh Replica whose eject history is restored from the
        tombstone while its first-seen warm-up clock resets — the
        reputation is the name's, the warm-up is the process's."""
        with self._lock:
            rep = self._replicas.get(name)
            if rep is not None:
                rep.base_url = base_url.rstrip("/")
                return rep
            rep = Replica(name, base_url, self.policy)
            hist = self._history.pop(name, None)
            self._replicas[name] = rep
        if hist:
            rep.restore_history(hist)
        self.publish()
        return rep

    def remove(self, name: str) -> bool:
        """Leave: drop the replica from routing entirely, retracting its
        per-replica labelsets so scrapes don't carry a ghost forever.
        Its eject history is kept as a tombstone for a same-name
        re-announce (no laundering)."""
        with self._lock:
            rep = self._replicas.pop(name, None)
            gone = rep is not None
            if gone:
                self._history[name] = rep.history()
        if gone:
            for gauge in (FLEET_REPLICA_QUEUE_DEPTH,
                          FLEET_REPLICA_OCCUPANCY, FLEET_REPLICA_INFLIGHT,
                          FLEET_REPLICA_STALE, FLEET_REPLICA_OUTLIER):
                gauge.remove(replica=name)
        self.publish()
        return gone

    def get(self, name: str) -> Replica | None:
        with self._lock:
            return self._replicas.get(name)

    def replicas(self) -> list:
        with self._lock:
            return list(self._replicas.values())

    def names(self) -> list:
        with self._lock:
            return list(self._replicas.keys())

    def next_rr(self) -> int:
        with self._lock:
            self._rr += 1
            return self._rr - 1

    def drain_events(self) -> list:
        """Collect every replica's pending membership events (see
        Replica.drain_events)."""
        out = []
        for r in self.replicas():
            out.extend(r.drain_events())
        return out

    # -- fleet views ---------------------------------------------------------

    def routable_count(self) -> int:
        return sum(1 for r in self.replicas() if r.routable())

    def total_capacity(self) -> int:
        """Admission capacity of the fleet = sum of ROUTABLE replicas'
        caps: an ejected (e.g. partitioned) or draining replica
        contributes nothing — counting it would let the router admit
        load the remaining replicas cannot carry."""
        return sum(r.cap() for r in self.replicas() if r.routable())

    def total_queue_depth(self) -> int:
        return sum(r.snapshot()["queue_depth"] for r in self.replicas())

    def snapshot(self) -> dict:
        reps = [r.snapshot() for r in self.replicas()]
        by_state: dict = {}
        for r in reps:
            by_state[r["state"]] = by_state.get(r["state"], 0) + 1
        return {"replicas": reps, "by_state": by_state,
                "routable": sum(1 for r in reps
                                if r["state"] in (HEALTHY, HALF_OPEN))}

    def publish(self) -> None:
        """Mirror membership into the cake_fleet_replicas{state=} gauge —
        the primary autoscaling signal."""
        counts = {HEALTHY: 0, EJECTED: 0, HALF_OPEN: 0, "draining": 0}
        for r in self.replicas():
            counts[r.snapshot()["state"]] += 1
        for state, n in counts.items():
            FLEET_REPLICAS.set(n, state=state)


def discover_replicas(cluster_key: str, timeout: float = 2.0) -> list:
    """Find announced serve replicas over the existing cluster discovery
    plumbing (UDP broadcast filtered by the PSK-derived cluster hash —
    cluster/discovery.py): `cake serve --announce` runs a
    WorkerAdvertiser whose caps carry role="serve", and this filters the
    replies down to those. Returns [(name, base_url), ...]."""
    from ..cluster.discovery import discover_workers
    out = []
    for w in discover_workers(cluster_key, timeout=timeout):
        if (w.get("caps") or {}).get("role") != "serve":
            continue
        out.append((w["name"], f"http://{w['host']}:{w['port']}"))
    return out
