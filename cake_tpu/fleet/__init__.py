"""Fleet serving: a router tier over N `cake serve` replicas.

One engine survives crashes (serve/supervisor.py) and worker death
(cluster/master.py); this package makes N of them survive each other —
health-driven membership with a gray-failure eject -> half-open ->
readmit machine (registry.py), prefix-affinity routing with
deterministic failover (routing.py), router-level overload control and
the `cake route` process itself (router.py), the chaos drill seam
(faults.py), the telemetry plane that rolls per-replica signals up
into burn rates / headroom / anomaly flags (telemetry.py — the feed the
autoscaler and `cake top` consume), and the closed loop that acts on
that feed: the pure scaling controller (autoscale.py) and the replica
lifecycle manager that spawns/drains/reaps real serve processes
(lifecycle.py), and the userspace network chaos layer that partitions
real router->replica sockets for soaks/smokes (netem.py). docs/fleet.md,
docs/telemetry.md and docs/autoscaling.md are the operator guides.
"""
from .autoscale import (Autoscaler, Decision, DecisionLog, ScalePolicy,
                        decide, select_victim)
from .lifecycle import ManagedReplica, ReplicaLifecycle
from .netem import ChaosProxy, NetemPlan
from .netem import parse_plan as parse_netem_plan
from .registry import (EJECTED, HALF_OPEN, HEALTHY, MembershipPolicy,
                       Replica, ReplicaRegistry, discover_replicas)
from .router import FleetRouter, create_router_app, serve_router
from .routing import (AFFINITY_BLOCK, affinity_key, conversation_head,
                      rank_replicas)
from .telemetry import FleetTelemetry

__all__ = [
    "Replica", "ReplicaRegistry", "MembershipPolicy", "discover_replicas",
    "HEALTHY", "EJECTED", "HALF_OPEN",
    "FleetRouter", "create_router_app", "serve_router", "FleetTelemetry",
    "affinity_key", "conversation_head", "rank_replicas", "AFFINITY_BLOCK",
    "Autoscaler", "Decision", "DecisionLog", "ScalePolicy", "decide",
    "select_victim", "ManagedReplica", "ReplicaLifecycle",
    "ChaosProxy", "NetemPlan", "parse_netem_plan",
]
