"""Closed-loop fleet autoscaler: the policy brain over the telemetry plane.

PR 16 built decision-grade signals (multi-window SLO burn rates,
capacity headroom, per-class QoS backlog, outlier flags) explicitly "for
the autoscaler"; this module is the consumer. Each router probe cycle
the controller evaluates the latest rollup against a :class:`ScalePolicy`
and returns a :class:`Decision` — scale_out, scale_in, or hold — which
the :class:`Autoscaler` wrapper executes through the replica lifecycle
manager (fleet/lifecycle.py).

The controller is :func:`decide`, a pure function in the style of the
telemetry plane's `ingest`: no I/O, no real clock — every input
(rollup, fleet view, policy, controller state, timestamp) is a
parameter, so tests pin the whole decision table with a fake clock.

Policy, in decision order:

  * BELOW-MIN REPLACEMENT — routable + pending spawns under
    CAKE_SCALE_MIN tops the fleet back up immediately, cooldown or not
    (the floor is not discretionary; this is what turns a kill -9 into
    a respawn within one cycle).
  * SCALE-OUT — fast-window burn rate over CAKE_SCALE_BURN_FAST, or
    headroom under CAKE_SCALE_HEADROOM_MIN tokens/s. QoS-aware by
    construction: the burn rate is interactive-TTFT-driven, while batch
    backlog (rollup qos_backlog) is deliberately NOT a trigger — batch
    absorbs, interactive pages.
  * SCALE-IN — only when fast AND slow burn are clean (<= 1) and
    headroom has sat above CAKE_SCALE_HEADROOM_HIGH CONTINUOUSLY for a
    full CAKE_SCALE_COOLDOWN_S (the high-water clock resets on any dip
    or burn), the fleet is above CAKE_SCALE_MIN, and the predicted
    post-removal headroom still clears CAKE_SCALE_HEADROOM_MIN
    (hysteresis: removing the replica must not re-trigger scale-out).
  * HOLDS — one action per cooldown; while any replica is inside its
    CAKE_SCALE_WARMUP_S warm-up (its empty histograms would misread);
    at the CAKE_SCALE_MAX / CAKE_SCALE_MIN bounds.

HARD RULE: outlier/stale flags are ADVISORY and never a scale input —
they pick WHICH replica drains (victim selection: outlier-flagged
first, then least prefix-affinity mass), never WHETHER the fleet
scales. The same rollup with and without flags yields the same action.

Every decision and lifecycle transition is a typed event on the
decisions ring (GET /api/v1/fleet/autoscale), executed actions count in
cake_fleet_scale_actions_total{direction,reason}, and `cake top`
renders the loop's last word as a dashboard row. docs/autoscaling.md is
the operator guide.
"""
from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field

from .. import knobs
from ..obs import FLEET_SCALE_ACTIONS, now

__all__ = ["ScalePolicy", "ControllerState", "Decision", "DecisionLog",
           "Autoscaler", "decide", "select_victim", "DECISION_KINDS",
           "SCALE_OUT", "SCALE_IN", "HOLD"]

SCALE_OUT = "scale_out"
SCALE_IN = "scale_in"
HOLD = "hold"

# typed vocabulary for the decisions ring — the same closed-catalog rule
# as the request timeline's EVENT_KINDS: the ring rejects unknown kinds,
# and docs/autoscaling.md lists exactly these
DECISION_KINDS = {
    "scale_out": "controller decided to add a replica (reason: "
                 "burn_fast / headroom_low / below_min)",
    "scale_in": "controller decided to retire a replica (victim named; "
                "reason: headroom_high)",
    "hold": "controller held (recorded on reason CHANGE, not every "
            "cycle): cooldown / warmup / at_max / at_min / no_victim / "
            "hysteresis / steady / disabled",
    "spawned": "lifecycle launched a replica process from "
               "CAKE_SCALE_SPAWN_CMD; admission pending",
    "admitted": "spawned replica's /health answered 200 and it joined "
                "the routing registry",
    "spawn_failed": "spawned replica never became healthy within "
                    "CAKE_SCALE_SPAWN_TIMEOUT_S (or died first) and "
                    "was killed",
    "retire": "lifecycle began a graceful scale-in: cordon -> SIGTERM "
              "-> drain",
    "reaped": "retired replica finished draining and its process "
              "exited (or was killed after the drain deadline)",
    "died": "a managed replica process exited unexpectedly (crash, "
            "kill -9); removed from routing — the below-min rule "
            "decides the replacement",
}


@dataclass(frozen=True)
class ScalePolicy:
    """The controller's thresholds, snapshotted from knobs at router
    build time (tests construct their own)."""

    burn_fast: float = 2.0          # fast-window burn scale-out trigger
    headroom_min: float = 0.0       # tokens/s floor (0 = trigger off)
    headroom_high: float = 0.0      # scale-in high-water (0 = no scale-in)
    cooldown_s: float = 60.0        # action spacing + scale-in dwell
    min_replicas: int = 1
    max_replicas: int = 8
    warmup_s: float = 30.0          # fresh-replica grace period
    enabled: bool = True

    @classmethod
    def from_knobs(cls) -> "ScalePolicy":
        return cls(
            burn_fast=knobs.get("CAKE_SCALE_BURN_FAST"),
            headroom_min=knobs.get("CAKE_SCALE_HEADROOM_MIN"),
            headroom_high=knobs.get("CAKE_SCALE_HEADROOM_HIGH"),
            cooldown_s=max(knobs.get("CAKE_SCALE_COOLDOWN_S"), 0.0),
            min_replicas=max(knobs.get("CAKE_SCALE_MIN"), 0),
            max_replicas=max(knobs.get("CAKE_SCALE_MAX"), 1),
            warmup_s=max(knobs.get("CAKE_SCALE_WARMUP_S"), 0.0),
            enabled=knobs.get("CAKE_SCALE"))


@dataclass
class ControllerState:
    """The controller's only memory between cycles, owned by the caller
    and mutated by decide() deterministically: when the last action
    fired (the cooldown anchor) and since when the scale-in conditions
    have held continuously (the high-water dwell clock)."""

    last_action_t: float = float("-inf")
    high_since: float | None = None


@dataclass
class Decision:
    """One cycle's verdict. `action` is SCALE_OUT / SCALE_IN / HOLD,
    `reason` names the trigger (or the hold cause), `victim` the
    replica a scale-in retires, and `detail` the signal values the
    decision was made on — the decisions ring keeps all of it so an
    operator can audit WHY after the fact."""

    action: str
    reason: str
    victim: str | None = None
    detail: dict = field(default_factory=dict)


def select_victim(replicas: list) -> dict | None:
    """Lowest-value retirement candidate among the MANAGED, routable
    rows: outlier-flagged first (the advisory flags' only power — they
    choose WHO drains, never WHETHER), then least prefix-affinity mass
    (tokens/s served over the fast window: the replica the fewest warm
    conversations would miss), name as the deterministic tiebreak.
    Only lifecycle-managed replicas are eligible — the router never
    retires a process it did not spawn."""
    candidates = [r for r in replicas
                  if r.get("managed") and not r.get("cordoned")
                  and r.get("state") in ("healthy", "half_open")]
    if not candidates:
        return None
    return sorted(candidates,
                  key=lambda r: (0 if r.get("outlier") else 1,
                                 r.get("affinity_mass") or 0.0,
                                 r.get("name") or ""))[0]


def decide(rollup: dict, fleet_view: dict, policy: ScalePolicy,
           state: ControllerState, t: float) -> Decision:
    """One control cycle, pure: rollup is the telemetry snapshot
    (burn_rate, headroom_tokens_per_s, qos_backlog), fleet_view the
    membership view ({"replicas": [row...], "pending_spawns": n}),
    `t` the cycle's timestamp on whatever clock the caller runs.
    Mutates `state` (cooldown anchor, high-water dwell) and nothing
    else."""
    reps = fleet_view.get("replicas") or []
    pending = int(fleet_view.get("pending_spawns") or 0)
    routable = [r for r in reps
                if r.get("state") in ("healthy", "half_open")
                and not r.get("cordoned")]
    members = [r for r in reps if not r.get("cordoned")]
    burn = rollup.get("burn_rate") or {}
    fast = float(burn.get("fast") or 0.0)
    slow = float(burn.get("slow") or 0.0)
    headroom = float(rollup.get("headroom_tokens_per_s") or 0.0)
    detail = {"burn_fast": fast, "burn_slow": slow,
              "headroom_tokens_per_s": headroom,
              "members": len(members), "routable": len(routable),
              "pending_spawns": pending,
              "qos_backlog": rollup.get("qos_backlog") or {}}

    def hold(reason: str) -> Decision:
        return Decision(HOLD, reason, detail=detail)

    if not policy.enabled:
        return hold("disabled")

    # 1. below-min replacement: the floor is not discretionary — it
    # bypasses the cooldown AND the warm-up hold (a dead replica's
    # replacement must not wait on either), capped only by max
    if len(routable) + pending < policy.min_replicas:
        if len(members) + pending >= policy.max_replicas:
            return hold("at_max")
        state.last_action_t = t
        state.high_since = None
        return Decision(SCALE_OUT, "below_min", detail=detail)

    in_cooldown = (t - state.last_action_t) < policy.cooldown_s
    warming = [r for r in routable
               if r.get("warm_age_s") is not None
               and r["warm_age_s"] < policy.warmup_s]
    detail["warming"] = len(warming)

    # 2. scale-out triggers. Evaluated before the scale-in dwell so any
    # pressure also resets the high-water clock (a fleet cannot be
    # "comfortably over-provisioned" and "burning" in the same cycle).
    # Batch backlog is visible in detail["qos_backlog"] but is NOT an
    # input: batch absorbs by design; the burn rate (interactive
    # TTFT-driven) and headroom are the only out-triggers.
    out_reason = None
    if fast > policy.burn_fast:
        out_reason = "burn_fast"
    elif policy.headroom_min > 0 and headroom < policy.headroom_min:
        out_reason = "headroom_low"
    if out_reason is not None:
        state.high_since = None
        if len(members) + pending >= policy.max_replicas:
            return hold("at_max")
        if in_cooldown:
            return hold("cooldown")
        if warming or pending:
            # fresh capacity is still materializing: judging the
            # trigger now would double-spend on the same pressure
            return hold("warmup")
        state.last_action_t = t
        return Decision(SCALE_OUT, out_reason, detail=detail)

    # 3. scale-in dwell: clean burn on BOTH windows + headroom above the
    # high-water mark, continuously for a full cooldown
    clean = fast <= 1.0 and slow <= 1.0
    high = policy.headroom_high > 0 and headroom >= policy.headroom_high
    if clean and high:
        if state.high_since is None:
            state.high_since = t
    else:
        state.high_since = None
    detail["high_for_s"] = round(t - state.high_since, 3) \
        if state.high_since is not None else 0.0
    if state.high_since is None \
            or (t - state.high_since) < policy.cooldown_s:
        return hold("steady")
    if in_cooldown:
        return hold("cooldown")
    if warming or pending:
        return hold("warmup")
    if len(routable) <= policy.min_replicas:
        return hold("at_min")
    victim = select_victim(routable)
    if victim is None:
        return hold("no_victim")
    # hysteresis guard: the fleet minus the victim must still clear the
    # scale-out floor, or the loop would flap out <-> in forever
    predicted = headroom - float(victim.get("headroom_tokens_per_s")
                                 or 0.0)
    detail["predicted_headroom_tokens_per_s"] = round(predicted, 3)
    if policy.headroom_min > 0 and predicted < policy.headroom_min:
        return hold("hysteresis")
    state.last_action_t = t
    state.high_since = None
    return Decision(SCALE_IN, "headroom_high", victim=victim.get("name"),
                    detail=detail)


class DecisionLog:
    """Bounded ring of typed controller/lifecycle events — the
    timeline-store shape (closed kind catalog, newest-last list) scoped
    to the autoscale loop. Event-loop-confined like the router state
    that owns it; timestamps are the caller's clock and rendered as
    ages (monotonic clocks mean nothing across processes)."""

    def __init__(self, cap: int | None = None, clock=now):
        cap = cap if cap is not None else knobs.get("CAKE_SCALE_DECISIONS")
        self._ring: deque = deque(maxlen=max(int(cap), 8))
        self._clock = clock

    def record(self, kind: str, t: float | None = None, **fields) -> None:
        if kind not in DECISION_KINDS:
            raise ValueError(f"unknown decision kind {kind!r} (catalog: "
                             f"{sorted(DECISION_KINDS)})")
        ev = {"kind": kind, "t": self._clock() if t is None else float(t)}
        ev.update(fields)
        self._ring.append(ev)

    def events(self, t: float | None = None) -> list:
        """Newest-last events with `t` converted to `age_s`."""
        t = self._clock() if t is None else float(t)
        out = []
        for ev in self._ring:
            row = {k: v for k, v in ev.items() if k != "t"}
            row["age_s"] = round(t - ev["t"], 3)
            out.append(row)
        return out

    def last(self, *kinds: str) -> dict | None:
        for ev in reversed(self._ring):
            if not kinds or ev["kind"] in kinds:
                return ev
        return None


class Autoscaler:
    """The loop: owns policy + controller state + the decisions ring,
    builds the fleet view from the registry and lifecycle, and executes
    decisions through the lifecycle manager. Driven by the router's
    probe cycle (step()); event-loop-confined like the router's own
    handler state."""

    def __init__(self, registry, lifecycle, *,
                 policy: ScalePolicy | None = None,
                 log: DecisionLog | None = None, clock=now):
        self.registry = registry
        self.lifecycle = lifecycle
        self.policy = policy or ScalePolicy.from_knobs()
        self.state = ControllerState()
        self.log = log if log is not None else DecisionLog(clock=clock)
        self._clock = clock
        self._last_hold_reason = None

    def fleet_view(self, rollup: dict) -> dict:
        """Membership + per-replica signals the controller ranks victims
        on: registry state/warm-age/cordon joined with the rollup's
        per-replica headroom and token rate (affinity mass), plus which
        replicas the lifecycle manages."""
        trows = rollup.get("replicas") or {}
        rows = []
        for rep in self.registry.replicas():
            snap = rep.snapshot()
            tr = trows.get(rep.name) or {}
            rows.append({
                "name": rep.name,
                "state": rep.state,
                "cordoned": snap.get("cordoned"),
                "warm_age_s": snap.get("warm_age_s"),
                "inflight": snap.get("inflight"),
                "managed": self.lifecycle.is_managed(rep.name),
                "outlier": snap.get("outlier"),
                "outlier_reason": snap.get("outlier_reason"),
                "stale": snap.get("stale"),
                "headroom_tokens_per_s":
                    tr.get("headroom_tokens_per_s") or 0.0,
                "affinity_mass": tr.get("tokens_per_s") or 0.0,
            })
        return {"replicas": rows,
                "pending_spawns": self.lifecycle.pending_count()}

    def step(self, rollup: dict, t: float | None = None) -> Decision:
        """One control cycle: decide, record, execute. Holds land on the
        ring only when their reason CHANGES (a steady fleet must not
        scroll the ring with identical holds every probe tick)."""
        t = self._clock() if t is None else float(t)
        decision = decide(rollup, self.fleet_view(rollup), self.policy,
                          self.state, t)
        if decision.action == HOLD:
            if decision.reason != self._last_hold_reason:
                self._last_hold_reason = decision.reason
                self.log.record(HOLD, t=t, reason=decision.reason,
                                detail=decision.detail)
            return decision
        self._last_hold_reason = None
        if decision.action == SCALE_OUT:
            self.log.record(SCALE_OUT, t=t, reason=decision.reason,
                            detail=decision.detail)
            FLEET_SCALE_ACTIONS.inc(direction="out",
                                    reason=decision.reason)
            self.lifecycle.spawn(reason=decision.reason)
        elif decision.action == SCALE_IN:
            self.log.record(SCALE_IN, t=t, reason=decision.reason,
                            replica=decision.victim,
                            detail=decision.detail)
            FLEET_SCALE_ACTIONS.inc(direction="in",
                                    reason=decision.reason)
            self.lifecycle.retire(decision.victim,
                                  reason=decision.reason)
        return decision

    def summary(self) -> dict:
        """The compact block merged into /api/v1/fleet/telemetry (and
        rendered as the `cake top` autoscale row)."""
        last = self.log.last(SCALE_OUT, SCALE_IN, HOLD)
        out = {"enabled": self.policy.enabled,
               "min": self.policy.min_replicas,
               "max": self.policy.max_replicas,
               "pending_spawns": self.lifecycle.pending_count(),
               "managed": len(self.lifecycle.managed_names())}
        if last is not None:
            out["last"] = {"kind": last["kind"],
                           "reason": last.get("reason"),
                           "replica": last.get("replica"),
                           "age_s": round(self._clock() - last["t"], 3)}
        return out

    def snapshot(self) -> dict:
        """GET /api/v1/fleet/autoscale: policy, controller state, the
        full decisions ring, and the lifecycle's process view."""
        t = self._clock()
        high = self.state.high_since
        return {
            "enabled": self.policy.enabled,
            "policy": {
                "burn_fast": self.policy.burn_fast,
                "headroom_min": self.policy.headroom_min,
                "headroom_high": self.policy.headroom_high,
                "cooldown_s": self.policy.cooldown_s,
                "min_replicas": self.policy.min_replicas,
                "max_replicas": self.policy.max_replicas,
                "warmup_s": self.policy.warmup_s,
            },
            "state": {
                "since_last_action_s":
                    round(t - self.state.last_action_t, 3)
                    if self.state.last_action_t != float("-inf") else None,
                "high_for_s": round(t - high, 3)
                    if high is not None else 0.0,
            },
            "decisions": self.log.events(t),
            "lifecycle": self.lifecycle.snapshot(),
        }
