"""Userspace network chaos layer: a TCP proxy that misbehaves on plan.

The fleet's fault injection so far is entirely in-process —
fleet/faults.py raises `InjectedFleetFault` inside the router, so no
socket ever misbehaves and the router's OWN network stack (connect
timeouts, half-open TCP, black holes) is never exercised. This module
closes that gap without needing root or iptables: :class:`ChaosProxy`
is an asyncio TCP proxy that fronts a replica's port, and soaks/smokes
point the router at the PROXY so every router->replica byte crosses a
socket the drill controls.

A netem plan is one `key[=val][;key=val...]` clause, the
`CAKE_FLEET_FAULT_PLAN` grammar pointed at the wire:

    partition           hard partition: refuse new connections and
                        sever live ones (connection reset — the
                        kill -9 / cable-pull shape)
    partition_in        asymmetric: client->server bytes are black-holed
                        (requests never reach the replica; the
                        connection stays open and silent)
    partition_out       asymmetric: server->client bytes are black-holed
                        (the replica answers into the void — the
                        probe-alive/data-dead gray failure)
    blackhole           accept new connections, then never relay a byte
                        in either direction (SYN-accepted-then-silence:
                        the failure mode an unbounded attempt timeout
                        hangs on forever)
    delay_ms=N          delay every relayed chunk by N ms (brownout)
    jitter_ms=N         add uniform [0, N] ms on top of delay_ms
    reset_after_bytes=N sever the connection after N server->client
                        bytes have been relayed (mid-response reset)
    heal_after_s=S      auto-heal the plan S seconds after it applies
    match=SUBSTR        restrict the fault to connections whose client
                        bytes contain SUBSTR (e.g. `match=/v1/chat`) —
                        unmatched connections relay clean. The sniff is
                        CONTINUOUS, not first-bytes-only: a kept-alive
                        connection that first carried a probe and later
                        carries matching data traffic becomes subject
                        the moment the match crosses (routers pool
                        connections; classifying only the first request
                        would let data ride probe-classified sockets).
                        This is what makes the asymmetric
                        probe-alive/data-dead drill real through one
                        port: /health traffic passes, data traffic dies.

Plans are runtime-controllable: `apply()`/`heal()` in-process, or the
tiny line-oriented CONTROL SOCKET (`SET <plan>` / `HEAL` / `STATUS`,
one JSON reply per line) so a multi-process soak flips faults
mid-traffic against real router->replica connections. Mid-plan flips
affect LIVE connections too: the relay pumps consult the current plan
per chunk, and applying `partition` severs everything in flight.

Like every drill plane in-tree (serve/faults.py, cluster/faults.py,
fleet/faults.py) this is test/soak tooling: deterministic, stdlib-only,
and safe to import anywhere — nothing activates without an explicit
start().
"""
from __future__ import annotations

import asyncio
import json
import logging
import random
from dataclasses import dataclass, field

from ..obs import now

log = logging.getLogger("cake_tpu.fleet.netem")

__all__ = ["ChaosProxy", "NetemPlan", "parse_plan", "control_send"]

# relay chunk size: small enough that delay_ms paces a stream rather
# than one giant buffered burst, big enough to not dominate CPU
_CHUNK = 16384

# bare flag keys: `partition` alone means partition=1
_FLAG_KEYS = ("partition", "partition_in", "partition_out", "blackhole")
_FLOAT_KEYS = ("delay_ms", "jitter_ms", "heal_after_s")
_INT_KEYS = ("reset_after_bytes",)


@dataclass
class NetemPlan:
    """One parsed plan clause. The zero plan (all defaults) relays
    clean — `ChaosProxy.heal()` just installs it."""

    partition: bool = False
    partition_in: bool = False
    partition_out: bool = False
    blackhole: bool = False
    delay_ms: float = 0.0
    jitter_ms: float = 0.0
    reset_after_bytes: int | None = None
    heal_after_s: float | None = None
    match: str = ""

    @classmethod
    def parse(cls, clause: str) -> "NetemPlan":
        plan = cls()
        for part in filter(None, (p.strip() for p in clause.split(";"))):
            k, _, v = part.partition("=")
            k = k.strip()
            v = v.strip()
            if k in _FLAG_KEYS:
                setattr(plan, k, v in ("", "1", "true", "on"))
            elif k in _FLOAT_KEYS:
                if not v:
                    raise ValueError(f"netem key {k!r} needs a value")
                setattr(plan, k, float(v))
            elif k in _INT_KEYS:
                if not v:
                    raise ValueError(f"netem key {k!r} needs a value")
                setattr(plan, k, int(v))
            elif k == "match":
                plan.match = v
            else:
                raise ValueError(f"unknown netem key {k!r}")
        return plan

    def faulty(self) -> bool:
        """Whether this plan misbehaves at all (the zero plan = healed)."""
        return bool(self.partition or self.partition_in
                    or self.partition_out or self.blackhole
                    or self.delay_ms or self.jitter_ms
                    or self.reset_after_bytes is not None)

    def snapshot(self) -> dict:
        out = {}
        for k in _FLAG_KEYS:
            if getattr(self, k):
                out[k] = True
        for k in _FLOAT_KEYS + _INT_KEYS:
            v = getattr(self, k)
            if v:
                out[k] = v
        if self.match:
            out["match"] = self.match
        return out


def parse_plan(spec: str) -> NetemPlan:
    """Exactly one clause, like faults.parse_plan (a proxy fronts ONE
    replica; run one proxy per victim)."""
    clauses = [c for c in (s.strip() for s in spec.split(",")) if c]
    if len(clauses) != 1:
        raise ValueError("netem plans take exactly one clause")
    return NetemPlan.parse(clauses[0])


@dataclass(eq=False)            # identity hash: _Conn lives in a set
class _Conn:
    """One proxied connection's state (event-loop-confined)."""

    down_w: asyncio.StreamWriter              # towards the client
    up_w: asyncio.StreamWriter | None = None  # towards the replica
    out_bytes: int = 0                        # server->client relayed
    matched: bool = False   # has carried bytes matching a plan's `match`
                            # (sticky: once data traffic crossed, the
                            # connection stays classified as data)
    tasks: list = field(default_factory=list)

    def abort(self) -> None:
        for w in (self.down_w, self.up_w):
            if w is None:
                continue
            try:
                w.transport.abort()     # RST, not FIN: a real partition
            except Exception:
                pass


class ChaosProxy:
    """TCP proxy fronting one replica port, executing the current
    :class:`NetemPlan`. All state is event-loop-confined to the loop
    that start()ed it; the control socket serializes onto the same
    loop."""

    def __init__(self, target_host: str, target_port: int, *,
                 listen_host: str = "127.0.0.1", listen_port: int = 0,
                 control: bool = True, clock=now):
        self.target_host = target_host
        self.target_port = int(target_port)
        self.listen_host = listen_host
        self._listen_port = int(listen_port)
        self._want_control = control
        self._clock = clock
        self.plan = NetemPlan()
        self.plan_applied_at: float | None = None
        self._server: asyncio.AbstractServer | None = None
        self._control: asyncio.AbstractServer | None = None
        self._conns: set[_Conn] = set()
        self._heal_task: asyncio.Task | None = None
        # drill ledger (status() reports it; smokes assert on it)
        self.accepted = 0
        self.refused = 0
        self.severed = 0
        self.relayed_in = 0
        self.relayed_out = 0

    # -- lifecycle -----------------------------------------------------------

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle, self.listen_host, self._listen_port)
        if self._want_control:
            self._control = await asyncio.start_server(
                self._handle_control, self.listen_host, 0)
        log.info("chaos proxy %s:%d -> %s:%d (control %s)",
                 self.listen_host, self.port,
                 self.target_host, self.target_port,
                 self.control_port or "off")

    @property
    def port(self) -> int:
        return self._server.sockets[0].getsockname()[1]

    @property
    def base_url(self) -> str:
        return f"http://{self.listen_host}:{self.port}"

    @property
    def control_port(self) -> int | None:
        if self._control is None:
            return None
        return self._control.sockets[0].getsockname()[1]

    async def close(self) -> None:
        if self._heal_task is not None:
            self._heal_task.cancel()
            self._heal_task = None
        for srv in (self._server, self._control):
            if srv is not None:
                srv.close()
                await srv.wait_closed()
        self._sever_all()
        self._server = self._control = None

    # -- plan control --------------------------------------------------------

    def apply(self, plan: "NetemPlan | str") -> NetemPlan:
        """Install a plan. `partition` severs live connections NOW;
        everything else takes effect per-chunk on live pumps and at
        accept/first-data on new connections. heal_after_s arms an
        auto-heal timer (replacing any previous one)."""
        if isinstance(plan, str):
            plan = parse_plan(plan)
        self.plan = plan
        self.plan_applied_at = self._clock()
        if self._heal_task is not None:
            self._heal_task.cancel()
            self._heal_task = None
        if plan.partition:
            # sever every live connection the new plan applies to
            # (all of them for an unmatched partition; the ones whose
            # traffic already matched for a `match` partition)
            self._sever_subject()
        if plan.heal_after_s is not None:
            self._heal_task = asyncio.ensure_future(
                self._auto_heal(plan.heal_after_s))
        log.warning("netem plan applied: %s", plan.snapshot() or "{}")
        return plan

    def heal(self) -> None:
        """Clear the plan: new connections relay clean. Live connections
        that were black-holed stay broken (a healed network does not
        resurrect a dead TCP stream) — sever them so both ends notice."""
        if self._heal_task is not None:
            self._heal_task.cancel()
            self._heal_task = None
        self._sever_subject()
        self.plan = NetemPlan()
        self.plan_applied_at = self._clock()
        log.warning("netem plan healed")

    async def _auto_heal(self, after_s: float) -> None:
        try:
            await asyncio.sleep(after_s)
        except asyncio.CancelledError:
            return
        self._heal_task = None
        self.heal()

    def status(self) -> dict:
        return {"target": f"{self.target_host}:{self.target_port}",
                "listen": f"{self.listen_host}:{self.port}",
                "plan": self.plan.snapshot(),
                "plan_age_s": round(self._clock() - self.plan_applied_at,
                                    3)
                if self.plan_applied_at is not None else None,
                "live_conns": len(self._conns),
                "accepted": self.accepted, "refused": self.refused,
                "severed": self.severed,
                "relayed_in": self.relayed_in,
                "relayed_out": self.relayed_out}

    def _sever_all(self) -> None:
        for conn in list(self._conns):
            conn.abort()
            self.severed += 1
        self._conns.clear()

    def _subject(self, conn: _Conn, plan: NetemPlan | None = None) -> bool:
        """Whether `plan` (current by default) applies to this
        connection: every connection for an unmatched plan, only ones
        whose traffic has carried the match substring otherwise."""
        plan = plan if plan is not None else self.plan
        return plan.faulty() and (not plan.match or conn.matched)

    def _sever_subject(self) -> None:
        """Sever live connections the CURRENT plan applies to — on
        apply (a partition kills in-flight streams) and on heal (a
        healed network does not resurrect a black-holed TCP stream;
        sever so both ends notice and retry clean)."""
        for conn in list(self._conns):
            if not self._subject(conn):
                continue
            conn.abort()
            self.severed += 1
            self._conns.discard(conn)

    # -- data path -----------------------------------------------------------

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        self.accepted += 1
        conn = _Conn(down_w=writer)
        plan = self.plan
        if plan.partition and not plan.match:
            # refuse at accept: the OS already completed the handshake
            # (we are userspace), so the closest honest shape is an
            # immediate RST before any byte moves
            self.refused += 1
            conn.abort()
            return
        # first-data sniff: `match` plans decide per connection from the
        # first client bytes; unmatched plans fault every connection
        try:
            first = await reader.read(_CHUNK)
        except Exception:
            first = b""
        if not first:
            conn.abort()
            return
        plan = self.plan          # re-read: it may have flipped mid-sniff
        if plan.match and plan.match.encode() in first:
            conn.matched = True
        self._conns.add(conn)
        try:
            if self._subject(conn, plan) and plan.partition:
                self.refused += 1
                return
            if self._subject(conn, plan) and plan.blackhole:
                # accept then never respond: drain the client into the
                # void until the plan changes or the client gives up
                await self._drain(reader, conn)
                return
            try:
                up_r, up_w = await asyncio.open_connection(
                    self.target_host, self.target_port)
            except OSError:
                return
            conn.up_w = up_w
            pump_in = asyncio.ensure_future(
                self._pump(reader, up_w, conn, inbound=True, first=first))
            pump_out = asyncio.ensure_future(
                self._pump(up_r, writer, conn, inbound=False))
            conn.tasks = [pump_in, pump_out]
            await asyncio.wait(conn.tasks)
        finally:
            self._conns.discard(conn)
            conn.abort()

    async def _drain(self, reader: asyncio.StreamReader,
                     conn: _Conn) -> None:
        while True:
            try:
                data = await reader.read(_CHUNK)
            except Exception:
                return
            if not data:
                return
            if not self.plan.blackhole:
                # plan flipped mid-hole: this connection is already a
                # dead end (its early bytes went nowhere) — sever so
                # the client retries on a clean one
                conn.abort()
                return

    async def _pump(self, reader: asyncio.StreamReader,
                    writer: asyncio.StreamWriter, conn: _Conn,
                    inbound: bool, first: bytes = b"") -> None:
        """One relay direction; consults the live plan per chunk so a
        mid-stream SET takes effect without reconnecting."""
        pending = first
        try:
            while True:
                data = pending or await reader.read(_CHUNK)
                pending = b""
                if not data:
                    break
                plan = self.plan
                # continuous sniff: a kept-alive connection becomes
                # subject the moment matching (data) traffic crosses it
                if (inbound and plan.match and not conn.matched
                        and plan.match.encode() in data):
                    conn.matched = True
                faulted = self._subject(conn, plan)
                if faulted and plan.partition:
                    conn.abort()
                    return
                if faulted and ((inbound and plan.partition_in)
                                or (not inbound and plan.partition_out)):
                    continue        # black hole: read and discard
                if faulted and (plan.delay_ms or plan.jitter_ms):
                    await asyncio.sleep(
                        (plan.delay_ms
                         + random.uniform(0.0, plan.jitter_ms)) / 1e3)
                reset = (plan.reset_after_bytes
                         if faulted and not inbound else None)
                if reset is not None:
                    # sever ON the byte budget, not after the chunk that
                    # crosses it: relay only the remainder, then reset
                    data = data[:max(reset - conn.out_bytes, 0)]
                    if not data:
                        self.severed += 1
                        conn.abort()
                        return
                writer.write(data)
                await writer.drain()
                if inbound:
                    self.relayed_in += len(data)
                else:
                    self.relayed_out += len(data)
                    conn.out_bytes += len(data)
                    if reset is not None and conn.out_bytes >= reset:
                        self.severed += 1
                        conn.abort()
                        return
        except Exception:
            pass
        finally:
            try:
                writer.write_eof()
            except Exception:
                pass

    # -- control socket ------------------------------------------------------

    async def _handle_control(self, reader: asyncio.StreamReader,
                              writer: asyncio.StreamWriter) -> None:
        """Line protocol: `SET <plan>` / `HEAL` / `STATUS`, one JSON
        object per reply line. Errors answer {"ok": false, ...} and
        keep the session open — a soak driver's typo must not kill the
        drill."""
        try:
            while True:
                line = await reader.readline()
                if not line:
                    return
                cmd, _, arg = line.decode("utf-8",
                                          "replace").strip().partition(" ")
                cmd = cmd.upper()
                try:
                    if cmd == "SET":
                        plan = self.apply(arg)
                        reply = {"ok": True, "plan": plan.snapshot()}
                    elif cmd == "HEAL":
                        self.heal()
                        reply = {"ok": True, "plan": {}}
                    elif cmd == "STATUS":
                        reply = {"ok": True, **self.status()}
                    else:
                        reply = {"ok": False,
                                 "error": f"unknown command {cmd!r} "
                                          "(SET/HEAL/STATUS)"}
                except ValueError as e:
                    reply = {"ok": False, "error": str(e)}
                writer.write(json.dumps(reply).encode() + b"\n")
                await writer.drain()
        except Exception:
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass


async def control_send(host: str, port: int, command: str) -> dict:
    """One control-socket round trip (soak drivers in OTHER processes
    flip faults with this): send one command line, return the parsed
    JSON reply."""
    reader, writer = await asyncio.open_connection(host, port)
    try:
        writer.write(command.strip().encode() + b"\n")
        await writer.drain()
        line = await reader.readline()
        if not line:
            raise ConnectionError("netem control socket closed")
        return json.loads(line)
    finally:
        writer.close()
        try:
            await writer.wait_closed()
        except Exception:
            pass
