"""Fleet router: one HTTP front for N `cake serve` replicas.

`cake route` runs this aiohttp app. It owns three jobs, layered on the
registry's membership machine (fleet/registry.py) and the affinity hash
(fleet/routing.py):

  1. ROUTE — each chat request's conversation head is chain-hashed and
     rendezvous-placed so follow-ups land on the replica already holding
     their prefix KV blocks (warm TTFT); CAKE_FLEET_AFFINITY=0 degrades
     to round-robin for A/B benching.

  2. FAIL OVER — a transport failure or replica 5xx retries on the
     deterministic next-best replica under a per-request budget
     (CAKE_FLEET_RETRIES) with capped-exponential backoff +/-25% jitter.
     Streamed requests fail over invisibly BEFORE the first byte
     reaches the client (the commit point); AFTER it the router
     SELF-HEALS: it keeps a bounded replay buffer of the relayed
     assistant text (CAKE_FLEET_RESUME_BUFFER_KB) and on a break
     re-issues the buffered partial in CONTINUATION MODE (the replica
     prefills prompt + partial and continues the same message) on the
     affinity next-best replica — overlap stripped, chunk ids rewritten
     onto the original stream, relayed on the SAME client socket — up
     to CAKE_FLEET_STREAM_RESUMES times. Only an exhausted budget (or a
     blown buffer) emits the typed SSE error event, whose resume block
     now carries a resume_token so the client can still finish via the
     same continuation mode by hand. Requests can optionally hedge
     (CAKE_FLEET_HEDGE_MS): no reply after the threshold fires a
     duplicate at the next-best replica and the first response wins
     ("The Tail at Scale") — streams hedge up to their commit point,
     the first replica to produce a body byte wins the socket.

  3. SHED — a per-replica in-flight cap and a global admission bound
     turn overload into typed 429s AT THE ROUTER (body carries
     shed_by=router), before any replica queues the request; Retry-After
     scales with the fleet backlog. Router drain mirrors engine drain:
     SIGTERM stops admission (503) while in-flight proxies finish.

The router deliberately does NOT load a tokenizer or model: it is a thin
tier that can run many-per-region, restart in milliseconds, and scale
separately from the replicas."""
from __future__ import annotations

import asyncio
import base64
import dataclasses
import json
import logging
import random
import uuid

from aiohttp import web

from .. import knobs
from ..obs import (FLEET_HEDGES, FLEET_KV_MIGRATIONS, FLEET_PROXIED,
                   FLEET_RETRIES, FLEET_SHEDS, FLEET_STREAM_RESUMES,
                   TRACE_HEADER, TimelineStore, now)
from . import faults
from .autoscale import Autoscaler, DecisionLog, ScalePolicy
from .kvshare.directory import encode_directory
from .lifecycle import ReplicaLifecycle
from .registry import ReplicaRegistry, discover_replicas
from .routing import affinity_key, conversation_head, rank_replicas
from .telemetry import FleetTelemetry

log = logging.getLogger("cake_tpu.fleet")

__all__ = ["FleetRouter", "create_router_app", "serve_router"]

# transport-level failure classes: the replica never (fully) answered.
# InjectedFleetFault subclasses ConnectionError, so drills ride this too.
_TRANSPORT_ERRORS = (ConnectionError, asyncio.TimeoutError, OSError)

# QoS plumbing, mirrored from serve/admission/classes.py by NAME ONLY:
# importing the serve package would pull jax into the router process,
# and the router tier deliberately stays model-free / import-light. The
# replica is the authority — it re-resolves and clamps the class; the
# router only needs "is this batch" for early shedding and forwards the
# headers verbatim.
QOS_HEADER = "X-Cake-QoS"
TENANT_HEADER = "X-Cake-Tenant"
_QOS_CLASSES = ("interactive", "standard", "batch")

# continuation handshake, mirrored from api/text.py by NAME ONLY (same
# import-light rule as the QoS headers): a replica answering a
# continuation-mode request reports how many chars of the partial
# assistant text it actually consumed, and the router strips EXACTLY
# the re-emitted remainder from the resumed stream's front. Position
# accounting, not content matching — a suffix-match heuristic cannot
# tell boundary re-emission from genuinely repeating tokens.
CONTINUATION_CHARS_HEADER = "X-Cake-Continuation-Chars"

# fleet-shared KV tier handshake, mirrored from fleet/kvshare/replica.py
# by NAME ONLY (replica.py imports jax; the router tier stays
# import-light): the router injects the warm-peer directory into every
# forwarded attempt, marks the one resumed leg whose target received a
# migrated stream blob, and the replica flags a blob-adopted resume on
# its response so the relay strips the re-emitted text by CUMULATIVE
# position instead of the continuation-chars formula.
KV_DIR_HEADER = "X-Cake-KV-Peers"
KV_RESUME_HEADER = "X-Cake-KV-Resume"
KV_RESUMED_HEADER = "X-Cake-KV-Resumed"


def _transport_errors():
    """aiohttp's client errors join the transport set lazily (the module
    must stay importable for unit tests even if aiohttp changes)."""
    try:
        import aiohttp
        return _TRANSPORT_ERRORS + (aiohttp.ClientError,)
    except ImportError:                     # pragma: no cover
        return _TRANSPORT_ERRORS


async def _deadline(aw, seconds):
    """Await `aw` under a deadline WITHOUT asyncio.wait_for: wait_for
    runs the awaitable in a child task, and the extra loop ticks that
    costs lose races against data that is already buffered — a replica
    that streams chunks and severs in the same breath would have its
    connection_lost exception processed before the relay loop reads the
    buffered chunks, turning a resumable post-commit break into a
    from-scratch retry. A call_later watchdog cancels in place instead;
    an overrun raises asyncio.TimeoutError (a classified transport
    failure), an external cancellation passes through untouched."""
    if not seconds:
        return await aw
    task = asyncio.current_task()
    fired = []
    handle = asyncio.get_running_loop().call_later(
        seconds, lambda: (fired.append(True), task.cancel()))
    try:
        return await aw
    except asyncio.CancelledError:
        if fired:
            raise asyncio.TimeoutError(
                f"no response within {seconds}s") from None
        raise
    finally:
        handle.cancel()


class _ClientGone(Exception):
    """Our DOWNSTREAM client vanished mid-relay. Distinct from upstream
    transport failures so a disconnecting client is never recorded as a
    replica failure (repeat disconnects would otherwise feed the gray
    detector and eject a healthy replica)."""


class _StreamRelay:
    """Client-side state of ONE streamed request across every replica
    attempt it takes: the (single) prepared client response, the
    identity of the first relayed stream (chunk id / created stamp —
    the resume path rewrites spliced chunks onto them so the client
    sees one continuous completion), and the bounded replay buffer a
    resume splice is rebuilt from. Event-loop-confined, like all
    handler state."""

    def __init__(self, limit_bytes: int):
        self.resp: web.StreamResponse | None = None
        self.claimed = False        # commit claim (one hedge leg wins)
        self.owner: str | None = None       # replica name that claimed
        self.commit_evt = asyncio.Event()
        self.cid: str | None = None         # first stream's chunk "id"
        self.created = None                 # ... and "created" stamp
        self.chunks = 0             # SSE events relayed to the client
        self.tokens = 0             # content-bearing chunks (~tokens)
        self.content_chars = 0      # relayed content length (always on)
        self.text = ""              # replay buffer (until overflow)
        self.text_bytes = 0         # running UTF-8 size of the buffer
        self.splice_chars = 0       # FULL partial length of the last
                                    # splice request (client's own
                                    # continuation prefix + buffer)
        self.limit = max(int(limit_bytes), 1024)
        self.overflow = False       # buffer blown: splice impossible
        self.opaque = False         # unparseable data event: ditto
        self.finished = False       # a finish_reason reached the client
        self.last_exc: BaseException | None = None

    def account(self, content: str | None, finish) -> None:
        """Fold one relayed chunk into the replay buffer/accounting."""
        if finish:
            self.finished = True
        if content:
            self.tokens += 1
            self.content_chars += len(content)
            if not self.overflow:
                self.text += content
                # running counter: re-encoding the whole buffer per
                # chunk would make the relay O(n^2) in stream length
                self.text_bytes += len(
                    content.encode("utf-8", "surrogatepass"))
                if self.text_bytes > self.limit:
                    # past the bound the splice can no longer be built:
                    # drop the buffer (memory bound is the point) and
                    # let a later break take the typed-error path
                    self.overflow = True
                    self.text = ""

    def spliceable(self) -> bool:
        return not (self.overflow or self.opaque)


class FleetRouter:
    """Router state + handlers. One instance per router process; all
    handler state is event-loop-confined (single asyncio thread), while
    the registry it routes over is thread-safe."""

    def __init__(self, registry: ReplicaRegistry, *,
                 retries: int | None = None,
                 backoff_s: float | None = None,
                 hedge_ms: float | None = None,
                 max_inflight: int | None = None,
                 affinity: bool | None = None,
                 affinity_blocks: int | None = None,
                 attempt_timeout_s: float | None = None,
                 connect_timeout_s: float | None = None,
                 first_byte_timeout_s: float | None = None,
                 probe_s: float | None = None,
                 cluster_key: str | None = None,
                 discover_s: float | None = None,
                 stream_resumes: int | None = None,
                 resume_buffer_kb: int | None = None,
                 autoscale: bool | None = None,
                 kvshare: bool | None = None):
        self.registry = registry
        self.retries = retries if retries is not None \
            else knobs.get("CAKE_FLEET_RETRIES")
        self.backoff_s = backoff_s if backoff_s is not None \
            else knobs.get("CAKE_FLEET_BACKOFF_S")
        self.hedge_ms = hedge_ms if hedge_ms is not None \
            else knobs.get("CAKE_FLEET_HEDGE_MS")
        self.max_inflight = max_inflight if max_inflight is not None \
            else knobs.get("CAKE_FLEET_MAX_INFLIGHT")
        self.affinity = affinity if affinity is not None \
            else knobs.get("CAKE_FLEET_AFFINITY")
        self.affinity_blocks = affinity_blocks if affinity_blocks is not None \
            else knobs.get("CAKE_FLEET_AFFINITY_BLOCKS")
        self.attempt_timeout_s = attempt_timeout_s \
            if attempt_timeout_s is not None \
            else knobs.get("CAKE_FLEET_ATTEMPT_TIMEOUT_S")
        # split deadlines (non-zero defaults): connect bounds the
        # refused/black-holed-SYN shapes, first-byte bounds
        # accept-then-silence — both classify as retryable transport
        # failures, converting a partition into bounded failover instead
        # of an attempt that hangs forever (the deprecated 0.0=forever
        # attempt timeout left both unbounded by default)
        self.connect_timeout_s = connect_timeout_s \
            if connect_timeout_s is not None \
            else knobs.get("CAKE_FLEET_CONNECT_TIMEOUT_S")
        self.first_byte_timeout_s = first_byte_timeout_s \
            if first_byte_timeout_s is not None \
            else knobs.get("CAKE_FLEET_FIRST_BYTE_TIMEOUT_S")
        self.probe_s = probe_s if probe_s is not None \
            else knobs.get("CAKE_FLEET_PROBE_S")
        self.cluster_key = cluster_key
        self.discover_s = discover_s if discover_s is not None \
            else knobs.get("CAKE_FLEET_DISCOVER_S")
        self.stream_resumes = stream_resumes if stream_resumes is not None \
            else knobs.get("CAKE_FLEET_STREAM_RESUMES")
        self.resume_buffer_kb = resume_buffer_kb \
            if resume_buffer_kb is not None \
            else knobs.get("CAKE_FLEET_RESUME_BUFFER_KB")
        # fleet-shared KV tier: inject warm-peer directories and ship
        # stream blobs on post-commit breaks (docs/kv_sharing.md)
        self.kvshare = kvshare if kvshare is not None \
            else knobs.get("CAKE_KVSHARE")
        self.kv_fetch_timeout_s = knobs.get("CAKE_KVSHARE_FETCH_TIMEOUT_S")
        self.session = None                 # aiohttp.ClientSession
        self.inflight = 0                   # event-loop-confined
        self.draining = False
        # router-tier timeline ring, deliberately SEPARATE from the
        # process-global obs.TIMELINES: the stitched /api/v1/requests
        # view distinguishes tiers by store, and an in-process replica
        # (tests, smokes, embedded topologies) must keep its
        # replica-tier timeline distinct from the router's
        self.timelines = TimelineStore()
        # telemetry plane: fed by the probe loop, served by
        # /api/v1/fleet/telemetry (and the `cake top` dashboard)
        self.telemetry = FleetTelemetry(registry)
        # closed loop: the autoscaler consumes each cycle's rollup and
        # executes through the lifecycle manager (both None when off —
        # CAKE_SCALE gates the subsystem, the telemetry stays advisory)
        enabled = autoscale if autoscale is not None \
            else knobs.get("CAKE_SCALE")
        self.lifecycle = None
        self.autoscaler = None
        if enabled:
            decisions = DecisionLog()
            self.lifecycle = ReplicaLifecycle(registry,
                                              record=decisions.record)
            policy = ScalePolicy.from_knobs()
            if autoscale:       # explicit flag wins over the env knob
                policy = dataclasses.replace(policy, enabled=True)
            self.autoscaler = Autoscaler(registry, self.lifecycle,
                                         policy=policy, log=decisions)
        self._tasks: list = []

    # -- lifecycle -----------------------------------------------------------

    async def start(self, app=None):
        import aiohttp
        self.session = aiohttp.ClientSession()
        await self._probe_once()
        self._tasks.append(asyncio.create_task(self._probe_loop()))
        if self.cluster_key and self.discover_s > 0:
            self._tasks.append(asyncio.create_task(self._discover_loop()))
        self.registry.publish()

    async def stop(self, app=None):
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass
        self._tasks.clear()
        if self.lifecycle is not None:
            await self.lifecycle.close()
        if self.session is not None:
            await self.session.close()
            self.session = None

    async def drain(self, app=None):
        """SIGTERM mirror of the engine drain: stop admission (new chats
        answer 503 + Retry-After) and wait for in-flight proxied
        requests to finish their final chunks, up to the same
        CAKE_DRAIN_TIMEOUT_S budget the replicas use."""
        self.draining = True
        deadline = now() + knobs.get("CAKE_DRAIN_TIMEOUT_S")
        while self.inflight > 0 and now() < deadline:
            await asyncio.sleep(0.05)
        if self.inflight:
            log.warning("router drain timed out with %d in flight",
                        self.inflight)

    # -- probe / discovery loops ---------------------------------------------

    async def _probe_once(self):
        async def probe(rep):
            try:
                import aiohttp
                tmo = aiohttp.ClientTimeout(total=max(
                    min(self.probe_s, 2.0), 0.2))
                async with self.session.get(rep.base_url + "/health",
                                            timeout=tmo) as r:
                    body = await r.json(content_type=None)
                    rep.observe_health(r.status, body)
            except asyncio.CancelledError:
                raise
            except Exception:
                rep.observe_health(None, None)
        # concurrent: one unreachable replica must not stall health
        # detection for the whole fleet (each dead probe burns its full
        # timeout; serially that would multiply the effective cadence)
        await asyncio.gather(*(probe(r)
                               for r in self.registry.replicas()))
        self.registry.publish()
        # membership events (partition suspected/healed) land in
        # per-replica pseudo-timelines (rid "replica:<name>") on the
        # router-tier store, so an episode is visible in the stitched
        # timeline view next to the requests it disrupted
        for kind, attrs in self.registry.drain_events():
            rid = f"replica:{attrs.get('replica', '?')}"
            self.timelines.begin(rid, tier="fleet")
            self.timelines.event(rid, kind, **attrs)
        # same cadence as the probes: scrape /metrics and roll up the
        # telemetry plane (stale replicas were just flagged above, so
        # this cycle's rollup already excludes them)
        try:
            await self.telemetry.step(self.session)
        except asyncio.CancelledError:
            raise
        except Exception:
            log.exception("telemetry rollup failed (cycle skipped)")
        # the closed loop rides the same cadence: reap unexpected
        # deaths first (the controller must see the hole this cycle),
        # then decide on the rollup just computed
        if self.autoscaler is not None:
            try:
                self.lifecycle.sweep()
                self.autoscaler.step(self.telemetry.snapshot())
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("autoscale step failed (cycle skipped)")

    async def _probe_loop(self):
        """Health-driven membership: every tick consumes each replica's
        /health engine block into its state machine — ejects on
        down/wedged, readmits ejected replicas whose hold expired and
        whose probes came back healthy, mirrors queue depth / occupancy
        into the autoscaling gauges."""
        while True:
            await asyncio.sleep(self.probe_s)
            await self._probe_once()

    async def _discover_loop(self):
        """Periodic UDP re-discovery over the cluster PSK plumbing: new
        `cake serve --announce` replicas join without a router restart."""
        loop = asyncio.get_running_loop()
        while True:
            await asyncio.sleep(self.discover_s)
            try:
                found = await loop.run_in_executor(
                    None, lambda: discover_replicas(self.cluster_key))
            except Exception:
                continue
            for name, base_url in found:
                self.registry.add(name, base_url)

    # -- admission / shedding ------------------------------------------------

    def _global_cap(self) -> int:
        if self.max_inflight > 0:
            return self.max_inflight
        return max(self.registry.total_capacity(), 1)

    def _retry_after(self) -> int:
        """Backlog-proportional Retry-After, the router-level analog of
        the engine's retry_after_hint: the fleet queue depth per
        routable replica."""
        routable = max(self.registry.routable_count(), 1)
        depth = self.registry.total_queue_depth() + self.inflight
        return max(1, min(30, 1 + depth // routable))

    def _shed(self, reason: str, rid: str | None = None) -> web.Response:
        FLEET_SHEDS.inc(reason=reason)
        FLEET_PROXIED.inc(outcome="shed")
        if rid:
            self.timelines.event(rid, "shed", reason=reason)
        return web.json_response(
            {"error": f"fleet overloaded: {reason}", "shed_by": "router"},
            status=429,
            headers={"Retry-After": str(self._retry_after())})

    def _no_replica(self, rid: str | None = None) -> web.Response:
        FLEET_PROXIED.inc(outcome="failed")
        if rid:
            self.timelines.event(rid, "shed", reason="no_replica")
        # during an in-flight scale-out the honest wait is the expected
        # spawn-to-routable time, not the backlog formula — a client
        # arriving mid cold start should wait the spawn out, not give up
        eta = self.lifecycle.pending_spawn_eta() \
            if self.lifecycle is not None else None
        body = {"error": "no routable replica (all ejected, draining, or "
                         "none registered)", "shed_by": "router"}
        if eta is not None:
            body["scale_out_pending"] = True
        return web.json_response(
            body, status=503,
            headers={"Retry-After": str(eta if eta is not None
                                        else self._retry_after())})

    # -- candidate ordering --------------------------------------------------

    def _order(self, messages: list) -> list:
        """Replica objects in attempt order: rendezvous over the
        conversation head's chain key (owner first, deterministic
        next-best after), or round-robin rotation when affinity is
        off."""
        names = self.registry.names()
        if not names:
            return []
        if self.affinity and messages:
            key = affinity_key(conversation_head(messages),
                               self.affinity_blocks)
            # weighted rendezvous: probed capacity (engine slots from
            # /health) scales each replica's score, so a heterogeneous
            # fleet places conversations proportionally
            weights = {r.name: r.weight()
                       for r in self.registry.replicas()}
            ranked = rank_replicas(key, names, weights)
        else:
            start = self.registry.next_rr() % len(names)
            ranked = sorted(names)
            ranked = ranked[start:] + ranked[:start]
        by_name = {r.name: r for r in self.registry.replicas()}
        return [by_name[n] for n in ranked if n in by_name]

    async def _sleep_backoff(self, attempt: int):
        """Capped exponential +/-25% jitter between failover attempts —
        the cluster recovery scheme, scaled for a request path."""
        base = min(self.backoff_s * (2 ** max(attempt - 1, 0)),
                   max(self.backoff_s * 8, 1.0))
        await asyncio.sleep(base * (0.75 + 0.5 * random.random()))

    # -- one outbound attempt ------------------------------------------------

    async def _one_json(self, rep, body: dict, rid: str | None = None,
                        fwd: dict | None = None):
        """One non-streamed attempt against `rep`. Returns
        ("skip", None)       — replica at cap / not acquirable,
        ("retryable", str)   — transport failure, replica 5xx or 429,
        ("final", Response)  — relay this (200 or non-retryable 4xx).
        Acquires and releases the replica's routing slot itself so a
        hedge winner can cancel the loser without leaking the slot."""
        lease = rep.try_acquire()
        if not lease:
            return ("skip", None)
        try:
            hook = faults.FAULT_HOOK
            if hook is not None:
                stall = hook.on_attempt(rep.name)
                if stall:
                    await asyncio.sleep(stall)
            import aiohttp
            # split deadlines: connect bounds the handshake, sock_read
            # bounds every read GAP — which covers waiting for response
            # headers, so a black-holed replica (SYN accepted, nothing
            # ever sent) fails in bounded time; the deprecated total
            # attempt deadline still rides on top when set
            tmo = aiohttp.ClientTimeout(
                total=self.attempt_timeout_s or None,
                connect=self.connect_timeout_s or None,
                sock_read=self.first_byte_timeout_s or None)
            t0 = now()
            hdrs = self._trace_headers(rid, fwd)
            peers = self._kv_peers(rep)
            if peers:
                hdrs[KV_DIR_HEADER] = peers
            async with self.session.post(
                    rep.base_url + "/v1/chat/completions",
                    json=body, timeout=tmo,
                    headers=hdrs) as r:
                ttfb_ms = (now() - t0) * 1e3
                data = await r.read()
                if r.status in (500, 502, 503):
                    rep.record_result(False, lease=lease)
                    if rid:
                        self.timelines.event(rid, "attempt", replica=rep.name,
                                        outcome="retryable",
                                        status=r.status)
                    return ("retryable",
                            f"{rep.name}: upstream {r.status}")
                if r.status == 429:
                    # replica backpressure is load, not sickness: do not
                    # feed the failure detector, just go elsewhere
                    if rid:
                        self.timelines.event(rid, "attempt", replica=rep.name,
                                        outcome="saturated", status=429)
                    return ("retryable",
                            f"{rep.name}: replica saturated (429)")
                rep.record_result(True, ttfb_ms, lease=lease)
                if rid:
                    self.timelines.event(rid, "attempt", replica=rep.name,
                                    outcome="final", status=r.status,
                                    ttfb_ms=round(ttfb_ms, 3))
                resp = web.Response(
                    body=data, status=r.status,
                    content_type=r.content_type or "application/json")
                if rid:
                    resp.headers[TRACE_HEADER] = rid
                return ("final", resp)
        except _transport_errors() as e:
            rep.record_result(False, transport=True, lease=lease)
            if rid:
                self.timelines.event(rid, "attempt", replica=rep.name,
                                outcome="transport_error", status=0)
            return ("retryable",
                    f"{rep.name}: {type(e).__name__}: {e}")
        finally:
            rep.release(lease)

    @staticmethod
    def _trace_headers(rid: str | None,
                       fwd: dict | None = None) -> dict:
        """Headers for one outbound attempt: the trace id (the replica
        adopts it into its request-id contextvar and its serve engine
        keys timeline events by it, so the router's
        /api/v1/requests/<id> can stitch both tiers) plus the
        passthrough admission headers captured in handle_chat —
        X-Cake-QoS / X-Cake-Tenant / Authorization — so the replica's
        admission plane sees the same class and tenant the router shed
        against."""
        out = dict(fwd) if fwd else {}
        if rid:
            out[TRACE_HEADER] = rid
        return out

    def _kv_peers(self, target) -> str | None:
        """X-Cake-KV-Peers value for one outbound attempt: every OTHER
        replica's registry-mirrored chain inventory. Draining/cordoned
        peers advertise on purpose — a replica on its way out is exactly
        the one whose cache peers should siphon — while ejected/stale/
        sick inventories come back empty (kv_inventory + probe
        retraction). None — header not injected — when kvshare is off
        or no peer has anything to advertise."""
        if not self.kvshare:
            return None
        peers = []
        for rep in self.registry.replicas():
            if rep.name == target.name:
                continue
            chains = rep.kv_inventory()
            if chains:
                peers.append((rep.base_url, chains))
        return encode_directory(peers)

    @staticmethod
    def _fwd_headers(request: web.Request) -> dict:
        """The admission headers a chat request carries through the
        router verbatim (class override, tenant key, auth credential —
        the replica re-resolves and clamps; the router never rewrites
        them)."""
        out = {}
        for h in (QOS_HEADER, TENANT_HEADER, "Authorization"):
            v = request.headers.get(h)
            if v:
                out[h] = v
        return out

    # -- request paths -------------------------------------------------------

    async def handle_chat(self, request: web.Request) -> web.StreamResponse:
        if self.draining:
            return web.json_response(
                {"error": "router draining for shutdown"}, status=503,
                headers={"Retry-After": str(self._retry_after())})
        try:
            body = await request.json()
        except Exception:
            return web.json_response({"error": "invalid JSON body"},
                                     status=400)
        messages = body.get("messages")
        if not isinstance(messages, list) or not messages:
            return web.json_response({"error": "messages[] required"},
                                     status=400)
        # cross-tier trace id: adopt the client's (a chained router, a
        # test harness) or mint one; it is injected into every outbound
        # attempt, adopted by the replica's API + serve engine, echoed
        # on the response, and keys this tier's timeline — one id end
        # to end
        rid = request.headers.get(TRACE_HEADER) \
            or "trace-" + uuid.uuid4().hex[:16]
        self.timelines.begin(rid, tier="router")
        # the admission class travels with the request (header or body
        # field); the REPLICA's plane is the authority that validates
        # and tenant-clamps it — the router only sheds early on it
        qos = str(request.headers.get(QOS_HEADER)
                  or body.get("qos") or "interactive").strip().lower()
        if qos not in _QOS_CLASSES:
            qos = "interactive"         # replica answers the 400
        fwd = self._fwd_headers(request)
        # router-level admission: shed BEFORE any replica queues it.
        # Batch sheds FIRST — at CAKE_QOS_BATCH_SHED_FRAC of the global
        # cap — so under pressure the remaining in-flight headroom stays
        # reserved for interactive traffic (batch clients hold their
        # Retry-After; chat keeps flowing)
        cap = self._global_cap()
        if self.inflight >= cap:
            return self._shed("global admission bound", rid)
        frac = knobs.get("CAKE_QOS_BATCH_SHED_FRAC")
        if qos == "batch" and frac < 1.0 \
                and self.inflight >= max(1, int(cap * frac)):
            return self._shed("batch_pressure", rid)
        order = self._order(messages)
        if not any(r.routable() for r in order):
            return self._no_replica(rid)
        self.timelines.event(rid, "route", candidates=[r.name for r in order],
                        stream=bool(body.get("stream")), qos=qos)
        self.inflight += 1
        try:
            if body.get("stream"):
                return await self._route_stream(request, body, order, rid,
                                                fwd=fwd)
            if self.hedge_ms > 0:
                return await self._route_json_hedged(body, order, rid,
                                                     fwd=fwd)
            return await self._route_json(body, order, 1 + self.retries,
                                          rid=rid, fwd=fwd)
        finally:
            self.inflight -= 1

    async def _route_json(self, body: dict, order: list, budget: int,
                          prior_attempts: int = 0,
                          rid: str | None = None,
                          fwd: dict | None = None) -> web.Response:
        """Sequential failover over `order` under an attempt budget.
        `prior_attempts`: attempts already spent by a caller (the hedged
        path) — they count against the budget and keep the exhausted-503
        honest about how many replicas were really tried."""
        attempts = prior_attempts
        cap_skipped = False
        detail = None
        for i, rep in enumerate(order):
            if attempts >= budget:
                break
            if not rep.routable():
                continue
            kind, val = await self._one_json(rep, body, rid, fwd)
            if kind == "skip":
                cap_skipped = True
                continue
            attempts += 1
            if kind == "final":
                FLEET_PROXIED.inc(
                    outcome="ok" if val.status < 400 else "failed")
                if rid:
                    self.timelines.event(rid, "done", status=val.status)
                return val
            detail = val
            # back off only when another attempt can actually happen —
            # sleeping after the last candidate just delays the 503
            if attempts < budget \
                    and any(r.routable() for r in order[i + 1:]):
                FLEET_RETRIES.inc()
                if rid:
                    self.timelines.event(rid, "retry")
                await self._sleep_backoff(attempts)
        if attempts == 0:
            return self._shed("replica in-flight caps", rid) \
                if cap_skipped else self._no_replica(rid)
        FLEET_PROXIED.inc(outcome="failed")
        if rid:
            self.timelines.event(rid, "done", status=503)
        return web.json_response(
            {"error": "fleet failover budget exhausted",
             "attempts": attempts, "last": detail, "shed_by": "router"},
            status=503,
            headers={"Retry-After": str(self._retry_after())})

    async def _route_json_hedged(self, body: dict, order: list,
                                 rid: str | None = None,
                                 fwd: dict | None = None) -> web.Response:
        """Tail-hedged non-streamed path: if the owner has not answered
        within CAKE_FLEET_HEDGE_MS, fire a duplicate at the next-best
        replica and take whichever finishes first (the loser is
        cancelled and its routing slot released by _one_json's
        finally). Falls back to the sequential path when fewer than two
        replicas are routable, or for the remaining budget after both
        hedge legs fail."""
        reps = [r for r in order if r.routable()]
        if len(reps) < 2:
            return await self._route_json(body, order, 1 + self.retries,
                                          rid=rid, fwd=fwd)
        primary = asyncio.create_task(
            self._one_json(reps[0], body, rid, fwd))
        done, _ = await asyncio.wait({primary},
                                     timeout=self.hedge_ms / 1e3)
        tasks = {primary}
        tried = 1
        if not done:
            FLEET_HEDGES.inc()
            if rid:
                self.timelines.event(rid, "hedge", replica=reps[1].name)
            tasks.add(asyncio.create_task(
                self._one_json(reps[1], body, rid, fwd)))
            tried = 2
        pending = tasks
        non_final = 0
        try:
            while pending:
                done, pending = await asyncio.wait(
                    pending, return_when=asyncio.FIRST_COMPLETED)
                for t in done:
                    kind, val = t.result()
                    if kind == "final":
                        FLEET_PROXIED.inc(
                            outcome="ok" if val.status < 400
                            else "failed")
                        if rid:
                            self.timelines.event(rid, "done",
                                            status=val.status)
                        return val
                    if kind != "skip":      # at-cap skips spend no budget
                        non_final += 1
        finally:
            for t in pending:
                t.cancel()
        # every fired leg failed/skipped: sequential over the replicas
        # not yet tried (when the primary failed fast the hedge never
        # fired, so reps[1] — the deterministic next-best — must still
        # get its attempt). Hedge attempts count against the budget via
        # prior_attempts, which also keeps the terminal 503 reporting
        # "budget exhausted after N attempts" rather than the misleading
        # no-replica message when reps[tried:] is empty.
        rest = reps[tried:]
        if non_final and any(r.routable() for r in rest):
            FLEET_RETRIES.inc()             # hedge -> sequential handoff
            if rid:
                self.timelines.event(rid, "retry")
        return await self._route_json(body, rest, 1 + self.retries,
                                      prior_attempts=non_final, rid=rid,
                                      fwd=fwd)

    async def _route_stream(self, request: web.Request, body: dict,
                            order: list, rid: str | None = None,
                            fwd: dict | None = None) -> web.StreamResponse:
        """SSE relay with pre-commit failover/hedging and post-commit
        SELF-HEALING: attempts rotate replicas until one starts
        streaming; once the first byte has been relayed the request is
        COMMITTED to that replica's stream identity, and a break after
        commit is spliced back together — the buffered partial content
        is re-issued in continuation mode on the affinity next-best
        survivor and the continuation relayed on the SAME client socket
        — up to CAKE_FLEET_STREAM_RESUMES times. Only an exhausted
        budget (or a blown replay buffer) emits the typed error event,
        which now carries a resume_token for a manual continuation."""
        st = _StreamRelay(self.resume_buffer_kb * 1024)
        bs = {"attempts": 0, "budget": 1 + self.retries,
              "cap_skipped": False}
        # rng-fold parity exception rides the resume: a sampled stream
        # (temperature > 0) still resumes, but its continuation draws
        # from a fresh fold — flagged on the timeline, same documented
        # exception as a crash rebuild
        sampled = float(body.get("temperature", 0.7) or 0.0) > 0.0
        if self.hedge_ms > 0:
            kind, val = await self._stream_first_hedged(
                request, body, order, rid, fwd, st, bs)
        else:
            kind, val = await self._stream_seq(
                request, body, order, rid, fwd, st, bs)
        failed: set = set()
        resumes = 0
        while kind == "broken":
            broken = val
            failed.add(broken.name)
            if st.finished:
                # the break lost only the [DONE] sentinel (the finish
                # chunk already reached the client): close the stream
                # clean — there is nothing left to resume
                return await self._finish_interrupted(st, rid)
            max_tok = int(body.get("max_tokens",
                                   body.get("max_completion_tokens",
                                            256)) or 256)
            if st.tokens >= max_tok:
                # every budgeted token was already delivered — only the
                # finish chunk and [DONE] died with the connection. A
                # splice here would decode PAST the client's budget
                # (max_tokens clamps at 1), so synthesize the finish
                # instead of resuming a completed generation.
                return await self._finish_interrupted(st, rid,
                                                      synth_finish=True)
            if resumes >= self.stream_resumes:
                FLEET_STREAM_RESUMES.inc(outcome="exhausted")
                return await self._stream_broken_terminal(
                    st, rid, broken, resumes)
            if not st.spliceable():
                FLEET_STREAM_RESUMES.inc(outcome="overflow")
                return await self._stream_broken_terminal(
                    st, rid, broken, resumes)
            resumes += 1
            if rid:
                self.timelines.event(rid, "stream_resume",
                                     replica=broken.name, attempt=resumes,
                                     **({"sampled": True} if sampled
                                        else {}))
            splice = self._splice_body(body, st)
            # affinity next-best over the (unchanged) conversation head:
            # the survivor ranked after the broken owner most likely
            # holds the shared prefix blocks, so the splice prefill is
            # the warm path. Replicas that already broke THIS stream
            # are skipped even if not yet ejected; the resume is a
            # fresh outbound placement, so it rotates under its own
            # attempt budget rather than whatever the initial
            # placement left over.
            rbs = {"attempts": 0, "budget": 1 + self.retries,
                   "cap_skipped": False}
            order = self._order(splice["messages"])
            # fleet-shared KV tier: before the continuation splice, try
            # to ship the broken owner's parked swap blob (drain parks
            # it; post-commit failover where the source still answers
            # exports the live slot — fetching IS the migration signal)
            # to the first viable survivor. Success marks that leg with
            # X-Cake-KV-Resume and orders the target first; every
            # failure mode falls through to the splice continuation,
            # which is the same request body either way.
            kv_resume = None
            if self.kvshare and rid:
                target = next((r for r in order
                               if r.name not in failed and r.routable()),
                              None)
                if target is not None and await self._migrate_stream(
                        broken, target, rid):
                    kv_resume = (target.name, rid)
                    order = [target] + [r for r in order
                                        if r.name != target.name]
            kind, val = await self._stream_seq(
                request, splice, order, rid,
                fwd, st, rbs, resumed=True, skip=failed,
                kv_resume=kv_resume)
            if kind == "none":
                FLEET_STREAM_RESUMES.inc(outcome="error")
                return await self._stream_broken_terminal(
                    st, rid, broken, resumes)
            if kind == "broken":
                FLEET_STREAM_RESUMES.inc(outcome="broken")
        if kind == "final":
            if resumes:
                FLEET_STREAM_RESUMES.inc(outcome="ok")
            if rid:
                self.timelines.event(rid, "done", status=val.status)
            return val
        # kind == "none": the stream never started anywhere
        if bs["attempts"] == 0:
            return self._shed("replica in-flight caps", rid) \
                if bs["cap_skipped"] else self._no_replica(rid)
        FLEET_PROXIED.inc(outcome="failed")
        if rid:
            self.timelines.event(rid, "done", status=503)
        return web.json_response(
            {"error": "fleet failover budget exhausted (stream never "
                      "started)", "attempts": bs["attempts"],
             "shed_by": "router"},
            status=503,
            headers={"Retry-After": str(self._retry_after())})

    async def _stream_seq(self, request, body, order: list,
                          rid: str | None, fwd: dict | None,
                          st: _StreamRelay, bs: dict,
                          resumed: bool = False, skip=(),
                          kv_resume: tuple | None = None):
        """Sequential streamed placement over `order` under bs's shared
        attempt budget: rotate candidates until one commits (relays a
        byte to the client). Pre-commit failures stay invisible.
        Returns ("final", resp) | ("broken", replica) | ("none", None);
        `skip` names replicas that already broke this stream;
        `kv_resume` = (replica_name, rid) marks the ONE candidate that
        holds a migrated stream blob — only its leg carries the
        X-Cake-KV-Resume header, so a rotation past it degrades to the
        plain continuation splice."""
        for i, rep in enumerate(order):
            if bs["attempts"] >= bs["budget"]:
                break
            if rep.name in skip or not rep.routable():
                continue
            kind, val = await self._stream_leg(request, rep, body, rid,
                                               fwd, st, resumed,
                                               kv_resume)
            if kind == "skip":
                bs["cap_skipped"] = True
                continue
            if kind == "lost":              # hedge twin owns the socket
                continue
            if kind in ("final", "broken"):
                return (kind, val)
            bs["attempts"] += 1
            # back off only when another attempt can actually happen
            rest = [r for r in order[i + 1:]
                    if r.name not in skip and r.routable()]
            if bs["attempts"] < bs["budget"] and rest:
                FLEET_RETRIES.inc()
                if rid:
                    self.timelines.event(rid, "retry")
                await self._sleep_backoff(bs["attempts"])
        return ("none", None)

    async def _stream_leg(self, request, rep, body, rid, fwd,
                          st: _StreamRelay, resumed: bool = False,
                          kv_resume: tuple | None = None):
        """One streamed attempt holding its own routing-slot lease (so
        a hedge winner can cancel the loser without leaking it)."""
        lease = rep.try_acquire()
        if not lease:
            return ("skip", None)
        try:
            return await self._relay_stream(request, rep, body, lease,
                                            rid, fwd, st, resumed,
                                            kv_resume)
        finally:
            rep.release(lease)

    async def _stream_first_hedged(self, request, body, order: list,
                                   rid: str | None, fwd: dict | None,
                                   st: _StreamRelay, bs: dict):
        """Pre-commit tail hedge for streams: if the owner has produced
        no body byte after CAKE_FLEET_HEDGE_MS, fire a duplicate at the
        next-best replica; the FIRST leg to claim the commit point owns
        the client socket and the loser is cancelled before it can ever
        write (the claim is the exclusion — a leg checks-and-sets it
        with no await in between). Hedge attempts spend the shared
        budget exactly like the non-streamed path; falls back to the
        sequential relay when fewer than two replicas are routable or
        every fired leg dies pre-commit."""
        reps = [r for r in order if r.routable()]
        if len(reps) < 2:
            return await self._stream_seq(request, body, order, rid, fwd,
                                          st, bs)
        legs: dict = {}

        def fire(rep):
            legs[rep.name] = asyncio.create_task(
                self._stream_leg(request, rep, body, rid, fwd, st))
        fire(reps[0])
        await asyncio.wait(set(legs.values()),
                           timeout=self.hedge_ms / 1e3)
        if not st.claimed and not legs[reps[0].name].done():
            FLEET_HEDGES.inc()
            if rid:
                self.timelines.event(rid, "hedge", replica=reps[1].name)
            fire(reps[1])
        tried = len(legs)
        watch = asyncio.create_task(st.commit_evt.wait())
        result = None
        try:
            pending = set(legs.values())
            while pending and result is None:
                done, _ = await asyncio.wait(
                    pending | {watch},
                    return_when=asyncio.FIRST_COMPLETED)
                if st.claimed:
                    # a leg owns the socket: cancel the one that lost
                    # the race (still pre-commit by construction) and
                    # ride the winner to its terminal state
                    for name, t in legs.items():
                        if name != st.owner and not t.done():
                            t.cancel()
                    result = await legs[st.owner]
                    break
                for t in done:
                    if t is watch:
                        continue
                    pending.discard(t)
                    kind, val = t.result()
                    if kind == "final":
                        result = (kind, val)
                        break
                    if kind in ("skip", "lost"):
                        if kind == "skip":
                            bs["cap_skipped"] = True
                        continue
                    bs["attempts"] += 1     # pre-commit failure
        finally:
            watch.cancel()
            for t in legs.values():
                if not t.done():
                    t.cancel()
            await asyncio.gather(*legs.values(), return_exceptions=True)
        if result is not None:
            return result
        # every fired leg failed pre-commit: sequential over the rest
        rest = reps[tried:]
        if bs["attempts"] and any(r.routable() for r in rest):
            FLEET_RETRIES.inc()             # hedge -> sequential handoff
            if rid:
                self.timelines.event(rid, "retry")
            # same spacing as every other failover attempt: a fleet-wide
            # hiccup (both hedge legs 503ing) must not be hammered with
            # a zero-delay third attempt
            await self._sleep_backoff(max(bs["attempts"], 1))
        return await self._stream_seq(request, body, rest, rid, fwd, st,
                                      bs)

    # -- resume plumbing -----------------------------------------------------

    async def _migrate_stream(self, broken, target, rid: str) -> bool:
        """Ship a broken stream's swap blob from its (possibly still
        answering) owner to `target`. Two bounded hops under the fetch
        timeout: GET the blob off the source — the source parks the
        slot on this fetch if it is still live — then POST it to the
        target, which stages it for the X-Cake-KV-Resume adoption.
        False (metrics say why) means the resume plane falls back to
        the continuation splice; a migration can never make a break
        worse, only cheaper."""
        import aiohttp
        tmo = aiohttp.ClientTimeout(total=self.kv_fetch_timeout_s or None)
        url = "/api/v1/kv/stream/" + rid
        try:
            async with self.session.get(broken.base_url + url,
                                        timeout=tmo) as r:
                if r.status != 200:
                    # 404 = never parked / already swept; 409 = kvshare
                    # off on the source; 503 = export timed out. All
                    # the same to the resume plane: no blob to ship.
                    FLEET_KV_MIGRATIONS.inc(outcome="source_miss")
                    self.timelines.event(
                        rid, "kv_migrate", outcome="source_miss",
                        **{"from": broken.name, "to": target.name})
                    return False
                blob = await r.read()
        except _transport_errors():
            # the break that got us here usually took the whole replica
            # down — an unreachable source is the EXPECTED shape, not
            # an error worth a second failure record against it
            FLEET_KV_MIGRATIONS.inc(outcome="source_miss")
            self.timelines.event(
                rid, "kv_migrate", outcome="source_miss",
                **{"from": broken.name, "to": target.name})
            return False
        try:
            async with self.session.post(target.base_url + url,
                                         data=blob, timeout=tmo) as r:
                if r.status != 200:
                    FLEET_KV_MIGRATIONS.inc(outcome="ship_error")
                    self.timelines.event(
                        rid, "kv_migrate", outcome="ship_error",
                        **{"from": broken.name, "to": target.name,
                           "status": r.status})
                    return False
        except _transport_errors():
            FLEET_KV_MIGRATIONS.inc(outcome="ship_error")
            self.timelines.event(
                rid, "kv_migrate", outcome="ship_error",
                **{"from": broken.name, "to": target.name})
            return False
        FLEET_KV_MIGRATIONS.inc(outcome="shipped")
        self.timelines.event(
            rid, "kv_migrate", outcome="shipped",
            **{"from": broken.name, "to": target.name,
               "bytes": len(blob)})
        return True

    @staticmethod
    def _splice_body(body: dict, st: _StreamRelay) -> dict:
        """The continuation-mode request that resumes a broken stream:
        original messages + the buffered partial as a final assistant
        turn with `"continue": true` (merged in place when the client
        was ITSELF already continuing), token budget reduced by what
        was already generated so the resumed replica produces exactly
        the remainder. Records the FULL partial length on the relay
        state — the continuation-chars handshake reports consumption
        against the whole merged partial, not just the buffer."""
        msgs = [dict(m) if isinstance(m, dict) else m
                for m in (body.get("messages") or [])]
        if msgs and isinstance(msgs[-1], dict) \
                and msgs[-1].get("continue") \
                and msgs[-1].get("role") == "assistant":
            msgs[-1]["content"] = str(msgs[-1].get("content") or "") \
                + st.text
        else:
            msgs.append({"role": "assistant", "content": st.text,
                         "continue": True})
        st.splice_chars = len(str(msgs[-1]["content"]))
        out = dict(body)
        out["messages"] = msgs
        max_tok = body.get("max_tokens", body.get("max_completion_tokens"))
        if max_tok is not None:
            out.pop("max_completion_tokens", None)
            out["max_tokens"] = max(int(max_tok) - st.tokens, 1)
        return out

    @staticmethod
    def _resume_token(st: _StreamRelay, resumes: int) -> str:
        """The typed error's machine-readable half: the splice
        accounting a client needs to verify its own continuation
        (committed text length + generated-token count — NOT the event
        count, which includes role/finish chunks) before finishing the
        stream by hand. base64url JSON, inspectable on purpose."""
        tok = {"v": 1, "mode": "continue",
               "content_chars": st.content_chars,
               "tokens_generated": st.tokens,
               "chunks_relayed": st.chunks,
               "resumes_attempted": resumes}
        return base64.urlsafe_b64encode(
            json.dumps(tok, separators=(",", ":")).encode()).decode()

    async def _stream_broken_terminal(self, st: _StreamRelay,
                                      rid: str | None, rep,
                                      resumes: int) -> web.StreamResponse:
        """Self-healing gave up (budget exhausted, buffer blown, or no
        survivor could splice): emit the typed error event + [DONE] so
        the client sees a structured failure it can finish by hand via
        continuation mode — never a silent dead socket."""
        FLEET_PROXIED.inc(outcome="broken_stream")
        e = st.last_exc
        payload = {"error": {
            "type": "replica_stream_broken",
            "replica": rep.name,
            "message": f"{type(e).__name__}: {e}" if e is not None
                       else "stream broken after commit",
            "resume": {
                "chunks_relayed": st.chunks,
                "content_chars": st.content_chars,
                "tokens_generated": st.tokens,
                "resumes_attempted": resumes,
                "resume_token": self._resume_token(st, resumes),
                "hint": "append the received partial text as "
                        '{"role": "assistant", "content": <text>, '
                        '"continue": true} and re-issue: the replica '
                        "continues the same message in place "
                        "(prefix-affinity lands the retry warm; greedy "
                        "continuations are bit-identical)",
            },
        }}
        if rid:
            self.timelines.event(rid, "done", status=200)
        try:
            await st.resp.write(b"data: "
                                + json.dumps(payload).encode() + b"\n\n")
            await st.resp.write(b"data: [DONE]\n\n")
            await st.resp.write_eof()
        except _transport_errors():
            pass                        # client also gone
        return st.resp

    async def _finish_interrupted(self, st: _StreamRelay,
                                  rid: str | None,
                                  synth_finish: bool = False
                                  ) -> web.StreamResponse:
        """Close a broken stream that has nothing left to generate.
        `synth_finish`: the break also ate the finish chunk (the whole
        token budget was delivered) — emit one in the original stream's
        identity so the client sees a complete, well-formed stream."""
        FLEET_PROXIED.inc(outcome="ok")
        if rid:
            self.timelines.event(rid, "done", status=200)
        try:
            if synth_finish:
                chunk = {"object": "chat.completion.chunk",
                         "choices": [{"index": 0, "delta": {},
                                      "finish_reason": "length"}]}
                if st.cid is not None:
                    chunk["id"] = st.cid
                if st.created is not None:
                    chunk["created"] = st.created
                await st.resp.write(b"data: "
                                    + json.dumps(chunk).encode()
                                    + b"\n\n")
            await st.resp.write(b"data: [DONE]\n\n")
            await st.resp.write_eof()
        except _transport_errors():
            pass
        return st.resp

    async def _relay_stream(self, request, rep, body,
                            lease: str = "slot", rid: str | None = None,
                            fwd: dict | None = None,
                            st: _StreamRelay | None = None,
                            resumed: bool = False,
                            kv_resume: tuple | None = None):
        """One streamed attempt relayed onto the client socket held by
        `st`. Returns:
          ("final", resp)  — terminal: clean EOF, a relayed refusal, or
                             the client itself went away;
          ("none", True)   — nothing (new) reached the client; the
                             caller may rotate to another candidate;
          ("lost", None)   — a hedge twin claimed the socket first;
          ("broken", rep)  — transport break AFTER this attempt relayed
                             bytes; st carries the replay buffer.
        Non-resumed attempts relay events verbatim while ACCOUNTING the
        delta text into the replay buffer; resumed attempts PARSE and
        REWRITE — the duplicate assistant-role chunk is dropped,
        retokenization overlap against the buffer tail is stripped, and
        the chunk id / created stamp are rewritten to the first
        stream's so the client sees one continuous completion."""
        hook = faults.FAULT_HOOK
        t0 = now()
        chunks = 0          # events read from THIS upstream (fault seam)
        relayed = 0         # events THIS attempt wrote to the client
        # full partial length of the splice request (client's own
        # continuation prefix included) — consumption is reported
        # against this, not just the router's buffer
        splice_chars = st.splice_chars if resumed else 0
        strip_left = 0      # re-emitted overlap chars still to drop
        stripped = 0        # overlap chars dropped at the splice point
        ttfb_ms = None
        try:
            if hook is not None:
                stall = hook.on_attempt(rep.name)
                if stall:
                    await asyncio.sleep(stall)
            import aiohttp
            # streams: connect deadline on the handshake, first-byte
            # deadline on the wait for response HEADERS — a replica
            # streams headers at prepare time, before its first token,
            # so the accept-then-silence black hole fails here in
            # bounded time as a retryable transport failure. The body
            # relay stays UNBOUNDED: generation time is open-ended and
            # the stream-resume plane owns mid-body breaks.
            tmo = aiohttp.ClientTimeout(
                total=None, connect=self.connect_timeout_s or None)
            hdrs = self._trace_headers(rid, fwd)
            peers = self._kv_peers(rep)
            if peers:
                hdrs[KV_DIR_HEADER] = peers
            if kv_resume is not None and kv_resume[0] == rep.name:
                # this candidate staged the migrated stream blob: ask
                # it to adopt instead of splice-prefilling (the body is
                # still the splice, so a failed adoption inside the
                # replica falls through to the same continuation)
                hdrs[KV_RESUME_HEADER] = kv_resume[1]
            hdrs_aw = self.session.post(
                rep.base_url + "/v1/chat/completions",
                json=body, timeout=tmo,
                headers=hdrs)
            async with await _deadline(
                    hdrs_aw, self.first_byte_timeout_s) as r:
                if r.status != 200:
                    data = await r.read()
                    if r.status in (500, 502, 503):
                        rep.record_result(False, lease=lease)
                        return ("none", True)
                    if r.status == 429:
                        return ("none", True)
                    if resumed:
                        # a refusal cannot be relayed onto a socket that
                        # is already a committed 200 SSE stream: count
                        # the candidate out and rotate (the replica
                        # answered, so it is not a transport failure)
                        rep.record_result(True, (now() - t0) * 1e3,
                                          lease=lease)
                        return ("none", True)
                    # non-retryable refusal (400 etc.): relay verbatim
                    rep.record_result(True, (now() - t0) * 1e3,
                                      lease=lease)
                    FLEET_PROXIED.inc(
                        outcome="ok" if r.status < 400 else "failed")
                    return ("final", web.Response(
                        body=data, status=r.status,
                        content_type=r.content_type
                        or "application/json"))
                if resumed:
                    if r.headers.get(KV_RESUMED_HEADER):
                        # blob-adopted resume: the replica replays the
                        # FULL generated text from token 0 (the swap
                        # blob's token record), so the re-emitted
                        # prefix is everything the client has received
                        # across ALL previous legs — cumulative
                        # position, not the splice-consumption formula
                        # (the adoption never consumed the splice).
                        # Text past the cumulative mark is generated-
                        # but-never-relayed tail the break ate: it
                        # relays as new content, which is exactly right.
                        strip_left = st.content_chars
                    else:
                        # deterministic overlap: the replica says how
                        # much of the partial its continuation consumed
                        # (ours consume all of it); the difference is
                        # re-emitted text the client already has. No
                        # header = assume exact continuation, strip
                        # nothing.
                        hdr = r.headers.get(CONTINUATION_CHARS_HEADER)
                        if hdr is not None:
                            try:
                                strip_left = max(
                                    splice_chars - int(hdr), 0)
                            except ValueError:
                                strip_left = 0
                buf = b""
                async for piece in r.content.iter_any():
                    if not piece:
                        continue
                    buf += piece
                    # relay whole SSE events, not TCP pieces: the break
                    # drill (and the resume accounting) count EVENTS,
                    # which TCP coalescing would otherwise blur
                    while b"\n\n" in buf:
                        event, buf = buf.split(b"\n\n", 1)
                        event += b"\n\n"
                        if hook is not None and hook.break_stream(
                                rep.name, chunks):
                            raise faults.InjectedFleetFault(
                                f"fault injected: stream to {rep.name} "
                                f"severed after {chunks} chunks")
                        chunks += 1
                        if ttfb_ms is None:
                            ttfb_ms = (now() - t0) * 1e3
                        if not resumed and st.claimed \
                                and st.owner != rep.name:
                            # a hedge twin claimed the socket between
                            # our upstream read and this event: stand
                            # down BEFORE parsing — a loser that folded
                            # its own stream's cid/opaque flags into the
                            # shared relay state would poison the
                            # winner's replay buffer
                            return ("lost", None)
                        # parse the event for the replay buffer (and,
                        # on a resumed leg, to rewrite/strip it)
                        content = finish = None
                        obj = None
                        if event.startswith(b"data:"):
                            pl = event[5:].strip()
                            if pl != b"[DONE]":
                                try:
                                    obj = json.loads(pl)
                                except Exception:
                                    # opaque payload: relayable, but a
                                    # future splice could not rebuild
                                    # it — disable resume honestly
                                    st.opaque = True
                        if isinstance(obj, dict):
                            choice = (obj.get("choices") or [{}])[0] or {}
                            delta = choice.get("delta") or {}
                            content = delta.get("content")
                            finish = choice.get("finish_reason")
                            if not resumed and st.cid is None \
                                    and obj.get("id"):
                                st.cid = obj["id"]
                                st.created = obj.get("created")
                        out = event
                        if resumed and isinstance(obj, dict):
                            if "role" in (choice.get("delta") or {}) \
                                    and not content:
                                continue    # duplicate assistant header
                            if strip_left and content:
                                cut = min(len(content), strip_left)
                                strip_left -= cut
                                stripped += cut
                                content = content[cut:]
                                choice["delta"]["content"] = content
                                if not content and finish is None:
                                    continue    # chunk fully re-emitted
                            if st.cid is not None and obj.get("id"):
                                obj["id"] = st.cid
                                if st.created is not None:
                                    obj["created"] = st.created
                            out = b"data: " \
                                + json.dumps(obj).encode() + b"\n\n"
                        if st.resp is None:
                            st.claimed = True
                            st.owner = rep.name
                            if rid:
                                self.timelines.event(
                                    rid, "commit", replica=rep.name,
                                    ttfb_ms=round(ttfb_ms, 3))
                            hdrs = {
                                "Content-Type": "text/event-stream",
                                "Cache-Control": "no-cache",
                                "Connection": "keep-alive",
                            }
                            if rid:
                                hdrs[TRACE_HEADER] = rid
                            st.resp = web.StreamResponse(headers=hdrs)
                            st.commit_evt.set()
                            try:
                                await st.resp.prepare(request)
                            except _transport_errors() as we:
                                raise _ClientGone() from we
                        try:
                            await st.resp.write(out)
                        except _transport_errors() as we:
                            raise _ClientGone() from we
                        if resumed and relayed == 0 and rid:
                            self.timelines.event(
                                rid, "resume_spliced", replica=rep.name,
                                overlap_chars=stripped)
                        relayed += 1
                        st.chunks += 1
                        st.account(content, finish)
                if st.resp is None:
                    # upstream 200 with an empty body: broken replica
                    rep.record_result(False, lease=lease)
                    return ("none", True)
                if resumed and relayed == 0:
                    # a 200 that relayed nothing new (only a role chunk
                    # or pure overlap): a failed splice candidate, not
                    # a finished stream
                    rep.record_result(False, lease=lease)
                    return ("none", True)
                if buf:
                    try:
                        await st.resp.write(buf)    # non-event tail
                    except _transport_errors() as we:
                        raise _ClientGone() from we
                rep.record_result(True, ttfb_ms, lease=lease)
                FLEET_PROXIED.inc(outcome="ok")
                await st.resp.write_eof()
                return ("final", st.resp)
        except _ClientGone:
            # the CLIENT went away, the replica was fine: closing the
            # upstream context cancels the replica-side generation (its
            # disconnect sweep frees the slot) and no failure is
            # recorded against it — a resume the client abandoned is
            # NOT replica evidence either
            rep.record_result(True, (now() - t0) * 1e3,
                              lease=lease)
            FLEET_PROXIED.inc(outcome="ok")
            return ("final",
                    st.resp if st.resp is not None and st.resp.prepared
                    else web.Response(status=200))
        except _transport_errors() as e:
            rep.record_result(False, transport=True, lease=lease)
            if st.resp is None or relayed == 0:
                return ("none", True)   # nothing (new) was relayed
            # break AFTER bytes reached the client: hand the replay
            # buffer back to _route_stream, whose resume budget decides
            # between a transparent splice and the typed error event
            st.last_exc = e
            if rid:
                self.timelines.event(rid, "stream_broken",
                                     replica=rep.name, chunks=st.chunks)
            return ("broken", rep)

    # -- passthrough + introspection ----------------------------------------

    async def handle_models(self, request: web.Request) -> web.Response:
        for rep in self.registry.replicas():
            if not rep.routable():
                continue
            try:
                import aiohttp
                tmo = aiohttp.ClientTimeout(total=5.0)
                async with self.session.get(
                        rep.base_url + "/v1/models", timeout=tmo) as r:
                    return web.Response(body=await r.read(),
                                        status=r.status,
                                        content_type=r.content_type
                                        or "application/json")
            except _transport_errors():
                continue
        return self._no_replica()

    async def handle_health(self, request: web.Request) -> web.Response:
        snap = self.registry.snapshot()
        ok = snap["routable"] > 0 and not self.draining
        body = {"status": "ok" if ok else "degraded",
                "fleet": snap, "inflight": self.inflight,
                "global_cap": self._global_cap()}
        if self.draining:
            body["draining"] = True
        return web.json_response(body, status=200 if ok else 503)

    async def handle_fleet(self, request: web.Request) -> web.Response:
        return web.json_response(self.registry.snapshot())

    async def handle_fleet_telemetry(self,
                                     request: web.Request) -> web.Response:
        """Decision-grade rollups (fleet/telemetry.py): series, burn
        rates, headroom, outliers — the autoscaler/`cake top` feed.
        With the closed loop on, the body carries the autoscaler's
        compact summary so `cake top` renders its row from one GET."""
        body = self.telemetry.snapshot()
        if self.autoscaler is not None:
            body = dict(body)
            body["autoscale"] = self.autoscaler.summary()
        return web.json_response(body)

    async def handle_fleet_autoscale(self,
                                     request: web.Request) -> web.Response:
        """The decisions ring + policy + lifecycle process view
        (fleet/autoscale.py); {"enabled": false} when the loop is off."""
        if self.autoscaler is None:
            return web.json_response({"enabled": False})
        return web.json_response(self.autoscaler.snapshot())

    async def handle_request_index(self,
                                   request: web.Request) -> web.Response:
        return web.json_response({"requests": self.timelines.ids()})

    async def handle_request_trace(self,
                                   request: web.Request) -> web.Response:
        """Fleet-wide stitched timeline: this tier's routing events
        (route/attempt/retry/hedge/commit/done) plus the replica tier's
        lifecycle events for the same id, fetched from the replica the
        attempt events name (falling back to asking every registered
        replica — the id may predate this router process). Each tier
        carries its own start_unix anchor, so a consumer lays both on
        one wall-clock axis."""
        rid = request.match_info["rid"]
        own = self.timelines.get(rid)
        tiers = [own] if own is not None else []
        names = {e.get("replica") for e in (own or {}).get("events", [])
                 if e.get("replica")}
        reps = self.registry.replicas()
        candidates = [r for r in reps if r.name in names] or reps
        import aiohttp
        tmo = aiohttp.ClientTimeout(total=2.0)

        # concurrent: the all-replicas fallback must not serialize one
        # probe timeout per unreachable member (debugging happens
        # exactly when some of the fleet is down)
        async def fetch(rep):
            try:
                async with self.session.get(
                        rep.base_url + "/api/v1/requests/" + rid,
                        timeout=tmo) as r:
                    if r.status != 200:
                        return None
                    body = await r.json(content_type=None)
                    body["replica"] = rep.name
                    return body
            except _transport_errors():
                return None
        for body in await asyncio.gather(*(fetch(r) for r in candidates)):
            if body is not None:
                tiers.append(body)
        if not tiers:
            return web.json_response(
                {"error": f"no timeline for request {rid!r} at the "
                          "router or any replica"}, status=404)
        return web.json_response({"request_id": rid, "tiers": tiers})


async def _metrics(request: web.Request) -> web.Response:
    from ..obs import REGISTRY
    return web.Response(
        body=REGISTRY.render().encode(),
        headers={"Content-Type":
                 "text/plain; version=0.0.4; charset=utf-8"})


def create_router_app(router: FleetRouter) -> web.Application:
    app = web.Application()
    app["router"] = router
    app.router.add_post("/v1/chat/completions", router.handle_chat)
    app.router.add_get("/v1/models", router.handle_models)
    app.router.add_get("/health", router.handle_health)
    app.router.add_get("/fleet", router.handle_fleet)
    app.router.add_get("/api/v1/fleet/telemetry",
                       router.handle_fleet_telemetry)
    app.router.add_get("/api/v1/fleet/autoscale",
                       router.handle_fleet_autoscale)
    app.router.add_get("/api/v1/requests", router.handle_request_index)
    app.router.add_get("/api/v1/requests/{rid}",
                       router.handle_request_trace)
    app.router.add_get("/metrics", _metrics)
    app.on_startup.append(router.start)
    app.on_shutdown.append(router.drain)
    app.on_cleanup.append(router.stop)
    return app


def serve_router(replicas: list, host: str = "0.0.0.0", port: int = 8100,
                 cluster_key: str | None = None,
                 autoscale: bool | None = None):
    """Blocking router entry (ref: `cake route`). `replicas` is
    [(name, base_url), ...] from --replica flags; when a cluster key is
    given, announced replicas discovered over UDP join too (and keep
    joining every CAKE_FLEET_DISCOVER_S). `autoscale` turns the closed
    loop on regardless of CAKE_SCALE (None defers to the knob)."""
    registry = ReplicaRegistry()
    for name, base_url in replicas:
        registry.add(name, base_url)
    if cluster_key:
        for name, base_url in discover_replicas(cluster_key):
            registry.add(name, base_url)
    router = FleetRouter(registry, cluster_key=cluster_key,
                         autoscale=autoscale)
    app = create_router_app(router)
    log.info("fleet router on http://%s:%d fronting %d replicas",
             host, port, len(registry.names()))
    web.run_app(app, host=host, port=port, print=None)
